"""Headline benchmark: pods scheduled/sec at scale (BASELINE.json metric).

Runs the scheduler_perf SchedulingBasic workload (in-process store + real
scheduler + informers, Node objects as data — no kubelets, the reference's
own trick) with the TPU batch backend, and prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "pods/s", "vs_baseline": N/ref}

Baseline: the reference's default-scheduler sustains ~100–300 pods/s on
scheduler_perf (BASELINE.md); vs_baseline uses 300 — the top of the
published envelope — so the ratio is conservative.

Presets: --preset smoke (100 nodes/1k pods, quick), --preset 1k,
--preset 5k (default; the BASELINE headline config).
Options: --backend host|tpu (default tpu), --batch-size (default 8192).
"""

from __future__ import annotations

import argparse
import asyncio
import gc
import json
import sys

REFERENCE_PODS_PER_SEC = 300.0


def _provenance(backend: str) -> dict:
    """Solve-backend provenance stamped into every headline/detail JSON:
    the jax platform and device count the run actually used, and whether
    the solve routed through the fused Pallas kernel, the lax.scan
    reference, and the donated carry — a relay-battery number is only
    comparable to a CPU one when both rows carry these fields."""
    if backend != "tpu":
        return {"solve_kernel": "host"}
    from kubernetes_tpu.ops.backend import solve_provenance
    return solve_provenance()

#: default --churn rate sweeps (pods/s arrival): bracket the knee from
#: a comfortable trickle to past the drain headline for the preset.
PRESET_CHURN_RATES = {
    "smoke": [50.0, 200.0, 800.0],
    "1k": [100.0, 400.0, 1600.0],
    "5k": [250.0, 1000.0, 4000.0],
    "50k": [250.0, 1000.0, 4000.0],
    "200k": [250.0, 1000.0, 4000.0],
    "1m": [250.0, 1000.0, 4000.0],
}

def _warn_policy_needs_boundary(args, boundary, what: str) -> None:
    """Shared "refuse to record a lie" guards (drain/churn/serve
    modes): the policy chain lives on the servers, so --policy-set/
    --audit-level without the apiserver boundary would measure nothing
    — and --policy-tenants only shapes a --policy-set, so alone it
    installs zero policies."""
    if args.policy_tenants and not args.policy_set:
        print("warning: --policy-tenants without --policy-set installs "
              f"NO policies; {what} will measure a policy-free chain",
              file=sys.stderr)
    if not boundary and (args.policy_set or args.audit_level):
        print("warning: --policy-set/--audit-level need "
              f"--through-apiserver; {what} will evaluate NO policies",
              file=sys.stderr)


PRESETS = {
    #       nodes, warmup pods, measured pods
    "smoke": (100, 200, 1000),
    "1k": (1000, 500, 3000),
    "5k": (5000, 1000, 10000),
    # config #5 scale: 50k nodes (KWOK-style, nodes are data); the node
    # dimension is what multi-slice sharding scales (SURVEY §5.7).
    "50k": (50000, 500, 5000),
    # Sharded-control-plane scale (ROADMAP #5): above KTPU_SHARD_THRESHOLD
    # the store/informer/host-prep path partitions into per-shard mvcc
    # stores (store/sharded.py) — flagless; --shards/KTPU_SHARDS override.
    "200k": (200000, 500, 5000),
    # r22 stretch preset: 1M nodes. Intended for --processes >= 2 (the
    # multi-process control plane); the finding — positive or negative,
    # with the bounding resource named — is recorded in BASELINE.md.
    "1m": (1_000_000, 500, 5000),
}


def _proc_tag(args) -> str:
    """Metric-name suffix for multi-process rows: an N-process headline
    must never be mistaken for (or averaged with) the in-process one."""
    return f"_procs{args.processes}" if (args.processes or 0) > 1 else ""


def _run_churn(args, nodes: int, shards, boundary, batch: int) -> int:
    """ChurnDay mode: rate sweep to the knee (+ optional fault row).

    Headline = the knee (highest absorbed open-loop arrival rate) with
    its exact p999; per-row details (p50/p99/p999, backlog growth,
    fault/recovery records) go to stderr like the drain detail JSON."""
    from kubernetes_tpu.perf.churn.driver import run_rate_sweep
    from kubernetes_tpu.perf.scheduler_perf import PerfRunner
    from kubernetes_tpu.utils.featuregate import DEFAULT_FEATURE_GATES

    rates = PRESET_CHURN_RATES[args.preset]
    if args.churn_rates:
        rates = [float(r) for r in args.churn_rates.split(",") if r]
    fault = None
    if args.churn_fault:
        kind, _, at = args.churn_fault.partition("@")
        fault = {"kind": kind, "at": float(at or 5.0)}
    use_tpu = DEFAULT_FEATURE_GATES.enabled("TPUScorer")
    if args.profile_dir:
        print("warning: --profile-dir is not supported in --churn mode "
              "(per-row runs would overwrite each other's traces); no "
              "trace will be written", file=sys.stderr)
    _warn_policy_needs_boundary(args, boundary, "churn rows")

    def runner_factory():
        be = None
        if use_tpu:
            from kubernetes_tpu.ops import TPUBackend
            be = TPUBackend(max_batch=args.chunk)
        return PerfRunner(backend=be, batch_size=batch if be else 1,
                          through_apiserver=boundary, shards=shards,
                          policy_count=args.policy_set,
                          policy_tenants=args.policy_tenants,
                          audit_rules=[{"level": args.audit_level}]
                          if args.audit_level else None,
                          processes=args.processes,
                          data_dir=args.data_dir or None)

    sweep = run_rate_sweep(
        nodes=nodes, rates=rates, duration=args.churn_duration,
        seed=args.churn_seed, model=args.churn_model,
        warmup=args.churn_warmup, agents=args.churn_agents,
        fault=fault, fault_rate=args.churn_fault_rate,
        runner_factory=runner_factory, timeout=1800.0)
    prov = _provenance(args.backend)
    print(json.dumps({"churn": sweep, "preset": args.preset,
                      "backend": args.backend,
                      "provenance": prov}), file=sys.stderr)
    knee = sweep["knee"]
    value = knee["knee_rate"] or 0.0
    out = {
        "provenance": prov,
        "metric": f"churn_knee_arrival_rate_{args.preset}_{args.backend}"
                  + (f"_apiserver_{args.transport}" if boundary else "")
                  + _proc_tag(args),
        "value": value,
        "unit": "pods/s",
        "vs_baseline": round(value / REFERENCE_PODS_PER_SEC, 3),
        "knee_p999_ms": knee["knee_p999_ms"],
        "first_saturated_rate": knee["first_saturated_rate"],
    }
    if sweep["fault_row"] is not None:
        out["fault_recovery_seconds_max"] = \
            sweep["fault_row"]["churn_recovery_seconds_max"]
    print(json.dumps(out))
    return 0


def _run_serve(args, nodes: int, warmup: int, measured: int, shards,
               boundary, batch: int) -> int:
    """--serve mode: the online-serving headline pair IN ONE RUN —
    (a) the unchanged bulk-drain throughput of the preset, then
    (b) a steady-state single-pod trickle (open-loop arrivals at
    --serve-rate, default the r15 worst-case 250/s) whose EXACT
    p50/p99/p999 attempt percentiles (r11 WindowedLatencyRecorder) are
    the serving tier's figure of merit. Fresh runner per phase so the
    drain's warmed chunk programs can't subsidize the serve numbers or
    vice versa."""
    from kubernetes_tpu.perf.scheduler_perf import PerfRunner
    from kubernetes_tpu.utils.featuregate import DEFAULT_FEATURE_GATES

    use_tpu = DEFAULT_FEATURE_GATES.enabled("TPUScorer")
    _warn_policy_needs_boundary(args, boundary, "serve rows")

    def make_runner():
        be = None
        if use_tpu:
            from kubernetes_tpu.ops import TPUBackend
            be = TPUBackend(max_batch=args.chunk)
        return PerfRunner(backend=be, batch_size=batch if be else 1,
                          through_apiserver=boundary, shards=shards,
                          policy_count=args.policy_set,
                          policy_tenants=args.policy_tenants,
                          audit_rules=[{"level": args.audit_level}]
                          if args.audit_level else None,
                          processes=args.processes,
                          data_dir=args.data_dir or None)

    drain_template = [
        {"opcode": "createNodes", "countParam": "$nodes"},
        {"opcode": "createPods", "countParam": "$warmup"},
        {"opcode": "barrier"},
        {"opcode": "createPods", "countParam": "$measured",
         "collectMetrics": True},
        {"opcode": "barrier"},
    ]
    drain = asyncio.run(make_runner().run(
        drain_template, {"nodes": nodes, "warmup": warmup,
                         "measured": measured}, timeout=1800.0))
    serve_template = [
        {"opcode": "createNodes", "countParam": "$nodes"},
        {"opcode": "createPods", "countParam": "$warmup"},
        {"opcode": "barrier"},
        {"opcode": "churnOpenLoop", "collectMetrics": True,
         "arrival": {"model": "poisson", "rate": "$rate"},
         "duration": "$duration", "seed": 17},
    ]
    serve = asyncio.run(make_runner().run(
        serve_template, {"nodes": nodes, "warmup": warmup,
                         "rate": args.serve_rate,
                         "duration": args.serve_duration}, timeout=1800.0))
    d, s = drain.as_dict(), serve.as_dict()
    prov = _provenance(args.backend)
    print(json.dumps({"serve": s, "drain": d, "preset": args.preset,
                      "backend": args.backend,
                      "provenance": prov}), file=sys.stderr)
    print(json.dumps({
        "provenance": prov,
        "metric": f"serve_single_pod_p50_ms_{args.preset}_{args.backend}"
                  + (f"_apiserver_{args.transport}" if boundary else "")
                  + _proc_tag(args),
        "value": s["attempt_p50_ms"],
        "unit": "ms",
        "serve_rate": args.serve_rate,
        "serve_p99_ms": s["attempt_p99_ms"],
        "serve_p999_ms": s["attempt_p999_ms"],
        "serve_percentiles_exact": s["attempt_percentiles_exact"],
        "serve_fast_path_pods": s["serving_fast_path_pods_total"],
        "drain_pods_per_sec": d["throughput_pods_per_sec"],
        "drain_vs_baseline": round(
            d["throughput_pods_per_sec"] / REFERENCE_PODS_PER_SEC, 3),
    }))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", choices=PRESETS, default="5k")
    ap.add_argument("--backend", choices=["host", "tpu"], default="tpu")
    ap.add_argument("--batch-size", type=int, default=16384,
                    help="pods popped per scheduling super-batch; the "
                         "backend chunks + pipelines internally. One "
                         "super-batch per measured burst avoids the "
                         "batch-boundary stall (tensor delta + used-state "
                         "re-upload + first-chunk latency with no binding "
                         "work to overlap)")
    ap.add_argument("--chunk", type=int, default=None,
                    help="OVERRIDE the backend solve chunk (jit batch "
                         "signature). Default: flagless — the backend's "
                         "adaptive tuner picks chunk AND pipeline depth "
                         "from warmup-measured transfer latency and "
                         "dirty-upload ratio (BASELINE.md r6 envelope)")
    ap.add_argument("--shards", type=int, default=None,
                    help="OVERRIDE the control-plane shard count (the "
                         "sweep knob; 1 = the classic single store). "
                         "Default: flagless — node counts at or above "
                         "KTPU_SHARD_THRESHOLD (100k) activate "
                         "KTPU_SHARDS or 8 shards; below it the r12 "
                         "single-store path runs bit-for-bit")
    ap.add_argument("--processes", type=int, default=None,
                    help="OVERRIDE the control-plane OS-process count "
                         "(r22 tentpole): N >= 2 runs one apiserver "
                         "process per shard plus a leader-elected "
                         "scheduler pair over the KTPU wire; 1 is the "
                         "kill switch (today's in-process tree, built "
                         "exactly as before). Default: flagless "
                         "KTPU_PROCESSES (unset = 1)")
    ap.add_argument("--data-dir", default="",
                    help="durability directory for the shard processes "
                         "(per-shard snapshots + write-ahead log; "
                         "KTPU_WAL_FSYNC picks the fsync policy). "
                         "Default: flagless KTPU_DATA_DIR (unset = "
                         "in-memory only)")
    ap.add_argument("--shortlist-k", type=int, default=None,
                    help="OVERRIDE the solver shortlist width (0 disables "
                         "the pruned solve — the before/after sweep knob). "
                         "Default: flagless — the tuner derives K from the "
                         "chunk width and observed fallback rate, active "
                         "only when the node count dwarfs the scan width")
    ap.add_argument("--class-pad", type=int, default=None,
                    help="OVERRIDE the class-dictionary plane cap (max "
                         "pod equivalence classes per chunk; 0 disables "
                         "class planes entirely — the per-pod-plane "
                         "before/after sweep knob). Default: flagless "
                         "KTPU_CLASS_PAD (31)")
    ap.add_argument("--serve", action="store_true",
                    help="online-serving mode (kubernetes_tpu/serving): "
                         "report steady-state single-pod placement "
                         "p50/p99/p999 (exact, open-loop trickle at "
                         "--serve-rate) ALONGSIDE the preset's unchanged "
                         "bulk-drain headline in one run")
    ap.add_argument("--serve-rate", type=float, default=250.0,
                    help="single-pod arrival rate for --serve (default "
                         "250/s — the r15 worst-case trickle row)")
    ap.add_argument("--serve-duration", type=float, default=10.0,
                    help="seconds of open-loop serve arrivals")
    ap.add_argument("--admission-window", type=float, default=None,
                    metavar="MS",
                    help="OVERRIDE the serving admission coalesce window "
                         "in milliseconds (0 = always dispatch "
                         "immediately). Default: flagless — the "
                         "AdaptiveTuner policy row sizes it from the "
                         "measured transfer latency and offered-rate "
                         "estimate (thresholds seeded from the r15 "
                         "churn knee)")
    ap.add_argument("--serving", choices=["on", "off"], default="on",
                    help="KTPU_SERVING kill switch: 'off' degrades the "
                         "dispatch loop structurally to the pre-serving "
                         "shape (the before/after sweep knob)")
    ap.add_argument("--solve-mode", choices=["greedy", "optimal", "auto"],
                    default=None,
                    help="KTPU_SOLVE_MODE: 'greedy' pins the r18 "
                         "wavefront scan (bit-identical kill switch), "
                         "'optimal' forces the Sinkhorn transport plan + "
                         "feasible rounding on eligible chunks, 'auto' "
                         "(the default policy) routes drain-scale and "
                         "gang chunks only. The r20 fragmentation pair "
                         "sweeps greedy vs optimal on one preset")
    ap.add_argument("--pallas", choices=["auto", "on", "off"],
                    default=None,
                    help="KTPU_PALLAS: 'off' pins the r20 lax.scan call "
                         "graph (bit-identical kill switch), 'on' forces "
                         "the fused Pallas wavefront kernel (compiled "
                         "where lowering exists, interpret elsewhere), "
                         "'auto' (the default policy) compiles on "
                         "accelerator backends only. The r21 relay "
                         "battery sweeps off vs on per preset; the "
                         "headline JSON stamps the resolved mode")
    ap.add_argument("--churn", action="store_true",
                    help="ChurnDay mode (perf/churn): instead of one "
                         "bulk drain, sweep an OPEN-LOOP Poisson/burst/"
                         "ramp arrival rate over the preset's nodes to "
                         "find the knee; the headline becomes exact "
                         "p50/p99/p999 attempt latency + knee rate, "
                         "with queue growth as the saturation signal")
    ap.add_argument("--churn-rates", default="",
                    help="comma-separated arrival rates (pods/s) to "
                         "sweep; default per preset")
    ap.add_argument("--churn-duration", type=float, default=10.0,
                    help="seconds of open-loop arrivals per rate row")
    ap.add_argument("--churn-seed", type=int, default=17,
                    help="arrival/fault timeline seed (same seed = "
                         "bit-identical timelines)")
    ap.add_argument("--churn-model",
                    choices=["poisson", "burst", "ramp"],
                    default="poisson")
    ap.add_argument("--churn-warmup", type=int, default=300,
                    help="drained warmup pods before the open-loop "
                         "window (jit compile exclusion)")
    ap.add_argument("--churn-fault", default="",
                    help='inject a fault mid-wave, "kind@seconds" '
                         '(e.g. "nodeDeath@5.0"): reruns one rate with '
                         "agent-backed staging, the deterministic fault "
                         "timeline, and time-to-recovery measured")
    ap.add_argument("--churn-fault-rate", type=float, default=None,
                    help="arrival rate for the fault scenario (default: "
                         "the measured knee rate)")
    ap.add_argument("--churn-agents", action="store_true",
                    help="agent-backed staging for ALL churn rows (N "
                         "hollow-kubelet NodeAgents instead of "
                         "createNodes data staging)")
    ap.add_argument("--through-apiserver", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="cross the process boundary: workload writes, "
                         "informers, and binding POSTs go over the "
                         "apiserver (reference scheduler_perf topology). "
                         "DEFAULT ON so the headline measures the honest "
                         "boundary; --no-through-apiserver for the "
                         "in-process store topology")
    ap.add_argument("--transport", choices=["wire", "http"], default="wire",
                    help="apiserver transport for --through-apiserver: "
                         "'wire' = the multiplexed framed wire core "
                         "components use (the reference's HTTP/2+protobuf "
                         "analog); 'http' = per-request HTTP/1.1+JSON")
    ap.add_argument("--policy-set", type=int, default=0,
                    help="install N ValidatingAdmissionPolicies (+ "
                         "bindings) matching pod CREATEs before the "
                         "run — the policy-chain overhead knob "
                         "(BASELINE r9 measures 10 vs 0). Counted in "
                         "the detail JSON's policy_evaluations_total")
    ap.add_argument("--policy-tenants", type=int, default=0,
                    help="shard --policy-set across N tenant namespaces "
                         "(per-namespace selectors, disjoint "
                         "resourceRules, ~1%% of policies matching any "
                         "given request — the realistic multi-tenant "
                         "shape; the 1k-policy headline row uses "
                         "--policy-set 1000 --policy-tenants 100). "
                         "0 = the legacy uniform all-matching set")
    ap.add_argument("--audit-level", default="",
                    choices=["", "Metadata", "Request",
                             "RequestResponse"],
                    help="enable the audit pipeline at this level for "
                         "every request (default: no audit rules = "
                         "level None, zero cost)")
    ap.add_argument("--profile-dir", default="",
                    help="write a jax.profiler device trace of the "
                         "MEASURED phase to this directory (tpu backend "
                         "only)")
    ap.add_argument("--trace", default="", metavar="OUT.json",
                    help="enable the in-process tracer and write the "
                         "run's span tree as Chrome trace-event JSON "
                         "(open in https://ui.perfetto.dev or "
                         "chrome://tracing). Spans cover apiserver "
                         "requests, admission, queue wait, framework "
                         "extension points, device-solve chunks and "
                         "binds; KTPU_TRACE_THRESHOLD_MS additionally "
                         "logs slow span trees")
    ap.add_argument("--feature-gates", default="",
                    help='e.g. "TPUScorer=true" — the north-star seam: the '
                         "batched device backend hangs off this gate "
                         "(--backend tpu is sugar for enabling it)")
    ap.add_argument("--lint", action="store_true",
                    help="run the repo's static analysis "
                         "(python -m kubernetes_tpu.analysis) and print a "
                         "finding summary; exit 0 clean / 1 findings / 2 "
                         "internal error")
    ap.add_argument("--lint-json", action="store_true",
                    help="--lint with machine-readable JSON on stdout")
    args = ap.parse_args(argv)

    if args.lint or args.lint_json:
        from kubernetes_tpu.analysis import main as lint_main
        return lint_main(["--json"] if args.lint_json else [])

    if args.shortlist_k is not None:
        # Flag reads are live (utils/flags.py), so ordering vs the
        # backend import no longer matters — the old import-time read
        # was the flag lint's first catch.
        import os
        os.environ["KTPU_SHORTLIST_K"] = str(args.shortlist_k)
    if args.admission_window is not None:
        import os
        os.environ["KTPU_ADMISSION_WINDOW"] = str(args.admission_window)
    if args.serving == "off":
        import os
        os.environ["KTPU_SERVING"] = "0"
    if args.solve_mode is not None:
        import os
        os.environ["KTPU_SOLVE_MODE"] = args.solve_mode
    if args.pallas is not None:
        import os
        os.environ["KTPU_PALLAS"] = args.pallas
    if args.class_pad is not None:
        import os
        if args.class_pad <= 0:
            os.environ["KTPU_CLASS_PLANES"] = "0"
        else:
            # Force the planes ON too: an inherited KTPU_CLASS_PLANES=0
            # (a leftover kill-switch export) must not silently turn the
            # advertised override into a per-pod-fallback run.
            os.environ["KTPU_CLASS_PLANES"] = "1"
            os.environ["KTPU_CLASS_PAD"] = str(args.class_pad)

    tracer = None
    if args.trace:
        from kubernetes_tpu.utils.tracing import DEFAULT_TRACER
        tracer = DEFAULT_TRACER
        tracer.enabled = True

    from kubernetes_tpu.perf.scheduler_perf import PerfRunner
    from kubernetes_tpu.utils.featuregate import DEFAULT_FEATURE_GATES

    # Backend selection goes through the TPUScorer feature gate (SURVEY
    # §5.6 seam #3): CLI --backend only sets the gate's value.
    DEFAULT_FEATURE_GATES.set("TPUScorer", args.backend == "tpu")
    if args.feature_gates:
        DEFAULT_FEATURE_GATES.set_from_spec(args.feature_gates)

    nodes, warmup, measured = PRESETS[args.preset]
    from kubernetes_tpu.store.sharded import control_plane_shards
    # PerfRunner owns propagating the override (it scopes KTPU_SHARDS
    # around the run so the host prep's policy sees the same S).
    shards = control_plane_shards(nodes, args.shards)
    backend = None
    batch = 1
    if DEFAULT_FEATURE_GATES.enabled("TPUScorer"):
        batch = args.batch_size
        args.backend = "tpu"
        if not args.churn and not args.serve:
            # Churn/serve modes build fresh backends per phase in their
            # own factories; constructing one here would be dead work.
            from kubernetes_tpu.ops import TPUBackend
            backend = TPUBackend(max_batch=args.chunk)  # None = adaptive
    else:
        args.backend = "host"

    # Warmup phase triggers jit compilation (first TPU compile is ~20-40s)
    # before the measured phase starts.
    template = [
        {"opcode": "createNodes", "countParam": "$nodes"},
        {"opcode": "createPods", "countParam": "$warmup"},
        {"opcode": "barrier"},
        {"opcode": "createPods", "countParam": "$measured",
         "collectMetrics": True},
        {"opcode": "barrier"},
    ]
    params = {"nodes": nodes, "warmup": warmup, "measured": measured}

    # The workload churns millions of short-lived dicts; default gen-0
    # collection every 700 allocations makes the interpreter spend ~6% of
    # the measured phase in GC (plus XLA's gc callback). Raising the
    # threshold trades peak RSS for wall, like tuning GOGC on the reference.
    gc.set_threshold(100_000, 50, 50)

    if args.profile_dir and backend is None:
        print("warning: --profile-dir needs --backend tpu; no trace "
              "will be written", file=sys.stderr)
    boundary = False
    if args.through_apiserver:
        boundary = "wire" if args.transport == "wire" else True
    if (args.processes or 0) > 1 and (args.policy_set or args.audit_level):
        print("warning: the multi-process control plane carries no "
              "policy chain yet; --policy-set/--audit-level are ignored "
              "at --processes >= 2", file=sys.stderr)
    if args.churn:
        return _run_churn(args, nodes, shards, boundary, batch)
    if args.serve:
        return _run_serve(args, nodes, warmup, measured, shards, boundary,
                          batch)
    _warn_policy_needs_boundary(args, boundary, "the run")
    runner = PerfRunner(backend=backend, batch_size=batch,
                        through_apiserver=boundary,
                        profile_dir=args.profile_dir or None,
                        policy_count=args.policy_set,
                        policy_tenants=args.policy_tenants,
                        audit_rules=[{"level": args.audit_level}]
                        if args.audit_level else None,
                        shards=shards,
                        processes=args.processes,
                        data_dir=args.data_dir or None)
    res = asyncio.run(runner.run(
        template, params,
        # The 1m stretch preset stages and syncs ~200x the 5k object
        # count before the measured phase begins; everything else keeps
        # the tighter window so a hung run fails fast.
        timeout=5400.0 if args.preset == "1m" else 1800.0))

    if tracer is not None:
        with open(args.trace, "w") as f:
            f.write(tracer.to_perfetto())
        print(f"trace: {args.trace} ({len(tracer.spans)} spans; open in "
              "https://ui.perfetto.dev)", file=sys.stderr)

    detail = res.as_dict()
    prov = _provenance(args.backend)
    print(json.dumps({"detail": detail, "preset": args.preset,
                      "backend": args.backend,
                      "provenance": prov}, ), file=sys.stderr)
    print(json.dumps({
        "provenance": prov,
        "metric": f"pods_per_sec_{args.preset}_nodes_{args.backend}"
                  + (f"_apiserver_{args.transport}"
                     if args.through_apiserver else "")
                  + _proc_tag(args),
        "value": detail["throughput_pods_per_sec"],
        "unit": "pods/s",
        "vs_baseline": round(
            detail["throughput_pods_per_sec"] / REFERENCE_PODS_PER_SEC, 3),
        # r20 headline: packing quality next to pods/s — occupied-node
        # fragmentation is the figure optimal mode moves; the all-nodes
        # figure stays for continuity with earlier rounds.
        "fragmentation_pct": detail["fragmentation_pct"],
        "fragmentation_occupied_pct": detail["fragmentation_occupied_pct"],
        "solve_mode": args.solve_mode or "auto",
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
