"""BASELINE config #4: device-plugin extended resources + NUMA topology
(NodeResourceTopologyMatch over NodeResourceTopology objects — SURVEY §2.5
cm/devicemanager + cm/topologymanager, scheduler-plugins noderesourcetopology)."""

import asyncio

import pytest

from kubernetes_tpu.api.types import (
    make_node,
    make_node_resource_topology,
    make_pod,
    split_node_topology,
)
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.config.scheduler import load_config
from kubernetes_tpu.metrics.registry import SchedulerMetrics
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.plugins.noderesourcetopology import pack_zones
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo
from kubernetes_tpu.store import install_core_validation, new_cluster_store

TPU = "google.com/tpu"

NRT_CONFIG = {
    "apiVersion": "kubescheduler.config.k8s.io/v1",
    "kind": "KubeSchedulerConfiguration",
    "profiles": [{
        "schedulerName": "default-scheduler",
        "plugins": {"multiPoint": {
            "enabled": [{"name": "NodeResourceTopologyMatch", "weight": 2}]}},
    }],
}


def run(coro):
    return asyncio.run(coro)


async def wait_for(predicate, timeout=10.0, interval=0.02):
    for _ in range(int(timeout / interval)):
        v = await predicate()
        if v:
            return v
        await asyncio.sleep(interval)
    return await predicate()


def tpu_pod(name, tpus, cpu="500m"):
    return make_pod(name, requests={"cpu": cpu, TPU: str(tpus)})


def two_zone_node(name, tpus_per_zone=4):
    node = make_node(name, allocatable={
        "cpu": "16", "memory": "64Gi", "pods": "110",
        TPU: str(2 * tpus_per_zone)})
    nrt = split_node_topology(
        name, {"cpu": "16"}, num_zones=2, devices={TPU: tpus_per_zone})
    return node, nrt


async def topo_stack(nodes_nrts, backend=None, batch_size=1):
    store = new_cluster_store()
    install_core_validation(store)
    for node, nrt in nodes_nrts:
        await store.create("nodes", node)
        if nrt is not None:
            await store.create("noderesourcetopologies", nrt)
    metrics = SchedulerMetrics()
    cfg = load_config(NRT_CONFIG)
    profiles = {p.scheduler_name: p.build_framework(store=store,
                                                    metrics=metrics)
                for p in cfg.profiles}
    sched = Scheduler(store, seed=3, profiles=profiles, metrics=metrics,
                      backend=backend)
    factory = InformerFactory(store)
    await sched.setup_informers(factory)
    factory.start()
    await factory.wait_for_sync()
    task = asyncio.ensure_future(sched.run(batch_size=batch_size))

    async def teardown():
        await sched.stop()
        task.cancel()
        factory.stop()
        store.stop()
    return store, sched, teardown


class TestPackZones:
    def test_first_fit_deterministic(self):
        nrt = make_node_resource_topology("n", [
            {"name": "z0", "resources": [{"name": TPU, "capacity": "4"}]},
            {"name": "z1", "resources": [{"name": TPU, "capacity": "4"}]},
        ])
        node = NodeInfo(make_node("n", allocatable={TPU: "8", "cpu": "8"}))
        for pname, tpus in [("b", 3), ("a", 2)]:
            node.add_pod(PodInfo(tpu_pod(pname, tpus)))
        free = pack_zones(nrt, node)
        # Sorted by key: "a"(2) → z0 (free 2), "b"(3) → z1 (free 1).
        assert [f[TPU] for f in free] == [2000, 1000]

    def test_unzoned_resources_unconstrained(self):
        nrt = make_node_resource_topology("n", [
            {"name": "z0", "resources": [{"name": TPU, "capacity": "4"}]}])
        node = NodeInfo(make_node("n", allocatable={TPU: "4", "cpu": "8"}))
        node.add_pod(PodInfo(make_pod("cpu-only", requests={"cpu": "4"})))
        free = pack_zones(nrt, node)
        assert free[0][TPU] == 4000  # cpu-only pod charges no zone


class TestSingleNumaFilter:
    def test_node_level_fit_but_zone_misaligned_rejected(self):
        """Two 3-TPU pods fragment both zones (1+1 free); a 2-TPU pod fits
        node-level (2 free) but no single zone — NRT must reject while
        plain NodeResourcesFit would admit."""
        async def body():
            node, nrt = two_zone_node("n1")
            store, sched, teardown = await topo_stack([(node, nrt)])
            await store.create("pods", tpu_pod("frag-a", 3))
            await store.create("pods", tpu_pod("frag-b", 3))

            async def both_bound():
                a = await store.get("pods", "default/frag-a")
                b = await store.get("pods", "default/frag-b")
                return bool(a["spec"].get("nodeName")) and \
                    bool(b["spec"].get("nodeName"))
            assert await wait_for(both_bound)

            await store.create("pods", tpu_pod("misfit", 2))
            await asyncio.sleep(0.5)
            p = await store.get("pods", "default/misfit")
            assert not p["spec"].get("nodeName")
            assert sched.queue.stats()["unschedulable"] == 1
            evs = (await store.list("events")).items
            assert any("single NUMA zone" in (e.get("message") or "")
                       for e in evs)
            await teardown()
        run(body())

    def test_score_prefers_alignable_node(self):
        """Node B has a whole free zone; node A is fragmented. The 4-TPU
        pod can only fit B; a 1-TPU pod prefers the emptier zone node
        by LeastAllocated zone scoring."""
        async def body():
            a, nrt_a = two_zone_node("a")
            b, nrt_b = two_zone_node("b")
            store, sched, teardown = await topo_stack(
                [(a, nrt_a), (b, nrt_b)])
            # Fragment A: 3+3 → zones 1/1.
            await store.create("pods", tpu_pod("fa", 3))
            await store.create("pods", tpu_pod("fb", 3))

            async def a_fragmented():
                pods = (await store.list("pods")).items
                return sum(1 for p in pods
                           if p["spec"].get("nodeName") == "a") == 2 or \
                    sum(1 for p in pods if p["spec"].get("nodeName")) == 2
            assert await wait_for(a_fragmented)
            # 4-TPU pod: only an intact zone fits — wherever it goes, that
            # node had a whole zone free.
            await store.create("pods", tpu_pod("big", 4))

            async def big_bound():
                p = await store.get("pods", "default/big")
                return p["spec"].get("nodeName")
            node = await wait_for(big_bound)
            assert node  # aligned somewhere a full zone existed
            await teardown()
        run(body())


class TestNrtChurnRequeue:
    def test_zone_capacity_increase_requeues_parked_pod(self):
        """A pod parked on 'cannot align' re-activates when the node's
        NodeResourceTopology gains zone capacity (EventsToRegister parity:
        NRT updates fire a ClusterEvent through the secondary-resource
        wiring, no 60s flush)."""
        async def body():
            node = make_node("n1", allocatable={
                "cpu": "16", "memory": "64Gi", "pods": "110", TPU: "8"})
            nrt = split_node_topology(
                "n1", {"cpu": "16"}, num_zones=2, devices={TPU: 2})
            store, sched, teardown = await topo_stack([(node, nrt)])
            await store.create("pods", tpu_pod("big", 4))
            await asyncio.sleep(0.4)
            p = await store.get("pods", "default/big")
            assert not p["spec"].get("nodeName")
            # Agent reports bigger zones (e.g. devices came online).
            bigger = split_node_topology(
                "n1", {"cpu": "16"}, num_zones=2, devices={TPU: 4})
            cur = await store.get("noderesourcetopologies", "n1")
            bigger["metadata"] = cur["metadata"]
            await store.update("noderesourcetopologies", bigger)

            async def bound():
                q = await store.get("pods", "default/big")
                return q["spec"].get("nodeName")
            assert await wait_for(bound, timeout=10.0) == "n1"
            await teardown()
        run(body())


class TestExtendedResourcesEndToEnd:
    @pytest.mark.parametrize("use_backend", [False, True])
    def test_capacity_respected_both_backends(self, use_backend):
        """Extended-resource columns flow through tensorize→kernels: 2
        nodes × 8 TPUs fit exactly eight 2-TPU pods; the ninth parks."""
        async def body():
            backend = None
            batch = 1
            if use_backend:
                from kubernetes_tpu.ops import TPUBackend
                backend = TPUBackend(max_batch=32)
                batch = 16
            nodes = [two_zone_node(f"n{i}") for i in range(2)]
            store, sched, teardown = await topo_stack(
                nodes, backend=backend, batch_size=batch)
            for i in range(9):
                await store.create("pods", tpu_pod(f"p{i}", 2))

            async def eight_bound():
                pods = (await store.list("pods")).items
                return sum(1 for p in pods
                           if p["spec"].get("nodeName")) == 8
            assert await wait_for(eight_bound, timeout=30.0)
            await asyncio.sleep(0.3)
            pods = (await store.list("pods")).items
            bound = [p for p in pods if p["spec"].get("nodeName")]
            assert len(bound) == 8  # never 9: 2×8 TPUs / 2 each
            per_node = {}
            for p in bound:
                per_node[p["spec"]["nodeName"]] = \
                    per_node.get(p["spec"]["nodeName"], 0) + 1
            assert all(v == 4 for v in per_node.values())
            await teardown()
        run(body())


class TestDeviceTopologyPerfFamily:
    def test_family_runs_and_schedules_all(self):
        from kubernetes_tpu.perf.scheduler_perf import load_config as load_suite
        from kubernetes_tpu.perf.scheduler_perf import run_suite
        import pathlib
        cfg = load_suite(str(pathlib.Path(__file__).parent.parent /
                             "kubernetes_tpu" / "perf" / "config" /
                             "performance-config.yaml"))
        out = run_suite(cfg, filter_name="DeviceTopology/100Nodes")
        res = out["DeviceTopology/100Nodes"]
        assert res["unschedulable_total"] == 0
        assert res["scheduled_total"] == 350  # 50 warmup + 300 measured
        assert res["measured_pods"] == 300
        assert res["throughput_pods_per_sec"] > 0
