"""Tier-1 guard for the shortlist-pruned solve (small-N, fast).

Pins: (a) the tuner's policy table — including the r10 large-N row and
the shortlist-width policy with its fallback-rate boost; (b) the
shortlist path being ACTIVE by default once the node count clears the
activation threshold, with bounded fallbacks on a benign (template)
workload; (c) a (1,)-mesh backend degrading cleanly to the single-chip
path. The heavyweight randomized differential parity lives in
tests/test_shortlist_solver.py.
"""

import random

import pytest

from kubernetes_tpu.ops.backend import AdaptiveTuner


class TestTunerPolicy:
    def test_chunk_depth_table(self):
        # r6 envelope rows (unchanged)...
        assert AdaptiveTuner.pick(0.020, 0.0) == (2048, 4)
        assert AdaptiveTuner.pick(0.020, 0.5) == (1024, 4)
        assert AdaptiveTuner.pick(0.0002, 0.0) == (1024, 2)
        assert AdaptiveTuner.pick(0.0002, 0.9) == (1024, 2)
        # ...plus the r10 large-N row: the 50k sweep measured chunk 1024
        # as the local optimum (shortlist scan width is 2·chunk, so a
        # wider chunk costs scan work faster than it amortizes the
        # per-chunk O(N) prefilter); the row pins it regardless of the
        # dirty signal, and remote rows are unaffected by N.
        assert AdaptiveTuner.pick(0.0002, 0.0, n_nodes=50_000) == (1024, 2)
        assert AdaptiveTuner.pick(0.0002, 0.9, n_nodes=50_000) == (1024, 2)
        assert AdaptiveTuner.pick(0.020, 0.0, n_nodes=50_000) == (2048, 4)
        assert AdaptiveTuner.pick(0.0002, 0.0, n_nodes=5_000) == (1024, 2)

    def test_large_n_row_applies_before_warmup(self):
        """The 50k preset must pick its chunk at the FIRST assign (the
        recompile belongs in warmup, not the measured phase): node count
        is structural, unlike the measured latency/dirty signals."""
        t = AdaptiveTuner()
        t.latency_s = 0.0002  # pre-probed: local
        t.n_nodes = 50_000
        assert t.total_chunks == 0
        assert t.decide() == (1024, 2)
        # Small-N still waits out the warmup window.
        t2 = AdaptiveTuner()
        t2.latency_s = 0.0002
        t2.n_nodes = 5_000
        assert t2.decide() is None

    def test_shortlist_width_policy(self):
        t = AdaptiveTuner()
        # Active once N ≥ 4·(K + chunk); K defaults to the chunk width.
        # The 5k preset deliberately keeps its full scan (measured ~10%
        # faster than pruning at that width ratio — BASELINE r10).
        assert t.shortlist_k(1024, 50_000) == 1024
        assert t.shortlist_k(1024, 8_192) == 1024
        assert t.shortlist_k(1024, 5_000) == 0
        assert t.shortlist_k(16, 150) == 16
        assert t.shortlist_k(16, 127) == 0
        # Fallback-rate feedback doubles K at decide() boundaries.
        t.observe_solve(1024, 512)  # 50% fallbacks
        t.decide()
        assert t.shortlist_boost == 2
        assert t.shortlist_k(1024, 50_000) == 2048
        # ...but a widened K can deactivate on clusters it outgrew.
        assert t.shortlist_k(1024, 9_000) == 0

    def test_shortlist_boost_needs_sample_and_rate(self):
        t = AdaptiveTuner()
        t.observe_solve(100, 100)  # tiny sample: not trusted yet
        t.decide()
        assert t.shortlist_boost == 1
        t.observe_solve(1024, 100)  # ~10% < 25%: healthy
        t.decide()
        assert t.shortlist_boost == 1


class TestBackendSmoke:
    def _template_pods(self, n):
        from kubernetes_tpu.api.types import make_pod
        from kubernetes_tpu.scheduler.types import PodInfo
        return [PodInfo(make_pod(
            f"pend-{i}", requests={"cpu": "500m", "memory": "512Mi"},
            uid=f"uid-{i}")) for i in range(n)]

    def _uniform_cluster(self, n):
        from kubernetes_tpu.api.types import make_node
        from kubernetes_tpu.scheduler.cache import SchedulerCache
        cache = SchedulerCache()
        for i in range(n):
            cache.add_node(make_node(
                f"n{i}", allocatable={"cpu": "8", "memory": "32Gi",
                                      "pods": "110"}))
        return cache.update_snapshot()

    def test_active_by_default_above_threshold(self):
        """No flags, no overrides: a cluster clearing the activation
        threshold (N ≥ 4·(K + chunk)) must take the pruned path, and a
        benign template workload must keep fallbacks bounded (the smoke
        bound is the tuner's own boost trigger — beyond it the pruning
        would be widening itself)."""
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.metrics.registry import SchedulerMetrics
        from kubernetes_tpu.ops.backend import TPUBackend
        snap = self._uniform_cluster(150)
        pods = self._template_pods(35)  # partial last chunk: padding rides
        b = TPUBackend(max_batch=16, mesh=None)
        b.metrics = SchedulerMetrics()
        assignments, _ = b.assign(pods, snap, default_fwk())
        m = b.metrics
        assert m.solver_shortlist_pods.value() == len(pods)
        # Scan width is the pruned K + P, not N.
        assert m.solver_scan_width.value() == 32
        fallbacks = m.solver_shortlist_fallbacks.value()
        assert fallbacks <= 0.25 * len(pods), fallbacks
        assert all(v is not None for v in assignments.values())
        # Per-chunk solve wall observed (the 98%-idle blind spot).
        assert m.solve_duration.count() >= 2

    def test_below_threshold_keeps_full_scan(self):
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.metrics.registry import SchedulerMetrics
        from kubernetes_tpu.ops.backend import TPUBackend
        snap = self._uniform_cluster(100)  # 100 < 4·(16+16)
        pods = self._template_pods(8)
        b = TPUBackend(max_batch=16, mesh=None)
        b.metrics = SchedulerMetrics()
        b.assign(pods, snap, default_fwk())
        assert b.metrics.solver_shortlist_pods.value() == 0
        assert b.metrics.solver_scan_width.value() == 100

    def test_one_device_mesh_degrades_to_single_chip(self):
        """A (1,)-mesh must behave exactly like mesh=None (the degrade
        guard for single-chip deployments of the sharded config)."""
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.parallel import build_mesh
        from kubernetes_tpu.ops.backend import TPUBackend
        snap = self._uniform_cluster(80)
        pods = self._template_pods(16)
        fwk = default_fwk()
        plain, _ = TPUBackend(max_batch=16, mesh=None).assign(
            pods, snap, fwk)
        meshed, _ = TPUBackend(max_batch=16, mesh=build_mesh(1)).assign(
            pods, snap, fwk)
        assert plain == meshed


class TestShardedDegrade:
    def test_one_shard_mesh_matches_single_chip_solver(self):
        import numpy as np
        import jax.numpy as jnp
        from kubernetes_tpu.ops import solver
        from kubernetes_tpu.parallel import build_mesh, sharded_greedy_assign
        rng = np.random.default_rng(5)
        N, P, R = 32, 6, 2
        alloc_q = rng.integers(8_000, 32_000, size=(N, R)).astype(np.int32)
        used_q = (alloc_q * 0.2).astype(np.int32)
        req_q = rng.integers(500, 4_000, size=(P, R)).astype(np.int32)
        mask = np.ones((P, N), np.bool_)
        sc = rng.uniform(0, 5, size=(P, N)).astype(np.float32)
        args = [jnp.asarray(x) for x in (
            req_q, req_q, alloc_q - used_q,
            np.full((N,), 110, np.int32), used_q, alloc_q, mask, sc,
            np.ones((R,), np.float32), np.ones((R,), np.bool_),
            np.zeros((2,), np.float32), np.zeros((2,), np.float32))] \
            + [jnp.float32(1.0), jnp.float32(1.0)]
        single = np.asarray(solver.greedy_assign_rescoring(
            *args, strategy="LeastAllocated"))
        for k in (0, 4):
            sharded = np.asarray(sharded_greedy_assign(
                build_mesh(1), *args, "LeastAllocated", shortlist_k=k))
            np.testing.assert_array_equal(single, sharded)
