"""MVCC store tests: RV semantics, watch replay/bookmarks/410, CAS, binding."""

import asyncio

import pytest

from kubernetes_tpu.api.labels import parse_selector
from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.store import (
    AlreadyExists,
    Conflict,
    Expired,
    Invalid,
    MVCCStore,
    NotFound,
    install_core_validation,
    new_cluster_store,
)


def run(coro):
    return asyncio.run(coro)


class TestCRUD:
    def test_create_get_rv_monotonic(self):
        async def body():
            s = MVCCStore()
            p1 = await s.create("pods", make_pod("a"))
            p2 = await s.create("pods", make_pod("b"))
            assert int(p2["metadata"]["resourceVersion"]) > int(p1["metadata"]["resourceVersion"])
            got = await s.get("pods", "default/a")
            assert got["metadata"]["name"] == "a"
            assert got["metadata"]["creationTimestamp"]
        run(body())

    def test_create_duplicate(self):
        async def body():
            s = MVCCStore()
            await s.create("pods", make_pod("a"))
            with pytest.raises(AlreadyExists):
                await s.create("pods", make_pod("a"))
        run(body())

    def test_update_rv_conflict(self):
        async def body():
            s = MVCCStore()
            p = await s.create("pods", make_pod("a"))
            stale = dict(p)
            p["metadata"]["labels"] = {"x": "1"}
            await s.update("pods", p)
            with pytest.raises(Conflict):
                await s.update("pods", stale)
        run(body())

    def test_guaranteed_update_retries(self):
        async def body():
            s = MVCCStore()
            await s.create("pods", make_pod("a"))

            async def bump(tag):
                def mutate(pod):
                    pod["metadata"].setdefault("annotations", {})[tag] = "1"
                    return pod
                return await s.guaranteed_update("pods", "default/a", mutate)

            await asyncio.gather(*(bump(f"t{i}") for i in range(5)))
            final = await s.get("pods", "default/a")
            assert len(final["metadata"]["annotations"]) == 5
        run(body())

    def test_delete_and_uid_precondition(self):
        async def body():
            s = MVCCStore()
            p = await s.create("pods", make_pod("a"))
            with pytest.raises(Conflict):
                await s.delete("pods", "default/a", uid="wrong")
            tomb = await s.delete("pods", "default/a", uid=p["metadata"]["uid"])
            assert tomb["metadata"]["name"] == "a"
            with pytest.raises(NotFound):
                await s.get("pods", "default/a")
        run(body())

    def test_list_selector_and_paging(self):
        async def body():
            s = MVCCStore()
            for i in range(5):
                await s.create("pods", make_pod(f"p{i}", labels={"idx": str(i % 2)}))
            res = await s.list("pods", selector=parse_selector("idx=0"))
            assert {p["metadata"]["name"] for p in res.items} == {"p0", "p2", "p4"}
            page = await s.list("pods", limit=2)
            assert len(page.items) == 2
            rest = await s.list("pods", continue_key="default/" + page.items[-1]["metadata"]["name"])
            assert len(rest.items) == 3
        run(body())

    def test_returned_objects_are_copies(self):
        async def body():
            s = MVCCStore()
            await s.create("pods", make_pod("a", labels={"k": "v"}))
            got = await s.get("pods", "default/a")
            got["metadata"]["labels"]["k"] = "mutated"
            again = await s.get("pods", "default/a")
            assert again["metadata"]["labels"]["k"] == "v"
        run(body())


class TestWatch:
    def test_watch_replay_then_live(self):
        async def body():
            s = MVCCStore()
            p = await s.create("pods", make_pod("a"))
            rv0 = int(p["metadata"]["resourceVersion"])
            await s.create("pods", make_pod("b"))

            seen = []
            w = await s.watch("pods", resource_version=rv0)

            async def consume():
                async for ev in w:
                    if ev.type == "BOOKMARK":
                        continue
                    seen.append((ev.type, ev.object["metadata"]["name"]))
                    if len(seen) == 3:
                        break

            task = asyncio.ensure_future(consume())
            await asyncio.sleep(0.01)
            await s.create("pods", make_pod("c"))
            await s.delete("pods", "default/a")
            await asyncio.wait_for(task, 2)
            assert seen == [("ADDED", "b"), ("ADDED", "c"), ("DELETED", "a")]
            s.stop()
        run(body())

    def test_watch_expired(self):
        async def body():
            s = MVCCStore(event_window=2)
            for i in range(6):
                await s.create("pods", make_pod(f"p{i}"))
            with pytest.raises(Expired):
                await s.watch("pods", resource_version=1)
            s.stop()
        run(body())

    def test_selector_watch_sees_set_transitions(self):
        """Relabeling an object out of a selector set must surface as DELETED
        to selector watchers (cacher prevObject semantics); into the set as
        ADDED."""
        async def body():
            s = MVCCStore()
            p = await s.create("pods", make_pod("a", labels={"app": "web"}))
            got = []
            w = await s.watch("pods", resource_version=0,
                              selector=parse_selector("app=web"))

            async def consume():
                async for ev in w:
                    if ev.type == "BOOKMARK":
                        continue
                    got.append((ev.type, ev.object["metadata"]["labels"]["app"]))
                    if len(got) == 2:
                        break

            task = asyncio.ensure_future(consume())
            await asyncio.sleep(0.01)
            p["metadata"]["labels"]["app"] = "db"   # leaves the set → DELETED
            p = await s.update("pods", p)
            p["metadata"]["labels"]["app"] = "web"  # re-enters → ADDED
            await s.update("pods", p)
            await asyncio.wait_for(task, 2)
            assert got == [("DELETED", "db"), ("ADDED", "web")]
            s.stop()
        run(body())

    def test_watch_namespace_filter(self):
        async def body():
            s = MVCCStore()
            w = await s.watch("pods", resource_version=0, namespace="ns1")
            got = []

            async def consume():
                async for ev in w:
                    got.append(ev.object["metadata"]["name"])
                    break

            task = asyncio.ensure_future(consume())
            await asyncio.sleep(0.01)
            await s.create("pods", make_pod("other", namespace="ns2"))
            await s.create("pods", make_pod("mine", namespace="ns1"))
            await asyncio.wait_for(task, 2)
            assert got == ["mine"]
            s.stop()
        run(body())


class TestBinding:
    def test_bind_sets_node_name(self):
        async def body():
            s = new_cluster_store()
            pod = await s.create("pods", make_pod("a"))
            binding = {
                "target": {"kind": "Node", "name": "node-1"},
                "metadata": {"uid": pod["metadata"]["uid"]},
            }
            st = await s.subresource("pods", "default/a", "binding", binding)
            # BindingREST.Create returns metav1.Status, not the pod.
            assert st["kind"] == "Status" and st["status"] == "Success"
            bound = await s.get("pods", "default/a")
            assert bound["spec"]["nodeName"] == "node-1"
            conds = {c["type"]: c["status"] for c in bound["status"]["conditions"]}
            assert conds["PodScheduled"] == "True"
        run(body())

    def test_bind_conflict_on_rebind(self):
        async def body():
            s = new_cluster_store()
            await s.create("pods", make_pod("a"))
            await s.subresource("pods", "default/a", "binding", {"target": {"name": "n1"}})
            with pytest.raises(Conflict):
                await s.subresource("pods", "default/a", "binding", {"target": {"name": "n2"}})
            # Re-binding to the same node is idempotent.
            await s.subresource("pods", "default/a", "binding", {"target": {"name": "n1"}})
        run(body())


class TestValidation:
    def test_pod_validation_and_defaults(self):
        async def body():
            s = new_cluster_store()
            install_core_validation(s)
            p = await s.create("pods", make_pod("ok"))
            assert p["spec"]["schedulerName"] == "default-scheduler"
            tol_keys = {t["key"] for t in p["spec"]["tolerations"]}
            assert "node.kubernetes.io/not-ready" in tol_keys

            bad = make_pod("bad")
            bad["spec"]["containers"] = []
            with pytest.raises(Invalid):
                await s.create("pods", bad)

            bad2 = make_pod("bad2", requests={"cpu": "2"}, limits={"cpu": "1"})
            with pytest.raises(Invalid):
                await s.create("pods", bad2)
        run(body())

    def test_node_validation(self):
        async def body():
            s = new_cluster_store()
            install_core_validation(s)
            await s.create("nodes", make_node("n1"))
            bad = make_node("n2", taints=[{"key": "", "effect": "NoSchedule"}])
            with pytest.raises(Invalid):
                await s.create("nodes", bad)
        run(body())


class TestCheckpoint:
    def test_dump_load(self):
        async def body():
            s = MVCCStore()
            await s.create("pods", make_pod("a"))
            await s.create("nodes", make_node("n1"))
            data = s.dump()
            s2 = MVCCStore.load(data)
            got = await s2.get("pods", "default/a")
            assert got["metadata"]["name"] == "a"
            assert s2.resource_version == s.resource_version
            # Old RVs are expired after restore (clients must relist).
            with pytest.raises(Expired):
                await s2.watch("pods", resource_version=1)
        run(body())
