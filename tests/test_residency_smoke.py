"""Device-residency smoke: every constraint family stays on the tensors.

One tiny TPUBackend.assign per constraint family; the backend degradation
counters (kind="host_fallback" / kind="spread_poisoned") must stay ZERO —
this is the tier-1 guard for the compiled namespaceSelector path and the
union spread table (heterogeneous templates, minDomains, restricted node
eligibility, non-self-matching selectors). A pod silently dropping to
per-pod host rows is a perf regression the 5k families pay for; this
catches it at toy scale.
"""

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.metrics.registry import SchedulerMetrics
from kubernetes_tpu.ops import TPUBackend
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.framework import Framework
from kubernetes_tpu.scheduler.plugins.registry import (
    DEFAULT_SCORE_WEIGHTS,
    build_plugins,
)
from kubernetes_tpu.scheduler.types import PodInfo

ZONE = "topology.kubernetes.io/zone"


def _cluster(n=6, zones=("z1", "z2", "z3")):
    cache = SchedulerCache()
    for i in range(n):
        cache.add_node(make_node(
            f"n{i}", labels={ZONE: zones[i % len(zones)]}))
    return cache.update_snapshot()


def _assign(snapshot, pods):
    fwk = Framework(build_plugins(), DEFAULT_SCORE_WEIGHTS)
    backend = TPUBackend(max_batch=16)
    backend.metrics = SchedulerMetrics()
    assignments, _ = backend.assign(pods, snapshot, fwk)
    deg = backend.metrics.backend_degradations
    return assignments, deg


def _spread(app, skew, **extra):
    c = {"maxSkew": skew, "topologyKey": ZONE,
         "whenUnsatisfiable": "DoNotSchedule",
         "labelSelector": {"matchLabels": {"app": app}}}
    c.update(extra)
    return c


class TestResidencySmoke:
    def test_affinity_with_namespace_selector_stays_on_device(self):
        cache = SchedulerCache()
        zones = ("z1", "z2", "z3")
        for i in range(6):
            cache.add_node(make_node(
                f"n{i}", labels={ZONE: zones[i % 3]}))
        # A resident hub in another namespace: only the {}-selector
        # (every namespace) finds it, pinning all workers to z1.
        cache.add_pod(PodInfo(make_pod(
            "hub", labels={"app": "web"}, node_name="n0",
            namespace="other")))
        snapshot = cache.update_snapshot()
        aff = {"podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [{
                "labelSelector": {"matchLabels": {"app": "web"}},
                "namespaceSelector": {},  # every namespace
                "topologyKey": ZONE}]}}
        pods = [PodInfo(make_pod(
            f"p{i}", labels={"app": "worker"}, affinity=aff,
            requests={"cpu": "100m"}, uid=f"u{i}")) for i in range(4)]
        assignments, deg = _assign(snapshot, pods)
        for p in pods:
            assert assignments[p.key] in ("n0", "n3")  # z1 only
        assert deg.value(kind="host_fallback") == 0

    def test_anti_affinity_with_namespace_selector_stays_on_device(self):
        snapshot = _cluster()
        aff = {"podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [{
                "labelSelector": {"matchLabels": {"app": "solo"}},
                "namespaceSelector": {},
                "topologyKey": "kubernetes.io/hostname"}]}}
        pods = [PodInfo(make_pod(
            f"a{i}", labels={"app": "solo"}, affinity=aff,
            requests={"cpu": "100m"}, uid=f"au{i}")) for i in range(4)]
        assignments, deg = _assign(snapshot, pods)
        nodes = [assignments[p.key] for p in pods]
        assert all(nodes) and len(set(nodes)) == 4
        assert deg.value(kind="host_fallback") == 0

    def test_heterogeneous_spread_zero_poisoning(self):
        snapshot = _cluster(n=9)
        pods = [PodInfo(make_pod(
            f"s{i}", labels={"app": "s"}, requests={"cpu": "100m"},
            uid=f"su{i}",
            topology_spread_constraints=[_spread("s", 1)]))
            for i in range(6)]
        pods += [PodInfo(make_pod(
            f"t{i}", labels={"app": "t"}, requests={"cpu": "100m"},
            uid=f"tu{i}",
            topology_spread_constraints=[_spread("t", 2)]))
            for i in range(6)]
        assignments, deg = _assign(snapshot, pods)
        assert all(assignments[p.key] for p in pods)
        assert deg.value(kind="spread_poisoned") == 0
        assert deg.value(kind="host_fallback") == 0

    def test_min_domains_spread_zero_poisoning(self):
        snapshot = _cluster(n=6, zones=("z1", "z2"))
        # minDomains=3 with only 2 zones → global min treated as 0
        # permanently, so each zone caps at maxSkew=2 matching pods:
        # exactly 4 of the 6 place, still fully on the device scan.
        pods = [PodInfo(make_pod(
            f"m{i}", labels={"app": "m"}, requests={"cpu": "100m"},
            uid=f"mu{i}",
            topology_spread_constraints=[
                _spread("m", 2, minDomains=3)])) for i in range(6)]
        assignments, deg = _assign(snapshot, pods)
        placed = [assignments[p.key] for p in pods if assignments[p.key]]
        assert len(placed) == 4
        zone_of = {f"n{i}": ("z1", "z2")[i % 2] for i in range(6)}
        counts = {"z1": 0, "z2": 0}
        for n in placed:
            counts[zone_of[n]] += 1
        assert counts == {"z1": 2, "z2": 2}
        assert deg.value(kind="spread_poisoned") == 0

    def test_restricted_eligibility_spread_zero_poisoning(self):
        # node_selector restricts the pod to z1/z2 nodes: eligibility
        # folds into the template's scan columns, not a host fallback.
        cache = SchedulerCache()
        for i in range(6):
            cache.add_node(make_node(
                f"n{i}", labels={ZONE: f"z{i % 3 + 1}",
                                 "tier": "fast" if i % 3 else "slow"}))
        snapshot = cache.update_snapshot()
        pods = [PodInfo(make_pod(
            f"e{i}", labels={"app": "e"}, requests={"cpu": "100m"},
            uid=f"eu{i}", node_selector={"tier": "fast"},
            topology_spread_constraints=[_spread("e", 1)]))
            for i in range(4)]
        assignments, deg = _assign(snapshot, pods)
        assert all(assignments[p.key] for p in pods)
        for p in pods:  # placements honor the selector
            idx = int(assignments[p.key][1:])
            assert idx % 3 != 0
        assert deg.value(kind="spread_poisoned") == 0

    def test_non_self_matching_spread_zero_poisoning(self):
        # The constraint's selector does NOT match the pods themselves:
        # selfMatch = 0 rides the scan's per-pod contributes term.
        snapshot = _cluster()
        pods = [PodInfo(make_pod(
            f"x{i}", labels={"app": "x"}, requests={"cpu": "100m"},
            uid=f"xu{i}",
            topology_spread_constraints=[_spread("other", 1)]))
            for i in range(4)]
        assignments, deg = _assign(snapshot, pods)
        assert all(assignments[p.key] for p in pods)
        assert deg.value(kind="spread_poisoned") == 0
