"""Coscheduling (PodGroup gang scheduling) — BASELINE config #3."""

import asyncio

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.metrics.registry import SchedulerMetrics
from kubernetes_tpu.ops import TPUBackend
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.framework import Framework
from kubernetes_tpu.scheduler.plugins.coscheduling import (
    POD_GROUP_LABEL,
    make_pod_group,
)
from kubernetes_tpu.scheduler.plugins.registry import (
    DEFAULT_PLUGINS,
    DEFAULT_SCORE_WEIGHTS,
    build_plugins,
)
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def run(coro):
    return asyncio.run(coro)


def gang_pod(name, group, cpu="500m", uid=None):
    return make_pod(name, labels={POD_GROUP_LABEL: group},
                    requests={"cpu": cpu}, uid=uid or name)


async def make_sched(store, backend=None):
    plugins = build_plugins(DEFAULT_PLUGINS + ["Coscheduling"], store=store)
    fwk = Framework(plugins, DEFAULT_SCORE_WEIGHTS,
                    metrics=SchedulerMetrics())
    sched = Scheduler(store, profiles={"default-scheduler": fwk},
                      seed=7, backend=backend)
    factory = InformerFactory(store)
    await sched.setup_informers(factory)
    factory.start()
    await factory.wait_for_sync()
    return sched, factory


async def bound_names(store):
    return {p["metadata"]["name"]
            for p in (await store.list("pods")).items
            if p["spec"].get("nodeName")}


class TestGangScheduling:
    def test_gang_waits_then_binds_together(self, backend=None):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            for i in range(4):
                await store.create("nodes", make_node(f"n{i}"))
            await store.create("podgroups", make_pod_group(
                "job1", min_member=3, schedule_timeout_seconds=5.0))
            sched, factory = await make_sched(store, backend=backend)
            task = asyncio.ensure_future(sched.run(
                batch_size=8 if backend else 1))

            # Two members: gang can't assemble; PreEnqueue gates them.
            await store.create("pods", gang_pod("g-0", "job1"))
            await store.create("pods", gang_pod("g-1", "job1"))
            await asyncio.sleep(0.4)
            assert await bound_names(store) == set()

            # Third member arrives → gate lifts → all three bind.
            await store.create("pods", gang_pod("g-2", "job1"))
            for _ in range(150):
                if len(await bound_names(store)) == 3:
                    break
                await asyncio.sleep(0.05)
            assert await bound_names(store) == {"g-0", "g-1", "g-2"}
            await sched.stop()
            task.cancel()
            factory.stop()
            store.stop()
        run(body())

    def test_gang_with_tpu_backend(self):
        self.test_gang_waits_then_binds_together(backend=TPUBackend(max_batch=8))

    def test_incomplete_gang_times_out_and_releases_resources(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            # One node, 8 cores: gang of 3×3 cores can never fully assemble
            # feasibly (only 2 fit) — waiters must time out and release.
            await store.create("nodes", make_node(
                "n0", allocatable={"cpu": "8", "memory": "32Gi",
                                   "pods": "110"}))
            await store.create("podgroups", make_pod_group(
                "big", min_member=3, schedule_timeout_seconds=0.5))
            sched, factory = await make_sched(store)
            task = asyncio.ensure_future(sched.run())

            for i in range(3):
                await store.create("pods", gang_pod(f"b-{i}", "big", cpu="3"))
            await asyncio.sleep(1.5)
            # Nothing durably bound (two waiters timed out, their assumes
            # were forgotten; the whole gang remains pending).
            assert await bound_names(store) == set()
            # A normal pod can still use the node's full capacity.
            await store.create("pods", make_pod(
                "solo", requests={"cpu": "6"}, uid="solo"))
            for _ in range(100):
                if "solo" in await bound_names(store):
                    break
                await asyncio.sleep(0.05)
            assert "solo" in await bound_names(store)
            await sched.stop()
            task.cancel()
            factory.stop()
            store.stop()
        run(body())

    def test_missing_pod_group_is_unresolvable(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            await store.create("nodes", make_node("n0"))
            sched, factory = await make_sched(store)
            task = asyncio.ensure_future(sched.run())
            await store.create("pods", gang_pod("lost", "nogroup"))
            await asyncio.sleep(0.4)
            assert await bound_names(store) == set()
            await sched.stop()
            task.cancel()
            factory.stop()
            store.stop()
        run(body())


class TestGangOverflowObservability:
    def test_gang_overflow_counter_fires(self):
        """More gangs in one chunk than the solver's capacity (_GANG_PAD):
        overflow gangs degrade to Permit-barrier-only atomicity and the
        degradation counter records exactly how many."""
        async def body():
            from kubernetes_tpu.ops.backend import _GANG_PAD
            store = new_cluster_store()
            install_core_validation(store)
            for i in range(8):
                await store.create("nodes", make_node(
                    f"n{i}", allocatable={"cpu": "64", "memory": "64Gi",
                                          "pods": "110"}))
            n_gangs = _GANG_PAD + 4
            for g in range(n_gangs):
                await store.create(
                    "podgroups", make_pod_group(f"gang{g}", min_member=2))
            backend = TPUBackend(max_batch=64)
            sched, factory = await make_sched(store, backend=backend)
            run_task = asyncio.ensure_future(sched.run(batch_size=64))
            for g in range(n_gangs):
                for m in range(2):
                    await store.create("pods", gang_pod(
                        f"g{g}-{m}", f"gang{g}", cpu="100m"))
            want = {f"g{g}-{m}" for g in range(n_gangs) for m in range(2)}
            for _ in range(400):
                if want <= await bound_names(store):
                    break
                await asyncio.sleep(0.02)
            assert want <= await bound_names(store), "gangs did not bind"
            overflow = sched.metrics.backend_degradations.value(
                kind="gang_overflow")
            assert overflow >= 4, f"overflow counter = {overflow}"
            await sched.stop()
            run_task.cancel()
            factory.stop()
            store.stop()
        run(body())
