"""Policy engine subsystem (kubernetes_tpu/policy): the sandboxed
expression evaluator, ValidatingAdmissionPolicy + bindings on BOTH
wires, failurePolicy semantics, param resolution, match constraints,
and the reference handler-chain order (authn → audit → impersonation →
APF → authz) on both wires."""

import asyncio

import pytest

from kubernetes_tpu.api.types import (
    make_config_map,
    make_namespace,
    make_pod,
    make_validating_admission_policy,
    make_vap_binding,
)
from kubernetes_tpu.apiserver.admission import WebhookAdmission
from kubernetes_tpu.apiserver.client import RemoteStore
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.apiserver.wire import WireServer, WireStore
from kubernetes_tpu.policy import PolicyEngine
from kubernetes_tpu.policy.expr import (
    BudgetExceeded,
    ExpressionError,
    compile_expression,
)
from kubernetes_tpu.store import install_core_validation, new_cluster_store
from kubernetes_tpu.store.mvcc import Invalid


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# evaluator sandbox
# ---------------------------------------------------------------------------

def _ev(src, **variables):
    variables.setdefault("object", {})
    variables.setdefault("oldObject", None)
    variables.setdefault("request", {})
    variables.setdefault("params", None)
    return compile_expression(src).evaluate(variables)


class TestEvaluator:
    def test_field_access_and_functions(self):
        pod = make_pod("a", labels={"app": "web"}, priority=5)
        assert _ev("object.metadata.name == 'a'", object=pod)
        assert _ev("object.spec.priority < 10", object=pod)
        assert _ev("object.metadata.labels['app'] in ('web', 'db')",
                   object=pod)
        assert _ev("size(object.spec.containers) == 1", object=pod)
        assert _ev("has(object.spec.priority) and "
                   "not has(object.spec.nodeName)", object=pod)
        assert _ev("object.metadata.name.startsWith('a')", object=pod)
        assert _ev("string(object.spec.priority) == '5'", object=pod)
        assert _ev("all(c.name != '' "
                   "for c in object.spec.containers)", object=pod)

    def test_missing_field_is_an_error_unless_has(self):
        with pytest.raises(ExpressionError):
            _ev("object.spec.nope == 1", object=make_pod("a"))
        assert _ev("has(object.spec.nope)", object=make_pod("a")) is False

    def test_attribute_escape_is_impossible(self):
        """The CEL-analog sandbox invariant: dunder access is rejected at
        compile time, and attribute access NEVER reaches Python object
        attributes — it is a mapping lookup only."""
        for src in ("object.__class__", "object.__dict__.x",
                    "().__class__.__bases__",
                    "object._private"):
            with pytest.raises(ExpressionError):
                compile_expression(src)
        # A dict KEY shaped like a method name is data, not a method:
        # attribute access finds the key, never dict.keys.
        assert _ev("object.keys == 'v'", object={"keys": "v"})
        # A genuine dict method name with no such key errors instead of
        # resolving to the bound method.
        with pytest.raises(ExpressionError):
            _ev("object.values == 1", object={"k": "v"})

    def test_forbidden_syntax_rejected_at_compile(self):
        for src in ("__import__('os')", "open('/etc/passwd')",
                    "lambda: 1", "object.spec ** 2", "x := 3",
                    "f'{object}'", "{**object}", "object.spec.run()"):
            with pytest.raises(ExpressionError):
                compile_expression(src)
        # Method objects are unreachable at eval time too: attribute
        # access on a non-mapping is an error, not a getattr.
        with pytest.raises(ExpressionError):
            _ev("[].append == 1")

    def test_cost_budget_bomb_dies(self):
        """Nested comprehension over a modest list must hit the step
        budget instead of stalling the apiserver."""
        items = [{"v": i} for i in range(200)]
        bomb = ("size([1 for a in object.items for b in object.items "
                "for c in object.items])")
        with pytest.raises(BudgetExceeded):
            _ev(bomb, object={"items": items})

    def test_sequence_repetition_and_huge_concat_bounded(self):
        with pytest.raises(ExpressionError):
            _ev("object.s * 100000", object={"s": "a" * 100})
        big = "x" * 60000
        with pytest.raises(BudgetExceeded):
            _ev("object.a + object.a", object={"a": big})

    def test_matches_bounded(self):
        assert _ev("object.name.matches('^web-[0-9]+$')",
                   object={"name": "web-3"})
        with pytest.raises(BudgetExceeded):
            _ev("object.name.matches(object.pat)",
                object={"name": "a", "pat": "x" * 1000})


# ---------------------------------------------------------------------------
# VAP over both wires
# ---------------------------------------------------------------------------

async def _policy_cluster(**api_kw):
    store = new_cluster_store()
    install_core_validation(store)
    engine = PolicyEngine(store)
    adm = WebhookAdmission(store, policy_engine=engine)
    api = APIServer(store, admission=adm, **api_kw)
    await api.start()
    wire = WireServer.for_apiserver(api, host="unix:")
    await wire.start()
    return store, engine, api, wire


class TestValidatingAdmissionPolicy:
    def test_policy_rejects_pod_on_both_wires_with_message(self):
        """The acceptance-criteria scenario: a VAP stored via the API
        rejects a matching pod on BOTH wires, message in the Status."""
        async def body():
            store, engine, api, wire = await _policy_cluster()
            rs = RemoteStore(api.url)
            # Stored VIA THE API, like any resource.
            await rs.create(
                "validatingadmissionpolicies",
                make_validating_admission_policy("deny-gpu", [
                    {"expression":
                         "all(not has(c.resources.limits)"
                         " or 'gpu' not in c.resources.limits"
                         " for c in object.spec.containers)",
                     "message": "gpu containers are forbidden here"}],
                    match_constraints={"resourceRules": [
                        {"resources": ["pods"],
                         "operations": ["CREATE"]}]}))
            await rs.create("validatingadmissionpolicybindings",
                            make_vap_binding("deny-gpu-b", "deny-gpu"))
            bad = make_pod("gpu-pod", limits={"gpu": "1"})
            with pytest.raises(Invalid) as ei:
                await rs.create("pods", bad)
            assert "gpu containers are forbidden here" in str(ei.value)
            c = WireStore(wire.target)
            with pytest.raises(Invalid) as ei:
                await c.create("pods", make_pod("gpu2", limits={"gpu": "1"}))
            assert "gpu containers are forbidden here" in str(ei.value)
            # Non-matching pods pass, on both wires.
            assert (await rs.create("pods", make_pod("ok1")))
            assert (await c.create("pods", make_pod("ok2")))
            # Operations constraint: UPDATE is outside CREATE-only rules.
            ok1 = await store.get("pods", "default/ok1")
            ok1["metadata"]["labels"] = {"x": "1"}
            await rs.update("pods", ok1)
            assert engine.rejections.value(policy="deny-gpu") == 2
            assert engine.evaluations.value(policy="deny-gpu") >= 4
            await c.close()
            await rs.close()
            await wire.stop()
            await api.stop()
            store.stop()
        run(body())

    def test_failure_policy_ignore_skips_broken_policy(self):
        async def body():
            store, engine, api, wire = await _policy_cluster()
            # Expression errors at runtime (missing field), one policy
            # per failurePolicy mode.
            await store.create(
                "validatingadmissionpolicies",
                make_validating_admission_policy("broken-ignore", [
                    {"expression": "object.spec.doesNotExist == 1"}],
                    failure_policy="Ignore"))
            await store.create("validatingadmissionpolicybindings",
                               make_vap_binding("bi", "broken-ignore"))
            rs = RemoteStore(api.url)
            assert (await rs.create("pods", make_pod("passes")))
            # Same breakage with Fail denies.
            await store.create(
                "validatingadmissionpolicies",
                make_validating_admission_policy("broken-fail", [
                    {"expression": "object.spec.doesNotExist == 1"}],
                    failure_policy="Fail"))
            await store.create("validatingadmissionpolicybindings",
                               make_vap_binding("bf", "broken-fail"))
            with pytest.raises(Invalid) as ei:
                await rs.create("pods", make_pod("denied"))
            assert "failurePolicy=Fail" in str(ei.value)
            await rs.close()
            await wire.stop()
            await api.stop()
            store.stop()
        run(body())

    def test_param_resolution_and_missing_param(self):
        async def body():
            store, engine, api, wire = await _policy_cluster()
            await store.create(
                "validatingadmissionpolicies",
                make_validating_admission_policy("cap", [
                    {"expression": "int(object.spec.priority) <= "
                                   "int(params.data.max)",
                     "message": "over the cap"}],
                    param_kind="ConfigMap"))
            await store.create(
                "validatingadmissionpolicybindings",
                make_vap_binding("cap-b", "cap", param_ref={
                    "name": "caps", "namespace": "default"}))
            rs = RemoteStore(api.url)
            # Param missing + failurePolicy=Fail (default) → deny.
            with pytest.raises(Invalid):
                await rs.create("pods", make_pod("p0", priority=1))
            await store.create("configmaps",
                               make_config_map("caps",
                                               data={"max": "100"}))
            assert (await rs.create("pods", make_pod("p1", priority=7)))
            with pytest.raises(Invalid) as ei:
                await rs.create("pods", make_pod("p2", priority=700))
            assert "over the cap" in str(ei.value)
            await rs.close()
            await wire.stop()
            await api.stop()
            store.stop()
        run(body())

    def test_namespace_selector_match_constraint(self):
        async def body():
            store, engine, api, wire = await _policy_cluster()
            await store.create("namespaces", make_namespace("plain"))
            prod = make_namespace("prod")
            prod["metadata"]["labels"] = {"env": "prod"}
            await store.create("namespaces", prod)
            await store.create(
                "validatingadmissionpolicies",
                make_validating_admission_policy("prod-only", [
                    {"expression": "has(object.spec.priority)",
                     "message": "prod pods need a priority"}],
                    match_constraints={
                        "resourceRules": [{"resources": ["pods"]}],
                        "namespaceSelector": {
                            "matchLabels": {"env": "prod"}}}))
            await store.create("validatingadmissionpolicybindings",
                               make_vap_binding("po-b", "prod-only"))
            rs = RemoteStore(api.url)
            # Unselected namespace: policy does not apply.
            assert (await rs.create(
                "pods", make_pod("free", namespace="plain")))
            with pytest.raises(Invalid):
                await rs.create("pods", make_pod("np", namespace="prod"))
            assert (await rs.create("pods", make_pod(
                "wp", namespace="prod", priority=3)))
            await rs.close()
            await wire.stop()
            await api.stop()
            store.stop()
        run(body())

    def test_bad_expression_rejected_at_policy_write(self):
        """Store-side validation: a policy whose expression does not
        compile in the sandbox grammar is rejected at CREATE (the
        reference typechecks CEL when the policy object is admitted)."""
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            with pytest.raises(Invalid):
                await store.create(
                    "validatingadmissionpolicies",
                    make_validating_admission_policy("evil", [
                        {"expression": "__import__('os').system('x')"}]))
            with pytest.raises(Invalid):
                await store.create(
                    "validatingadmissionpolicybindings",
                    {"kind": "ValidatingAdmissionPolicyBinding",
                     "metadata": {"name": "nameless"}, "spec": {}})
            store.stop()
        run(body())

    def test_unbound_policy_is_inert(self):
        async def body():
            store, engine, api, wire = await _policy_cluster()
            await store.create(
                "validatingadmissionpolicies",
                make_validating_admission_policy("inert", [
                    {"expression": "1 == 2", "message": "never"}]))
            rs = RemoteStore(api.url)
            assert (await rs.create("pods", make_pod("fine")))
            assert engine.evaluations.value(policy="inert") == 0
            await rs.close()
            await wire.stop()
            await api.stop()
            store.stop()
        run(body())


# ---------------------------------------------------------------------------
# VAP breadth: matchConditions, variables, messageExpression,
# auditAnnotations, DELETE/object=null (ISSUE 15)
# ---------------------------------------------------------------------------

class TestVAPBreadth:
    def test_match_conditions_gate_and_failure_policy(self):
        async def body():
            store, engine, api, wire = await _policy_cluster()
            pol = make_validating_admission_policy("cond", [
                {"expression": "1 == 2", "message": "always denies"}],
                match_constraints={"resourceRules": [
                    {"resources": ["pods"], "operations": ["CREATE"]}]})
            pol["spec"]["matchConditions"] = [
                {"name": "only-special",
                 "expression":
                     "object.metadata.name.startsWith('special')"}]
            await store.create("validatingadmissionpolicies", pol)
            await store.create("validatingadmissionpolicybindings",
                               make_vap_binding("cond-b", "cond"))
            rs = RemoteStore(api.url)
            # condition false → the policy does not apply at all
            assert (await rs.create("pods", make_pod("plain")))
            with pytest.raises(Invalid) as ei:
                await rs.create("pods", make_pod("special-1"))
            assert "always denies" in str(ei.value)
            # condition ERROR obeys failurePolicy: Fail denies …
            bad = make_validating_admission_policy("cond-err", [
                {"expression": "1 == 1"}],
                match_constraints={"resourceRules": [
                    {"resources": ["pods"], "operations": ["CREATE"]}]})
            bad["spec"]["matchConditions"] = [
                {"name": "boom",
                 "expression": "object.spec.noSuchField == 1"}]
            await store.create("validatingadmissionpolicies", bad)
            await store.create("validatingadmissionpolicybindings",
                               make_vap_binding("cond-err-b", "cond-err"))
            with pytest.raises(Invalid) as ei:
                await rs.create("pods", make_pod("anyname"))
            assert "matchCondition" in str(ei.value)
            # … and Ignore skips the policy
            ign = await store.get("validatingadmissionpolicies",
                                  "cond-err")
            ign["spec"]["failurePolicy"] = "Ignore"
            await store.update("validatingadmissionpolicies", ign)
            assert (await rs.create("pods", make_pod("anyname2")))
            await rs.close()
            await wire.stop()
            await api.stop()
            store.stop()
        run(body())

    def test_variables_composition_and_message_expression(self):
        async def body():
            store, engine, api, wire = await _policy_cluster()
            pol = make_validating_admission_policy("vars", [
                {"expression": "size(variables.cnames) >= 1 and "
                               "variables.first != 'forbidden'",
                 "message": "static fallback",
                 "messageExpression":
                     "'container ' + variables.first + ' is forbidden'"}],
                match_constraints={"resourceRules": [
                    {"resources": ["pods"], "operations": ["CREATE"]}]})
            pol["spec"]["variables"] = [
                {"name": "cnames",
                 "expression":
                     "[c.name for c in object.spec.containers]"},
                # chained composition: a variable referencing a variable
                {"name": "first", "expression": "variables.cnames[0]"},
            ]
            await store.create("validatingadmissionpolicies", pol)
            await store.create("validatingadmissionpolicybindings",
                               make_vap_binding("vars-b", "vars"))
            rs = RemoteStore(api.url)
            assert (await rs.create("pods", make_pod("fine")))
            bad = make_pod("bad")
            bad["spec"]["containers"][0]["name"] = "forbidden"
            with pytest.raises(Invalid) as ei:
                await rs.create("pods", bad)
            # messageExpression composed the deny message
            assert "container forbidden is forbidden" in str(ei.value)
            # broken messageExpression falls back to the static message
            pol2 = await store.get("validatingadmissionpolicies", "vars")
            pol2["spec"]["validations"][0]["messageExpression"] = \
                "object.spec.doesNotExist"
            await store.update("validatingadmissionpolicies", pol2)
            bad2 = make_pod("bad2")
            bad2["spec"]["containers"][0]["name"] = "forbidden"
            with pytest.raises(Invalid) as ei:
                await rs.create("pods", bad2)
            assert "static fallback" in str(ei.value)
            await rs.close()
            await wire.stop()
            await api.stop()
            store.stop()
        run(body())

    def test_variables_reevaluate_per_binding_params(self):
        """A params-referencing variable must see EACH binding's own
        params (fresh memo per binding): two bindings with different
        ConfigMaps enforce different caps on the same policy."""
        async def body():
            store, engine, api, wire = await _policy_cluster()
            await store.create("configmaps",
                               make_config_map("cap5", data={"max": "5"}))
            await store.create("configmaps",
                               make_config_map("cap9", data={"max": "9"}))
            pol = make_validating_admission_policy("vcap", [
                {"expression":
                     "int(object.spec.priority) <= variables.cap",
                 "messageExpression":
                     "'cap ' + string(variables.cap) + ' exceeded'"}],
                param_kind="ConfigMap",
                match_constraints={"resourceRules": [
                    {"resources": ["pods"], "operations": ["CREATE"]}]})
            pol["spec"]["variables"] = [
                {"name": "cap", "expression": "int(params.data.max)"}]
            await store.create("validatingadmissionpolicies", pol)
            # LOOSE binding first: priority 7 passes b9 (memoizing
            # cap=9), then b5 must deny with ITS cap — a memo leaked
            # across bindings would reuse 9 and wrongly admit.
            for bname, cm in (("b9", "cap9"), ("b5", "cap5")):
                await store.create(
                    "validatingadmissionpolicybindings",
                    make_vap_binding(bname, "vcap", param_ref={
                        "name": cm, "namespace": "default"}))
            rs = RemoteStore(api.url)
            assert (await rs.create("pods", make_pod("p4", priority=4)))
            with pytest.raises(Invalid) as ei:
                await rs.create("pods", make_pod("p7", priority=7))
            assert "cap 5 exceeded" in str(ei.value)
            # Drop the tighter binding: priority 7 is fine under cap9.
            await store.delete("validatingadmissionpolicybindings", "b5")
            assert (await rs.create("pods", make_pod("p7b", priority=7)))
            with pytest.raises(Invalid) as ei:
                await rs.create("pods", make_pod("p10", priority=10))
            assert "cap 9 exceeded" in str(ei.value)
            await rs.close()
            await wire.stop()
            await api.stop()
            store.stop()
        run(body())

    def test_audit_annotations_flow_into_audit_event(self):
        """auditAnnotations publish on the request's ResponseComplete
        event as annotations["<policy>/<key>"] — the contextvar seam
        between the VAP stage and the audit pipeline."""
        async def body():
            from kubernetes_tpu.policy import AuditPipeline, AuditPolicy
            audit = AuditPipeline(AuditPolicy.metadata_for_all())
            store, engine, api, wire = await _policy_cluster(audit=audit)
            pol = make_validating_admission_policy("annot", [
                {"expression": "1 == 1"}],
                match_constraints={"resourceRules": [
                    {"resources": ["pods"], "operations": ["CREATE"]}]})
            pol["spec"]["auditAnnotations"] = [
                {"key": "pod-name",
                 "valueExpression":
                     "'seen-' + object.metadata.name"},
                # null value → annotation omitted, no error
                {"key": "absent",
                 "valueExpression":
                     "object.metadata.labels['x'] if "
                     "has(object.metadata.labels['x']) else None"},
            ]
            await store.create("validatingadmissionpolicies", pol)
            await store.create("validatingadmissionpolicybindings",
                               make_vap_binding("annot-b", "annot"))
            rs = RemoteStore(api.url)
            await rs.create("pods", make_pod("a-pod"))
            await asyncio.sleep(0.05)
            done = [e for e in audit.sink.entries
                    if e["stage"] == "ResponseComplete"
                    and e["objectRef"]["name"] == "a-pod"]
            assert done, audit.sink.entries
            ann = done[0].get("annotations") or {}
            assert ann.get("annot/pod-name") == "seen-a-pod"
            assert "annot/absent" not in ann
            await rs.close()
            await wire.stop()
            await api.stop()
            store.stop()
        run(body())

    def test_delete_object_null_on_both_wires(self):
        """DELETE runs expression policies with object=null and the
        stored object as oldObject (the reference contract), routed
        through admission on the HTTP and KTPU wires alike."""
        async def body():
            store, engine, api, wire = await _policy_cluster()
            pol = make_validating_admission_policy("no-del", [
                {"expression": "object == None and "
                               "oldObject.metadata.name != 'protected'",
                 "message": "protected pods cannot be deleted"}],
                match_constraints={"resourceRules": [
                    {"resources": ["pods"],
                     "operations": ["DELETE"]}]})
            await store.create("validatingadmissionpolicies", pol)
            await store.create("validatingadmissionpolicybindings",
                               make_vap_binding("no-del-b", "no-del"))
            rs = RemoteStore(api.url)
            # the CREATE is outside the DELETE-only rule
            await rs.create("pods", make_pod("protected"))
            await rs.create("pods", make_pod("plain"))
            with pytest.raises(Invalid) as ei:
                await rs.delete("pods", "default/protected")
            assert "cannot be deleted" in str(ei.value)
            await rs.delete("pods", "default/plain")  # allowed
            c = WireStore(wire.target)
            with pytest.raises(Invalid) as ei:
                await c.delete("pods", "default/protected")
            assert "cannot be deleted" in str(ei.value)
            assert (await store.get("pods", "default/protected"))
            await c.close()
            await rs.close()
            await wire.stop()
            await api.stop()
            store.stop()
        run(body())


# ---------------------------------------------------------------------------
# chain order, both wires
# ---------------------------------------------------------------------------

class TestHandlerChainOrder:
    def test_http_middleware_order_matches_reference(self):
        """§3.2 DefaultBuildHandlerChain: authn → audit → impersonation
        → APF → authz (authz innermost)."""
        store = new_cluster_store()
        api = APIServer(store)
        names = [getattr(m, "__name__", "") for m in api.app.middlewares]
        want = ["_mw_authn", "_mw_audit", "_mw_impersonation",
                "_mw_priority", "_mw_authz"]
        idx = [names.index(w) for w in want]
        assert idx == sorted(idx), names
        store.stop()

    def test_wire_chain_order_matches_reference(self):
        assert WireServer.HANDLER_CHAIN == (
            "authn", "audit", "impersonation", "apf", "authz",
            "admission")

    def test_audit_sees_original_user_authz_sees_impersonated(self):
        """Behavioral order pin: audit (outer) records the authenticated
        principal; authz (inner) runs as the impersonated user — on both
        wires."""
        async def body():
            from kubernetes_tpu.apiserver.rbac import RBACAuthorizer
            from kubernetes_tpu.policy import AuditPipeline, AuditPolicy
            authz = RBACAuthorizer()
            authz.add_role({"metadata": {"name": "imp"},
                            "rules": [{"verbs": ["impersonate"],
                                       "resources": ["users"]}]})
            authz.add_role({"metadata": {"name": "writer"},
                            "rules": [{"verbs": ["*"],
                                       "resources": ["pods"]}]})
            authz.add_binding({"roleRef": {"name": "imp"},
                               "subjects": [{"kind": "User",
                                             "name": "admin"}]})
            authz.add_binding({"roleRef": {"name": "writer"},
                               "subjects": [{"kind": "User",
                                             "name": "bob"}]})
            store = new_cluster_store()
            install_core_validation(store)
            # Request level for pods so the HTTP create's objectRef gets
            # its name from the request body (no name in a POST URL).
            audit = AuditPipeline(AuditPolicy([
                {"level": "Request", "resources": ["pods"]},
                {"level": "Metadata"}]))
            api = APIServer(store,
                            bearer_tokens={"t": "admin"},
                            authorizer=authz, audit=audit)
            await api.start()
            wire = WireServer.for_apiserver(api, host="unix:")
            await wire.start()
            # admin alone has NO pod rights; impersonating bob works —
            # proving authz ran as bob (after impersonation).
            rs = RemoteStore(api.url, token="t", impersonate="bob")
            await rs.create("pods", make_pod("h1"))
            c = WireStore(wire.target, token="t", impersonate="bob")
            await c.create("pods", make_pod("w1"))
            await asyncio.sleep(0.05)
            done = {e["objectRef"]["name"]: e
                    for e in audit.sink.entries
                    if e["stage"] == "ResponseComplete"
                    and e["objectRef"]["resource"] == "pods"}
            for name in ("h1", "w1"):
                e = done[name]
                assert e["user"]["username"] == "admin"  # original
                assert e["impersonatedUser"]["username"] == "bob"
                assert e["responseStatus"]["code"] == 201
            await c.close()
            await rs.close()
            await wire.stop()
            await api.stop()
            store.stop()
        run(body())
