"""Randomized differential parity for the speculative wavefront solve.

The contract under test: every `*_wave` scan in ops/solver.py produces
assignments BIT-IDENTICAL to its W=1 counterpart at every wave width —
tight-capacity conflict storms (speculation must replay, exactly),
packing strategies whose scores RISE on debit (the non-monotone hazard
the pairwise re-score exists for), spread constraints with contested
domains (the structural non-monotonicity rule), the shortlist∩wavefront
composition, sharded meshes at {1, 4, 8}, and the W ∈ {1, 2, 8, P}
extremes including W > P. The tier-1 activation/kill-switch/tuner pins
live in tests/test_wavefront_smoke.py.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from kubernetes_tpu.ops import kernels, solver

WIDTHS = (1, 2, 8)


def _problem(rng, n, p, r, tight=False, strategy="LeastAllocated",
             classes=None):
    """Random solver arg dict; tight=True makes capacity contested so
    speculative picks collide with earlier debits (the replay path)."""
    if tight:
        alloc_q = rng.integers(2, 6, size=(n, r)).astype(np.int32) * 1000
        req_q = rng.integers(500, 2500, size=(p, r)).astype(np.int32)
        free_pods = rng.integers(1, 3, size=(n,)).astype(np.int32)
    else:
        alloc_q = rng.integers(20, 60, size=(n, r)).astype(np.int32) * 1000
        req_q = rng.integers(100, 3000, size=(p, r)).astype(np.int32)
        free_pods = rng.integers(2, 8, size=(n,)).astype(np.int32)
    used_q = (alloc_q * rng.uniform(0, 0.5, size=(n, r))).astype(np.int32)
    if classes:
        rows = rng.integers(0, classes, size=(p,)).astype(np.int32)
        # Pods of one class share request rows (the class-key contract).
        class_req = rng.integers(100, 3000, size=(classes, r)).astype(np.int32)
        req_q = class_req[rows]
        mask = rng.random((classes, n)) > 0.15
        scores = rng.uniform(0, 4, size=(classes, n)).astype(np.float32)
    else:
        rows = None
        mask = rng.random((p, n)) > 0.15
        scores = rng.uniform(0, 4, size=(p, n)).astype(np.float32)
    args = dict(
        req_q=jnp.asarray(req_q), req_nz_q=jnp.asarray(req_q),
        free_q=jnp.asarray(alloc_q - used_q),
        free_pods=jnp.asarray(free_pods),
        used_nz_q=jnp.asarray(used_q), alloc_q=jnp.asarray(alloc_q),
        mask=jnp.asarray(mask), static_scores=jnp.asarray(scores),
        fit_col_w=jnp.ones((r,), jnp.float32),
        bal_col_mask=jnp.ones((r,), np.bool_),
        shape_u=jnp.asarray([0.0, 100.0], jnp.float32),
        shape_s=jnp.asarray([0.0, 10.0], jnp.float32),
        w_fit=jnp.float32(1.0), w_bal=jnp.float32(1.0))
    if rows is not None:
        args["rows"] = jnp.asarray(rows)
    return args, (np.asarray(mask), np.asarray(scores))


class TestRescoringWaveParity:
    @pytest.mark.parametrize("strategy",
                             ["LeastAllocated", "MostAllocated",
                              "RequestedToCapacityRatio"])
    def test_conflict_storm_bit_identity(self, strategy):
        """Tight capacity + every strategy (incl. the ones whose score
        RISES on debit): assignments equal the serial scan at every W."""
        for seed in range(4):
            rng = np.random.default_rng(seed)
            args, _ = _problem(rng, n=24, p=31, r=2, tight=True)
            ref = np.asarray(solver.greedy_assign_rescoring(
                strategy=strategy, **args))
            for w in WIDTHS + (31, 64):
                a, com, rep = solver.greedy_assign_rescoring_wave(
                    strategy=strategy, wave_w=w, **args)
                np.testing.assert_array_equal(np.asarray(a), ref,
                                              err_msg=f"W={w} {strategy}")
                assert int(com) + int(rep) == 31

    def test_class_planes_and_exceptions(self):
        """Class-row indirection + pinned-column exceptions ride the
        wave exactly like the serial scan."""
        for seed in range(3):
            rng = np.random.default_rng(100 + seed)
            args, _ = _problem(rng, n=40, p=26, r=3, classes=4)
            exc = np.full((26,), -1, np.int32)
            exc[rng.integers(0, 26, size=5)] = \
                rng.integers(0, 40, size=5).astype(np.int32)
            args["exc"] = jnp.asarray(exc)
            ref = np.asarray(solver.greedy_assign_rescoring(
                strategy="LeastAllocated", **args))
            for w in WIDTHS:
                a, _, _ = solver.greedy_assign_rescoring_wave(
                    strategy="LeastAllocated", wave_w=w, **args)
                np.testing.assert_array_equal(np.asarray(a), ref)

    def test_uniform_template_commits_speculatively(self):
        """The template regime (identical pods, uniform nodes — the
        bench presets' shape): prefix-distinct speculation must commit
        without replays, or the wavefront buys nothing where it matters."""
        n, p, r = 256, 64, 2
        args = dict(
            req_q=jnp.asarray(np.full((p, r), 500, np.int32)),
            req_nz_q=jnp.asarray(np.full((p, r), 500, np.int32)),
            free_q=jnp.asarray(np.full((n, r), 8000, np.int32)),
            free_pods=jnp.asarray(np.full((n,), 110, np.int32)),
            used_nz_q=jnp.asarray(np.zeros((n, r), np.int32)),
            alloc_q=jnp.asarray(np.full((n, r), 8000, np.int32)),
            mask=jnp.asarray(np.ones((1, n), np.bool_)),
            static_scores=jnp.asarray(np.zeros((1, n), np.float32)),
            fit_col_w=jnp.ones((r,), jnp.float32),
            bal_col_mask=jnp.ones((r,), np.bool_),
            shape_u=jnp.zeros((2,), jnp.float32),
            shape_s=jnp.zeros((2,), jnp.float32),
            w_fit=jnp.float32(1.0), w_bal=jnp.float32(1.0),
            rows=jnp.asarray(np.zeros((p,), np.int32)))
        ref = np.asarray(solver.greedy_assign_rescoring(
            strategy="LeastAllocated", **args))
        a, com, rep = solver.greedy_assign_rescoring_wave(
            strategy="LeastAllocated", wave_w=8, **args)
        np.testing.assert_array_equal(np.asarray(a), ref)
        assert int(rep) == 0 and int(com) == p


class TestMultistartWaveParity:
    def _multi_args(self, rng, p, k=4):
        perms = np.tile(np.arange(p, dtype=np.int32), (k, 1))
        for i in range(1, k):
            perms[i] = rng.permutation(p).astype(np.int32)
        gang = np.zeros((p, 16), np.float32)
        gr = np.zeros((16,), np.float32)
        return (jnp.asarray(perms), jnp.asarray(gang), jnp.asarray(gr))

    def test_permuted_orders_and_gangs(self):
        for seed in range(3):
            rng = np.random.default_rng(200 + seed)
            p = 24
            args, _ = _problem(rng, n=48, p=p, r=2, tight=(seed == 0))
            perms, gang, gr = self._multi_args(rng, p)
            # One gang of 5 with an unreachable quota: all-or-nothing
            # must drop its partial placements identically.
            gang = np.asarray(gang).copy()
            gang[:5, 0] = 1.0
            grq = np.asarray(gr).copy()
            grq[0] = 5.0
            ref = np.asarray(solver.multistart_greedy_assign(
                strategy="LeastAllocated", perms=perms,
                gang_onehot=jnp.asarray(gang),
                gang_required=jnp.asarray(grq), **args))
            for w in WIDTHS:
                a, com, rep = solver.multistart_greedy_assign_wave(
                    strategy="LeastAllocated", wave_w=w, perms=perms,
                    gang_onehot=jnp.asarray(gang),
                    gang_required=jnp.asarray(grq), **args)
                np.testing.assert_array_equal(np.asarray(a), ref)
                # Poisoned chunks rerun the W=1 multistart whole; either
                # way accounting covers the chunk once.
                assert int(com) + int(rep) == p


class TestShortlistWaveParity:
    def _shortlist_state(self, args, masks, k, strategy):
        mask_np, scores_np = masks
        free_q = np.asarray(args["free_q"])
        req = np.asarray(args["req_q"])
        rows = np.asarray(args["rows"]) if "rows" in args \
            else np.arange(req.shape[0], dtype=np.int32)
        sc0 = kernels.chunk_start_scores(
            args["alloc_q"], args["used_nz_q"],
            jnp.asarray(req), jnp.asarray(scores_np[rows]),
            args["fit_col_w"], args["bal_col_mask"], args["shape_u"],
            args["shape_s"], args["w_fit"], args["w_bal"], strategy)
        feas0 = mask_np[rows] \
            & np.all(req[:, None, :] <= free_q[None, :, :], axis=-1) \
            & (np.asarray(args["free_pods"]) >= 1)[None, :]
        cand, thresh = solver.shortlist_prefilter(
            jnp.asarray(feas0), sc0, k)
        hn = jnp.asarray(mask_np[rows].any(axis=1))
        cls = jnp.arange(req.shape[0], dtype=jnp.int32)
        return dict(sc0=sc0, sl_class=cls, sl_cand=cand,
                    sl_thresh=thresh, has_node=hn)

    @pytest.mark.parametrize("strategy",
                             ["LeastAllocated", "MostAllocated"])
    def test_shortlist_wave_bit_identity(self, strategy):
        """shortlist∩wavefront: the pick must clear BOTH the bound check
        and the pairwise wave check; either failure replays exactly."""
        for seed in range(4):
            rng = np.random.default_rng(300 + seed)
            args, masks = _problem(rng, n=64, p=19, r=2,
                                   tight=(seed % 2 == 0))
            sl = self._shortlist_state(args, masks, k=6, strategy=strategy)
            # sc0 here is per-POD (rows gathered), so the scan's class
            # index is the identity.
            ref = np.asarray(solver.greedy_assign_rescoring(
                strategy=strategy, **args))
            for w in WIDTHS + (19,):
                a, nfall, com, rep = \
                    solver.greedy_assign_rescoring_shortlist_wave(
                        strategy=strategy, wave_w=w, **sl, **args)
                np.testing.assert_array_equal(
                    np.asarray(a), ref, err_msg=f"W={w} {strategy}")
                assert int(com) + int(rep) == 19

    def test_multistart_shortlist_wave(self):
        for seed in range(3):
            rng = np.random.default_rng(400 + seed)
            p = 16
            args, masks = _problem(rng, n=96, p=p, r=2)
            sl = self._shortlist_state(args, masks, k=5,
                                       strategy="LeastAllocated")
            perms = np.tile(np.arange(p, dtype=np.int32), (3, 1))
            for i in range(1, 3):
                perms[i] = rng.permutation(p).astype(np.int32)
            gang = jnp.zeros((p, 16), jnp.float32)
            gr = jnp.zeros((16,), jnp.float32)
            ref, _ = solver.multistart_greedy_assign_shortlist(
                strategy="LeastAllocated", perms=jnp.asarray(perms),
                gang_onehot=gang, gang_required=gr, **sl, **args)
            for w in WIDTHS:
                a, _, com, rep = \
                    solver.multistart_greedy_assign_shortlist_wave(
                        strategy="LeastAllocated", wave_w=w,
                        perms=jnp.asarray(perms), gang_onehot=gang,
                        gang_required=gr, **sl, **args)
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(ref))
                assert int(com) + int(rep) == p


class TestSpreadWaveParity:
    def _spread_problem(self, rng, n, p, domains, cons):
        """Contested spread: few domains, tight maxSkew, every pod
        gating AND contributing — commits open/close domains mid-wave,
        the structural replay rule's worst case."""
        r = 2
        args, _ = _problem(rng, n=n, p=p, r=r)
        dom = np.zeros((n, domains), np.float32)
        for i in range(n):
            dom[i, i % domains] = 1.0
        cid = np.zeros((domains, cons), np.float32)
        for d in range(domains):
            cid[d, d % cons] = 1.0
        applies = (rng.random((p, cons)) > 0.3).astype(np.float32)
        contrib = np.maximum(
            applies, (rng.random((p, cons)) > 0.5)).astype(np.float32)
        sp = dict(
            dom_onehot=jnp.asarray(dom), cid_onehot=jnp.asarray(cid),
            dom_counts=jnp.asarray(
                rng.integers(0, 3, size=(domains,)).astype(np.float32)),
            max_skew=jnp.asarray(
                rng.integers(1, 3, size=(cons,)).astype(np.float32)),
            min_ok=jnp.ones((cons,), jnp.float32),
            has_key_nc=jnp.asarray(np.ones((n, cons), np.float32)),
            applies=jnp.asarray(applies), contributes=jnp.asarray(contrib))
        return args, sp

    def test_contested_domains_bit_identity(self):
        for seed in range(4):
            rng = np.random.default_rng(500 + seed)
            args, sp = self._spread_problem(rng, n=30, p=21, domains=5,
                                            cons=2)
            ref, ref_dc = solver.greedy_assign_rescoring_spread(
                strategy="LeastAllocated", **sp, **args)
            for w in WIDTHS + (21,):
                a, dc, com, rep = solver.greedy_assign_rescoring_spread_wave(
                    strategy="LeastAllocated", wave_w=w, **sp, **args)
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(ref),
                                              err_msg=f"W={w}")
                np.testing.assert_array_equal(np.asarray(dc),
                                              np.asarray(ref_dc))
                assert int(com) + int(rep) == 21

    def test_contribute_only_pods_keep_speculating(self):
        """Pods that CONTRIBUTE to counts but carry no gating constraint
        (app = 0) must not force replays — only gated members after a
        count-moving commit replay. Template pods on uniform nodes are
        the regime where the spread-free wave provably commits 100%
        (TestRescoringWaveParity.test_uniform_template...), so any
        replay here would be the structural rule misfiring on app=0."""
        n, p, r, domains, cons = 40, 16, 2, 4, 2
        args = dict(
            req_q=jnp.asarray(np.full((p, r), 500, np.int32)),
            req_nz_q=jnp.asarray(np.full((p, r), 500, np.int32)),
            free_q=jnp.asarray(np.full((n, r), 8000, np.int32)),
            free_pods=jnp.asarray(np.full((n,), 110, np.int32)),
            used_nz_q=jnp.asarray(np.zeros((n, r), np.int32)),
            alloc_q=jnp.asarray(np.full((n, r), 8000, np.int32)),
            mask=jnp.asarray(np.ones((p, n), np.bool_)),
            static_scores=jnp.asarray(np.zeros((p, n), np.float32)),
            fit_col_w=jnp.ones((r,), jnp.float32),
            bal_col_mask=jnp.ones((r,), np.bool_),
            shape_u=jnp.zeros((2,), jnp.float32),
            shape_s=jnp.zeros((2,), jnp.float32),
            w_fit=jnp.float32(1.0), w_bal=jnp.float32(1.0))
        dom = np.zeros((n, domains), np.float32)
        for i in range(n):
            dom[i, i % domains] = 1.0
        cid = np.zeros((domains, cons), np.float32)
        for d in range(domains):
            cid[d, d % cons] = 1.0
        sp = dict(
            dom_onehot=jnp.asarray(dom), cid_onehot=jnp.asarray(cid),
            dom_counts=jnp.asarray(np.zeros((domains,), np.float32)),
            max_skew=jnp.asarray(np.ones((cons,), np.float32)),
            min_ok=jnp.ones((cons,), jnp.float32),
            has_key_nc=jnp.asarray(np.ones((n, cons), np.float32)),
            applies=jnp.zeros((p, cons), jnp.float32),
            contributes=jnp.ones((p, cons), jnp.float32))
        ref, ref_dc = solver.greedy_assign_rescoring_spread(
            strategy="LeastAllocated", **sp, **args)
        a, dc, com, rep = solver.greedy_assign_rescoring_spread_wave(
            strategy="LeastAllocated", wave_w=8, **sp, **args)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(ref))
        np.testing.assert_array_equal(np.asarray(dc), np.asarray(ref_dc))
        assert int(rep) == 0 and int(com) == p


class TestShardedWaveParity:
    @pytest.mark.parametrize("shards", [1, 4, 8])
    def test_mesh_bit_identity(self, shards):
        from kubernetes_tpu.parallel import build_mesh, \
            sharded_greedy_assign
        rng = np.random.default_rng(700 + shards)
        n, p, r = 64, 18, 2
        args, _ = _problem(rng, n=n, p=p, r=r)
        mesh = build_mesh(shards)
        ref = np.asarray(solver.greedy_assign_rescoring(
            strategy="LeastAllocated", **args))
        pos = (args["req_q"], args["req_nz_q"], args["free_q"],
               args["free_pods"], args["used_nz_q"], args["alloc_q"],
               args["mask"], args["static_scores"], args["fit_col_w"],
               args["bal_col_mask"], args["shape_u"], args["shape_s"],
               args["w_fit"], args["w_bal"])
        for w in (0, 1, 2, 8):
            got = np.asarray(sharded_greedy_assign(
                mesh, *pos, "LeastAllocated", wave_w=w))
            np.testing.assert_array_equal(got, ref,
                                          err_msg=f"shards={shards} W={w}")

    def test_mesh_exceptions_global_coords(self):
        """Pinned columns are GLOBAL node ids; owner-shard translation
        must keep them exact across shard counts."""
        from kubernetes_tpu.parallel import build_mesh, \
            sharded_greedy_assign
        rng = np.random.default_rng(800)
        n, p, r = 64, 12, 2
        args, _ = _problem(rng, n=n, p=p, r=r)
        exc = np.full((p,), -1, np.int32)
        exc[[1, 5, 9]] = [60, 3, 33]
        ref = np.asarray(solver.greedy_assign_rescoring(
            strategy="LeastAllocated", exc=jnp.asarray(exc), **args))
        pos = (args["req_q"], args["req_nz_q"], args["free_q"],
               args["free_pods"], args["used_nz_q"], args["alloc_q"],
               args["mask"], args["static_scores"], args["fit_col_w"],
               args["bal_col_mask"], args["shape_u"], args["shape_s"],
               args["w_fit"], args["w_bal"])
        for shards in (1, 4, 8):
            got = np.asarray(sharded_greedy_assign(
                build_mesh(shards), *pos, "LeastAllocated",
                exc=jnp.asarray(exc), wave_w=4))
            np.testing.assert_array_equal(got, ref)


class TestBackendE2EParity:
    def test_backend_wave_vs_kill_switch(self):
        """End-to-end through TPUBackend: flagless wavefront assignments
        equal KTPU_WAVEFRONT=0 at W ∈ {1, 4, 8} and the W=chunk extreme
        (KTPU_WAVE_WIDTH=chunk)."""
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.api.types import make_node, make_pod
        from kubernetes_tpu.ops.backend import TPUBackend
        from kubernetes_tpu.scheduler.cache import SchedulerCache
        from kubernetes_tpu.scheduler.types import PodInfo
        from kubernetes_tpu.utils import flags

        rng = np.random.default_rng(11)
        cache = SchedulerCache()
        for i in range(60):
            cache.add_node(make_node(
                f"n{i}", allocatable={"cpu": str(2 + int(rng.integers(6))),
                                      "memory": "16Gi", "pods": "16"}))
        snap = cache.update_snapshot()
        pods = [PodInfo(make_pod(
            f"p{i}", requests={"cpu": f"{250 * (1 + int(rng.integers(4)))}m",
                               "memory": "512Mi"},
            uid=f"u{i}")) for i in range(70)]
        fwk = default_fwk()
        with flags.scoped_set("KTPU_WAVEFRONT", "0"):
            base, _ = TPUBackend(max_batch=32, mesh=None).assign(
                pods, snap, fwk)
        for w in (1, 4, 8, 32):
            with flags.scoped_set("KTPU_WAVE_WIDTH", str(w)):
                got, _ = TPUBackend(max_batch=32, mesh=None).assign(
                    pods, snap, fwk)
            assert got == base, f"W={w} diverged from kill switch"

    def test_backend_wave_sharded_mesh(self):
        """Wavefront under the backend's auto-partitioned mesh at shard
        counts {1, 4, 8}: assignments equal the single-device backend."""
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.api.types import make_node, make_pod
        from kubernetes_tpu.ops.backend import TPUBackend
        from kubernetes_tpu.parallel import build_mesh
        from kubernetes_tpu.scheduler.cache import SchedulerCache
        from kubernetes_tpu.scheduler.types import PodInfo

        cache = SchedulerCache()
        for i in range(64):
            cache.add_node(make_node(
                f"m{i}", allocatable={"cpu": "8", "memory": "32Gi",
                                      "pods": "110"}))
        snap = cache.update_snapshot()
        pods = [PodInfo(make_pod(
            f"q{i}", requests={"cpu": "500m", "memory": "1Gi"},
            uid=f"w{i}")) for i in range(48)]
        fwk = default_fwk()
        base, _ = TPUBackend(max_batch=16, mesh=None).assign(
            pods, snap, fwk)
        for shards in (1, 4, 8):
            got, _ = TPUBackend(max_batch=16,
                                mesh=build_mesh(shards)).assign(
                pods, snap, fwk)
            assert got == base, f"shards={shards} diverged"
