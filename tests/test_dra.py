"""DRA (dynamic resource allocation): DynamicResources plugin +
resourceclaim controller + backend vectorization.

Reference semantics mirrored: pkg/scheduler/framework/plugins/
dynamicresources (structured parameters: scheduler-side allocation
persisted to claim.status at PreBind), pkg/controller/resourceclaim
(template stamping, reservedFor lifecycle, deallocation).
"""

import asyncio
import unittest

from kubernetes_tpu.api.types import (
    make_device_class,
    make_node,
    make_pod,
    make_resource_claim,
    make_resource_claim_template,
    make_resource_slice,
)
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.controllers import ResourceClaimController
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def run(coro):
    return asyncio.run(coro)


def tpu_slice(node: str, zones: int = 2, per_zone: int = 4) -> dict:
    return make_resource_slice(node, "dra.ktpu", [
        {"name": f"dev-{z}-{k}",
         "attributes": {"type": "tpu", "numa": str(z)}}
        for z in range(zones) for k in range(per_zone)])


def claim(name: str, count: int, numa_aligned: bool = True, **kw) -> dict:
    return make_resource_claim(
        name,
        requests=[{"name": "tpus", "deviceClassName": "tpu",
                   "count": count}],
        constraints=[{"matchAttribute": "numa"}] if numa_aligned else [],
        **kw)


class DRAHarness:
    """Store + scheduler (+ optional claim controller) with DRA objects."""

    def __init__(self, nodes: int = 2, backend=None, controller=False):
        self.nodes = nodes
        self.backend = backend
        self.controller = controller

    async def __aenter__(self):
        self.store = new_cluster_store()
        install_core_validation(self.store)
        await self.store.create("deviceclasses",
                                make_device_class("tpu", {"type": "tpu"}))
        for i in range(self.nodes):
            await self.store.create("nodes", make_node(
                f"n{i}", allocatable={"cpu": "16", "memory": "64Gi",
                                      "pods": "110"}))
            await self.store.create("resourceslices", tpu_slice(f"n{i}"))
        self.sched = Scheduler(self.store, seed=3, backend=self.backend)
        self.factory = InformerFactory(self.store)
        await self.sched.setup_informers(self.factory)
        self.rc = None
        if self.controller:
            self.rc = ResourceClaimController(self.store)
            self.rc.setup(self.factory)
        self.factory.start()
        await self.factory.wait_for_sync()
        if self.rc is not None:
            self.rc.start()
        self.run_task = asyncio.ensure_future(self.sched.run(batch_size=32))
        return self

    async def __aexit__(self, *exc):
        await self.sched.stop()
        self.run_task.cancel()
        if self.rc is not None:
            await self.rc.stop()
        self.factory.stop()
        self.store.stop()

    async def wait_bound(self, keys, timeout=8.0):
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            pods = {p["metadata"]["name"]: p
                    for p in (await self.store.list("pods")).items}
            if all(pods.get(k, {}).get("spec", {}).get("nodeName")
                   for k in keys):
                return pods
            await asyncio.sleep(0.02)
        raise AssertionError(f"pods not bound: {keys}")


class TestDRAScheduling(unittest.TestCase):
    def test_claimed_pod_schedules_and_allocation_persists(self):
        async def body():
            async with DRAHarness(nodes=2) as h:
                await h.store.create("resourceclaims", claim("c1", 2))
                await h.store.create("pods", make_pod(
                    "p1", requests={"cpu": "1"},
                    resource_claims=[{"name": "tpus",
                                      "resourceClaimName": "c1"}]))
                pods = await h.wait_bound(["p1"])
                node = pods["p1"]["spec"]["nodeName"]
                c = await h.store.get("resourceclaims", "default/c1")
                alloc = c["status"]["allocation"]
                self.assertEqual(alloc["nodeName"], node)
                self.assertEqual(len(alloc["devices"]), 2)
                # matchAttribute numa: both devices in one zone
                zones = {d.split("-")[1] for d in alloc["devices"]}
                self.assertEqual(len(zones), 1)
                self.assertEqual(
                    c["status"]["reservedFor"][0]["name"], "p1")
        run(body())

    def test_devices_are_finite_and_alignment_constrains(self):
        async def body():
            # 1 node, 2 zones x 4 devices. Aligned 3-device claims: only
            # two fit zone-wise (3+3 leaves 1+1 free, no zone has 3).
            async with DRAHarness(nodes=1) as h:
                for i in range(3):
                    await h.store.create("resourceclaims",
                                         claim(f"c{i}", 3))
                    await h.store.create("pods", make_pod(
                        f"p{i}", requests={"cpu": "1"},
                        resource_claims=[{"name": "t",
                                          "resourceClaimName": f"c{i}"}]))
                await h.wait_bound(["p0", "p1"])
                await asyncio.sleep(0.3)
                p2 = await h.store.get("pods", "default/p2")
                self.assertFalse(p2["spec"].get("nodeName"),
                                 "third aligned 3-TPU claim cannot fit")
        run(body())

    def test_unaligned_claim_spans_zones(self):
        async def body():
            async with DRAHarness(nodes=1) as h:
                await h.store.create(
                    "resourceclaims", claim("c6", 6, numa_aligned=False))
                await h.store.create("pods", make_pod(
                    "p6", requests={"cpu": "1"},
                    resource_claims=[{"name": "t",
                                      "resourceClaimName": "c6"}]))
                pods = await h.wait_bound(["p6"])
                c = await h.store.get("resourceclaims", "default/c6")
                self.assertEqual(len(c["status"]["allocation"]["devices"]),
                                 6)
                self.assertTrue(pods["p6"]["spec"]["nodeName"])
        run(body())

    def test_pod_missing_claim_gates_until_claim_appears(self):
        async def body():
            async with DRAHarness(nodes=1) as h:
                await h.store.create("pods", make_pod(
                    "p1", requests={"cpu": "1"},
                    resource_claims=[{"name": "t",
                                      "resourceClaimName": "late"}]))
                await asyncio.sleep(0.3)
                p = await h.store.get("pods", "default/p1")
                self.assertFalse(p["spec"].get("nodeName"))
                await h.store.create("resourceclaims", claim("late", 1))
                await h.wait_bound(["p1"])
        run(body())

    def test_batched_backend_matches_host_path(self):
        async def body():
            from kubernetes_tpu.ops import TPUBackend
            async with DRAHarness(nodes=3,
                                  backend=TPUBackend(max_batch=32)) as h:
                # 3 nodes x 8 devices; 2-aligned claims: 12 fit total.
                for i in range(12):
                    await h.store.create("resourceclaims",
                                         claim(f"c{i}", 2))
                    await h.store.create("pods", make_pod(
                        f"p{i}", requests={"cpu": "1"},
                        resource_claims=[{"name": "t",
                                          "resourceClaimName": f"c{i}"}]))
                pods = await h.wait_bound([f"p{i}" for i in range(12)])
                # Every allocation zone-aligned and no device double-booked.
                used: set[tuple[str, str]] = set()
                for i in range(12):
                    c = await h.store.get("resourceclaims", f"default/c{i}")
                    alloc = c["status"]["allocation"]
                    self.assertEqual(
                        alloc["nodeName"],
                        pods[f"p{i}"]["spec"]["nodeName"])
                    self.assertEqual(
                        len({d.split("-")[1]
                             for d in alloc["devices"]}), 1)
                    for d in alloc["devices"]:
                        pair = (alloc["nodeName"], d)
                        self.assertNotIn(pair, used, "double-booked device")
                        used.add(pair)
                self.assertEqual(len(used), 24)
        run(body())


class TestPickDevices(unittest.TestCase):
    def test_match_attribute_applies_claim_wide(self):
        """Two requests under one matchAttribute constraint must land in
        the SAME attribute group (reference MatchAttribute semantics) —
        2+2 free per zone cannot satisfy two 2-device requests that must
        agree on numa when only one zone has 4 free."""
        from kubernetes_tpu.scheduler.plugins.dynamicresources import (
            DynamicResources,
        )
        plugin = DynamicResources()
        classes = {"tpu": make_device_class("tpu", {"type": "tpu"})}
        c = make_resource_claim(
            "c", requests=[
                {"name": "a", "deviceClassName": "tpu", "count": 2},
                {"name": "b", "deviceClassName": "tpu", "count": 2}],
            constraints=[{"matchAttribute": "numa"}])
        split = [  # 2 free in numa 0, 2 free in numa 1 — must NOT satisfy
            {"name": f"dev-{z}-{k}",
             "attributes": {"type": "tpu", "numa": str(z)}}
            for z in range(2) for k in range(2)]
        self.assertIsNone(plugin._pick_devices(c, split, classes))
        one_zone = [  # 4 free in numa 1 — satisfiable, all one group
            {"name": f"dev-1-{k}",
             "attributes": {"type": "tpu", "numa": "1"}}
            for k in range(4)]
        picked = plugin._pick_devices(c, one_zone, classes)
        self.assertEqual(len(picked), 4)
        self.assertEqual({d.split("-")[1] for d in picked}, {"1"})


class TestResourceClaimController(unittest.TestCase):
    def test_template_stamping_and_e2e_lifecycle(self):
        async def body():
            async with DRAHarness(nodes=1, controller=True) as h:
                await h.store.create(
                    "resourceclaimtemplates",
                    make_resource_claim_template(
                        "tpu-tmpl",
                        requests=[{"name": "t", "deviceClassName": "tpu",
                                   "count": 4}],
                        constraints=[{"matchAttribute": "numa"}]))
                await h.store.create("pods", make_pod(
                    "worker", requests={"cpu": "1"},
                    resource_claims=[{
                        "name": "t",
                        "resourceClaimTemplateName": "tpu-tmpl"}]))
                # controller stamps worker-t; scheduler allocates + binds
                await h.wait_bound(["worker"])
                c = await h.store.get("resourceclaims", "default/worker-t")
                self.assertEqual(len(c["status"]["allocation"]["devices"]),
                                 4)
                self.assertEqual(c["metadata"]["ownerReferences"][0]["name"],
                                 "worker")
                # delete the pod -> controller releases + deletes the
                # generated claim -> devices return to the pool
                await h.store.delete("pods", "default/worker")
                deadline = asyncio.get_event_loop().time() + 5
                while asyncio.get_event_loop().time() < deadline:
                    lst = await h.store.list("resourceclaims")
                    if not lst.items:
                        break
                    await asyncio.sleep(0.02)
                self.assertEqual(
                    (await h.store.list("resourceclaims")).items, [])
                # pool is free again: a fresh 8-device unaligned claim fits
                await h.store.create(
                    "resourceclaims", claim("all8", 8, numa_aligned=False))
                await h.store.create("pods", make_pod(
                    "big", requests={"cpu": "1"},
                    resource_claims=[{"name": "t",
                                      "resourceClaimName": "all8"}]))
                await h.wait_bound(["big"])
        run(body())

    def test_user_claim_deallocates_when_consumers_drain(self):
        async def body():
            async with DRAHarness(nodes=1, controller=True) as h:
                await h.store.create("resourceclaims", claim("shared", 2))
                await h.store.create("pods", make_pod(
                    "p1", requests={"cpu": "1"},
                    resource_claims=[{"name": "t",
                                      "resourceClaimName": "shared"}]))
                await h.wait_bound(["p1"])
                await h.store.delete("pods", "default/p1")
                deadline = asyncio.get_event_loop().time() + 5
                while asyncio.get_event_loop().time() < deadline:
                    c = await h.store.get("resourceclaims",
                                          "default/shared")
                    if not (c.get("status") or {}).get("allocation"):
                        break
                    await asyncio.sleep(0.02)
                c = await h.store.get("resourceclaims", "default/shared")
                self.assertIsNone((c.get("status") or {}).get("allocation"))
                self.assertEqual((c.get("status") or {}).get("reservedFor"),
                                 [])
        run(body())


if __name__ == "__main__":
    unittest.main()


class TestKwokDevicePublishing(unittest.TestCase):
    def test_kwok_nodes_publish_resource_slices(self):
        """The device-plugin seam: extended resources on kwok nodes also
        arrive as ResourceSlices (devicemanager/DRA-driver analog), so
        claim-based pods schedule onto kwok clusters."""
        async def body():
            from kubernetes_tpu.controllers import KwokController
            store = new_cluster_store()
            install_core_validation(store)
            await store.create("deviceclasses",
                               make_device_class("tpu", {"type": "tpu"}))
            kwok = KwokController(
                store, node_count=3,
                node_template={"allocatable": {
                    "cpu": "16", "memory": "64Gi", "pods": "110",
                    "google.com/tpu": "8"}},
                device_zones=2)
            factory = InformerFactory(store)
            kwok.setup(factory)
            sched = Scheduler(store, seed=6)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            await kwok.register_nodes()
            slices = (await store.list("resourceslices")).items
            self.assertEqual(len(slices), 3)
            devices = slices[0]["spec"]["devices"]
            self.assertEqual(len(devices), 8)
            self.assertEqual({d["attributes"]["numa"] for d in devices},
                             {"0", "1"})
            # a DRA claim schedules against the published inventory
            kwok.start()
            run_task = asyncio.ensure_future(sched.run(batch_size=8))
            await store.create("resourceclaims", make_resource_claim(
                "want-tpus",
                requests=[{"name": "t", "deviceClassName": "tpu",
                           "count": 4}],
                constraints=[{"matchAttribute": "numa"}]))
            await store.create("pods", make_pod(
                "claimer", requests={"cpu": "1"},
                resource_claims=[{"name": "t",
                                  "resourceClaimName": "want-tpus"}]))
            for _ in range(300):
                p = await store.get("pods", "default/claimer")
                if p["spec"].get("nodeName"):
                    break
                await asyncio.sleep(0.02)
            self.assertTrue(p["spec"].get("nodeName"))
            c = await store.get("resourceclaims", "default/want-tpus")
            self.assertEqual(
                len(c["status"]["allocation"]["devices"]), 4)
            await sched.stop()
            run_task.cancel()
            await kwok.stop()
            factory.stop()
            store.stop()
        run(body())
