"""Hypothesis state-machine tests for the scheduling queue and cache
invariants (SURVEY §5.2: the discipline Go's race detector + mutexes
enforced structurally — here the GIL hides data races but not logical
ones, so the tiers/assume-expire state machines are property-tested)."""

import asyncio

from hypothesis import settings
from hypothesis import strategies as st
from hypothesis.stateful import (
    RuleBasedStateMachine,
    invariant,
    rule,
)

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.framework import Framework
from kubernetes_tpu.scheduler.queue import ClusterEvent, SchedulingQueue
from kubernetes_tpu.scheduler.types import PodInfo

POD_NAMES = [f"pod-{i}" for i in range(8)]
NODE_NAMES = [f"node-{i}" for i in range(4)]


def _pi(name, priority=0):
    return PodInfo(make_pod(name, priority=priority, uid=f"uid-{name}",
                            requests={"cpu": "100m"}))


class QueueMachine(RuleBasedStateMachine):
    """activeQ / backoffQ / unschedulable / gated / in-flight tier
    invariants: a pod key lives in AT MOST one tier; pop moves
    active → in-flight; done/delete clear everywhere; move_all never
    loses pods."""

    def __init__(self):
        super().__init__()
        self.loop = asyncio.new_event_loop()
        fwk = Framework([], {})
        self.q = SchedulingQueue(fwk)
        self.known: set[str] = set()

    def go(self, coro):
        return self.loop.run_until_complete(coro)

    def teardown(self):
        self.loop.close()

    @rule(name=st.sampled_from(POD_NAMES),
          priority=st.integers(min_value=0, max_value=100))
    def add(self, name, priority):
        self.go(self.q.add(_pi(name, priority)))
        self.known.add(f"default/{name}")

    @rule()
    def pop_one(self):
        async def body():
            stats = self.q.stats()
            if stats["active"] == 0:
                return []
            return await self.q.pop_batch(1)
        pods = self.go(body())
        for pi in pods:
            # popped pods are in-flight, owned by "the cycle": requeue
            # unschedulable or ack done — model a failed cycle here.
            self.go(self.q.add_unschedulable(pi))

    @rule(name=st.sampled_from(POD_NAMES))
    def ack_done(self, name):
        self.go(self.q.done(f"default/{name}"))

    @rule(name=st.sampled_from(POD_NAMES))
    def delete(self, name):
        self.go(self.q.delete(f"default/{name}"))
        self.known.discard(f"default/{name}")

    @rule()
    def cluster_event(self):
        self.go(self.q.move_all(ClusterEvent("Node", "Add")))

    @rule()
    def flush(self):
        self.go(self.q.flush_unschedulable_leftover())

    @invariant()
    def tiers_disjoint_and_complete(self):
        q = self.q
        tiers = {
            "active": set(q._active_keys),
            "backoff": set(q._backoff_keys),
            "unsched": set(q._unschedulable),
            "gated": set(q._gated),
        }
        names = list(tiers)
        for i, a in enumerate(names):
            for b in names[i + 1:]:
                overlap = tiers[a] & tiers[b]
                assert not overlap, f"{a} ∩ {b} = {overlap}"
        # stats() agrees with the internal sets.
        st_ = q.stats()
        assert st_["active"] == len(tiers["active"])
        assert st_["backoff"] == len(tiers["backoff"])
        assert st_["unschedulable"] == len(tiers["unsched"])


class CacheMachine(RuleBasedStateMachine):
    """assume/confirm/expire + snapshot: assumed pods appear on their node
    exactly once; forget removes them; snapshot generation is monotonic
    and node pod-counts match the model."""

    def __init__(self):
        super().__init__()
        self.cache = SchedulerCache(assumed_pod_ttl=1e9)
        self.now = 0.0
        for n in NODE_NAMES:
            self.cache.add_node(make_node(n))
        #: model: pod key -> node name
        self.placed: dict[str, str] = {}
        self.last_generation = -1

    @rule(name=st.sampled_from(POD_NAMES),
          node=st.sampled_from(NODE_NAMES))
    def assume(self, name, node):
        key = f"default/{name}"
        if key in self.placed:
            return
        self.cache.assume_pod(_pi(name), node)
        self.placed[key] = node

    @rule(name=st.sampled_from(POD_NAMES))
    def finish_binding(self, name):
        key = f"default/{name}"
        if key in self.placed:
            self.cache.finish_binding(key, now=self.now)

    @rule(name=st.sampled_from(POD_NAMES))
    def forget(self, name):
        key = f"default/{name}"
        if key in self.placed and self.cache.is_assumed(key):
            self.cache.forget_pod(key)
            del self.placed[key]

    @rule(name=st.sampled_from(POD_NAMES),
          node=st.sampled_from(NODE_NAMES))
    def confirm_via_watch(self, name, node):
        """The bound pod arrives via the informer: add_pod confirms an
        assumed pod on the SAME node, and a DIFFERENT watched node
        corrects the optimistic assume (the API is the truth). Once
        CONFIRMED, nodeName is immutable — the API can never report a
        bound pod moving, so the drawn node only applies while assumed."""
        key = f"default/{name}"
        if key not in self.placed:
            return
        target = node if self.cache.is_assumed(key) else self.placed[key]
        pi = PodInfo(make_pod(name, node_name=target, uid=f"uid-{name}",
                              requests={"cpu": "100m"}))
        self.cache.add_pod(pi)
        self.placed[key] = target

    @rule(name=st.sampled_from(POD_NAMES))
    def remove(self, name):
        key = f"default/{name}"
        if key in self.placed and not self.cache.is_assumed(key):
            self.cache.remove_pod(key)
            del self.placed[key]

    @invariant()
    def snapshot_matches_model(self):
        snap = self.cache.update_snapshot()
        assert snap.generation >= self.last_generation
        self.last_generation = snap.generation
        seen: dict[str, str] = {}
        for ni in snap:
            for pi in ni.pods:
                assert pi.key not in seen, \
                    f"{pi.key} on both {seen[pi.key]} and {ni.name}"
                seen[pi.key] = ni.name
        assert seen == self.placed


TestQueueProperties = QueueMachine.TestCase
TestQueueProperties.settings = settings(
    max_examples=40, stateful_step_count=30, deadline=None)
TestCacheProperties = CacheMachine.TestCase
TestCacheProperties.settings = settings(
    max_examples=40, stateful_step_count=30, deadline=None)
