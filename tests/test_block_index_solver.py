"""Block-sparse prefilter: randomized differential parity vs the full
shortlist prefilter (ops/solver.block_bound_prefilter vs
kernels.chunk_start_scores + shortlist_prefilter), end to end through
every scan variant that consumes the prefilter outputs.

The contract under test is absolute (ISSUE 20 / the KTPU_BLOCK_INDEX
knob's README section): the two-pass block-bound form is a pruning of
the SAME argmax — assignments bit-identical to the full-width pass at
every width (KTPU_BLOCK_WIDTH), strategy, and shard count, including
the engineered-adversarial cases (tight capacity, exact score ties at
the K boundary, class exceptions through the backend, spread gating,
the shortlist∩wavefront composition, padding columns, N % width != 0
and N < width shapes). Pruning itself must also actually FIRE on the
shapes it was built for (uniform fleets, dominated blocks) — a suite
where every case falls back would vacuously pass parity.
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import kernels, solver
from test_shortlist_solver import prefilter, solver_args, synthetic


def block_prefilter(d, k, bw, strategy, w_fit=1.0, w_bal=1.0,
                    n_real=None):
    """Per-pod block-bound shortlist args the way the backend builds
    them, plus the (scanned, pruned) counters on the side."""
    free_q = d["alloc_q"] - d["used_q"]
    free_pods = d["alloc_pods"] - d["used_pods"]
    fits0 = np.all(d["req_q"][:, None, :] <= free_q[None], axis=-1) \
        & (free_pods >= 1)[None]
    N = d["alloc_q"].shape[0]
    n_real = N if n_real is None else n_real
    feas = d["mask"] & fits0 & (np.arange(N) < n_real)[None]
    sc0, cand, th, scanned, pruned = solver.block_bound_prefilter(
        jnp.asarray(d["alloc_q"]), jnp.asarray(d["used_q"]),
        jnp.asarray(d["req_q"]), jnp.asarray(d["static_sc"]),
        jnp.asarray(feas), jnp.asarray(d["col_w"]),
        jnp.asarray(d["col_mask"]), jnp.asarray(d["shape_u"]),
        jnp.asarray(d["shape_s"]), jnp.float32(w_fit),
        jnp.float32(w_bal), strategy, jnp.int32(n_real), k, bw)
    P = d["req_q"].shape[0]
    args = (sc0, jnp.arange(P, dtype=jnp.int32), cand, th,
            jnp.asarray(d["mask"].any(axis=1)))
    return args, int(scanned), int(pruned)


def _same_thresh(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return np.all((a == b) | (np.isneginf(a) & np.isneginf(b)))


# ---------------------------------------------------------------------------
# prefilter-level parity: candidates and thresholds must be identical
# ---------------------------------------------------------------------------

class TestPrefilterParity:
    @pytest.mark.parametrize("strategy", [
        "LeastAllocated", "MostAllocated", "RequestedToCapacityRatio"])
    @pytest.mark.parametrize("bw", [8, 16])
    def test_randomized(self, strategy, bw):
        for seed in range(4):
            rng = np.random.default_rng(seed)
            d = synthetic(rng)
            _, _, cand_f, th_f, _ = prefilter(d, 6, strategy)
            (_, _, cand_b, th_b, _), scanned, _ = \
                block_prefilter(d, 6, bw, strategy)
            np.testing.assert_array_equal(
                np.asarray(cand_f), np.asarray(cand_b))
            assert _same_thresh(th_f, th_b)
            assert scanned == d["req_q"].shape[0] \
                * -(-d["alloc_q"].shape[0] // bw)

    def test_padding_columns_excluded(self):
        """n_real < N (bucket padding): padded columns must influence
        neither the aggregates nor the candidates — parity vs a full
        prefilter whose feasibility masks them out the r18 way."""
        for seed in range(3):
            rng = np.random.default_rng(30 + seed)
            d = synthetic(rng, N=96)
            n_real = 77
            d2 = dict(d)
            d2["mask"] = d["mask"] & (np.arange(96) < n_real)[None]
            _, _, cand_f, th_f, _ = prefilter(d2, 5, "LeastAllocated")
            (_, _, cand_b, th_b, _), _, _ = block_prefilter(
                d, 5, 16, "LeastAllocated", n_real=n_real)
            np.testing.assert_array_equal(
                np.asarray(cand_f), np.asarray(cand_b))
            assert _same_thresh(th_f, th_b)

    def test_ragged_last_block(self):
        """N % width != 0: the tail block is partial and its fold fills
        ride the directional sentinels — still bit-identical."""
        rng = np.random.default_rng(40)
        d = synthetic(rng, N=72)  # 72 / 16 -> 4 full + 1 ragged block
        _, _, cand_f, th_f, _ = prefilter(d, 4, "LeastAllocated")
        (_, _, cand_b, th_b, _), _, _ = block_prefilter(
            d, 4, 16, "LeastAllocated")
        np.testing.assert_array_equal(
            np.asarray(cand_f), np.asarray(cand_b))
        assert _same_thresh(th_f, th_b)

    def test_width_wider_than_n_is_a_shape_error(self):
        """N < width leaves M+1 > B: the prefilter refuses (ValueError)
        — the tuner/block_width policy routes width 0 there instead
        (the KTPU_BLOCK_WIDTH override never reaches the kernel)."""
        rng = np.random.default_rng(41)
        d = synthetic(rng, N=8)
        with pytest.raises(ValueError):
            block_prefilter(d, 4, 16, "LeastAllocated")

    def test_score_ties_at_k_boundary(self):
        """Quantized scores, zero score weights: exact float ties
        straddle the shortlist boundary — the after-last-selected-block
        gate in the uniform arm must keep top_k's lowest-index tie rule
        exact."""
        for seed in range(4):
            rng = np.random.default_rng(200 + seed)
            d = synthetic(rng, score_levels=2)
            for k in (1, 4, 9):
                _, _, cand_f, th_f, _ = prefilter(
                    d, k, "LeastAllocated", w_fit=0.0, w_bal=0.0)
                (_, _, cand_b, th_b, _), _, _ = block_prefilter(
                    d, k, 8, "LeastAllocated", w_fit=0.0, w_bal=0.0)
                np.testing.assert_array_equal(
                    np.asarray(cand_f), np.asarray(cand_b))
                assert _same_thresh(th_f, th_b)

    def test_pruning_fires_on_dominated_blocks(self):
        """Strict-bound arm: two leading blocks carry every winner by a
        wide static-score margin — the other blocks must prune (the
        anti-vacuity half of the parity contract)."""
        n, r, c, k, bw = 256, 3, 4, 3, 16
        static = np.full((c, n), -100.0, np.float32)
        static[:, : bw * 2] = 100.0
        d = dict(
            alloc_q=np.full((n, r), 40_000, np.int32),
            used_q=np.full((n, r), 10_000, np.int32),
            alloc_pods=np.full((n,), 110, np.int32),
            used_pods=np.zeros((n,), np.int32),
            req_q=np.full((c, r), 5_000, np.int32),
            mask=np.ones((c, n), bool), static_sc=static,
            col_w=np.ones((r,), np.float32),
            col_mask=np.ones((r,), np.bool_),
            shape_u=np.array([0.0, 100.0], np.float32),
            shape_s=np.array([0.0, 10.0], np.float32))
        (_, _, cand_b, th_b, _), scanned, pruned = block_prefilter(
            d, k, bw, "LeastAllocated")
        assert pruned > 0
        _, _, cand_f, th_f, _ = prefilter(d, k, "LeastAllocated")
        np.testing.assert_array_equal(
            np.asarray(cand_f), np.asarray(cand_b))
        assert _same_thresh(th_f, th_b)

    def test_pruning_fires_on_uniform_fleet(self):
        """Uniform arm: the 50k-preset shape (identical nodes, identical
        scores) defeats the strict bound by construction — the uniform
        certificate must prune anyway, and stay exact."""
        n, r, c, k, bw = 256, 3, 4, 3, 16
        d = dict(
            alloc_q=np.full((n, r), 40_000, np.int32),
            used_q=np.full((n, r), 10_000, np.int32),
            alloc_pods=np.full((n,), 110, np.int32),
            used_pods=np.zeros((n,), np.int32),
            req_q=np.full((c, r), 5_000, np.int32),
            mask=np.ones((c, n), bool),
            static_sc=np.zeros((c, n), np.float32),
            col_w=np.ones((r,), np.float32),
            col_mask=np.ones((r,), np.bool_),
            shape_u=np.array([0.0, 100.0], np.float32),
            shape_s=np.array([0.0, 10.0], np.float32))
        (_, _, cand_b, th_b, _), _, pruned = block_prefilter(
            d, k, bw, "LeastAllocated")
        assert pruned > 0
        _, _, cand_f, th_f, _ = prefilter(d, k, "LeastAllocated")
        np.testing.assert_array_equal(
            np.asarray(cand_f), np.asarray(cand_b))
        assert _same_thresh(th_f, th_b)

    def test_pruning_survives_advancing_drain_frontier(self):
        """Drain steady state: the low blocks are already full, so the
        selection sits MID-RANGE (blocks 3..4 here, not 0..M-1). The
        uniform arm keys on the last selected block, not a fixed
        prefix — the filled frontier prunes via the empty arm, the
        uniform tail behind the selection still prunes, and nothing
        falls back. (A fixed 0..M-1 gate would drive pruned to 0 for
        every post-warmup chunk of the 200k/1m drain benches.)"""
        n, r, c, k, bw = 256, 3, 4, 3, 16
        used = np.full((n, r), 10_000, np.int32)
        used[: bw * 3] = 40_000  # three leading blocks fully drained
        d = dict(
            alloc_q=np.full((n, r), 40_000, np.int32),
            used_q=used,
            alloc_pods=np.full((n,), 110, np.int32),
            used_pods=np.zeros((n,), np.int32),
            req_q=np.full((c, r), 5_000, np.int32),
            mask=np.ones((c, n), bool),
            static_sc=np.zeros((c, n), np.float32),
            col_w=np.ones((r,), np.float32),
            col_mask=np.ones((r,), np.bool_),
            shape_u=np.array([0.0, 100.0], np.float32),
            shape_s=np.array([0.0, 10.0], np.float32))
        (_, _, cand_b, th_b, _), _, pruned = block_prefilter(
            d, k, bw, "LeastAllocated")
        assert pruned > 0
        _, _, cand_f, th_f, _ = prefilter(d, k, "LeastAllocated")
        np.testing.assert_array_equal(
            np.asarray(cand_f), np.asarray(cand_b))
        assert _same_thresh(th_f, th_b)


# ---------------------------------------------------------------------------
# scan-level parity: the prefilter outputs feed every shortlist scan
# ---------------------------------------------------------------------------

class TestScanParity:
    @pytest.mark.parametrize("strategy", ["LeastAllocated",
                                          "MostAllocated"])
    def test_randomized_identity_scan(self, strategy):
        for seed in range(4):
            rng = np.random.default_rng(seed)
            d = synthetic(rng)
            args = solver_args(d)
            full = np.asarray(solver.greedy_assign_rescoring(
                *args, strategy=strategy))
            bargs, _, _ = block_prefilter(d, 6, 8, strategy)
            sl, _ = solver.greedy_assign_rescoring_shortlist(
                *args, strategy, *bargs)
            np.testing.assert_array_equal(full, np.asarray(sl))

    def test_tight_capacity_forces_solve_fallback(self):
        """Capacity debits exhaust shortlists mid-scan: the scan's own
        full-row fallback must compose with the block prefilter (its
        sc0 zeros at pruned columns are never read — fallback rows are
        recomputed live) and stay bit-identical."""
        hit = 0
        for seed in range(4):
            rng = np.random.default_rng(100 + seed)
            d = synthetic(rng, P=20, N=48, tight=True)
            args = solver_args(d)
            full = np.asarray(solver.greedy_assign_rescoring(
                *args, strategy="LeastAllocated"))
            bargs, _, _ = block_prefilter(d, 4, 8, "LeastAllocated")
            sl, nfall = solver.greedy_assign_rescoring_shortlist(
                *args, "LeastAllocated", *bargs)
            np.testing.assert_array_equal(full, np.asarray(sl))
            hit += int(nfall)
        assert hit > 0

    def test_spread_scan(self):
        """Spread gating is prefilter-blind and non-monotone — the block
        prefilter must compose with the spread shortlist scan exactly."""
        from test_shortlist_solver import TestSpreadParity
        for seed in range(4):
            rng = np.random.default_rng(400 + seed)
            N, P = 48, 12
            d = synthetic(rng, P=P, N=N)
            args = solver_args(d)
            sp = TestSpreadParity._spread(TestSpreadParity(), rng, N, P)
            full, dc_full = solver.greedy_assign_rescoring_spread(
                *args, "LeastAllocated", *sp)
            bargs, _, _ = block_prefilter(d, 5, 8, "LeastAllocated")
            sl, dc_sl, _ = solver.greedy_assign_rescoring_spread_shortlist(
                *args, "LeastAllocated", *sp, *bargs)
            np.testing.assert_array_equal(
                np.asarray(full), np.asarray(sl))
            np.testing.assert_allclose(
                np.asarray(dc_full), np.asarray(dc_sl))


# ---------------------------------------------------------------------------
# sharded path (8-virtual-device CPU mesh, conftest-forced)
# ---------------------------------------------------------------------------

class TestShardedParity:
    @pytest.mark.parametrize("n_devices", [1, 4, 8])
    @pytest.mark.parametrize("bw", [4, 8])
    def test_matches_single_chip(self, n_devices, bw):
        if len(jax.devices()) < n_devices:
            pytest.skip("not enough devices")
        from kubernetes_tpu.parallel import build_mesh
        from kubernetes_tpu.parallel.sharded import sharded_greedy_assign
        rng = np.random.default_rng(11)
        d = synthetic(rng, P=12, N=64)
        args = solver_args(d)
        single = np.asarray(solver.greedy_assign_rescoring(
            *args, strategy="LeastAllocated"))
        sharded = np.asarray(sharded_greedy_assign(
            build_mesh(n_devices), *args, "LeastAllocated",
            shortlist_k=3, block_w=bw))
        np.testing.assert_array_equal(single, sharded)

    def test_shard_local_width_clamp(self):
        """A width whose M+1 > B at the LOCAL shard (global N is wide
        enough, each shard's slice is not) must route to 0 — never a
        shape error, never a wrong answer."""
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        from kubernetes_tpu.parallel import build_mesh
        from kubernetes_tpu.parallel.sharded import sharded_greedy_assign
        rng = np.random.default_rng(12)
        d = synthetic(rng, P=12, N=64)  # 8 columns per shard
        args = solver_args(d)
        single = np.asarray(solver.greedy_assign_rescoring(
            *args, strategy="LeastAllocated"))
        sharded = np.asarray(sharded_greedy_assign(
            build_mesh(8), *args, "LeastAllocated",
            shortlist_k=3, block_w=16))
        np.testing.assert_array_equal(single, sharded)

    def test_multislice(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        from kubernetes_tpu.parallel import build_multislice_mesh
        from kubernetes_tpu.parallel.sharded import (
            sharded_greedy_assign_multislice,
        )
        rng = np.random.default_rng(13)
        d = synthetic(rng, P=12, N=64)
        args = solver_args(d)
        single = np.asarray(solver.greedy_assign_rescoring(
            *args, strategy="LeastAllocated"))
        ms = np.asarray(sharded_greedy_assign_multislice(
            build_multislice_mesh(2, 4), *args, "LeastAllocated",
            shortlist_k=4, block_w=4))
        np.testing.assert_array_equal(single, ms)


# ---------------------------------------------------------------------------
# backend end to end: KTPU_BLOCK_INDEX on vs off must be bit-identical
# ---------------------------------------------------------------------------

class TestBackendParity:
    def _cluster_and_pods(self, seed, n_nodes=160, n_pods=50):
        from test_tpu_backend import TOL_POOL, random_cluster
        from kubernetes_tpu.api.types import make_pod
        from kubernetes_tpu.scheduler.types import PodInfo
        rng = random.Random(seed)
        snap = random_cluster(rng, n_nodes)
        # Template pods with taints/tolerations: the class-exception
        # (exc) columns ride the masks the prefilter consumes.
        pods = [PodInfo(make_pod(
            f"pend-{i}",
            requests={"cpu": "500m", "memory": "512Mi"} if i % 2
            else {"cpu": "1", "memory": "2Gi"},
            tolerations=TOL_POOL if i % 2 else None,
            uid=f"uid-{i}")) for i in range(n_pods)]
        return snap, pods

    @pytest.mark.parametrize("wavefront", [False, True])
    def test_forced_on_off_identical(self, monkeypatch, wavefront):
        """Forced-on (small LARGE_N, KTPU_BLOCK_WIDTH=16) vs the
        KTPU_BLOCK_INDEX=0 kill switch: identical assignments, and the
        forced run must actually scan blocks. The wavefront case pins
        the shortlist∩wave composition (the prefilter feeds the wave
        scan's candidates too)."""
        import kubernetes_tpu.ops.backend as backend_mod
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.metrics.registry import SchedulerMetrics
        snap, pods = self._cluster_and_pods(9)
        fwk = default_fwk()
        monkeypatch.setenv("KTPU_SHORTLIST_K", "16")
        if wavefront:
            monkeypatch.setenv("KTPU_WAVEFRONT", "1")
            monkeypatch.setenv("KTPU_WAVE_WIDTH", "4")
        monkeypatch.setenv("KTPU_BLOCK_INDEX", "0")
        off, _ = backend_mod.TPUBackend(
            max_batch=16, mesh=None).assign(pods, snap, fwk)
        monkeypatch.setenv("KTPU_BLOCK_INDEX", "1")
        monkeypatch.setenv("KTPU_BLOCK_WIDTH", "16")
        monkeypatch.setattr(backend_mod.AdaptiveTuner, "LARGE_N", 1)
        b = backend_mod.TPUBackend(max_batch=16, mesh=None)
        b.metrics = SchedulerMetrics()
        on, _ = b.assign(pods, snap, fwk)
        assert off == on
        assert b.metrics.solver_blocks_scanned.value() > 0
