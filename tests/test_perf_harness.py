"""scheduler_perf harness: workload execution, metrics, YAML suite."""

import asyncio

from kubernetes_tpu.ops import TPUBackend
from kubernetes_tpu.perf import PerfRunner, run_suite


class TestPerfRunner:
    def test_basic_workload_host(self):
        template = [
            {"opcode": "createNodes", "countParam": "$nodes"},
            {"opcode": "createPods", "count": 20, "collectMetrics": True},
            {"opcode": "barrier"},
        ]
        res = asyncio.run(PerfRunner().run(template, {"nodes": 5},
                                           timeout=30.0))
        d = res.as_dict()
        assert d["scheduled_total"] >= 20
        assert d["throughput_pods_per_sec"] > 0
        assert 0 < d["fragmentation_pct"] <= 100
        # createNodes staging is timed into the detail JSON (the 1m
        # preset's pre-measurement wall is recorded data, not dark).
        assert d["staging_seconds"] > 0

    def test_basic_workload_tpu_backend(self):
        template = [
            {"opcode": "createNodes", "count": 8},
            {"opcode": "createPods", "count": 40, "collectMetrics": True},
            {"opcode": "barrier"},
        ]
        runner = PerfRunner(backend=TPUBackend(max_batch=16), batch_size=16)
        res = asyncio.run(runner.run(template, {}, timeout=60.0))
        assert res.scheduled_total >= 40

    def test_unschedulable_pods_counted(self):
        template = [
            {"opcode": "createNodes", "count": 2},
            {"opcode": "createPods", "count": 3,
             "podTemplate": {"requests": {"cpu": "100"}}},
            {"opcode": "createPods", "count": 10, "collectMetrics": True},
            {"opcode": "barrier"},
        ]
        # barrier waits for all 13 but 3 can never schedule → rely on the
        # measured phase's own wait; barrier then times out… so use a
        # template without the trailing barrier for the huge pods.
        template = [
            {"opcode": "createNodes", "count": 2},
            {"opcode": "createPods", "count": 10, "collectMetrics": True},
            {"opcode": "barrier"},
            {"opcode": "createPods", "count": 3,
             "podTemplate": {"requests": {"cpu": "100"}}},
            {"opcode": "sleep", "duration": 0.3},
        ]
        res = asyncio.run(PerfRunner().run(template, {}, timeout=30.0))
        assert res.scheduled_total >= 10
        assert res.unschedulable_total >= 3

    def test_churn_op(self):
        template = [
            {"opcode": "createNodes", "count": 4},
            {"opcode": "createPods", "count": 20},
            {"opcode": "barrier"},
            {"opcode": "churn", "count": 5},
            {"opcode": "barrier"},
        ]
        res = asyncio.run(PerfRunner().run(template, {}, timeout=30.0))
        assert res.scheduled_total >= 25  # 20 initial + 5 recreated


class TestSuiteConfig:
    def test_yaml_suite_smallest(self, tmp_path):
        import yaml
        cfg = [{
            "name": "Tiny",
            "workloadTemplate": [
                {"opcode": "createNodes", "countParam": "$n"},
                {"opcode": "createPods", "count": 10, "collectMetrics": True},
                {"opcode": "barrier"},
            ],
            "workloads": [{"name": "5Nodes", "params": {"n": 5}}],
        }]
        p = tmp_path / "cfg.yaml"
        p.write_text(yaml.safe_dump(cfg))
        from kubernetes_tpu.perf.scheduler_perf import load_config
        results = run_suite(load_config(str(p)))
        assert "Tiny/5Nodes" in results
        assert results["Tiny/5Nodes"]["scheduled_total"] >= 10

    def test_repo_config_parses(self):
        from kubernetes_tpu.perf.scheduler_perf import load_config
        cfg = load_config("kubernetes_tpu/perf/config/performance-config.yaml")
        names = {c["name"] for c in cfg}
        assert {"SchedulingBasic", "SchedulingNodeAffinity",
                "SchedulingTaints", "Unschedulable"} <= names


class TestNewFamilies:
    def test_repo_config_has_all_reference_families(self):
        """SURVEY §3.5's workload family list is fully present."""
        from kubernetes_tpu.perf.scheduler_perf import load_config
        cfg = load_config("kubernetes_tpu/perf/config/performance-config.yaml")
        names = {c["name"] for c in cfg}
        assert {"SchedulingPodAffinity", "TopologySpreading", "Preemption",
                "SchedulingGated", "DeviceTopology",
                "SchedulingPodAntiAffinity"} <= names

    def test_ungate_pods_opcode(self):
        """Gated pods park in the gated tier; ungatePods lifts the gates and
        the measured window covers gate-removal → bound."""
        template = [
            {"opcode": "createNodes", "count": 5},
            {"opcode": "createPods", "count": 12,
             "podTemplate": {"scheduling_gates": ["hold"]}},
            {"opcode": "sleep", "duration": 0.2},
            {"opcode": "ungatePods", "collectMetrics": True},
        ]
        res = asyncio.run(PerfRunner().run(template, {}, timeout=30.0))
        assert res.scheduled_total == 12
        assert res.measured_pods == 12
        assert res.throughput > 0

    def test_preemption_family_scoped_barrier(self):
        """High-priority pods preempt a full cluster; the measured op's
        scoped barrier completes even though victims are deleted."""
        template = [
            {"opcode": "createNodes", "count": 4,
             "nodeTemplate": {"allocatable":
                              {"cpu": "2", "memory": "8Gi", "pods": "16"}}},
            {"opcode": "createPods", "count": 8,
             "podTemplate": {"priority": 0, "requests": {"cpu": "1"}}},
            {"opcode": "barrier"},
            {"opcode": "createPods", "count": 4, "collectMetrics": True,
             "podTemplate": {"priority": 100, "requests": {"cpu": "1"}}},
        ]
        res = asyncio.run(PerfRunner().run(template, {}, timeout=60.0))
        assert res.measured_pods == 4
        assert res.scheduled_total >= 12  # 8 fillers + 4 preemptors

    def test_through_apiserver_mode(self):
        """The whole workload crosses the HTTP process boundary."""
        template = [
            {"opcode": "createNodes", "count": 5},
            {"opcode": "createPods", "count": 20, "collectMetrics": True},
            {"opcode": "barrier"},
        ]
        res = asyncio.run(PerfRunner(through_apiserver=True).run(
            template, {}, timeout=60.0))
        assert res.scheduled_total == 20
        assert res.unschedulable_total == 0

    def test_preemption_family_on_tpu_backend(self):
        """Regression: the batched backend path must trigger PostFilter
        preemption (it once dropped state/snapshot from _handle_failure,
        so batch-scheduled clusters could never preempt)."""
        from kubernetes_tpu.ops import TPUBackend
        template = [
            {"opcode": "createNodes", "count": 4,
             "nodeTemplate": {"allocatable":
                              {"cpu": "2", "memory": "8Gi", "pods": "16"}}},
            {"opcode": "createPods", "count": 8,
             "podTemplate": {"priority": 0, "requests": {"cpu": "1"}}},
            {"opcode": "barrier"},
            {"opcode": "createPods", "count": 4, "collectMetrics": True,
             "podTemplate": {"priority": 100, "requests": {"cpu": "1"}}},
        ]
        res = asyncio.run(PerfRunner(
            backend=TPUBackend(max_batch=8), batch_size=8).run(
            template, {}, timeout=60.0))
        assert res.measured_pods == 4


class TestAgentBackedStaging:
    def test_start_agents_opcode(self):
        """startAgents boots N in-process NodeAgents: they register their
        own Nodes, consume field-selector pod watches, and mark bound
        pods Running — kwok-free staging (the AgentBackedBasic family)."""
        template = [
            {"opcode": "startAgents", "count": 5},
            {"opcode": "createPods", "count": 20, "collectMetrics": True},
            {"opcode": "barrier"},
        ]
        res = asyncio.run(PerfRunner().run(template, {}, timeout=60.0))
        assert res.scheduled_total >= 20
        assert res.throughput > 0

    def test_repo_config_has_agent_family(self):
        from kubernetes_tpu.perf.scheduler_perf import load_config
        cfg = load_config(
            "kubernetes_tpu/perf/config/performance-config.yaml")
        fam = next(c for c in cfg if c["name"] == "AgentBackedBasic")
        assert fam["workloadTemplate"][0]["opcode"] == "startAgents"
