"""scheduler_perf harness: workload execution, metrics, YAML suite."""

import asyncio

from kubernetes_tpu.ops import TPUBackend
from kubernetes_tpu.perf import PerfRunner, run_suite


class TestPerfRunner:
    def test_basic_workload_host(self):
        template = [
            {"opcode": "createNodes", "countParam": "$nodes"},
            {"opcode": "createPods", "count": 20, "collectMetrics": True},
            {"opcode": "barrier"},
        ]
        res = asyncio.run(PerfRunner().run(template, {"nodes": 5},
                                           timeout=30.0))
        d = res.as_dict()
        assert d["scheduled_total"] >= 20
        assert d["throughput_pods_per_sec"] > 0
        assert 0 < d["fragmentation_pct"] <= 100

    def test_basic_workload_tpu_backend(self):
        template = [
            {"opcode": "createNodes", "count": 8},
            {"opcode": "createPods", "count": 40, "collectMetrics": True},
            {"opcode": "barrier"},
        ]
        runner = PerfRunner(backend=TPUBackend(max_batch=16), batch_size=16)
        res = asyncio.run(runner.run(template, {}, timeout=60.0))
        assert res.scheduled_total >= 40

    def test_unschedulable_pods_counted(self):
        template = [
            {"opcode": "createNodes", "count": 2},
            {"opcode": "createPods", "count": 3,
             "podTemplate": {"requests": {"cpu": "100"}}},
            {"opcode": "createPods", "count": 10, "collectMetrics": True},
            {"opcode": "barrier"},
        ]
        # barrier waits for all 13 but 3 can never schedule → rely on the
        # measured phase's own wait; barrier then times out… so use a
        # template without the trailing barrier for the huge pods.
        template = [
            {"opcode": "createNodes", "count": 2},
            {"opcode": "createPods", "count": 10, "collectMetrics": True},
            {"opcode": "barrier"},
            {"opcode": "createPods", "count": 3,
             "podTemplate": {"requests": {"cpu": "100"}}},
            {"opcode": "sleep", "duration": 0.3},
        ]
        res = asyncio.run(PerfRunner().run(template, {}, timeout=30.0))
        assert res.scheduled_total >= 10
        assert res.unschedulable_total >= 3

    def test_churn_op(self):
        template = [
            {"opcode": "createNodes", "count": 4},
            {"opcode": "createPods", "count": 20},
            {"opcode": "barrier"},
            {"opcode": "churn", "count": 5},
            {"opcode": "barrier"},
        ]
        res = asyncio.run(PerfRunner().run(template, {}, timeout=30.0))
        assert res.scheduled_total >= 25  # 20 initial + 5 recreated


class TestSuiteConfig:
    def test_yaml_suite_smallest(self, tmp_path):
        import yaml
        cfg = [{
            "name": "Tiny",
            "workloadTemplate": [
                {"opcode": "createNodes", "countParam": "$n"},
                {"opcode": "createPods", "count": 10, "collectMetrics": True},
                {"opcode": "barrier"},
            ],
            "workloads": [{"name": "5Nodes", "params": {"n": 5}}],
        }]
        p = tmp_path / "cfg.yaml"
        p.write_text(yaml.safe_dump(cfg))
        from kubernetes_tpu.perf.scheduler_perf import load_config
        results = run_suite(load_config(str(p)))
        assert "Tiny/5Nodes" in results
        assert results["Tiny/5Nodes"]["scheduled_total"] >= 10

    def test_repo_config_parses(self):
        from kubernetes_tpu.perf.scheduler_perf import load_config
        cfg = load_config("kubernetes_tpu/perf/config/performance-config.yaml")
        names = {c["name"] for c in cfg}
        assert {"SchedulingBasic", "SchedulingNodeAffinity",
                "SchedulingTaints", "Unschedulable"} <= names
