"""Tier-1 guard for the class-dictionary device planes (small-N, fast).

Pins: (a) class planes ACTIVE by default — a template chunk ships ONE
class row, a mixed chunk a handful, and the plane-byte/prep metrics
flow; (b) the KTPU_CLASS_PLANES=0 kill switch degrading structurally to
per-pod planes (C == P) with identical assignments; (c) the exception
list carrying single-column host rows (NodeName pins) without splitting
a class; (d) the KTPU_CLASS_PAD overflow fallback counting its pods;
(e) the AdaptiveTuner chunk table re-swept under class-plane prep costs
(BASELINE r14: the large-N row held at 1024). The heavyweight
randomized parity lives in tests/test_class_planes.py.
"""

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.metrics.registry import SchedulerMetrics
from kubernetes_tpu.ops.backend import (
    AdaptiveTuner,
    TPUBackend,
    _class_rows_bucket,
    class_pad,
)
from kubernetes_tpu.scheduler.types import PodInfo


def _uniform_cluster(n):
    from kubernetes_tpu.scheduler.cache import SchedulerCache
    cache = SchedulerCache()
    for i in range(n):
        cache.add_node(make_node(
            f"n{i}", allocatable={"cpu": "8", "memory": "32Gi",
                                  "pods": "110"}))
    return cache.update_snapshot()


def _template_pods(n, cpu="500m"):
    return [PodInfo(make_pod(
        f"pend-{i}", requests={"cpu": cpu, "memory": "512Mi"},
        uid=f"uid-{i}")) for i in range(n)]


def _backend(chunk=16):
    b = TPUBackend(max_batch=chunk, mesh=None)
    b.metrics = SchedulerMetrics()
    return b


class TestClassPlaneKnobs:
    def test_default_cap_and_bucket(self, monkeypatch):
        monkeypatch.delenv("KTPU_CLASS_PLANES", raising=False)
        monkeypatch.delenv("KTPU_CLASS_PAD", raising=False)
        assert class_pad() == 31
        monkeypatch.setenv("KTPU_CLASS_PAD", "7")
        assert class_pad() == 7
        monkeypatch.setenv("KTPU_CLASS_PLANES", "0")
        assert class_pad() == 0
        # Plane rows: power-of-two buckets with the reserved empty row 0.
        assert _class_rows_bucket(0) == 2
        assert _class_rows_bucket(1) == 2
        assert _class_rows_bucket(2) == 4
        assert _class_rows_bucket(7) == 8
        assert _class_rows_bucket(31) == 32


class TestActiveByDefault:
    def test_template_chunk_ships_one_class(self, monkeypatch):
        monkeypatch.delenv("KTPU_CLASS_PLANES", raising=False)
        from test_tpu_backend import default_fwk
        snap = _uniform_cluster(100)
        pods = _template_pods(35)  # partial last chunk: padding rides
        b = _backend(chunk=16)
        assignments, _ = b.assign(pods, snap, default_fwk())
        assert all(v is not None for v in assignments.values())
        m = b.metrics
        assert m.plane_classes.value() == 1
        assert m.class_split_fallbacks.value() == 0
        # Plane payloads were uploaded and host prep was timed.
        assert m.plane_bytes.value() > 0
        assert m.prep_duration.count() >= 3

    def test_kill_switch_degrades_to_per_pod(self, monkeypatch):
        from test_tpu_backend import default_fwk
        snap = _uniform_cluster(100)
        pods = _template_pods(32)
        fwk = default_fwk()
        monkeypatch.delenv("KTPU_CLASS_PLANES", raising=False)
        on = _backend(chunk=16)
        a_on, _ = on.assign(pods, snap, fwk)
        monkeypatch.setenv("KTPU_CLASS_PLANES", "0")
        off = _backend(chunk=16)
        a_off, _ = off.assign(pods, snap, fwk)
        assert a_on == a_off
        # Structural degrade: per-pod planes (C == chunk pad), counted
        # as plain plane classes, NOT as class-split fallbacks.
        assert off.metrics.plane_classes.value() == 16
        assert off.metrics.class_split_fallbacks.value() == 0
        assert on.metrics.plane_classes.value() == 1

    def test_exception_list_path(self, monkeypatch):
        """A NodeName pod rides the exception column: same class as its
        template (C == 1), lands exactly on the named node — exercised
        under the SHORTLIST regime so the pinned-pod bound-check
        fallback runs too (N=150 ≥ 4·(K+chunk))."""
        monkeypatch.delenv("KTPU_CLASS_PLANES", raising=False)
        from test_tpu_backend import default_fwk
        snap = _uniform_cluster(150)
        pods = _template_pods(16)
        pinned = PodInfo(make_pod(
            "pinned", requests={"cpu": "500m", "memory": "512Mi"},
            node_name="n149", uid="uid-pin"))
        pods = pods[:8] + [pinned] + pods[8:]
        b = _backend(chunk=16)
        assignments, _ = b.assign(pods, snap, default_fwk())
        assert assignments[pinned.key] == "n149"
        assert all(v is not None for v in assignments.values())
        m = b.metrics
        assert m.plane_classes.value() == 1
        assert m.solver_shortlist_pods.value() == len(pods)

    def test_overflow_fallback_counts_pods(self, monkeypatch):
        monkeypatch.delenv("KTPU_CLASS_PLANES", raising=False)
        monkeypatch.setenv("KTPU_CLASS_PAD", "2")
        from test_tpu_backend import default_fwk
        snap = _uniform_cluster(60)
        pods = []
        for i in range(12):  # 4 distinct request templates > pad 2
            pods.append(PodInfo(make_pod(
                f"pend-{i}",
                requests={"cpu": f"{(1 + i % 4) * 100}m",
                          "memory": "256Mi"}, uid=f"uid-{i}")))
        b = _backend(chunk=16)
        assignments, _ = b.assign(pods, snap, default_fwk())
        assert all(v is not None for v in assignments.values())
        assert b.metrics.class_split_fallbacks.value() == len(pods)
        assert b.metrics.plane_classes.value() == len(pods)


class TestTunerResweep:
    def test_chunk_rows_post_class_planes(self):
        """BASELINE r14 re-sweep under O(C·N) prep: the large-N local
        row HELD at (1024, 2) — the shortlist scan width (2·chunk), not
        the per-chunk plane cost the class format shrank, still sets
        the optimum. Remote rows and the small-N local row unchanged."""
        assert AdaptiveTuner.pick(0.0002, 0.0, n_nodes=50_000) == (1024, 2)
        assert AdaptiveTuner.pick(0.0002, 0.9, n_nodes=50_000) == (1024, 2)
        assert AdaptiveTuner.pick(0.0002, 0.0, n_nodes=200_000) == (1024, 2)
        assert AdaptiveTuner.pick(0.020, 0.0) == (2048, 4)
        assert AdaptiveTuner.pick(0.020, 0.5) == (1024, 4)
        assert AdaptiveTuner.pick(0.0002, 0.0) == (1024, 2)
