"""Observability polish (SURVEY §5.1/§3.2): utiltrace threshold logging,
RBAC-lite authorization, jax profiler hook."""

import asyncio
import logging

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.apiserver.client import RemoteStore
from kubernetes_tpu.apiserver.rbac import (
    RBACAuthorizer,
    make_cluster_role,
    make_cluster_role_binding,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.store import install_core_validation, new_cluster_store
from kubernetes_tpu.utils.trace import Trace


def run(coro):
    return asyncio.run(coro)


class TestUtilTrace:
    def test_slow_trace_logs_steps(self, caplog):
        with caplog.at_level(logging.INFO, logger="kubernetes_tpu.trace"):
            with Trace("Scheduling", threshold_ms=0.0, pods=3) as tr:
                tr.step("snapshot")
                tr.step("solve")
        assert len(caplog.records) == 1
        msg = caplog.records[0].message
        assert "Trace[Scheduling{pods=3}]" in msg
        assert 'step "snapshot"' in msg and 'step "solve"' in msg

    def test_fast_trace_is_silent(self, caplog):
        with caplog.at_level(logging.INFO, logger="kubernetes_tpu.trace"):
            with Trace("Scheduling", threshold_ms=10_000.0) as tr:
                tr.step("snapshot")
        assert not caplog.records

    def test_scheduler_emits_trace_when_slow(self, caplog):
        """threshold 0 → every attempt traces, proving the wiring."""
        from kubernetes_tpu.client import InformerFactory
        from kubernetes_tpu.scheduler import Scheduler

        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            await store.create("nodes", make_node("n1"))
            sched = Scheduler(store, seed=1, trace_threshold_ms=0.0)
            factory = InformerFactory(store)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            task = asyncio.ensure_future(sched.run())
            await store.create("pods", make_pod("p", requests={"cpu": "1"}))
            for _ in range(200):
                p = await store.get("pods", "default/p")
                if p["spec"].get("nodeName"):
                    break
                await asyncio.sleep(0.02)
            await sched.stop()
            task.cancel()
            factory.stop()
            store.stop()
        with caplog.at_level(logging.INFO, logger="kubernetes_tpu.trace"):
            run(body())
        assert any("Trace[Scheduling" in r.message for r in caplog.records)


class TestRBAC:
    def test_authorizer_decisions(self):
        authz = RBACAuthorizer(
            roles=[
                make_cluster_role("reader", [
                    {"verbs": ["get", "list", "watch"],
                     "resources": ["pods", "nodes"]}]),
                make_cluster_role("admin", [
                    {"verbs": ["*"], "resources": ["*"]}]),
            ],
            bindings=[
                make_cluster_role_binding("rb", "reader", ["alice"]),
                make_cluster_role_binding("ab", "admin", ["root"]),
            ])
        assert authz.allowed("alice", "get", "pods")
        assert authz.allowed("alice", "watch", "nodes")
        assert not authz.allowed("alice", "create", "pods")
        assert not authz.allowed("alice", "get", "secrets")
        assert authz.allowed("root", "delete", "pods")
        assert not authz.allowed("mallory", "get", "pods")

    def test_group_bindings_track_membership_not_names(self):
        """A Group binding grants members of the group (via groups=) and
        never a USER who merely shares the group's name (ADVICE r3)."""
        authz = RBACAuthorizer(
            roles=[make_cluster_role("admin", [
                {"verbs": ["*"], "resources": ["*"]}])])
        authz.add_binding({
            "roleRef": {"kind": "ClusterRole", "name": "admin"},
            "subjects": [{"kind": "Group", "name": "admins"}]})
        # user literally named "admins" gets nothing
        assert not authz.allowed("admins", "delete", "pods")
        # a member of the group does
        assert authz.allowed("alice", "delete", "pods", groups=["admins"])
        assert not authz.allowed("alice", "delete", "pods", groups=["dev"])

    def test_serviceaccount_subject_maps_to_token_username(self):
        authz = RBACAuthorizer(
            roles=[make_cluster_role("reader", [
                {"verbs": ["get"], "resources": ["pods"]}])])
        authz.add_binding({
            "roleRef": {"kind": "ClusterRole", "name": "reader"},
            "subjects": [{"kind": "ServiceAccount", "name": "builder",
                          "namespace": "ci"}]})
        assert authz.allowed("system:serviceaccount:ci:builder",
                             "get", "pods")
        assert not authz.allowed("builder", "get", "pods")

    def test_apiserver_group_membership_authz(self):
        """user_groups on the server feeds Group bindings end-to-end,
        including the implicit system:authenticated group."""
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            authz = RBACAuthorizer(
                roles=[make_cluster_role("podadmin", [
                    {"verbs": ["*"], "resources": ["pods"]}]),
                    make_cluster_role("discovery", [
                        {"verbs": ["get", "list"],
                         "resources": ["namespaces"]}])])
            authz.add_binding({
                "roleRef": {"kind": "ClusterRole", "name": "podadmin"},
                "subjects": [{"kind": "Group", "name": "sre"}]})
            authz.add_binding({
                "roleRef": {"kind": "ClusterRole", "name": "discovery"},
                "subjects": [{"kind": "Group",
                              "name": "system:authenticated"}]})
            srv = APIServer(
                store,
                bearer_tokens={"t-a": "alice", "t-b": "bob"},
                user_groups={"alice": ["sre"]},
                authorizer=authz)
            await srv.start()
            a = RemoteStore(srv.url, token="t-a")
            created = await a.create("pods", make_pod("p1"))
            assert created["metadata"]["name"] == "p1"
            # bob is authenticated (namespaces OK) but not in sre (pods 403)
            b = RemoteStore(srv.url, token="t-b")
            await b.list("namespaces")
            from kubernetes_tpu.store.mvcc import StoreError
            with pytest.raises(StoreError):
                await b.create("pods", make_pod("p2"))
            await a.close()
            await b.close()
            await srv.stop()
            store.stop()
        run(body())

    def test_apiserver_enforces_rbac(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            authz = RBACAuthorizer(
                roles=[make_cluster_role("scheduler", [
                    {"verbs": ["*"], "resources": ["pods", "nodes"]}]),
                    make_cluster_role("reader", [
                        {"verbs": ["get", "list"],
                         "resources": ["pods"]}])],
                bindings=[
                    make_cluster_role_binding("b1", "scheduler", ["sched"]),
                    make_cluster_role_binding("b2", "reader", ["ro"])])
            srv = APIServer(
                store,
                bearer_tokens={"t-sched": "sched", "t-ro": "ro"},
                authorizer=authz)
            await srv.start()

            rw = RemoteStore(srv.url, token="t-sched")
            created = await rw.create("pods", make_pod("a"))
            assert created["metadata"]["name"] == "a"

            ro = RemoteStore(srv.url, token="t-ro")
            got = await ro.get("pods", "default/a")
            assert got["metadata"]["name"] == "a"
            from kubernetes_tpu.store.mvcc import StoreError
            with pytest.raises(StoreError):
                await ro.create("pods", make_pod("b"))   # 403
            with pytest.raises(StoreError):
                await ro.list("nodes")                   # 403

            await rw.close()
            await ro.close()
            await srv.stop()
            store.stop()
        run(body())


class TestProfilerHook:
    def test_start_stop_profile_no_crash(self, tmp_path):
        """The hook must degrade gracefully when the platform profiler is
        unavailable (axon relay) and produce a trace dir when it works."""
        from kubernetes_tpu.ops import TPUBackend
        backend = TPUBackend(max_batch=8)
        ok = backend.start_profile(str(tmp_path / "trace"))
        backend.stop_profile()
        assert ok in (True, False)  # no exception either way


class TestBackendDegradationMetrics:
    """§5.5: the TPU backend's silent fallbacks are observable — spread
    residency here; gang overflow in test_coscheduling."""

    def test_heterogeneous_min_domains_batch_stays_on_device(self):
        async def body():
            import asyncio

            from kubernetes_tpu.api.types import make_node, make_pod
            from kubernetes_tpu.client import InformerFactory
            from kubernetes_tpu.ops import TPUBackend
            from kubernetes_tpu.scheduler import Scheduler
            from kubernetes_tpu.store import (
                install_core_validation,
                new_cluster_store,
            )
            store = new_cluster_store()
            install_core_validation(store)
            for i in range(4):
                await store.create("nodes", make_node(
                    f"n{i}",
                    labels={"topology.kubernetes.io/zone": f"z{i % 2}"}))
            sched = Scheduler(store, seed=4, backend=TPUBackend(max_batch=16))
            factory = InformerFactory(store)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            run_task = asyncio.ensure_future(sched.run(batch_size=16))

            def spread_pod(name, app, skew, extra=None):
                c = {"maxSkew": skew,
                     "topologyKey": "topology.kubernetes.io/zone",
                     "whenUnsatisfiable": "DoNotSchedule",
                     "labelSelector": {"matchLabels": {"app": app}}}
                if extra:
                    c.update(extra)
                return make_pod(name, labels={"app": app},
                                topology_spread_constraints=[c])
            # EVERY template rides the union table now — heterogeneous
            # batches and minDomains constraints included. The
            # spread_poisoned counter marks only the missing-table escape
            # hatch and must stay ZERO here.
            for i in range(4):
                await store.create("pods", spread_pod(f"a{i}", "a", 1))
                await store.create("pods", spread_pod(
                    f"b{i}", "b", 2, extra={"minDomains": 2}))
            for _ in range(300):
                pods = (await store.list("pods")).items
                if sum(1 for p in pods if p["spec"].get("nodeName")) == 8:
                    break
                await asyncio.sleep(0.02)
            pods = (await store.list("pods")).items
            assert sum(1 for p in pods if p["spec"].get("nodeName")) == 8
            assert sched.metrics.backend_degradations.value(
                kind="spread_poisoned") == 0
            await sched.stop()
            run_task.cancel()
            factory.stop()
            store.stop()
        run(body())


class TestRequestTracing:
    """§5.1 OTel-style spans: one trace covers a pod's create → schedule
    → bind across the apiserver and scheduler, exportable to Perfetto."""

    def test_pod_journey_trace_and_perfetto_export(self):
        async def body():
            import asyncio
            import json as _json

            from kubernetes_tpu.api.types import make_node, make_pod
            from kubernetes_tpu.apiserver import APIServer, RemoteStore
            from kubernetes_tpu.client import InformerFactory
            from kubernetes_tpu.scheduler import Scheduler
            from kubernetes_tpu.store import (
                install_core_validation,
                new_cluster_store,
            )
            from kubernetes_tpu.utils.tracing import DEFAULT_TRACER
            DEFAULT_TRACER.enabled = True
            DEFAULT_TRACER.clear()
            try:
                backing = new_cluster_store()
                install_core_validation(backing)
                srv = APIServer(backing)
                await srv.start()
                rs = RemoteStore(srv.url)
                await rs.create("nodes", make_node("n0"))
                sched = Scheduler(rs, seed=9)
                factory = InformerFactory(rs)
                await sched.setup_informers(factory)
                factory.start()
                await factory.wait_for_sync()
                run_task = asyncio.ensure_future(sched.run(batch_size=4))
                await rs.create("pods", make_pod("traced"))
                for _ in range(300):
                    p = await rs.get("pods", "default/traced")
                    if p["spec"].get("nodeName"):
                        break
                    await asyncio.sleep(0.02)
                assert p["spec"].get("nodeName") == "n0"
                await sched.stop()
                run_task.cancel()
                factory.stop()
                await rs.close()
                await srv.stop()
                backing.stop()

                journey = DEFAULT_TRACER.trace_for("default/traced")
                names = [s.name for s in journey]
                # create request, scheduling attempt, binding cycle, and
                # the binding POST back through the apiserver — ordered.
                assert "apiserver.create.pods" in names, names
                assert "scheduler.attempt" in names, names
                assert "scheduler.bind" in names, names
                assert names.index("apiserver.create.pods") \
                    < names.index("scheduler.attempt") \
                    < names.index("scheduler.bind"), names
                # the binding POST is a second pod-attributed apiserver
                # span after the bind began
                api_spans = [s for s in journey
                             if s.name.startswith("apiserver.")]
                assert len(api_spans) >= 2, names
                # W3C traceparent propagation: the binding POST's server
                # span belongs to scheduler.bind's TRACE (same trace_id),
                # not a fresh one.
                bind = next(s for s in journey
                            if s.name == "scheduler.bind")
                bind_post = next(
                    (s for s in api_spans
                     if s.start >= bind.start and s.trace_id ==
                     bind.trace_id), None)
                assert bind_post is not None, [
                    (s.name, s.trace_id) for s in journey]
                assert all(s.end is not None for s in journey)
                # Perfetto export round-trips
                doc = _json.loads(DEFAULT_TRACER.to_perfetto())
                evs = doc["traceEvents"]
                assert any(e["name"] == "scheduler.bind" for e in evs)
                assert any(e["name"] == "store.subresource.binding"
                           for e in evs)
                assert all("ts" in e and "dur" in e for e in evs)
            finally:
                DEFAULT_TRACER.enabled = False
                DEFAULT_TRACER.clear()
        run(body())
