"""Watch-cache serving tier (store/cacher.py): randomized differential
guards pinning the tier bit-equal to the mvcc core.

- LIST-from-cacher vs LIST-from-mvcc equality at sampled RVs under
  concurrent writes (the historical-snapshot rollback is exact);
- watch backfill from the per-resource ring vs the store's global-scan
  replay: identical event sequences for every watcher shape;
- bookmark monotonicity;
- ring overflow → too-old-RV (410) parity with the store path;
- snapshot-pinned continue tokens: every page of one paginated LIST is
  served at the first page's RV, identically on the HTTP and KTPU wires
  (and via the gRPC pinned-token form).
"""

import asyncio
import json
import random

import pytest

from kubernetes_tpu.api.labels import parse_selector
from kubernetes_tpu.store.mvcc import Expired, MVCCStore

def run(coro):
    return asyncio.run(coro)


def canon(items) -> str:
    return json.dumps(items, sort_keys=True)


async def take(gen, n, timeout=2.0):
    out = []
    while len(out) < n:
        ev = await asyncio.wait_for(gen.__anext__(), timeout)
        if ev.type != "BOOKMARK":
            out.append(ev)
    await gen.aclose()
    return out


def fingerprint(evs):
    return [(e.type, e.object["metadata"]["name"], e.rv) for e in evs]


def _rand_labels(rng):
    labels = {}
    if rng.random() < 0.7:
        labels["app"] = rng.choice(["web", "db"])
    if rng.random() < 0.5:
        labels["tier"] = rng.choice(["a", "b"])
    return labels


async def _churn(s: MVCCStore, rng: random.Random, steps: int,
                 on_step=None, prefix: str = "o"):
    """Random create/update/delete traffic over pods (labels, tracked +
    untracked fields, namespaces). Concurrent writers must use disjoint
    `prefix`es: each tracks its own alive-set, so shared keys would race
    create-vs-create across await boundaries."""
    names = [(f"{prefix}{i}", ("default", "ns1")[i % 2]) for i in range(16)]
    alive = set()
    for step in range(steps):
        name, ns = rng.choice(names)
        key = f"{ns}/{name}"
        if key not in alive:
            await s.create("pods", {
                "metadata": {"name": name, "namespace": ns,
                             "labels": _rand_labels(rng)},
                "spec": {"nodeName": rng.choice(["", "n1", "n2"]),
                         "untracked": rng.choice(["x", "y"])},
                "status": {"phase": rng.choice(["Pending", "Running"])}})
            alive.add(key)
        elif rng.random() < 0.3:
            await s.delete("pods", key)
            alive.discard(key)
        else:
            cur = await s.get("pods", key)
            mutation = rng.random()
            if mutation < 0.4:
                cur["metadata"]["labels"] = _rand_labels(rng)
            elif mutation < 0.7:
                cur["spec"]["nodeName"] = rng.choice(["", "n1", "n2"])
            else:
                cur["status"]["phase"] = rng.choice(
                    ["Pending", "Running", "Succeeded"])
            await s.update("pods", cur)
        if on_step is not None:
            await on_step(step)


# LIST shapes the differential covers: plain, namespaced, selector,
# tracked field, untracked field, joint.
def _list_shapes():
    return [
        {},
        {"namespace": "ns1"},
        {"selector": parse_selector("app=web")},
        {"fields": {"spec.nodeName": "n1"}},
        {"fields": {"spec.untracked": "x"}},
        {"namespace": "default", "fields": {"spec.nodeName": "n2"},
         "selector": parse_selector("app")},
    ]


class TestListDifferential:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_sampled_rv_bit_equality_under_concurrent_writes(self, seed):
        """At random points of a concurrent write stream, capture the
        direct-mvcc LIST; the cacher must later reproduce it bit-exactly
        from its historical snapshot at that RV."""
        async def body():
            rng = random.Random(seed)
            s = MVCCStore()
            assert s.cacher is not None  # active by default
            await s.list("pods")  # touch: ring covers from rv 0
            samples = []  # (rv, shape index, canonical direct items)

            async def sample(step):
                if rng.random() < 0.15:
                    i = rng.randrange(len(_list_shapes()))
                    direct = await s.list_direct(
                        "pods", **_list_shapes()[i])
                    samples.append(
                        (direct.resource_version, i, canon(direct.items)))
                if rng.random() < 0.3:
                    await asyncio.sleep(0)  # let writers interleave

            # Two concurrent writers + the sampler riding one of them.
            await asyncio.gather(
                _churn(s, rng, 120, on_step=sample),
                _churn(s, random.Random(seed + 100), 120, prefix="q"))
            assert len(samples) >= 5
            for rv, i, want in samples:
                got = await s.list("pods", **_list_shapes()[i],
                                   resource_version=rv,
                                   resource_version_match="Exact")
                assert got.resource_version == rv
                assert canon(got.items) == want, (rv, i)
            # Current-RV equality across every shape, too.
            for shape in _list_shapes():
                a = await s.list("pods", **shape)
                b = await s.list_direct("pods", **shape)
                assert canon(a.items) == canon(b.items)
                assert a.resource_version == b.resource_version
            s.stop()
        run(body())

    def test_paging_pinned_to_snapshot_rv(self):
        """Pages of one paginated LIST all serve the FIRST page's
        snapshot, even with writes landing between pages."""
        async def body():
            s = MVCCStore()
            for i in range(7):
                await s.create("pods", {
                    "metadata": {"name": f"p{i}", "namespace": "default"},
                    "spec": {}})
            baseline = await s.list_direct("pods")
            page = await s.list("pods", limit=3)
            rv0 = page.resource_version
            assert page.cont and page.cont.startswith(f"{rv0}:")
            pages = list(page.items)
            cont = page.cont
            k = 0
            while cont:
                # Writes between pages: must NOT leak into the snapshot.
                await s.create("pods", {
                    "metadata": {"name": f"late{k}",
                                 "namespace": "default"}, "spec": {}})
                await s.delete("pods", "default/p0") if k == 0 else None
                k += 1
                nxt = await s.list("pods", limit=3, continue_key=cont)
                assert nxt.resource_version == rv0
                pages.extend(nxt.items)
                cont = nxt.cont
                if not nxt.items:
                    break
            assert canon(pages) == canon(baseline.items)
            s.stop()
        run(body())


def _oracle_replay(store: MVCCStore, shape: dict, after_rv: int):
    """The expected backfill: the linear predicate scan over the store's
    recorded history (the pre-cacher algorithm, verbatim — same oracle
    as tests/test_watch_index.py)."""
    from kubernetes_tpu.api.meta import namespace_of
    from kubernetes_tpu.store.mvcc import _WatchChannel
    chan = _WatchChannel(
        queue=None, resource="pods", namespace=shape.get("namespace"),
        selector=shape.get("selector"), fields=shape.get("fields"))
    out = []
    for res, ev in store._events:
        if res != "pods" or ev.rv <= after_rv:
            continue
        if chan.namespace and namespace_of(ev.object) != chan.namespace:
            continue
        selected = MVCCStore._select_for(ev, chan)
        if selected is not None:
            out.append(selected)
    return out


class TestBackfillDifferential:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_ring_vs_store_replay_sequences(self, seed):
        """Backfill served from the per-resource ring must be the exact
        event sequence the store's global-history scan replays, for every
        watcher shape (selector/field synthesis included)."""
        async def body():
            rng = random.Random(seed)
            s = MVCCStore()
            await s.list("pods")  # ring covers from rv 0
            rvs = []

            async def mark(step):
                if rng.random() < 0.1:
                    rvs.append(s.resource_version)

            await _churn(s, rng, 150, on_step=mark)
            shapes = [
                {},
                {"namespace": "ns1"},
                {"selector": parse_selector("app=web")},
                {"fields": {"spec.nodeName": "n1"}},
                {"fields": {"spec.untracked": "x"}},
            ]
            assert rvs
            for rv in rvs[:6]:
                for shape in shapes:
                    want = _oracle_replay(s, shape, rv)
                    for opener in (s.watch, s.watch_direct):
                        gen = await opener("pods", resource_version=rv,
                                           **shape)
                        got = await take(gen, len(want)) if want else []
                        if not want:
                            await gen.aclose()
                        assert fingerprint(got) == fingerprint(want), \
                            (opener.__name__, rv, shape)
            s.stop()
        run(body())


class TestBookmarksAndExpiry:
    def test_bookmark_rvs_monotonic_and_progress(self, monkeypatch):
        async def body():
            from kubernetes_tpu.store import mvcc
            monkeypatch.setattr(mvcc, "BOOKMARK_INTERVAL_S", 0.03)
            s = MVCCStore()
            gen = await s.watch("pods")
            marks = []

            async def consume():
                async for ev in gen:
                    if ev.type == "BOOKMARK":
                        marks.append(ev.rv)
                        if len(marks) >= 3:
                            return

            task = asyncio.ensure_future(consume())
            for i in range(5):
                await s.create("pods", {
                    "metadata": {"name": f"p{i}", "namespace": "default"},
                    "spec": {}})
                await asyncio.sleep(0.03)
            await asyncio.wait_for(task, 3.0)
            assert marks == sorted(marks)
            assert marks[-1] >= 1  # carries real store progress
            assert marks[-1] <= s.resource_version
            s.stop()
        run(body())

    def test_future_rv_expires_on_both_watch_paths(self):
        """An RV ahead of the store (a client that outlived an
        RV-resetting restart) must 410 into a relist on BOTH paths —
        silently resuming would drop every event until the new counter
        caught up to the stale RV."""
        async def body():
            s = MVCCStore()
            await s.create("pods", {
                "metadata": {"name": "p0", "namespace": "default"},
                "spec": {}})
            with pytest.raises(Expired):
                await s.watch("pods", resource_version=999)
            with pytest.raises(Expired):
                await s.watch_direct("pods", resource_version=999)
            s.stop()
        run(body())

    def test_ring_overflow_too_old_parity(self):
        """When the retained window is exceeded, BOTH paths 410 — the
        cacher must not resurrect RVs the store has compacted."""
        async def body():
            s = MVCCStore(event_window=6)
            await s.list("pods")  # cache alive from rv 0
            for i in range(30):
                await s.create("pods", {
                    "metadata": {"name": f"p{i}", "namespace": "default"},
                    "spec": {}})
            with pytest.raises(Expired):
                await s.watch_direct("pods", resource_version=2)
            with pytest.raises(Expired):
                await s.watch("pods", resource_version=2)
            with pytest.raises(Expired):
                await s.list("pods", resource_version=2,
                             resource_version_match="Exact")
            # Recent RVs (inside the ring) still serve.
            recent = s.resource_version - 2
            got = await s.list("pods", resource_version=recent,
                               resource_version_match="Exact")
            assert got.resource_version == recent
            assert len(got.items) == 28
            s.stop()
        run(body())


class TestCrossWireParity:
    def test_http_and_ktpu_pages_pin_one_snapshot_rv(self):
        """Satellite: the two wires must agree on the snapshot RV across
        pages of a paginated LIST, with writes landing between pages."""
        async def body():
            from kubernetes_tpu.apiserver.client import RemoteStore
            from kubernetes_tpu.apiserver.server import APIServer
            from kubernetes_tpu.apiserver.wire import WireServer, WireStore
            s = MVCCStore()
            for i in range(6):
                await s.create("pods", {
                    "metadata": {"name": f"p{i}", "namespace": "default"},
                    "spec": {}})
            api = APIServer(s)
            await api.start()
            wire = WireServer.for_apiserver(api, host="unix:")
            await wire.start()
            http = RemoteStore(api.url)
            ktpu = WireStore(wire.target)
            try:
                h1 = await http.list("pods", limit=4)
                k1 = await ktpu.list("pods", limit=4)
                assert h1.resource_version == k1.resource_version
                # Writes land between pages on both wires.
                for i in range(3):
                    await s.create("pods", {
                        "metadata": {"name": f"late{i}",
                                     "namespace": "default"}, "spec": {}})
                h2 = await http.list("pods", continue_key=h1.cont)
                k2 = await ktpu.list("pods", continue_key=k1.cont)
                # Page 2 stays pinned to page 1's snapshot on BOTH wires:
                # the late* pods are invisible, the RV is page 1's.
                assert h2.resource_version == h1.resource_version
                assert k2.resource_version == k1.resource_version
                names_h = [p["metadata"]["name"]
                           for p in h1.items + h2.items]
                names_k = [p["metadata"]["name"]
                           for p in k1.items + k2.items]
                assert names_h == names_k == [f"p{i}" for i in range(6)]
            finally:
                await ktpu.close()
                await http.close()
                await wire.stop()
                await api.stop()
                s.stop()
        run(body())

    def test_grpc_exact_rv_via_pinned_token(self):
        """gRPC needs no proto change: '<rv>:' continue tokens give it
        the same exact-RV snapshot reads as the other wires."""
        async def body():
            from kubernetes_tpu.apiserver.grpc_server import (
                GRPCAPIServer,
                GRPCRemoteStore,
            )
            s = MVCCStore()
            for i in range(4):
                await s.create("pods", {
                    "metadata": {"name": f"p{i}", "namespace": "default"},
                    "spec": {}})
            rv0 = s.resource_version
            for i in range(3):
                await s.create("pods", {
                    "metadata": {"name": f"late{i}",
                                 "namespace": "default"}, "spec": {}})
            srv = GRPCAPIServer(s)
            await srv.start()
            rs = GRPCRemoteStore(srv.target)
            try:
                lst = await rs.list("pods", resource_version=rv0,
                                    resource_version_match="Exact")
                assert lst.resource_version == rv0
                assert [p["metadata"]["name"] for p in lst.items] == \
                    [f"p{i}" for i in range(4)]
                # Pinned pagination: the client-rebuilt token resumes at
                # the same snapshot.
                page = await rs.list("pods", limit=2,
                                     resource_version=rv0,
                                     resource_version_match="Exact")
                rest = await rs.list("pods", continue_key=page.cont)
                assert rest.resource_version == rv0
                assert [p["metadata"]["name"]
                        for p in page.items + rest.items] == \
                    [f"p{i}" for i in range(4)]
            finally:
                await rs.close()
                await srv.stop()
                s.stop()
        run(body())
