"""ktpuctl CLI (SURVEY §2.7): get/describe/apply/delete/scale/cordon/
drain/top against the in-process store AND over the HTTP apiserver."""

import asyncio
import io

import yaml

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.cli.kubectl import build_parser, run_command
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def run(coro):
    return asyncio.run(coro)


async def _cli(store, *argv):
    args = build_parser().parse_args(list(argv))
    out = io.StringIO()
    rc = await run_command(store, args, out)
    return rc, out.getvalue()


async def seeded_store():
    store = new_cluster_store()
    install_core_validation(store)
    for i in range(2):
        await store.create("nodes", make_node(f"n{i}"))
    await store.create("pods", make_pod(
        "web-1", labels={"app": "web"}, node_name="n0",
        requests={"cpu": "500m", "memory": "1Gi"}, phase="Running"))
    await store.create("pods", make_pod("pending-1"))
    return store


class TestGetDescribe:
    def test_get_pods_table(self):
        async def body():
            store = await seeded_store()
            rc, out = await _cli(store, "get", "pods")
            assert rc == 0
            assert "web-1" in out and "Running" in out and "n0" in out
            assert "pending-1" in out and "<none>" in out
            store.stop()
        run(body())

    def test_get_with_selector_and_yaml(self):
        async def body():
            store = await seeded_store()
            rc, out = await _cli(store, "get", "pods", "-l", "app=web",
                                 "-o", "yaml")
            assert rc == 0
            docs = yaml.safe_load(out)
            assert [i["metadata"]["name"] for i in docs["items"]] == ["web-1"]
            store.stop()
        run(body())

    def test_get_nodes_and_aliases(self):
        async def body():
            store = await seeded_store()
            rc, out = await _cli(store, "get", "no")
            assert rc == 0 and "Ready" in out
            store.stop()
        run(body())

    def test_describe_includes_events(self):
        async def body():
            store = await seeded_store()
            await store.create("events", {
                "kind": "Event", "metadata": {"name": "e1",
                                              "namespace": "default"},
                "involvedObject": {"kind": "Pod", "name": "web-1"},
                "type": "Normal", "reason": "Scheduled",
                "message": "assigned"})
            rc, out = await _cli(store, "describe", "pods", "web-1")
            assert rc == 0
            assert "web-1" in out and "Scheduled" in out
            store.stop()
        run(body())


class TestApplyScaleDelete:
    def test_apply_create_then_configure(self, tmp_path):
        async def body():
            store = await seeded_store()
            manifest = tmp_path / "m.yaml"
            manifest.write_text(yaml.safe_dump_all([
                {"apiVersion": "apps/v1", "kind": "Deployment",
                 "metadata": {"name": "d"},
                 "spec": {"replicas": 2,
                          "selector": {"matchLabels": {"app": "d"}},
                          "template": {
                              "metadata": {"labels": {"app": "d"}},
                              "spec": {"containers": [
                                  {"name": "c", "image": "x:1"}]}}}}]))
            rc, out = await _cli(store, "apply", "-f", str(manifest))
            assert rc == 0 and "created" in out
            # Mutate + re-apply → configured, replicas updated.
            text = manifest.read_text().replace("replicas: 2", "replicas: 5")
            manifest.write_text(text)
            rc, out = await _cli(store, "apply", "-f", str(manifest))
            assert rc == 0 and "configured" in out
            d = await store.get("deployments", "default/d")
            assert d["spec"]["replicas"] == 5
            store.stop()
        run(body())

    def test_scale_and_delete(self):
        async def body():
            store = await seeded_store()
            await store.create("deployments", {
                "apiVersion": "apps/v1", "kind": "Deployment",
                "metadata": {"name": "d", "namespace": "default"},
                "spec": {"replicas": 1}})
            rc, _ = await _cli(store, "scale", "deploy", "d",
                               "--replicas", "4")
            assert rc == 0
            d = await store.get("deployments", "default/d")
            assert d["spec"]["replicas"] == 4
            rc, out = await _cli(store, "delete", "deployments", "d")
            assert rc == 0 and "deleted" in out
            store.stop()
        run(body())


class TestNodeOps:
    def test_cordon_drain_uncordon(self):
        async def body():
            store = await seeded_store()
            ds_pod = make_pod("ds-pod", node_name="n0")
            ds_pod["metadata"]["ownerReferences"] = [
                {"kind": "DaemonSet", "name": "ds", "uid": "u1",
                 "controller": True}]
            await store.create("pods", ds_pod)
            rc, out = await _cli(store, "drain", "n0")
            assert rc == 0
            node = await store.get("nodes", "n0")
            assert node["spec"]["unschedulable"] is True
            pods = {p["metadata"]["name"]
                    for p in (await store.list("pods")).items}
            assert "web-1" not in pods        # evicted
            assert "ds-pod" in pods           # DaemonSet-owned kept
            rc, _ = await _cli(store, "uncordon", "n0")
            node = await store.get("nodes", "n0")
            assert "unschedulable" not in node["spec"]
            store.stop()
        run(body())

    def test_top_nodes(self):
        async def body():
            store = await seeded_store()
            rc, out = await _cli(store, "top", "nodes")
            assert rc == 0
            assert "n0" in out and "CPU" in out and "%" in out
            store.stop()
        run(body())


class TestOverHTTP:
    def test_cli_through_apiserver(self):
        """The same verbs work across the wire (RemoteStore)."""
        async def body():
            from kubernetes_tpu.apiserver.client import RemoteStore
            from kubernetes_tpu.apiserver.server import APIServer
            store = await seeded_store()
            srv = APIServer(store)
            await srv.start()
            rs = RemoteStore(srv.url)
            rc, out = await _cli(rs, "get", "pods")
            assert rc == 0 and "web-1" in out
            rc, _ = await _cli(rs, "cordon", "n1")
            node = await store.get("nodes", "n1")
            assert node["spec"]["unschedulable"] is True
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())


class TestCreatePatch:
    def test_create_f_over_http(self, tmp_path):
        """kubectl create -f against the LIVE HTTP server: created once,
        AlreadyExists on repeat (create is not apply)."""
        async def body():
            from kubernetes_tpu.apiserver.client import RemoteStore
            from kubernetes_tpu.apiserver.server import APIServer
            store = new_cluster_store()
            install_core_validation(store)
            srv = APIServer(store)
            await srv.start()
            rs = RemoteStore(srv.url)
            manifest = tmp_path / "p.yaml"
            manifest.write_text(yaml.safe_dump(
                {"apiVersion": "v1", "kind": "Pod",
                 "metadata": {"name": "made"},
                 "spec": {"containers": [{"name": "c", "image": "x"}]}}))
            rc, out = await _cli(rs, "create", "-f", str(manifest))
            assert rc == 0 and "pods/made created" in out
            assert (await store.get("pods", "default/made"))
            rc, _ = await _cli(rs, "create", "-f", str(manifest))
            assert rc == 1  # AlreadyExists → error, unlike apply
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())

    def test_patch_strategic_merge_over_http_flows_policy_chain(self):
        """kubectl patch over the live server: strategic merge on the
        server side, and the merged result runs the expression-policy
        admission chain — a patch that violates a policy is rejected."""
        async def body():
            from kubernetes_tpu.api.types import (
                make_validating_admission_policy,
                make_vap_binding,
            )
            from kubernetes_tpu.apiserver.admission import (
                WebhookAdmission,
            )
            from kubernetes_tpu.apiserver.client import RemoteStore
            from kubernetes_tpu.apiserver.server import APIServer
            from kubernetes_tpu.policy import PolicyEngine
            store = new_cluster_store()
            install_core_validation(store)
            adm = WebhookAdmission(store,
                                   policy_engine=PolicyEngine(store))
            srv = APIServer(store, admission=adm)
            await srv.start()
            rs = RemoteStore(srv.url)
            await store.create("pods", make_pod(
                "web", labels={"app": "web"}))
            # Strategic merge: containers merge by name, labels merge.
            rc, out = await _cli(
                rs, "patch", "pods", "web", "-p",
                '{"metadata": {"labels": {"tier": "fe"}},'
                ' "spec": {"containers":'
                ' [{"name": "main", "image": "app:2"}]}}')
            assert rc == 0 and "patched" in out
            got = await store.get("pods", "default/web")
            assert got["metadata"]["labels"] == {"app": "web",
                                                 "tier": "fe"}
            assert [c["image"] for c in got["spec"]["containers"]] == \
                ["app:2"]
            # A policy forbidding priority>100 rejects a violating patch.
            await store.create(
                "validatingadmissionpolicies",
                make_validating_admission_policy("prio-cap", [
                    {"expression": "not has(object.spec.priority) or "
                                   "object.spec.priority <= 100",
                     "message": "priority capped at 100"}]))
            await store.create("validatingadmissionpolicybindings",
                               make_vap_binding("prio-cap-b", "prio-cap"))
            rc, _ = await _cli(rs, "patch", "pods", "web", "-p",
                               '{"spec": {"priority": 10000}}')
            assert rc == 1
            got = await store.get("pods", "default/web")
            assert "priority" not in got["spec"]
            rc, _ = await _cli(rs, "patch", "pods", "web", "-p",
                               '{"spec": {"priority": 50}}')
            assert rc == 0
            assert (await store.get(
                "pods", "default/web"))["spec"]["priority"] == 50
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())

    def test_patch_in_process_fallback(self):
        async def body():
            store = await seeded_store()
            rc, out = await _cli(
                store, "patch", "pods", "web-1", "-p",
                '{"metadata": {"labels": {"x": "1"}}}')
            assert rc == 0 and "patched" in out
            got = await store.get("pods", "default/web-1")
            assert got["metadata"]["labels"]["x"] == "1"
            # merge type: lists replace wholesale.
            rc, _ = await _cli(
                store, "patch", "pods", "web-1", "--type", "merge",
                "-p", '{"spec": {"containers": [{"name": "only",'
                      ' "image": "y"}]}}')
            assert rc == 0
            got = await store.get("pods", "default/web-1")
            assert [c["name"] for c in got["spec"]["containers"]] == \
                ["only"]
            store.stop()
        run(body())


class TestRolloutAndTop:
    def test_rollout_status_restart_history(self):
        async def body():
            import io

            from kubernetes_tpu.cli.kubectl import build_parser, run_command
            from kubernetes_tpu.client import InformerFactory
            from kubernetes_tpu.controllers import (
                ControllerManager,
                DeploymentController,
                ReplicaSetController,
                make_deployment,
            )
            store = new_cluster_store()
            install_core_validation(store)
            await store.create("deployments", make_deployment(
                "web", 2, {"matchLabels": {"app": "web"}},
                {"metadata": {"labels": {"app": "web"}},
                 "spec": {"containers": [
                     {"name": "main", "image": "app"}]}}))
            mgr = ControllerManager(store, [
                DeploymentController(store), ReplicaSetController(store)])
            await mgr.start()

            async def rollout(*argv):
                out = io.StringIO()
                args = build_parser().parse_args(["rollout", *argv])
                rc = await run_command(store, args, out)
                return rc, out.getvalue()

            # bind pods (scheduler-sim; readyReplicas counts bound
            # pods), then wait for the controller to report the rollout
            await store.create("nodes", make_node("n0"))

            async def bind_all():
                from kubernetes_tpu.api.meta import namespaced_name
                for p in (await store.list("pods")).items:
                    if not p["spec"].get("nodeName"):
                        try:
                            await store.subresource(
                                "pods", namespaced_name(p), "binding",
                                {"target": {"kind": "Node",
                                            "name": "n0"}})
                        except Exception:
                            pass
            for _ in range(300):
                await bind_all()
                rc, text = await rollout("status", "deployment", "web")
                if rc == 0:
                    break
                await asyncio.sleep(0.02)
            assert rc == 0 and "successfully rolled out" in text
            rc, text = await rollout("restart", "deployment", "web")
            assert rc == 0 and "restarted" in text
            dep = await store.get("deployments", "default/web")
            assert dep["spec"]["template"]["metadata"]["annotations"][
                "kubectl.kubernetes.io/restartedAt"]
            rc, text = await rollout("history", "deployment", "web")
            assert rc == 0 and "REVISION" in text
            await mgr.stop()
            store.stop()
        run(body())

    def test_top_pods(self):
        async def body():
            import io

            from kubernetes_tpu.cli.kubectl import build_parser, run_command
            store = new_cluster_store()
            install_core_validation(store)
            await store.create("pods", make_pod(
                "busy", requests={"cpu": "500m", "memory": "1Gi"},
                node_name="n0"))
            out = io.StringIO()
            args = build_parser().parse_args(["top", "pods"])
            rc = await run_command(store, args, out)
            assert rc == 0
            text = out.getvalue()
            assert "busy" in text and "500m" in text and "n0" in text
            store.stop()
        run(body())


class TestLogsDiff:
    """kubectl logs (agent-recorded status read path) + kubectl diff
    (local vs server through the dry-run admission chain) — SURVEY §2.7
    carryovers."""

    def test_logs_reads_agent_recorded_status(self):
        async def body():
            store = await seeded_store()
            # The hollow kubelet's status writes: phase/podIP/conditions
            # (agent/agent.py _mark_running) are the log's source.
            def mark(p):
                p["status"].update({
                    "podIP": "10.20.0.1",
                    "conditions": [{"type": "Ready", "status": "True"}]})
                return p
            await store.guaranteed_update("pods", "default/web-1", mark)
            await store.create("events", {
                "kind": "Event", "metadata": {"name": "ev-log",
                                              "namespace": "default"},
                "involvedObject": {"kind": "Pod", "name": "web-1",
                                   "namespace": "default"},
                "type": "Normal", "reason": "Scheduled",
                "message": "assigned default/web-1 to n0"})
            rc, out = await _cli(store, "logs", "web-1")
            assert rc == 0
            assert "scheduled to node n0" in out
            assert "podIP 10.20.0.1" in out
            assert "condition Ready=True" in out
            assert "phase Running" in out
            assert "event Normal Scheduled" in out
            store.stop()
        run(body())

    def test_logs_missing_pod_errors(self):
        async def body():
            store = await seeded_store()
            rc, _ = await _cli(store, "logs", "nope")
            assert rc == 1
            store.stop()
        run(body())

    def test_diff_in_process(self):
        async def body(tmp_path):
            store = await seeded_store()
            live = await store.get("pods", "default/web-1")
            # Identical manifest (the live object itself) → no diff.
            same = tmp_path / "same.yaml"
            same.write_text(yaml.safe_dump(live))
            rc, out = await _cli(store, "diff", "-f", str(same))
            assert rc == 0 and out == ""
            # A label change → unified diff, rc 1, nothing persisted.
            changed = dict(live, metadata={**live["metadata"],
                                           "labels": {"app": "web2"}})
            mod = tmp_path / "mod.yaml"
            mod.write_text(yaml.safe_dump(changed))
            rc, out = await _cli(store, "diff", "-f", str(mod))
            assert rc == 1
            assert "-    app: web" in out and "+    app: web2" in out
            still = await store.get("pods", "default/web-1")
            assert still["metadata"]["labels"] == {"app": "web"}
            store.stop()

        import tempfile
        from pathlib import Path
        with tempfile.TemporaryDirectory() as d:
            run(body(Path(d)))

    def test_diff_through_dry_run_admission_chain(self):
        """Against a live server the desired state flows through
        ?dryRun=All — the FULL expression-policy admission chain runs,
        nothing persists (RV unchanged), and a policy that rejects the
        desired state fails the diff with rc 2."""
        async def body(tmp_path):
            from kubernetes_tpu.api.types import (
                make_validating_admission_policy,
                make_vap_binding,
            )
            from kubernetes_tpu.apiserver.admission import (
                WebhookAdmission,
            )
            from kubernetes_tpu.apiserver.client import RemoteStore
            from kubernetes_tpu.apiserver.server import APIServer
            from kubernetes_tpu.policy import PolicyEngine
            store = new_cluster_store()
            install_core_validation(store)
            adm = WebhookAdmission(store,
                                   policy_engine=PolicyEngine(store))
            srv = APIServer(store, admission=adm)
            await srv.start()
            rs = RemoteStore(srv.url)
            await rs.create("pods", make_pod("web", labels={"app": "web"}))
            live = await store.get("pods", "default/web")
            rv0 = live["metadata"]["resourceVersion"]
            changed = dict(live, metadata={**live["metadata"],
                                           "labels": {"app": "web",
                                                      "tier": "fe"}})
            mod = tmp_path / "mod.yaml"
            mod.write_text(yaml.safe_dump(changed))
            rc, out = await _cli(rs, "diff", "-f", str(mod))
            assert rc == 1
            assert "+    tier: fe" in out
            # Dry run: the server persisted NOTHING.
            after = await store.get("pods", "default/web")
            assert after["metadata"]["resourceVersion"] == rv0
            assert "tier" not in after["metadata"]["labels"]
            # A policy rejecting the desired state fails the diff.
            await store.create(
                "validatingadmissionpolicies",
                make_validating_admission_policy("no-tier", [
                    {"expression":
                     "not has(object.metadata.labels) or "
                     "not ('tier' in object.metadata.labels)",
                     "message": "tier label forbidden"}]))
            await store.create("validatingadmissionpolicybindings",
                               make_vap_binding("no-tier-b", "no-tier"))
            rc, _ = await _cli(rs, "diff", "-f", str(mod))
            assert rc == 2
            # Store-level validation runs on the dry-run path too: an
            # unpersistable manifest (bad resource quantity) must fail
            # the diff (rc 2), not diff clean and fail at apply time.
            bad = dict(live)
            bad["spec"] = {**live["spec"], "containers": [
                {"name": "main", "image": "app",
                 "resources": {"requests": {"cpu": "not-a-cpu"}}}]}
            badf = tmp_path / "bad.yaml"
            badf.write_text(yaml.safe_dump(bad))
            rc, _ = await _cli(rs, "diff", "-f", str(badf))
            assert rc == 2
            await rs.close()
            await srv.stop()
            store.stop()

        import tempfile
        from pathlib import Path
        with tempfile.TemporaryDirectory() as d:
            run(body(Path(d)))
