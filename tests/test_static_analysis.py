"""ktpu-lint (kubernetes_tpu/analysis): seeded-violation fixtures per
pass, baseline round-trip, CLI exit codes, and the tier-1 gate.

The fixtures are the pass's own differential tests: each plants one
violation per finding code in a temp tree shaped like the repo and
asserts the pass catches exactly it. The gate then asserts the REAL
tree is clean (zero unsuppressed findings against the checked-in
baseline) — the invariant every future PR inherits.
"""

import json
import os
import textwrap

import pytest

from kubernetes_tpu.analysis import run_all
from kubernetes_tpu.analysis.engine import (
    Module,
    apply_baseline,
    load_baseline,
)
from kubernetes_tpu.analysis import (
    flags_pass,
    jit_purity,
    locks,
    metrics_lint,
)


def _module(tmp_path, rel, source) -> Module:
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return Module.load(str(path), str(tmp_path))


# ---------------------------------------------------------------------------
# pass 1: jit-purity
# ---------------------------------------------------------------------------

class TestJitPurity:
    FIXTURE = """
        import time
        import jax
        import jax.numpy as jnp
        import numpy as np
        from functools import partial
        from jax import lax


        @jax.jit
        def bad(x):
            v = float(jnp.max(x))          # JP103: cast concretizes
            y = np.asarray(x)              # JP101: host materialization
            t = time.time()                # JP102: frozen at trace time
            if jnp.any(x > 0):             # JP103: python branch
                return x
            return helper(x)

        def helper(x):
            return x.item()                # JP101, via the call graph

        def scan_user(xs):
            def step(carry, x):
                print(carry)               # JP102 inside a scan body
                return carry, x
            return lax.scan(step, 0, xs)

        def host_driver(x):
            # NOT jit-reachable: no decorator, nothing hands it to a
            # trace wrapper — host syncs here are sanctioned.
            return np.asarray(x)
    """

    def test_seeded_violations_caught(self, tmp_path):
        mod = _module(tmp_path, "kubernetes_tpu/ops/solver.py",
                      self.FIXTURE)
        found = jit_purity.run([mod])
        codes = sorted((f.code, f.symbol.split(":")[0]) for f in found)
        assert ("JP101", "bad") in codes            # np.asarray
        assert ("JP101", "helper") in codes         # .item() via graph
        assert ("JP102", "bad") in codes            # time.time
        assert ("JP102", "scan_user.step") in codes  # print in scan body
        jp103 = [s for c, s in codes if c == "JP103"]
        assert "bad" in jp103                       # float() and/or if
        assert sum(1 for c, s in codes if s == "bad" and c == "JP103") == 2

    def test_host_driver_not_flagged(self, tmp_path):
        mod = _module(tmp_path, "kubernetes_tpu/ops/solver.py",
                      self.FIXTURE)
        found = jit_purity.run([mod])
        assert not any(f.symbol.startswith("host_driver") for f in found)

    def test_clean_kernel_passes(self, tmp_path):
        mod = _module(tmp_path, "kubernetes_tpu/ops/kernels.py", """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def clean(x, y):
                m = jnp.where(x > 0, x, y)
                n = int(x.shape[0])   # shape math is static — legal
                return m * n
        """)
        assert jit_purity.run([mod]) == []


# ---------------------------------------------------------------------------
# pass 2: lock discipline
# ---------------------------------------------------------------------------

class TestLockDiscipline:
    FIXTURE = """
        import asyncio
        import threading

        import numpy as np


        class Inverted:
            def __init__(self):
                self._lock_a = threading.Lock()
                self._lock_b = threading.Lock()

            def ab(self):
                with self._lock_a:
                    with self._lock_b:
                        return 1

            def ba(self):
                with self._lock_b:
                    with self._lock_a:   # LK201: closes the cycle
                        return 2


        class HeldAcross:
            def __init__(self):
                self._lock = threading.Lock()
                self._values = {}

            def fetch(self):
                with self._lock:
                    return np.asarray([1.0])      # LK203

            async def sleepy(self):
                with self._lock:
                    await asyncio.sleep(0.1)      # LK202

            def send(self, sock):
                with self._lock:
                    sock.sendall(b"x")            # LK204

            def rotate(self, path):
                with self._lock:
                    with open(path, "a") as f:    # LK206
                        f.write("x")

            def shuffle(self, path):
                with self._lock:
                    import os
                    os.replace(path, path + ".1")  # LK206

            def one_statement(self, path):
                with self._lock, open(path, "a") as f:  # LK206 too
                    f.write("x")

            def rotate_outside(self, path):
                segment = None
                with self._lock:
                    segment = dict(self._values)
                with open(path, "a") as f:        # clean: lock released
                    f.write(str(segment))

            def write(self, k):
                with self._lock:
                    self._values[k] = 1

            def render(self):
                return sorted(self._values.items())   # LK205


        class CondOk:
            def __init__(self):
                self._cond = asyncio.Condition()
                self._items = []

            async def wait(self):
                async with self._cond:
                    await self._cond.wait()       # sanctioned
                    await asyncio.wait_for(self._cond.wait_for(
                        lambda: self._items), 1.0)  # sanctioned, wrapped
                    return list(self._items)


        class ProcSpawner:
            def __init__(self):
                self._lock = threading.Lock()
                self._proc = None
                self._workers = []

            def spawn(self):
                import subprocess
                with self._lock:
                    subprocess.run(["true"])      # LK207: exec under lock

            def boot(self):
                import multiprocessing
                with self._lock:
                    self._proc = multiprocessing.Process(  # LK207
                        target=print)
                    self._proc.start()            # LK207: proc receiver

            def reap(self):
                with self._lock:
                    self._workers[0].join()       # LK207: subscripted

            def tag(self, parts):
                with self._lock:
                    return ",".join(parts)        # clean: not a process

            def reap_outside(self, proc):
                with self._lock:
                    alive = bool(self._proc)
                proc.join()                       # clean: lock released
                return alive
    """

    def _run(self, tmp_path):
        mod = _module(tmp_path, "kubernetes_tpu/store/fixture.py",
                      self.FIXTURE)
        return locks.run([mod])

    def test_cycle_detected(self, tmp_path):
        found = self._run(tmp_path)
        assert any(f.code == "LK201" for f in found)

    def test_held_across_hazards(self, tmp_path):
        codes = {f.code: f for f in self._run(tmp_path)}
        assert "LK202" in codes     # await under a threading lock
        assert "LK203" in codes     # device fetch under a lock
        assert "LK204" in codes     # wire send under a lock

    def test_file_io_under_lock(self, tmp_path):
        """LK206 (ISSUE 15, the audit sink workers): open()/os.replace
        under a held lock flagged — in `with open(...)` context-expr
        form and the bare-call form — while I/O after the lock is
        released stays clean."""
        found = self._run(tmp_path)
        lk206 = [f for f in found if f.code == "LK206"]
        assert {f.symbol for f in lk206} == {
            "HeldAcross.rotate:open", "HeldAcross.shuffle:os.replace",
            "HeldAcross.one_statement:open"}
        assert not any("rotate_outside" in f.symbol for f in found)

    def test_unlocked_iteration_of_guarded_state(self, tmp_path):
        found = self._run(tmp_path)
        lk205 = [f for f in found if f.code == "LK205"]
        assert len(lk205) == 1
        assert "_values" in lk205[0].symbol

    def test_condition_wait_is_sanctioned(self, tmp_path):
        found = self._run(tmp_path)
        assert not any("CondOk" in f.symbol for f in found)

    def test_process_spawn_join_under_lock(self, tmp_path):
        """LK207 (ISSUE r22, the multiproc supervisor): spawning an OS
        process or joining one while holding a lock is flagged —
        interpreter boot is ~100s of ms, a join unbounded — while
        `",".join(...)` under a lock and a process join after release
        stay clean."""
        found = self._run(tmp_path)
        lk207 = [f for f in found if f.code == "LK207"]
        assert {f.symbol.split(":")[0] for f in lk207} == {
            "ProcSpawner.spawn", "ProcSpawner.boot", "ProcSpawner.reap"}
        assert len(lk207) == 4          # boot: Process(...) AND .start()
        assert not any(f.symbol.startswith(("ProcSpawner.tag",
                                            "ProcSpawner.reap_outside"))
                       for f in found)


# ---------------------------------------------------------------------------
# pass 3: flag registry
# ---------------------------------------------------------------------------

class TestFlagRegistry:
    def test_unrouted_read_and_unknown_flag(self, tmp_path):
        mod = _module(tmp_path, "kubernetes_tpu/ops/fixture.py", """
            import os

            def bad():
                a = os.environ.get("KTPU_SERVING", "1")     # FL301
                b = os.environ["KTPU_BOGUS_FLAG"]           # FL301+FL302
                c = os.getenv("KTPU_CLASS_PAD")             # FL301
                os.environ["KTPU_SERVING"] = "0"            # write: legal
                os.environ.pop("KTPU_SERVING", None)        # write: legal
                return a, b, c
        """)
        found = flags_pass.run([mod], root=str(tmp_path))
        fl301 = sorted(f.symbol for f in found if f.code == "FL301")
        assert fl301 == ["KTPU_BOGUS_FLAG", "KTPU_CLASS_PAD",
                         "KTPU_SERVING"]
        assert [f.symbol for f in found if f.code == "FL302"] \
            == ["KTPU_BOGUS_FLAG"]

    def test_registry_reads_are_exempt(self, tmp_path):
        mod = _module(tmp_path, "kubernetes_tpu/utils/flags.py", """
            import os

            def read(name):
                return os.environ.get(name) or os.environ.get("KTPU_X")
        """)
        found = flags_pass.run([mod], root=str(tmp_path))
        assert not any(f.code == "FL301" for f in found)

    def test_registry_contract(self):
        """Every flag: registered, documented, expected default — and
        NAMED here, which is what the FL304 'every flag has a test'
        check greps for: KTPU_SERVING, KTPU_CLASS_PLANES,
        KTPU_WAVEFRONT, KTPU_PALLAS, KTPU_WAVE_WIDTH, KTPU_SOLVE_MODE,
        KTPU_SINKHORN_ITERS, KTPU_SINKHORN_TEMP, KTPU_DESCHEDULER,
        KTPU_DESCHEDULER_BUDGET, KTPU_TOPOLOGY, KTPU_MESH_SHAPE,
        KTPU_WATCH_CACHE,
        KTPU_POLICY_INDEX, KTPU_SHARDS,
        KTPU_SHARD_THRESHOLD, KTPU_CLASS_PAD, KTPU_PIPELINE_DEPTH,
        KTPU_SHORTLIST_K, KTPU_BLOCK_INDEX, KTPU_BLOCK_WIDTH,
        KTPU_ADMISSION_WINDOW,
        KTPU_TRACE_THRESHOLD_MS, KTPU_DATA_DIR, KTPU_LOCK_CHECK,
        KTPU_DEBUG_FREEZE, KTPU_TEST_PLATFORM, KTPU_PROCESSES,
        KTPU_WAL, KTPU_WAL_FSYNC, KTPU_LEASE_DURATION."""
        from kubernetes_tpu.utils import flags
        expected_defaults = {
            "KTPU_SERVING": True,
            "KTPU_CLASS_PLANES": True,
            "KTPU_WAVEFRONT": True,
            "KTPU_PALLAS": "auto",
            "KTPU_WAVE_WIDTH": None,
            "KTPU_SOLVE_MODE": "auto",
            "KTPU_SINKHORN_ITERS": 24,
            "KTPU_SINKHORN_TEMP": 0.05,
            "KTPU_DESCHEDULER": False,
            "KTPU_DESCHEDULER_BUDGET": 8,
            "KTPU_TOPOLOGY": True,
            "KTPU_MESH_SHAPE": "auto",
            "KTPU_WATCH_CACHE": True,
            "KTPU_POLICY_INDEX": True,
            "KTPU_SHARDS": None,
            "KTPU_SHARD_THRESHOLD": 100_000,
            "KTPU_PROCESSES": None,
            "KTPU_WAL": True,
            "KTPU_WAL_FSYNC": "batch",
            "KTPU_LEASE_DURATION": 15.0,
            "KTPU_CLASS_PAD": 31,
            "KTPU_PIPELINE_DEPTH": None,
            "KTPU_SHORTLIST_K": None,
            "KTPU_BLOCK_INDEX": True,
            "KTPU_BLOCK_WIDTH": None,
            "KTPU_ADMISSION_WINDOW": None,
            "KTPU_TRACE_THRESHOLD_MS": None,
            "KTPU_DATA_DIR": None,
            "KTPU_LOCK_CHECK": False,
            "KTPU_DEBUG_FREEZE": False,
            "KTPU_TEST_PLATFORM": "cpu",
        }
        assert set(flags.FLAGS) == set(expected_defaults)
        for name, default in expected_defaults.items():
            assert flags.FLAGS[name].default == default, name
            assert flags.FLAGS[name].doc.strip(), name
        kills = {n for n, f in flags.FLAGS.items() if f.kill_switch}
        assert kills == {"KTPU_SERVING", "KTPU_CLASS_PLANES",
                         "KTPU_WAVEFRONT", "KTPU_PALLAS",
                         "KTPU_SOLVE_MODE", "KTPU_TOPOLOGY",
                         "KTPU_WATCH_CACHE",
                         "KTPU_POLICY_INDEX", "KTPU_SHARDS",
                         "KTPU_PROCESSES", "KTPU_WAL",
                         "KTPU_BLOCK_INDEX"}

    def test_parse_behaviors(self, monkeypatch):
        from kubernetes_tpu.utils import flags
        for off in ("0", "false", "False", "FALSE", "off", "no"):
            monkeypatch.setenv("KTPU_SERVING", off)
            assert flags.get("KTPU_SERVING") is False, off
        monkeypatch.setenv("KTPU_SERVING", "1")
        assert flags.get("KTPU_SERVING") is True
        monkeypatch.delenv("KTPU_SERVING")
        assert flags.get("KTPU_SERVING") is True
        # malformed values degrade to the default, never crash
        monkeypatch.setenv("KTPU_CLASS_PAD", "garbage")
        assert flags.get("KTPU_CLASS_PAD") == 31
        monkeypatch.setenv("KTPU_TRACE_THRESHOLD_MS", "not-a-float")
        assert flags.get("KTPU_TRACE_THRESHOLD_MS") is None
        # ms windows clamp negative to 0
        monkeypatch.setenv("KTPU_ADMISSION_WINDOW", "-5")
        assert flags.get("KTPU_ADMISSION_WINDOW") == 0.0
        with pytest.raises(KeyError):
            flags.get("KTPU_NOT_REGISTERED")

    def test_scoped_set_restores(self, monkeypatch):
        from kubernetes_tpu.utils import flags
        monkeypatch.delenv("KTPU_SHARDS", raising=False)
        with flags.scoped_set("KTPU_SHARDS", 4):
            assert flags.get("KTPU_SHARDS") == 4
        assert flags.get("KTPU_SHARDS") is None
        monkeypatch.setenv("KTPU_SHARDS", "2")
        with flags.scoped_set("KTPU_SHARDS", 8):
            assert flags.get("KTPU_SHARDS") == 8
        assert flags.get("KTPU_SHARDS") == 2

    def test_readme_table_in_sync(self):
        """FL305 end to end: the checked-in README matches the render."""
        from kubernetes_tpu.analysis.engine import repo_root
        found = flags_pass.run([], root=repo_root())
        assert not any(f.code == "FL305" for f in found), \
            [f.message for f in found]


# ---------------------------------------------------------------------------
# pass 4: metrics lint
# ---------------------------------------------------------------------------

class TestMetricsLint:
    def test_seeded_violations_caught(self, tmp_path):
        mod = _module(tmp_path, "kubernetes_tpu/metrics/registry.py", """
            class Metrics:
                def __init__(self, r):
                    self.a = r.counter("foo_count", "no _total")
                    self.b = r.gauge("window_ms", "bad unit")
                    self.c = r.histogram("req_duration", "no unit")
                    self.d = r.counter("x_total", "hot label",
                                       labels=("pod",))
                    self.e = r.gauge("ok_gauge_total", "fake counter")
                    self.f = r.histogram(
                        "apiserver_request_duration_seconds", "clean",
                        labels=("verb", "resource", "code"))
        """)
        by_code = {}
        for f in metrics_lint.run([mod]):
            by_code.setdefault(f.code, []).append(f.symbol)
        assert by_code.get("MT402") == ["foo_count"]
        assert by_code.get("MT404") == ["window_ms"]
        assert by_code.get("MT406") == ["req_duration"]
        assert by_code.get("MT405") == ["x_total:pod"]
        assert by_code.get("MT403") == ["ok_gauge_total"]
        clean = "apiserver_request_duration_seconds"
        assert not any(clean in syms
                       for syms in by_code.values() for syms in [syms]
                       if any(clean == s.split(":")[0] for s in syms))

    def test_registrations_outside_registry_scanned(self, tmp_path):
        """ISSUE 15 widened the scan: a counter constructed in
        policy/audit.py (the sink counters) is linted like one in
        metrics/registry.py — a bad name anywhere fails."""
        mod = _module(tmp_path, "kubernetes_tpu/policy/audit.py", """
            class Sink:
                def __init__(self, r):
                    self.drops = r.counter("audit_dropped", "no _total")
        """)
        found = metrics_lint.run([mod])
        assert [f.code for f in found] == ["MT402"]

    def test_real_sink_counters_visible_to_pass(self):
        """Non-vacuity: the pass actually reaches the live audit/vap
        registrations (policy_index_*, audit_webhook_*, rotation) —
        and finds them clean."""
        from kubernetes_tpu.analysis.engine import load_modules
        mods = [m for m in load_modules()
                if m.rel in ("kubernetes_tpu/policy/audit.py",
                             "kubernetes_tpu/policy/vap.py")]
        names = {name for m in mods
                 for _k, name, _l, _ln in metrics_lint._registrations(m)}
        assert {"policy_index_hits_total",
                "policy_index_residue_scans_total",
                "policy_index_rebuilds_total",
                "audit_log_rotations_total",
                "audit_webhook_batches_total",
                "audit_webhook_retries_total"} <= names
        assert metrics_lint.run(mods) == []

    def test_block_index_counters_visible_to_pass(self):
        """Non-vacuity for the ISSUE 20 block-index metrics: the lint
        pass actually reaches the live registrations (the scanned /
        pruned counters the KTPU_BLOCK_INDEX flag gates, plus the
        resident refresh histogram) — and finds them clean. A rename
        that dropped the _total/_seconds suffixes, or a registration
        moved out of the scanned set, fails here instead of silently
        exempting the new names."""
        from kubernetes_tpu.analysis.engine import load_modules
        mods = [m for m in load_modules()
                if m.rel == "kubernetes_tpu/metrics/registry.py"]
        names = {name for m in mods
                 for _k, name, _l, _ln in metrics_lint._registrations(m)}
        assert {"scheduler_tpu_solver_blocks_scanned_total",
                "scheduler_tpu_solver_blocks_pruned_total",
                "scheduler_tpu_solver_block_refresh_seconds"} <= names
        assert metrics_lint.run(mods) == []

    def test_real_registry_would_catch_ms_gauge(self, tmp_path):
        """The r17 defect as a regression fixture: a `_ms` gauge in the
        registry is exactly what the pass exists to reject."""
        mod = _module(tmp_path, "kubernetes_tpu/metrics/registry.py", """
            def build(r):
                return r.gauge(
                    "scheduler_admission_window_ms",
                    "Serving admission coalesce window")
        """)
        found = metrics_lint.run([mod])
        assert [f.code for f in found] == ["MT404"]


# ---------------------------------------------------------------------------
# baseline + CLI + the tier-1 gate
# ---------------------------------------------------------------------------

class TestBaseline:
    def test_round_trip(self, tmp_path):
        mod = _module(tmp_path, "kubernetes_tpu/ops/fixture.py", """
            import os
            def bad():
                return os.environ.get("KTPU_SERVING")
        """)
        found = flags_pass.run([mod], root=str(tmp_path))
        assert len(found) == 1
        baseline_file = tmp_path / "baseline.json"
        baseline_file.write_text(json.dumps({
            "version": 1,
            "suppressions": [{"key": found[0].key,
                              "reason": "fixture: deliberate"}],
        }))
        baseline = load_baseline(str(baseline_file))
        unsup, sup, stale = apply_baseline(found, baseline)
        assert unsup == [] and len(sup) == 1 and stale == []

    def test_stale_suppressions_reported(self):
        unsup, sup, stale = apply_baseline(
            [], {"flag-registry:FL301:gone.py:KTPU_X": "obsolete"})
        assert stale == ["flag-registry:FL301:gone.py:KTPU_X"]

    def test_keys_are_line_stable(self, tmp_path):
        src = """
            import os
            def bad():
                return os.environ.get("KTPU_SERVING")
        """
        m1 = _module(tmp_path, "kubernetes_tpu/ops/fixture.py", src)
        k1 = flags_pass.run([m1], root=str(tmp_path))[0].key
        m2 = _module(tmp_path, "kubernetes_tpu/ops/fixture.py",
                     "\n\n# moved down\n" + textwrap.dedent(src))
        k2 = flags_pass.run([m2], root=str(tmp_path))[0].key
        assert k1 == k2


class TestCLI:
    def test_exit_zero_on_clean_tree(self, capsys):
        from kubernetes_tpu.analysis import main
        assert main([]) == 0
        out = capsys.readouterr().out
        assert "0 unsuppressed" in out

    def test_exit_two_on_internal_error(self, tmp_path, capsys):
        from kubernetes_tpu.analysis import main
        broken = tmp_path / "broken.json"
        broken.write_text("{not json")
        assert main(["--baseline", str(broken)]) == 2

    def test_json_output_schema(self, capsys):
        from kubernetes_tpu.analysis import main
        assert main(["--json"]) == 0
        data = json.loads(capsys.readouterr().out)
        assert set(data) == {"findings", "suppressed",
                             "stale_suppressions", "per_pass"}
        assert set(data["per_pass"]) == {
            "jit-purity", "lock-discipline", "flag-registry",
            "metrics-lint"}


class TestTierOneGate:
    def test_tree_is_clean(self):
        """THE gate: zero unsuppressed findings on the real tree. A new
        finding either gets fixed or goes into analysis/baseline.json
        with a reason string — never ignored."""
        unsup, _sup, stale, per_pass = run_all()
        assert unsup == [], "\n".join(
            f"{f.path}:{f.line}: {f.code} {f.message}" for f in unsup)
        # triage rot guard: the checked-in baseline matches real findings
        assert stale == [], stale

    def test_jit_purity_walked_the_solve_path(self):
        """Anti-vacuity: the purity pass must actually discover the
        solver/kernel entry points — a refactor that silently empties
        the reachable set would make the pass pass forever."""
        from kubernetes_tpu.analysis.engine import (
            FunctionIndex,
            load_modules,
        )
        mods = load_modules()
        entry_mods = [m for m in mods
                      if m.rel.endswith(
                          jit_purity.ENTRY_MODULE_SUFFIXES)]
        indices = {m.rel: FunctionIndex(m) for m in entry_mods}
        entry_map = {rel: jit_purity._entry_functions(idx)
                     for rel, idx in indices.items()}
        assert entry_map["kubernetes_tpu/ops/solver.py"], \
            "no jit entries found in ops/solver.py"
        # The r18 wavefront scans are new jit entry points on the
        # hottest path — discovery must see them as entries...
        solver_entries = entry_map["kubernetes_tpu/ops/solver.py"]
        for fn in ("greedy_assign_rescoring_wave",
                   "multistart_greedy_assign_wave",
                   "greedy_assign_rescoring_spread_wave",
                   "greedy_assign_rescoring_shortlist_wave",
                   "multistart_greedy_assign_shortlist_wave"):
            assert fn in solver_entries, \
                f"wavefront entry {fn} not discovered"
        reach = jit_purity._reachable(indices, entry_map)
        rels = {rel for rel, _ in reach}
        assert "kubernetes_tpu/ops/kernels.py" in rels, \
            "call graph no longer reaches the kernels"
        # ...and the walk must reach the wave-step/replay bodies (new
        # lax.scan / fori_loop callees nested under the entries) in both
        # the single-device and the shard_map solvers — an emptied
        # reachable set here would let host syncs into the wave bodies
        # pass the gate forever.
        solver_reach = {qn for rel, qn in reach
                        if rel == "kubernetes_tpu/ops/solver.py"}
        for qn in ("_rescoring_wave_scan.wave_step",
                   "_rescoring_wave_scan.wave_step.slow.body",
                   "_shortlist_wave_scan.wave_step",
                   "greedy_assign_rescoring_spread_wave.wave_step",
                   "_wave_spec_picks", "_wave_conflicts"):
            assert qn in solver_reach, \
                f"purity walk no longer reaches {qn}"
        # The r20 optimal mode adds the Sinkhorn iteration body (a
        # fori_loop callee under the jitted plan) in both the plain and
        # the shard_map solvers — same anti-vacuity stake: a host sync
        # inside the transport loop must stay visible to the gate.
        assert "sinkhorn_plan" in solver_entries, \
            "sinkhorn_plan not discovered as a jit entry"
        assert "sinkhorn_plan.step" in solver_reach, \
            "purity walk no longer reaches the Sinkhorn iteration body"
        sharded_reach = {qn for rel, qn in reach
                         if rel == "kubernetes_tpu/parallel/sharded.py"}
        assert any(qn.endswith("_wave_body.wave_step")
                   for qn in sharded_reach), \
            "purity walk no longer reaches the sharded wave body"
        assert any(qn.endswith("sink_run.step")
                   for qn in sharded_reach), \
            "purity walk no longer reaches the sharded Sinkhorn body"
        # The r21 fused Pallas kernel: pl.pallas_call is a trace
        # wrapper, so the nested kernel BODIES (the grid-step solve and
        # the shard-local wave eval, including the in-kernel conflict
        # replay fori_loop) are entry points in their own right — a
        # host sync inside a kernel body fails at runtime on real
        # lowering, so it must stay visible to the gate here.
        pallas_entries = entry_map["kubernetes_tpu/ops/pallas_kernel.py"]
        for fn in ("wave_solve._wave_step_kernel",
                   "wave_eval._wave_eval_kernel"):
            assert fn in pallas_entries, \
                f"pallas kernel body {fn} not discovered"
        pallas_reach = {qn for rel, qn in reach
                        if rel == "kubernetes_tpu/ops/pallas_kernel.py"}
        assert "wave_solve._wave_step_kernel.slow.body" in pallas_reach, \
            "purity walk no longer reaches the in-kernel replay body"
        # The pallas entry wrappers in ops/solver.py are jit entries too.
        assert "greedy_assign_rescoring_wave_pallas" in solver_entries
        assert "multistart_greedy_assign_wave_pallas" in solver_entries
        # ISSUE 20's block-sparse prefilter: the lax.cond branch bodies
        # (exact accept vs whole-chunk full-width fallback) are named
        # functions passed to a trace wrapper — entry points in their
        # own right — and the walk must reach the prefilter plus every
        # aggregate/bound/gather kernel it composes. A host sync inside
        # any of these runs on the hottest large-N path.
        for fn in ("block_bound_prefilter._block_exact",
                   "block_bound_prefilter._block_fallback_full"):
            assert fn in solver_entries, \
                f"block cond branch {fn} not discovered as an entry"
        for qn in ("block_bound_prefilter",
                   "block_bound_prefilter._block_exact",
                   "block_bound_prefilter._block_fallback_full"):
            assert qn in solver_reach, \
                f"purity walk no longer reaches {qn}"
        kernels_reach = {qn for rel, qn in reach
                         if rel == "kubernetes_tpu/ops/kernels.py"}
        for qn in ("block_capacity_aggregates", "block_feasible_stat",
                   "block_score_upper_bound", "gathered_start_scores",
                   "gathered_start_scores.one", "_block_fold"):
            assert qn in kernels_reach, \
                f"purity walk no longer reaches block kernel {qn}"
        assert len(reach) >= 20
