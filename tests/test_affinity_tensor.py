"""Differential: tensorized InterPodAffinity filter vs the host plugin."""

import random

import numpy as np

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.ops.affinity import AffinityCompiler
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.framework import CycleState
from kubernetes_tpu.scheduler.plugins.interpodaffinity import InterPodAffinity
from kubernetes_tpu.scheduler.types import PodInfo

ZONES = ["z1", "z2", "z3"]
APPS = ["web", "db", "cache", "batch"]
HOSTNAME = "kubernetes.io/hostname"
ZONE = "topology.kubernetes.io/zone"


def term(app, key, anti=False):
    return {"labelSelector": {"matchLabels": {"app": app}},
            "topologyKey": key}


def affinity_spec(required=None, anti=None, rng=None):
    out = {}
    if required:
        out.setdefault("podAffinity", {})[
            "requiredDuringSchedulingIgnoredDuringExecution"] = required
    if anti:
        out.setdefault("podAntiAffinity", {})[
            "requiredDuringSchedulingIgnoredDuringExecution"] = anti
    return out


def random_affinity_cluster(rng, n_nodes=20, pods_per_node=3):
    cache = SchedulerCache()
    for i in range(n_nodes):
        cache.add_node(make_node(
            f"n{i}", labels={ZONE: rng.choice(ZONES)}))
        for j in range(rng.randrange(pods_per_node + 1)):
            app = rng.choice(APPS)
            aff = None
            r = rng.random()
            if r < 0.25:
                aff = affinity_spec(anti=[term(rng.choice(APPS),
                                               rng.choice([HOSTNAME, ZONE]))])
            elif r < 0.35:
                aff = affinity_spec(required=[term(rng.choice(APPS),
                                                   rng.choice([HOSTNAME, ZONE]))])
            cache.add_pod(PodInfo(make_pod(
                f"res-{i}-{j}", labels={"app": app}, node_name=f"n{i}",
                affinity=aff, namespace=rng.choice(["default", "other"]))))
    return cache.update_snapshot()


def random_pending_affinity(rng, n=12):
    pods = []
    for i in range(n):
        aff = {}
        if rng.random() < 0.6:
            aff = affinity_spec(
                required=[term(rng.choice(APPS), rng.choice([HOSTNAME, ZONE]))]
                if rng.random() < 0.5 else None,
                anti=[term(rng.choice(APPS), rng.choice([HOSTNAME, ZONE]))]
                if rng.random() < 0.7 else None)
        pods.append(PodInfo(make_pod(
            f"pend-{i}", labels={"app": rng.choice(APPS)},
            affinity=aff or None,
            namespace=rng.choice(["default", "other"]),
            uid=f"u{i}")))
    return pods


class TestAffinityDifferential:
    def test_filter_rows_match_host_plugin(self):
        plugin = InterPodAffinity()
        for seed in range(6):
            rng = random.Random(seed)
            snapshot = random_affinity_cluster(rng)
            pending = random_pending_affinity(rng)
            compiler = AffinityCompiler(snapshot, n_pad=32)
            for pi in pending:
                assert compiler.supported(pi)
                row = compiler.filter_row(pi)
                state = CycleState()
                st = plugin.pre_filter(state, pi, snapshot)
                for j, ni in enumerate(snapshot.nodes):
                    if st.is_skip():
                        host_ok = True
                    else:
                        host_ok = plugin.filter(state, pi, ni).is_success()
                    assert bool(row[j]) == host_ok, (
                        f"seed={seed} pod={pi.key} node={ni.name}: "
                        f"tensor={bool(row[j])} host={host_ok}")

    def test_first_pod_in_group_rule(self):
        cache = SchedulerCache()
        cache.add_node(make_node("n0", labels={ZONE: "z1"}))
        snapshot = cache.update_snapshot()
        pod = PodInfo(make_pod(
            "first", labels={"app": "web"},
            affinity=affinity_spec(required=[term("web", ZONE)]), uid="u"))
        compiler = AffinityCompiler(snapshot, n_pad=8)
        row = compiler.filter_row(pod)
        assert bool(row[0])  # self-matching first pod may land

        # A pod whose affinity targets a DIFFERENT app (doesn't self-match)
        # must NOT get the escape.
        pod2 = PodInfo(make_pod(
            "notfirst", labels={"app": "db"},
            affinity=affinity_spec(required=[term("web", ZONE)]), uid="u2"))
        assert not bool(compiler.filter_row(pod2)[0])

    def test_missing_topology_key_rejects_affinity(self):
        cache = SchedulerCache()
        cache.add_node(make_node("nokey"))  # no zone label
        cache.add_pod(PodInfo(make_pod(
            "res", labels={"app": "web"}, node_name="nokey")))
        snapshot = cache.update_snapshot()
        pod = PodInfo(make_pod(
            "p", labels={"app": "web"},
            affinity=affinity_spec(required=[term("web", ZONE)]), uid="u"))
        compiler = AffinityCompiler(snapshot, n_pad=8)
        assert not bool(compiler.filter_row(pod)[0])


class TestBackendAffinityWorkload:
    def test_backend_anti_affinity_spreads_exclusively(self):
        """One pod per hostname-domain via anti-affinity: N pods fill N
        nodes exactly; pod N+1 is unschedulable."""
        from kubernetes_tpu.ops import TPUBackend
        from kubernetes_tpu.scheduler.framework import Framework
        from kubernetes_tpu.scheduler.plugins.registry import (
            DEFAULT_SCORE_WEIGHTS, build_plugins)

        cache = SchedulerCache()
        for i in range(6):
            cache.add_node(make_node(f"n{i}"))
        snapshot = cache.update_snapshot()
        anti = affinity_spec(anti=[term("web", HOSTNAME)])
        pods = [PodInfo(make_pod(
            f"w{i}", labels={"app": "web"}, affinity=anti,
            requests={"cpu": "100m"}, uid=f"u{i}")) for i in range(7)]
        fwk = Framework(build_plugins(), DEFAULT_SCORE_WEIGHTS)
        backend = TPUBackend(max_batch=8)
        assignments, diags = backend.assign(pods, snapshot, fwk)
        nodes_used = [assignments[p.key] for p in pods if assignments[p.key]]
        assert len(nodes_used) == 6
        assert len(set(nodes_used)) == 6
        unassigned = [p for p in pods if assignments[p.key] is None]
        assert len(unassigned) == 1


class TestDeviceSpreadScan:
    """PodTopologySpread hard constraints enforced INSIDE the device scan
    for homogeneous batches (solver.greedy_assign_rescoring_spread)."""

    def _spread_pods(self, count, start=0):
        cons = [{"maxSkew": 1, "topologyKey": ZONE,
                 "whenUnsatisfiable": "DoNotSchedule",
                 "labelSelector": {"matchLabels": {"app": "s"}}}]
        return [PodInfo(make_pod(
            f"s{start + i}", labels={"app": "s"},
            requests={"cpu": "100m"}, uid=f"su{start + i}",
            topology_spread_constraints=cons)) for i in range(count)]

    def _cluster(self, nodes_per_zone=3):
        cache = SchedulerCache()
        n = 0
        for z in ZONES:
            for _ in range(nodes_per_zone):
                cache.add_node(make_node(f"n{n}", labels={ZONE: z}))
                n += 1
        return cache

    def test_batch_respects_max_skew(self):
        from kubernetes_tpu.ops import TPUBackend
        from kubernetes_tpu.scheduler.framework import Framework
        from kubernetes_tpu.scheduler.plugins.registry import (
            DEFAULT_SCORE_WEIGHTS, build_plugins)
        cache = self._cluster()
        snapshot = cache.update_snapshot()
        pods = self._spread_pods(30)
        fwk = Framework(build_plugins(), DEFAULT_SCORE_WEIGHTS)
        backend = TPUBackend(max_batch=32)
        assignments, _ = backend.assign(pods, snapshot, fwk)
        zone_of = {f"n{i}": ZONES[i // 3] for i in range(9)}
        counts = {z: 0 for z in ZONES}
        for p in pods:
            assert assignments[p.key] is not None
            counts[zone_of[assignments[p.key]]] += 1
        # One batch, maxSkew=1 → zones within 1 of each other.
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_cross_chunk_counts_chain(self):
        """Chunks chain domain counts on device: a second chunk sees the
        first chunk's placements."""
        from kubernetes_tpu.ops import TPUBackend
        from kubernetes_tpu.scheduler.framework import Framework
        from kubernetes_tpu.scheduler.plugins.registry import (
            DEFAULT_SCORE_WEIGHTS, build_plugins)
        cache = self._cluster()
        snapshot = cache.update_snapshot()
        pods = self._spread_pods(24)
        fwk = Framework(build_plugins(), DEFAULT_SCORE_WEIGHTS)
        backend = TPUBackend(max_batch=8)  # 3 chunks
        assignments, _ = backend.assign(pods, snapshot, fwk)
        zone_of = {f"n{i}": ZONES[i // 3] for i in range(9)}
        counts = {z: 0 for z in ZONES}
        for p in pods:
            assert assignments[p.key] is not None
            counts[zone_of[assignments[p.key]]] += 1
        assert max(counts.values()) - min(counts.values()) <= 1

    def test_mixed_batch_rides_union_table_zero_poisoning(self):
        """A batch with two DIFFERENT spread templates (the Hetero family
        shape) rides ONE union scan table: both constraints honored,
        every pod placed, ZERO spread_poisoned degradations."""
        from kubernetes_tpu.metrics.registry import SchedulerMetrics
        from kubernetes_tpu.ops import TPUBackend
        from kubernetes_tpu.scheduler.framework import Framework
        from kubernetes_tpu.scheduler.plugins.registry import (
            DEFAULT_SCORE_WEIGHTS, build_plugins)
        cache = self._cluster()
        snapshot = cache.update_snapshot()
        pods = self._spread_pods(9)
        other_cons = [{"maxSkew": 2, "topologyKey": ZONE,
                       "whenUnsatisfiable": "DoNotSchedule",
                       "labelSelector": {"matchLabels": {"app": "t"}}}]
        pods += [PodInfo(make_pod(
            f"t{i}", labels={"app": "t"}, requests={"cpu": "100m"},
            uid=f"tu{i}", topology_spread_constraints=other_cons))
            for i in range(6)]
        fwk = Framework(build_plugins(), DEFAULT_SCORE_WEIGHTS)
        backend = TPUBackend(max_batch=32)
        backend.metrics = SchedulerMetrics()
        assignments, _ = backend.assign(pods, snapshot, fwk)
        zone_of = {f"n{i}": ZONES[i // 3] for i in range(9)}
        s_counts = {z: 0 for z in ZONES}
        t_counts = {z: 0 for z in ZONES}
        for p in pods:
            node = assignments[p.key]
            assert node is not None
            if p.labels["app"] == "s":
                s_counts[zone_of[node]] += 1
            else:
                t_counts[zone_of[node]] += 1
        assert max(s_counts.values()) - min(s_counts.values()) <= 1
        assert max(t_counts.values()) - min(t_counts.values()) <= 2
        assert backend.metrics.backend_degradations.value(
            kind="spread_poisoned") == 0
        assert backend.metrics.backend_degradations.value(
            kind="host_fallback") == 0


class _FakeNsInformer:
    """indexer.list() over static Namespace objects."""

    class _Idx:
        def __init__(self, items):
            self._items = items

        def list(self):
            return self._items

    def __init__(self, namespaces: dict[str, dict]):
        self.indexer = self._Idx([
            {"metadata": {"name": n, "labels": labels}}
            for n, labels in namespaces.items()])

    def add_event_handler(self, h):
        pass


def resolver_for(namespaces: dict[str, dict]):
    from kubernetes_tpu.scheduler.plugins.interpodaffinity import (
        NamespaceResolver,
    )
    r = NamespaceResolver()
    r._informer = _FakeNsInformer(namespaces)
    return r


class TestNamespaceSelector:
    """namespaceSelector terms: resolver semantics + host/tensor parity
    (reference: PreFilter's GetNamespaceLabelsSnapshot merge)."""

    NAMESPACES = {"default": {"team": "a"}, "other": {"team": "b"},
                  "third": {"team": "a"}}

    def ns_term(self, app, key, ns_sel):
        return {"labelSelector": {"matchLabels": {"app": app}},
                "topologyKey": key, "namespaceSelector": ns_sel}

    def test_resolver_semantics(self):
        from kubernetes_tpu.api.labels import ALL_NAMESPACES, ns_contains
        r = resolver_for(self.NAMESPACES)
        t = self.ns_term("web", ZONE, {"matchLabels": {"team": "a"}})
        assert r(t, "default") == ("default", "third")
        # empty selector ({}) matches EVERY namespace — including ones
        # without a Namespace object (reference: it matches any label
        # set) — so it resolves to the wildcard sentinel.
        t_all = self.ns_term("web", ZONE, {})
        assert r(t_all, "default") == ALL_NAMESPACES
        assert ns_contains(r(t_all, "default"), "no-such-namespace")
        # explicit namespaces union with the selector's matches
        t_union = dict(t, namespaces=["other"])
        assert r(t_union, "default") == ("default", "other", "third")
        # nil selector: explicit list or owner namespace
        plain = {"labelSelector": {}, "topologyKey": ZONE}
        assert r(plain, "default") == ("default",)

    def test_static_resolution_without_resolver(self):
        """resolve_term_namespaces without a resolver: {} selector is the
        wildcard; non-empty selectors match explicit namespaces only —
        identical to an informer-less NamespaceResolver, so compiled rows
        and host rows agree by construction."""
        from kubernetes_tpu.api.labels import ALL_NAMESPACES
        from kubernetes_tpu.scheduler.plugins.interpodaffinity import (
            NamespaceResolver,
            resolve_term_namespaces,
        )
        bare = NamespaceResolver()  # no informer wired
        for term in (
                self.ns_term("web", ZONE, {}),
                self.ns_term("web", ZONE, {"matchLabels": {"team": "a"}}),
                dict(self.ns_term("web", ZONE,
                                  {"matchLabels": {"team": "a"}}),
                     namespaces=["other"]),
                {"labelSelector": {}, "topologyKey": ZONE},
        ):
            assert resolve_term_namespaces(term, "default") == \
                bare(term, "default")
        assert resolve_term_namespaces(
            self.ns_term("w", ZONE, {}), "default") == ALL_NAMESPACES

    def test_host_and_tensor_parity_with_ns_selector(self):
        plugin = InterPodAffinity()
        plugin.ns_resolver = resolver_for(self.NAMESPACES)
        for seed in range(4):
            rng = random.Random(1000 + seed)
            snapshot = random_affinity_cluster(rng)
            compiler = AffinityCompiler(
                snapshot, n_pad=32, ns_resolver=plugin.ns_resolver)
            pending = []
            for i in range(8):
                sel = rng.choice([
                    {"matchLabels": {"team": "a"}},
                    {"matchLabels": {"team": "b"}}, {}])
                aff = affinity_spec(
                    required=[self.ns_term(rng.choice(APPS), ZONE, sel)]
                    if rng.random() < 0.5 else None,
                    anti=[self.ns_term(rng.choice(APPS), HOSTNAME, sel)]
                    if rng.random() < 0.7 else None)
                if not aff:
                    continue
                pending.append(PodInfo(make_pod(
                    f"nssel-{i}", labels={"app": rng.choice(APPS)},
                    affinity=aff, namespace=rng.choice(
                        ["default", "other"]), uid=f"nu{i}")))
            for pi in pending:
                assert compiler.supported(pi)
                row = compiler.filter_row(pi)
                state = CycleState()
                st = plugin.pre_filter(state, pi, snapshot)
                for j, ni in enumerate(snapshot.nodes):
                    host_ok = True if st.is_skip() else \
                        plugin.filter(state, pi, ni).is_success()
                    assert bool(row[j]) == host_ok, (
                        f"seed={seed} pod={pi.key} node={ni.name}: "
                        f"tensor={bool(row[j])} host={host_ok}")


class TestSpreadDifferential:
    """Compiled spread primitives vs the host PodTopologySpread plugin:
    minDomains, namespaceSelector, restricted eligibility, and
    non-self-matching selectors must agree node-for-node."""

    def _snapshot(self, rng, n_nodes=12):
        cache = SchedulerCache()
        for i in range(n_nodes):
            labels = {ZONE: rng.choice(ZONES)}
            if rng.random() < 0.7:
                labels["tier"] = rng.choice(["fast", "slow"])
            cache.add_node(make_node(f"n{i}", labels=labels))
            for j in range(rng.randrange(3)):
                cache.add_pod(PodInfo(make_pod(
                    f"r-{i}-{j}", labels={"app": rng.choice(APPS)},
                    node_name=f"n{i}",
                    namespace=rng.choice(["default", "other"]))))
        return cache.update_snapshot()

    def _constraint(self, rng):
        c = {"maxSkew": rng.choice([1, 2]), "topologyKey": ZONE,
             "whenUnsatisfiable": "DoNotSchedule",
             "labelSelector": {"matchLabels": {"app": rng.choice(APPS)}}}
        if rng.random() < 0.4:
            c["minDomains"] = rng.choice([2, 4, 6])
        if rng.random() < 0.4:
            c["namespaceSelector"] = {}
        return c

    def test_spread_filter_rows_match_host_plugin(self):
        from kubernetes_tpu.scheduler.plugins.podtopologyspread import (
            PodTopologySpread,
        )
        plugin = PodTopologySpread()
        for seed in range(6):
            rng = random.Random(2000 + seed)
            snapshot = self._snapshot(rng)
            compiler = AffinityCompiler(snapshot, n_pad=16)
            for k in range(6):
                cons = [self._constraint(rng)
                        for _ in range(rng.choice([1, 2]))]
                pod = PodInfo(make_pod(
                    f"pend-{seed}-{k}", labels={"app": rng.choice(APPS)},
                    namespace=rng.choice(["default", "other"]),
                    node_selector={"tier": "fast"}
                    if rng.random() < 0.4 else None,
                    topology_spread_constraints=cons, uid=f"du{seed}{k}"))
                row = compiler.spread_filter_row(pod, cons)
                state = CycleState()
                st = plugin.pre_filter(state, pod, snapshot)
                for j, ni in enumerate(snapshot.nodes):
                    host_ok = True if st.is_skip() else \
                        plugin.filter(state, pod, ni).is_success()
                    assert bool(row[j]) == host_ok, (
                        f"seed={seed} pod={pod.key} node={ni.name} "
                        f"cons={cons}: tensor={bool(row[j])} "
                        f"host={host_ok}")

    def test_min_domains_deficit_floors_min_to_zero(self):
        """Fewer eligible domains than minDomains → global min treated 0:
        a domain at maxSkew matching pods rejects even when another
        domain is emptier (host plugin and compiled row agree)."""
        from kubernetes_tpu.scheduler.plugins.podtopologyspread import (
            PodTopologySpread,
        )
        cache = SchedulerCache()
        for i, z in enumerate(["z1", "z1", "z2"]):
            cache.add_node(make_node(f"n{i}", labels={ZONE: z}))
        for j in range(2):  # z1 already holds 2 matching pods
            cache.add_pod(PodInfo(make_pod(
                f"r{j}", labels={"app": "m"}, node_name=f"n{j % 2}")))
        snapshot = cache.update_snapshot()
        cons = [{"maxSkew": 2, "topologyKey": ZONE,
                 "whenUnsatisfiable": "DoNotSchedule", "minDomains": 3,
                 "labelSelector": {"matchLabels": {"app": "m"}}}]
        pod = PodInfo(make_pod("p", labels={"app": "m"},
                               topology_spread_constraints=cons, uid="u"))
        plugin = PodTopologySpread()
        compiler = AffinityCompiler(snapshot, n_pad=8)
        row = compiler.spread_filter_row(pod, cons)
        state = CycleState()
        plugin.pre_filter(state, pod, snapshot)
        expect = [False, False, True]  # z1 at 2+1-0 > 2; z2 at 0+1-0 ≤ 2
        for j, ni in enumerate(snapshot.nodes):
            host_ok = plugin.filter(state, pod, ni).is_success()
            assert host_ok == expect[j]
            assert bool(row[j]) == expect[j]


class TestScoreDifferential:
    """Compiled score paths vs the host plugins — the namespaceSelector
    host-score fallback is gone (score_supported is always True), so the
    compiled rows need their own parity coverage."""

    def test_ipa_score_row_matches_host_plugin_with_ns_selector(self):
        plugin = InterPodAffinity({"hardPodAffinityWeight": 3})
        plugin.ns_resolver = resolver_for(
            TestNamespaceSelector.NAMESPACES)
        for seed in range(4):
            rng = random.Random(3000 + seed)
            snapshot = random_affinity_cluster(rng)
            compiler = AffinityCompiler(
                snapshot, n_pad=32, ns_resolver=plugin.ns_resolver)
            feasible = np.zeros((32,), dtype=np.bool_)
            feasible[: len(snapshot.nodes)] = True
            for k in range(6):
                sel = rng.choice([
                    {"matchLabels": {"team": "a"}}, {}, None])
                t = {"labelSelector":
                     {"matchLabels": {"app": rng.choice(APPS)}},
                     "topologyKey": rng.choice([HOSTNAME, ZONE])}
                if sel is not None:
                    t["namespaceSelector"] = sel
                pod = PodInfo(make_pod(
                    f"sc-{seed}-{k}", labels={"app": rng.choice(APPS)},
                    namespace=rng.choice(["default", "other"]),
                    affinity={"podAffinity": {
                        "preferredDuringSchedulingIgnoredDuringExecution":
                        [{"weight": rng.choice([1, 50]),
                          "podAffinityTerm": t}]}}, uid=f"sc{seed}{k}"))
                row = compiler.score_row(pod, 3.0, feasible)
                state = CycleState()
                st = plugin.pre_score(state, pod, list(snapshot.nodes))
                for j, ni in enumerate(snapshot.nodes):
                    host = 0.0 if st.is_skip() else \
                        plugin.score(state, pod, ni)
                    assert abs(float(row[j]) - host) < 1e-4, (
                        f"seed={seed} k={k} node={ni.name}: "
                        f"tensor={float(row[j])} host={host}")

    def test_spread_raw_scores_match_host_plugin_with_ns_selector(self):
        from kubernetes_tpu.scheduler.plugins.podtopologyspread import (
            PodTopologySpread,
        )
        plugin = PodTopologySpread()
        for seed in range(4):
            rng = random.Random(4000 + seed)
            snapshot = random_affinity_cluster(rng, n_nodes=10)
            compiler = AffinityCompiler(snapshot, n_pad=16)
            for k in range(4):
                cons = [{"maxSkew": 1, "topologyKey": ZONE,
                         "whenUnsatisfiable": "ScheduleAnyway",
                         "labelSelector":
                         {"matchLabels": {"app": rng.choice(APPS)}}}]
                if rng.random() < 0.5:
                    cons[0]["namespaceSelector"] = {}
                pod = PodInfo(make_pod(
                    f"sp-{seed}-{k}", labels={"app": rng.choice(APPS)},
                    namespace=rng.choice(["default", "other"]),
                    topology_spread_constraints=cons, uid=f"sp{seed}{k}"))
                raw = compiler.spread_raw_scores(pod, cons)
                state = CycleState()
                st = plugin.pre_score(state, pod, list(snapshot.nodes))
                for j, ni in enumerate(snapshot.nodes):
                    host = 0.0 if st.is_skip() else \
                        plugin.score(state, pod, ni)
                    assert abs(float(raw[j]) - host) < 1e-4, (
                        f"seed={seed} k={k} node={ni.name}: "
                        f"tensor={float(raw[j])} host={host}")
