"""Differential tests: TPU batch backend vs the host (CPU oracle) path.

SURVEY §7 phase 5: "Differential test: TPU vs CPU oracle on randomized
clusters". Kernels are checked one-for-one against the host plugins they
tensorize; the backend is checked end-to-end for (a) soundness — it never
assigns an infeasible placement, including under intra-batch contention —
and (b) score parity — single-pod batches pick a host-argmax node.
"""

import asyncio
import random

import numpy as np
import pytest

import jax.numpy as jnp

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.ops import TPUBackend
from kubernetes_tpu.ops import kernels
from kubernetes_tpu.ops.tensorize import ClusterTensors, PodBatch
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.framework import CycleState, Framework
from kubernetes_tpu.scheduler.plugins.nodeaffinity import TaintToleration
from kubernetes_tpu.scheduler.plugins.noderesources import (
    BalancedAllocation,
    NodeResourcesFit,
    insufficient_resources,
)
from kubernetes_tpu.scheduler.plugins.registry import (
    DEFAULT_SCORE_WEIGHTS,
    build_plugins,
)
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# fixtures: randomized clusters
# ---------------------------------------------------------------------------

TAINT_POOL = [
    {"key": "dedicated", "value": "infra", "effect": "NoSchedule"},
    {"key": "gpu", "value": "true", "effect": "NoSchedule"},
    {"key": "flaky", "value": "", "effect": "PreferNoSchedule"},
    {"key": "old", "value": "", "effect": "PreferNoSchedule"},
]
TOL_POOL = [
    {"key": "dedicated", "operator": "Equal", "value": "infra",
     "effect": "NoSchedule"},
    {"key": "gpu", "operator": "Exists"},
    {"key": "flaky", "operator": "Exists"},
]


def random_cluster(rng: random.Random, n_nodes: int, resident_per_node: int = 3):
    """Build a snapshot via the real cache so NodeInfo aggregates are honest."""
    cache = SchedulerCache()
    for i in range(n_nodes):
        taints = [t for t in TAINT_POOL if rng.random() < 0.25]
        node = make_node(
            f"n{i}",
            allocatable={
                "cpu": f"{rng.choice([2, 4, 8, 16])}",
                "memory": f"{rng.choice([4, 16, 64, 256])}Gi",
                "pods": str(rng.choice([10, 110])),
            },
            taints=taints or None,
        )
        cache.add_node(node)
        for j in range(rng.randrange(resident_per_node + 1)):
            pod = make_pod(
                f"resident-{i}-{j}", node_name=f"n{i}",
                requests={"cpu": f"{rng.randrange(100, 2000)}m",
                          "memory": f"{rng.randrange(64, 2048)}Mi"},
                tolerations=TOL_POOL,
            )
            cache.add_pod(PodInfo(pod))
    return cache.update_snapshot()


def random_pending(rng: random.Random, n: int):
    pods = []
    for i in range(n):
        tols = [t for t in TOL_POOL if rng.random() < 0.4]
        pods.append(PodInfo(make_pod(
            f"pend-{i}",
            requests={"cpu": f"{rng.randrange(100, 4000)}m",
                      "memory": f"{rng.randrange(64, 8192)}Mi"},
            tolerations=tols or None,
            uid=f"uid-{i}",
        )))
    return pods


def default_fwk():
    return Framework(build_plugins(), DEFAULT_SCORE_WEIGHTS)


# ---------------------------------------------------------------------------
# kernel-level differential
# ---------------------------------------------------------------------------

class TestScoreWire:
    def test_f16_within_band_f32_beyond(self):
        """Dirty score planes ship f16 only inside its faithful range;
        oversized plugin weights (sums >1024) must fall back to f32 and
        never reach the device as inf (ADVICE r3)."""
        from kubernetes_tpu.ops.backend import compress_score_wire
        small = np.full((4, 8), 600.0, dtype=np.float32)
        assert compress_score_wire(small).dtype == np.float16
        big = np.full((4, 8), 700.0 * 100, dtype=np.float32)  # weight 700
        wire = compress_score_wire(big)
        assert wire.dtype == np.float32
        assert np.isfinite(wire).all()
        assert compress_score_wire(np.zeros((0, 0), np.float32)).dtype \
            == np.float16


class TestKernelsVsHost:
    def setup_method(self):
        self.rng = random.Random(7)
        self.snapshot = random_cluster(self.rng, 40)
        self.pods = random_pending(self.rng, 16)
        self.ct = ClusterTensors(self.snapshot)
        self.batch = PodBatch(self.pods, self.ct, 16)

    def test_fit_mask_matches_insufficient_resources(self):
        mask = np.asarray(kernels.fit_filter_mask(
            jnp.asarray(self.ct.alloc_q), jnp.asarray(self.ct.used_q),
            jnp.asarray(self.ct.used_pods), jnp.asarray(self.ct.alloc_pods),
            jnp.asarray(self.batch.req_q)))
        for i, pi in enumerate(self.pods):
            for j, ni in enumerate(self.snapshot.nodes):
                host_fits = not insufficient_resources(pi, ni)
                # Soundness: device-feasible ⇒ host-feasible (quantization
                # may only reject, never admit).
                if mask[i, j]:
                    assert host_fits, (pi.key, ni.name)
                # Tightness on this value range (quanta are ≤ memory/2^20):
                if not mask[i, j]:
                    assert not host_fits, (pi.key, ni.name)

    def test_taint_mask_matches_host_filter(self):
        plug = TaintToleration()
        mask = np.asarray(kernels.taint_filter_mask(
            jnp.asarray(self.ct.taint_filter_mat),
            jnp.asarray(self.batch.untol_filter)))
        state = CycleState()
        for i, pi in enumerate(self.pods):
            for j, ni in enumerate(self.snapshot.nodes):
                assert mask[i, j] == plug.filter(state, pi, ni).is_success()

    def test_fit_score_matches_host(self):
        plug = NodeResourcesFit()
        col_w = np.zeros(len(self.ct.resources), np.float32)
        for spec in plug.score_resources:
            col_w[self.ct.r_index[spec["name"]]] = spec.get("weight", 1)
        scores = np.asarray(kernels.fit_score(
            jnp.asarray(self.ct.alloc_q), jnp.asarray(self.ct.used_nz_q),
            jnp.asarray(self.batch.req_nz_q), jnp.asarray(col_w),
            "LeastAllocated"))
        state = CycleState()
        for i, pi in enumerate(self.pods):
            for j, ni in enumerate(self.snapshot.nodes):
                host = plug.score(state, pi, ni)
                assert scores[i, j] == pytest.approx(host, abs=0.05), \
                    (pi.key, ni.name)

    def test_balanced_score_matches_host(self):
        plug = BalancedAllocation()
        col_mask = np.zeros(len(self.ct.resources), np.bool_)
        for r in plug.resources:
            col_mask[self.ct.r_index[r]] = True
        scores = np.asarray(kernels.balanced_allocation_score(
            jnp.asarray(self.ct.alloc_q), jnp.asarray(self.ct.used_nz_q),
            jnp.asarray(self.batch.req_nz_q), jnp.asarray(col_mask)))
        state = CycleState()
        for i, pi in enumerate(self.pods):
            for j, ni in enumerate(self.snapshot.nodes):
                host = plug.score(state, pi, ni)
                assert scores[i, j] == pytest.approx(host, abs=0.05)

    def test_taint_score_matches_host_normalized(self):
        plug = TaintToleration()
        feasible = np.ones((16, self.ct.n_pad), np.bool_)
        feasible[:, self.ct.n_real:] = False
        scores = np.asarray(kernels.taint_toleration_score(
            jnp.asarray(self.ct.taint_prefer_mat),
            jnp.asarray(self.batch.untol_prefer), jnp.asarray(feasible)))
        state = CycleState()
        for i, pi in enumerate(self.pods):
            raw = {ni.name: plug.score(state, pi, ni)
                   for ni in self.snapshot.nodes}
            plug.normalize_scores(state, pi, raw)
            for j, ni in enumerate(self.snapshot.nodes):
                assert scores[i, j] == pytest.approx(raw[ni.name], abs=0.05)


# ---------------------------------------------------------------------------
# backend-level differential
# ---------------------------------------------------------------------------

class TestBackendVsOracle:
    def test_mesh_path_is_active_and_matches_single_device(self):
        """The 8-virtual-device conftest must put the backend on its
        node-axis mesh (the production multi-chip path), and the sharded
        program must produce the same assignments as mesh=None."""
        rng = random.Random(7)
        snapshot = random_cluster(rng, 30)
        pods = random_pending(rng, 16)
        fwk = default_fwk()
        sharded = TPUBackend(max_batch=8)
        assert sharded.mesh is not None, \
            "expected auto mesh on the 8-device test platform"
        single = TPUBackend(max_batch=8, mesh=None)
        a_sh, _ = sharded.assign(pods, snapshot, fwk)
        a_si, _ = single.assign(pods, snapshot, fwk)
        assert a_sh == a_si

    def test_chunked_pipeline_matches_one_chunk(self):
        """Internal chunking (device-chained used-state) must agree with a
        single-chunk solve of the same batch."""
        rng = random.Random(31)
        snapshot = random_cluster(rng, 30)
        pods = random_pending(rng, 24)
        fwk = default_fwk()
        chunked, _ = TPUBackend(max_batch=8).assign(pods, snapshot, fwk)
        whole, _ = TPUBackend(max_batch=24).assign(pods, snapshot, fwk)
        assert chunked == whole

    def test_single_pod_picks_host_argmax(self):
        rng = random.Random(11)
        for trial in range(5):
            snapshot = random_cluster(rng, 25)
            [pod] = random_pending(rng, 1)
            fwk = default_fwk()
            backend = TPUBackend(max_batch=8)
            assignments, diags = backend.assign([pod], snapshot, fwk)
            chosen = assignments[pod.key]

            # Host oracle: feasible set + combined scores.
            state = CycleState()
            fwk.run_pre_filter(state, pod, snapshot)
            feasible = [ni for ni in snapshot.nodes
                        if fwk.run_filters(state, pod, ni).is_success()]
            if not feasible:
                assert chosen is None
                continue
            assert chosen is not None, f"trial {trial}: host found {len(feasible)} nodes"
            assert chosen in {ni.name for ni in feasible}
            fwk.run_pre_score(state, pod, feasible)
            host_scores = fwk.run_scores(state, pod, feasible)
            best = max(host_scores.values())
            assert host_scores[chosen] == pytest.approx(best, abs=0.1), \
                f"trial {trial}: {host_scores[chosen]} vs max {best}"

    def test_batch_assignments_are_sequentially_feasible(self):
        rng = random.Random(23)
        for trial in range(3):
            snapshot = random_cluster(rng, 20, resident_per_node=2)
            pods = random_pending(rng, 30)
            fwk = default_fwk()
            backend = TPUBackend(max_batch=32)
            assignments, _ = backend.assign(pods, snapshot, fwk)

            # Replay on a fresh working copy with the host plugins.
            working = {ni.name: ni.clone() for ni in snapshot.nodes}
            for pi in pods:
                node = assignments.get(pi.key)
                if node is None:
                    continue
                ni = working[node]
                assert not insufficient_resources(pi, ni), \
                    f"trial {trial}: {pi.key} infeasible on {node}"
                state = CycleState()
                assert fwk.run_filters(state, pi, ni).is_success()
                ni.add_pod(pi)

    def test_unschedulable_diagnostics_name_the_resource(self):
        snapshot = random_cluster(random.Random(3), 5)
        huge = PodInfo(make_pod("huge", requests={"cpu": "4000"}))
        fwk = default_fwk()
        backend = TPUBackend(max_batch=4)
        assignments, diags = backend.assign([huge], snapshot, fwk)
        assert assignments[huge.key] is None
        statuses = diags[huge.key]
        assert statuses, "expected per-node failure reasons"
        reasons = {r for st in statuses.values() for r in st.reasons}
        assert any("Insufficient cpu" in r for r in reasons)

    def test_batch_contention_never_overcommits(self):
        """8 pods of 3 cores into nodes with 4 cores free: at most one per
        node; leftovers come back unassigned, never overpacked."""
        cache = SchedulerCache()
        for i in range(4):
            cache.add_node(make_node(f"n{i}", allocatable={
                "cpu": "4", "memory": "16Gi", "pods": "110"}))
        snapshot = cache.update_snapshot()
        pods = [PodInfo(make_pod(f"big-{i}", requests={"cpu": "3"},
                                 uid=f"u{i}")) for i in range(8)]
        fwk = default_fwk()
        backend = TPUBackend(max_batch=8)
        assignments, _ = backend.assign(pods, snapshot, fwk)
        per_node: dict[str, int] = {}
        for pi in pods:
            n = assignments.get(pi.key)
            if n:
                per_node[n] = per_node.get(n, 0) + 1
        assert sum(per_node.values()) == 4
        assert all(v == 1 for v in per_node.values())

    def test_taints_respected_in_batch(self):
        cache = SchedulerCache()
        cache.add_node(make_node("tainted", taints=[
            {"key": "dedicated", "value": "infra", "effect": "NoSchedule"}]))
        cache.add_node(make_node("open"))
        snapshot = cache.update_snapshot()
        plain = PodInfo(make_pod("plain", requests={"cpu": "1"}, uid="u1"))
        tolerant = PodInfo(make_pod(
            "tolerant", requests={"cpu": "1"}, uid="u2",
            tolerations=[{"key": "dedicated", "operator": "Equal",
                          "value": "infra", "effect": "NoSchedule"}]))
        fwk = default_fwk()
        backend = TPUBackend(max_batch=4)
        assignments, _ = backend.assign([plain, tolerant], snapshot, fwk)
        assert assignments[plain.key] == "open"
        assert assignments[tolerant.key] in ("open", "tainted")

    def test_anti_affinity_symmetry_within_batch(self):
        """Pod A has anti-affinity against app=web; pod B (app=web, no
        constraints of its own) must not verify onto A's node."""
        cache = SchedulerCache()
        cache.add_node(make_node("n0", labels={"zone": "z1"}))
        snapshot = cache.update_snapshot()
        anti = {
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [{
                    "labelSelector": {"matchLabels": {"app": "web"}},
                    "topologyKey": "kubernetes.io/hostname",
                }]}}
        a = PodInfo(make_pod("a", labels={"app": "db"}, affinity=anti,
                             requests={"cpu": "1"}, uid="ua"))
        b = PodInfo(make_pod("b", labels={"app": "web"},
                             requests={"cpu": "1"}, uid="ub"))
        fwk = default_fwk()
        backend = TPUBackend(max_batch=4)
        assignments, _ = backend.assign([a, b], snapshot, fwk)
        assert assignments[a.key] == "n0"
        # b would violate a's anti-affinity on the only node → unassigned.
        assert assignments[b.key] is None


# ---------------------------------------------------------------------------
# end-to-end through the Scheduler batched loop
# ---------------------------------------------------------------------------

class TestSchedulerWithBackend:
    def test_batched_e2e_binds_all(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            for i in range(10):
                await store.create("nodes", make_node(f"node-{i}"))
            sched = Scheduler(store, seed=1, backend=TPUBackend(max_batch=32))
            factory = InformerFactory(store)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            for i in range(60):
                await store.create("pods", make_pod(
                    f"p{i}", requests={"cpu": "200m", "memory": "256Mi"}))
            task = asyncio.ensure_future(sched.run(batch_size=32))
            for _ in range(100):
                pods = (await store.list("pods")).items
                bound = [p for p in pods if p["spec"].get("nodeName")]
                if len(bound) >= 60:
                    break
                await asyncio.sleep(0.05)
            await sched.stop()
            task.cancel()
            assert len(bound) == 60
            spread = {p["spec"]["nodeName"] for p in bound}
            assert len(spread) == 10  # LeastAllocated balances
        run(body())
