"""EventRecorder: bounded broadcaster + per-(object, reason) aggregation
(the upstream EventCorrelator/EventAggregator analog)."""

import asyncio

from kubernetes_tpu.client.events import EventRecorder
from kubernetes_tpu.store.mvcc import MVCCStore


def run(coro):
    return asyncio.run(coro)


def _pod(name):
    return {"kind": "Pod",
            "metadata": {"name": name, "namespace": "default"}}


class TestAggregation:
    def test_repeat_same_object_reason_bumps_count(self):
        async def body():
            s = MVCCStore()
            rec = EventRecorder(s, "scheduler")
            for _ in range(5):
                rec.event(_pod("a"), "Warning", "FailedScheduling",
                          "0/3 nodes available")
            rec.event(_pod("a"), "Normal", "Scheduled", "bound")
            rec.event(_pod("b"), "Warning", "FailedScheduling", "nope")
            # 7 calls → 3 distinct (object, type, reason) Events pending.
            assert rec.emitted == 7
            assert rec.aggregated == 4
            assert rec.dropped == 0
            await asyncio.sleep(0.05)  # drain
            evs = (await s.list("events")).items
            assert len(evs) == 3
            failed_a = [e for e in evs
                        if e["reason"] == "FailedScheduling"
                        and e["involvedObject"]["name"] == "a"]
            assert len(failed_a) == 1
            assert failed_a[0]["count"] == 5
            assert failed_a[0]["lastTimestamp"]
        run(body())

    def test_aggregation_is_buffer_local(self):
        """Once drained, a recurrence starts a fresh Event (we do not
        PATCH stored events, unlike the full upstream correlator)."""
        async def body():
            s = MVCCStore()
            rec = EventRecorder(s, "scheduler")
            rec.event(_pod("a"), "Warning", "FailedScheduling", "x")
            await asyncio.sleep(0.05)
            rec.event(_pod("a"), "Warning", "FailedScheduling", "x")
            await asyncio.sleep(0.05)
            evs = (await s.list("events")).items
            assert len(evs) == 2
            assert all(e.get("count") == 1 for e in evs)
        run(body())

    def test_preloop_buffer_flushes_via_aggregated_recurrence(self):
        """Events recorded before any loop runs must still drain when the
        next event() under a loop is an aggregated recurrence."""
        s = MVCCStore()
        rec = EventRecorder(s, "scheduler")
        rec.event(_pod("a"), "Warning", "FailedScheduling", "x")  # no loop

        async def body():
            rec.event(_pod("a"), "Warning", "FailedScheduling", "x")
            assert rec.aggregated == 1
            await asyncio.sleep(0.05)
            evs = (await s.list("events")).items
            assert len(evs) == 1 and evs[0]["count"] == 2
        run(body())

    def test_flood_of_distinct_objects_still_bounded(self):
        async def body():
            s = MVCCStore()
            rec = EventRecorder(s, "scheduler")
            rec.MAX_PENDING = 100  # non-priority bound under test
            # No loop yield between these: the buffer caps the burst.
            # Distinct objects → no aggregation; distinct reasons → the
            # spam filter's per-reason budget never empties.
            for i in range(300):
                rec.event(_pod(f"p{i}"), "Warning", f"R{i}", "x")
            assert rec.dropped == 300 - rec.MAX_PENDING
            await asyncio.sleep(0.2)
            evs = (await s.list("events")).items
            assert len(evs) == rec.MAX_PENDING
        run(body())

    def test_buffer_full_log_one_line_per_decade(self, caplog):
        """The buffer-full warning fires once per DECADE of drops per
        (source, reason) — 1st, 10th, 100th, 1000th — so a retry storm
        of one reason logs O(log n) lines and can't bury the first drop
        of a different reason. The drop COUNTERS are untouched."""
        import logging

        async def body():
            s = MVCCStore()
            rec = EventRecorder(s, "scheduler")
            rec.MAX_PENDING = 0        # every event hits the full path
            rec.MAX_PENDING_PRIORITY = 0
            rec._spam.allow = lambda *a: True  # isolate the full path
            with caplog.at_level(logging.WARNING,
                                 "kubernetes_tpu.client.events"):
                for i in range(1500):
                    rec.event(_pod(f"p{i}"), "Warning", "Evicted", "x")
                lines = [r for r in caplog.records
                         if "buffer full" in r.getMessage()]
                assert len(lines) == 4  # drops 1, 10, 100, 1000
                assert rec.dropped == 1500
                # A second reason is not starved: its FIRST drop logs.
                rec.event(_pod("q"), "Warning", "NodeLost", "x")
                lines = [r for r in caplog.records
                         if "buffer full" in r.getMessage()]
                assert len(lines) == 5
                assert "NodeLost" in lines[-1].getMessage()
            assert rec.dropped == 1501
        run(body())


class TestPriorityAndSpam:
    def test_scheduled_burst_rides_the_deeper_priority_bound(self):
        """The 1000-agent shedding fix: a bind burst larger than
        MAX_PENDING must NOT shed its per-pod "Scheduled" events."""
        async def body():
            s = MVCCStore()
            rec = EventRecorder(s, "scheduler")
            rec.MAX_PENDING = 100
            for i in range(2000):
                rec.event(_pod(f"p{i}"), "Normal", "Scheduled", "bound")
            assert rec.dropped == 0
            await asyncio.sleep(0.5)
            evs = (await s.list("events")).items
            assert len(evs) == 2000
        run(body())

    def test_spam_filter_sheds_repeating_reason_family(self):
        async def body():
            s = MVCCStore()
            rec = EventRecorder(s, "scheduler")
            rec._spam.burst = 50
            rec._spam.qps = 0.0  # no refill inside the test window
            # Distinct objects (no aggregation), one repeating reason.
            for i in range(200):
                rec.event(_pod(f"p{i}"), "Warning", "FailedScheduling",
                          "0/3 nodes")
            assert rec.spam_filtered == 150
            assert rec.dropped == 150
            # The filter is per-reason: another family still has budget.
            rec.event(_pod("q"), "Normal", "Pulled", "ok")
            assert rec.spam_filtered == 150
        run(body())

    def test_priority_event_evicts_buffered_noise_when_full(self):
        async def body():
            s = MVCCStore()
            rec = EventRecorder(s, "scheduler")
            rec.MAX_PENDING = 10
            rec.MAX_PENDING_PRIORITY = 10  # force the shared-bound path
            for i in range(10):
                rec.event(_pod(f"n{i}"), "Warning", f"Noise{i}", "x")
            assert len(rec._pending) == 10
            rec.event(_pod("s"), "Normal", "Scheduled", "bound")
            # One noise event evicted (counted dropped); Scheduled is in.
            assert rec.dropped == 1
            reasons = [e["reason"] for e in rec._pending]
            assert "Scheduled" in reasons and len(reasons) == 10
        run(body())

    def test_drain_writes_priority_first(self):
        async def body():
            s = MVCCStore()
            rec = EventRecorder(s, "scheduler")
            # Build the batch with no loop running, then drain once.
            rec.event(_pod("a"), "Warning", "Noise", "x")
            rec.event(_pod("b"), "Normal", "Scheduled", "bound")
            rec.event(_pod("c"), "Warning", "Noise2", "x")
            rec.event(_pod("d"), "Normal", "Scheduled", "bound")
            await asyncio.sleep(0.1)
            evs = (await s.list("events")).items
            evs.sort(key=lambda e:
                     int(e["metadata"]["resourceVersion"]))
            reasons = [e["reason"] for e in evs]
            assert reasons == ["Scheduled", "Scheduled", "Noise",
                               "Noise2"]
        run(body())


class TestDrainWindow:
    """The backlog-proportional gather width (r10): a 5000-agent
    mark-Running burst must drain in a near-constant number of gather
    round trips instead of backlog/128 sequential ones — the residual
    ≤1.6k-drop regime the fixed window left at 5000 agents."""

    class _CountingStore:
        def __init__(self):
            self.in_flight = 0
            self.max_in_flight = 0
            self.created = 0

        async def create(self, kind, obj, _owned=False, return_copy=True):
            self.in_flight += 1
            self.max_in_flight = max(self.max_in_flight, self.in_flight)
            await asyncio.sleep(0)
            self.in_flight -= 1
            self.created += 1

    def test_big_backlog_widens_the_gather(self):
        async def body():
            import time
            s = self._CountingStore()
            rec = EventRecorder(s, "scheduler")
            # 5000 distinct "Scheduled" (priority: deep bound, no spam
            # filter) queued synchronously — one drain batch.
            for i in range(5000):
                rec.event(_pod(f"p{i}"), "Normal", "Scheduled", "bound")
            assert rec.dropped == 0
            deadline = time.monotonic() + 10.0  # loaded-box tolerant
            while s.created < 5000 and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            assert s.created == 5000
            # 5000/4 = 1250 → capped at DRAIN_WINDOW_MAX.
            assert s.max_in_flight == EventRecorder.DRAIN_WINDOW_MAX
        run(body())

    def test_small_backlog_keeps_the_floor(self):
        async def body():
            import time
            s = self._CountingStore()
            rec = EventRecorder(s, "scheduler")
            for i in range(200):
                rec.event(_pod(f"p{i}"), "Normal", "Scheduled", "bound")
            deadline = time.monotonic() + 10.0
            while s.created < 200 and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            assert s.created == 200
            assert s.max_in_flight <= EventRecorder.DRAIN_WINDOW
        run(body())
