"""EventRecorder: bounded broadcaster + per-(object, reason) aggregation
(the upstream EventCorrelator/EventAggregator analog)."""

import asyncio

from kubernetes_tpu.client.events import EventRecorder
from kubernetes_tpu.store.mvcc import MVCCStore


def run(coro):
    return asyncio.run(coro)


def _pod(name):
    return {"kind": "Pod",
            "metadata": {"name": name, "namespace": "default"}}


class TestAggregation:
    def test_repeat_same_object_reason_bumps_count(self):
        async def body():
            s = MVCCStore()
            rec = EventRecorder(s, "scheduler")
            for _ in range(5):
                rec.event(_pod("a"), "Warning", "FailedScheduling",
                          "0/3 nodes available")
            rec.event(_pod("a"), "Normal", "Scheduled", "bound")
            rec.event(_pod("b"), "Warning", "FailedScheduling", "nope")
            # 7 calls → 3 distinct (object, type, reason) Events pending.
            assert rec.emitted == 7
            assert rec.aggregated == 4
            assert rec.dropped == 0
            await asyncio.sleep(0.05)  # drain
            evs = (await s.list("events")).items
            assert len(evs) == 3
            failed_a = [e for e in evs
                        if e["reason"] == "FailedScheduling"
                        and e["involvedObject"]["name"] == "a"]
            assert len(failed_a) == 1
            assert failed_a[0]["count"] == 5
            assert failed_a[0]["lastTimestamp"]
        run(body())

    def test_aggregation_is_buffer_local(self):
        """Once drained, a recurrence starts a fresh Event (we do not
        PATCH stored events, unlike the full upstream correlator)."""
        async def body():
            s = MVCCStore()
            rec = EventRecorder(s, "scheduler")
            rec.event(_pod("a"), "Warning", "FailedScheduling", "x")
            await asyncio.sleep(0.05)
            rec.event(_pod("a"), "Warning", "FailedScheduling", "x")
            await asyncio.sleep(0.05)
            evs = (await s.list("events")).items
            assert len(evs) == 2
            assert all(e.get("count") == 1 for e in evs)
        run(body())

    def test_preloop_buffer_flushes_via_aggregated_recurrence(self):
        """Events recorded before any loop runs must still drain when the
        next event() under a loop is an aggregated recurrence."""
        s = MVCCStore()
        rec = EventRecorder(s, "scheduler")
        rec.event(_pod("a"), "Warning", "FailedScheduling", "x")  # no loop

        async def body():
            rec.event(_pod("a"), "Warning", "FailedScheduling", "x")
            assert rec.aggregated == 1
            await asyncio.sleep(0.05)
            evs = (await s.list("events")).items
            assert len(evs) == 1 and evs[0]["count"] == 2
        run(body())

    def test_flood_of_distinct_objects_still_bounded(self):
        async def body():
            s = MVCCStore()
            rec = EventRecorder(s, "scheduler")
            # No loop yield between these: the buffer caps the burst.
            for i in range(3000):
                rec.event(_pod(f"p{i}"), "Normal", "Scheduled", "bound")
            assert rec.dropped == 3000 - rec.MAX_PENDING
            await asyncio.sleep(0.2)
            evs = (await s.list("events")).items
            assert len(evs) == rec.MAX_PENDING
        run(body())
