"""Scheduler-extender seam over localhost HTTP (north-star seam #2).

Parity target: pkg/scheduler/extender.go HTTPExtender + the config wire
types. The demo ExtenderServer stands in for an out-of-process extender.
"""

import asyncio

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.extender import (
    ExtenderError,
    ExtenderServer,
    HTTPExtender,
)
from kubernetes_tpu.scheduler.types import PodInfo
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def run(coro):
    return asyncio.run(coro)


async def _cluster(n_nodes=4):
    store = new_cluster_store()
    install_core_validation(store)
    for i in range(n_nodes):
        await store.create("nodes", make_node(
            f"n{i}", allocatable={"cpu": "8", "memory": "16Gi",
                                  "pods": "110"}))
    return store


async def _run_scheduler(store, sched, n_pods):
    factory = InformerFactory(store)
    await sched.setup_informers(factory)
    factory.start()
    await factory.wait_for_sync()
    runner = asyncio.ensure_future(sched.run())
    for i in range(n_pods):
        await store.create("pods", make_pod(
            f"p{i}", "default", requests={"cpu": "100m"}))
    bound = {}
    for _ in range(100):
        await asyncio.sleep(0.05)
        lst = await store.list("pods")
        bound = {o["metadata"]["name"]: o["spec"].get("nodeName")
                 for o in lst.items if o.get("spec", {}).get("nodeName")}
        if len(bound) == n_pods:
            break
    await sched.stop()
    runner.cancel()
    factory.stop()
    return bound


class TestExtenderVerbs:
    def test_filter_narrows_feasible_set(self):
        async def body():
            ext_srv = ExtenderServer(
                filter_fn=lambda pod, names: (
                    [n for n in names if n == "n2"],
                    {n: "extender says no" for n in names if n != "n2"}))
            await ext_srv.start()
            store = await _cluster()
            sched = Scheduler(store)
            sched.extenders = [HTTPExtender(
                ext_srv.url, filter_verb="filter", name="demo")]
            bound = await _run_scheduler(store, sched, 3)
            assert set(bound.values()) == {"n2"}
            verbs = [v for v, _ in ext_srv.requests]
            assert "filter" in verbs
            await ext_srv.stop()
            store.stop()
        run(body())

    def test_prioritize_weighted_scores_steer_choice(self):
        async def body():
            ext_srv = ExtenderServer(
                prioritize_fn=lambda pod, names: {"n1": 10})
            await ext_srv.start()
            store = await _cluster()
            sched = Scheduler(store)
            sched.extenders = [HTTPExtender(
                ext_srv.url, prioritize_verb="prioritize", weight=100,
                name="demo")]
            bound = await _run_scheduler(store, sched, 3)
            # weight 100 × score 10 swamps the in-tree scorers.
            assert set(bound.values()) == {"n1"}
            await ext_srv.stop()
            store.stop()
        run(body())

    def test_bind_verb_replaces_default_binder(self):
        async def body():
            store = await _cluster()

            def do_bind(args):
                # The extender performs the actual binding (BindingREST).
                async def _b():
                    from kubernetes_tpu.store.mvcc import StoreError
                    try:
                        await store.subresource(
                            "pods",
                            f"{args['podNamespace']}/{args['podName']}",
                            "binding", {"target": {"name": args["node"]}})
                    except StoreError:
                        pass
                asyncio.ensure_future(_b())
                return None
            ext_srv = ExtenderServer(bind_fn=do_bind)
            await ext_srv.start()
            sched = Scheduler(store)
            sched.extenders = [HTTPExtender(
                ext_srv.url, bind_verb="bind", name="demo")]
            bound = await _run_scheduler(store, sched, 3)
            assert len(bound) == 3
            assert [v for v, _ in ext_srv.requests].count("bind") == 3
            await ext_srv.stop()
            store.stop()
        run(body())

    def test_node_cache_capable_sends_names_only(self):
        async def body():
            ext_srv = ExtenderServer(
                filter_fn=lambda pod, names: (names, {}))
            await ext_srv.start()
            store = await _cluster()
            sched = Scheduler(store)
            sched.extenders = [HTTPExtender(
                ext_srv.url, filter_verb="filter",
                node_cache_capable=True, name="demo")]
            bound = await _run_scheduler(store, sched, 2)
            assert len(bound) == 2
            _, args = ext_srv.requests[0]
            assert "nodenames" in args and "nodes" not in args
            await ext_srv.stop()
            store.stop()
        run(body())


class TestExtenderFailureModes:
    def test_ignorable_extender_down_is_skipped(self):
        async def body():
            store = await _cluster()
            sched = Scheduler(store)
            sched.extenders = [HTTPExtender(
                "http://127.0.0.1:1", filter_verb="filter",
                ignorable=True, timeout=0.2, name="down")]
            bound = await _run_scheduler(store, sched, 2)
            assert len(bound) == 2  # scheduling proceeds without it
            store.stop()
        run(body())

    def test_non_ignorable_extender_down_raises(self):
        async def body():
            ext = HTTPExtender("http://127.0.0.1:1", filter_verb="filter",
                               timeout=0.2, name="down")
            store = await _cluster(1)
            lst = await store.list("nodes")
            from kubernetes_tpu.scheduler.cache import SchedulerCache
            cache = SchedulerCache()
            for n in lst.items:
                cache.add_node(n)
            snap = cache.update_snapshot()
            pod = PodInfo(make_pod("p", requests={"cpu": "1"}))
            with pytest.raises(ExtenderError):
                await ext.filter(pod, list(snap.nodes))
            await ext.close()
            store.stop()
        run(body())

    def test_managed_resources_gates_interest(self):
        ext = HTTPExtender("http://x", filter_verb="filter",
                           managed_resources=["example.com/gpu"])
        plain = PodInfo(make_pod("p", requests={"cpu": "1"}))
        gpu = PodInfo(make_pod("g", requests={"example.com/gpu": "1"}))
        assert not ext.is_interested(plain)
        assert ext.is_interested(gpu)

    def test_from_config_parses_reference_yaml_shape(self):
        cfg = {
            "urlPrefix": "http://127.0.0.1:9999/scheduler",
            "filterVerb": "filter", "prioritizeVerb": "prioritize",
            "bindVerb": "bind", "weight": 5, "nodeCacheCapable": True,
            "ignorable": True, "httpTimeout": "500ms",
            "managedResources": [{"name": "example.com/gpu",
                                  "ignoredByScheduler": True}],
        }
        ext = HTTPExtender.from_config(cfg)
        assert ext.weight == 5
        assert ext.node_cache_capable and ext.ignorable
        assert ext.timeout == pytest.approx(0.5)
        assert ext.managed_resources == {"example.com/gpu"}
        assert ext.is_binder()
