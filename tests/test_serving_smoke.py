"""Tier-1 smoke for the online serving tier (kubernetes_tpu/serving).

Pins: (a) the serving tier is ACTIVE BY DEFAULT — a trickle of lone
pods rides the pinned single-pod fast path, counted in the metrics;
(b) fast-path assignments are BIT-IDENTICAL to the batch path
(randomized differential vs TPUBackend.assign — the same pod through
both machines lands on the same node); (c) the KTPU_SERVING=0 kill
switch degrades STRUCTURALLY (no tier attached, no resident planes, no
fast-path counts) with identical end-to-end placements; (d) the
resident device planes stay exact across node add / remove / cordon /
drain (mirror and device array equal a fresh full upload, fast path
still agrees with the batch path); (e) the admission-window policy row
and its KTPU_ADMISSION_WINDOW override. The heavy serve-vs-drain
numbers live in bench --serve (BASELINE r16).
"""

import asyncio
import random

import numpy as np
import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.metrics.registry import SchedulerMetrics
from kubernetes_tpu.ops.backend import AdaptiveTuner, TPUBackend
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.types import PodInfo
from kubernetes_tpu.serving import serving_enabled
from kubernetes_tpu.serving.admission import AdmissionWindow
from kubernetes_tpu.serving.fastpath import SinglePodFastPath
from kubernetes_tpu.serving.resident import ResidentPlanes
from kubernetes_tpu.utils import locking
from test_tpu_backend import default_fwk


@pytest.fixture(autouse=True)
def _lock_check(monkeypatch):
    """Tier-1 rides the runtime lock/dispatch-hygiene detector: every
    lock built while this suite runs is instrumented, and the solve
    fetch / fast-path fetch / wire flush seams raise if entered with a
    lock held (utils/locking.py; the static pass's runtime twin)."""
    monkeypatch.setenv("KTPU_LOCK_CHECK", "1")
    locking.reset_observed()
    yield
    locking.reset_observed()


def _cluster(n, alloc=None, taint_every=0):
    cache = SchedulerCache()
    for i in range(n):
        taints = None
        if taint_every and i % taint_every == 0:
            taints = [{"key": "dedicated", "value": "infra",
                       "effect": "NoSchedule"}]
        cache.add_node(make_node(
            f"n{i}",
            allocatable=alloc or {"cpu": "8", "memory": "32Gi",
                                  "pods": "110"},
            taints=taints))
    return cache, cache.update_snapshot()


def _backend(chunk=16):
    b = TPUBackend(max_batch=chunk, mesh=None)
    b.metrics = SchedulerMetrics()
    return b


def _fast(backend):
    res = ResidentPlanes(backend)
    return res, SinglePodFastPath(backend, res)


class TestAdmissionPolicy:
    def test_tuner_policy_row(self):
        # At/below the r15 trickle (250/s): always immediate.
        assert AdaptiveTuner.admission_window(0.0, 0.0) == 0.0
        assert AdaptiveTuner.admission_window(0.0, 250.0) == 0.0
        # Above it: sized to ~TARGET pods, capped at 4 ms local.
        w = AdaptiveTuner.admission_window(0.0, 1000.0)
        assert 0.0 < w <= AdaptiveTuner.ADMISSION_MAX_WINDOW_S
        assert AdaptiveTuner.admission_window(0.0, 100000.0) \
            == pytest.approx(8.0 / 100000.0)
        # Relay-attached: the cap quadruples (dispatches cost an RTT),
        # so a rate the local cap would clamp gets a wider window.
        assert AdaptiveTuner.admission_window(0.030, 600.0) \
            > AdaptiveTuner.ADMISSION_MAX_WINDOW_S
        assert AdaptiveTuner.admission_window(0.030, 600.0) \
            <= 4 * AdaptiveTuner.ADMISSION_MAX_WINDOW_S

    def test_fast_path_cap_row(self):
        # Seeds before any measurement: 0.25 s chunk / 1 ms fast → 250.
        assert AdaptiveTuner.fast_path_cap(0.0, 0.0) == 250
        # Measured walls drive the crossover, clamped to [8, 512].
        assert AdaptiveTuner.fast_path_cap(0.4, 2e-3) == 200
        assert AdaptiveTuner.fast_path_cap(0.01, 5e-3) == 8
        assert AdaptiveTuner.fast_path_cap(10.0, 1e-3) == 512

    def test_fast_path_rate_limit_row(self):
        # Seed: 50% utilization of the optimistic 1 ms seed → 500/s
        # (clears the 250/s trickle with margin before any sample);
        # measured walls refine it (0.6 ms → ~833/s).
        assert AdaptiveTuner.fast_path_rate_limit(0.0) \
            == pytest.approx(500.0)
        assert AdaptiveTuner.fast_path_rate_limit(0.6e-3) \
            == pytest.approx(833.3, rel=1e-3)

    def test_fast_path_seed_scales_with_nodes(self):
        """An UNMEASURED fast wall seeds from the 5k calibration point
        scaled linearly with n (solve_one is a full-N scan): at 200k
        the cold cap must read ~0.25s/40ms = 8, not the 512 clamp that
        once let one big dispatch serial-drain 243 pods at ~125 ms
        each. Measured walls ignore the node count entirely, and at or
        below the calibration point the seeds are byte-identical to
        the old policy."""
        calib = AdaptiveTuner.FAST_PATH_SEED_CALIB_N
        assert AdaptiveTuner.fast_path_cap(0.0, 0.0, n_nodes=calib) == 250
        assert AdaptiveTuner.fast_path_rate_limit(0.0, n_nodes=calib) \
            == pytest.approx(500.0)
        # 200k: seed 40 ms → cap 0.25/0.04 ≈ 6 → clamped to the 8 floor,
        # rate limit 0.5/0.04 = 12.5/s (serial capacity there is ~8/s).
        assert AdaptiveTuner.fast_path_cap(0.0, 0.0, n_nodes=200_000) == 8
        assert AdaptiveTuner.fast_path_rate_limit(0.0, n_nodes=200_000) \
            == pytest.approx(12.5)
        # a measured wall wins over any node count
        assert AdaptiveTuner.fast_path_cap(0.4, 2e-3, n_nodes=200_000) \
            == 200
        assert AdaptiveTuner.fast_path_rate_limit(0.6e-3,
                                                  n_nodes=200_000) \
            == pytest.approx(833.3, rel=1e-3)

    def test_override_and_budget_gate(self, monkeypatch):
        monkeypatch.setenv("KTPU_ADMISSION_WINDOW", "2.5")
        win = AdmissionWindow()
        win.rate_est = 0.0  # override applies regardless of rate
        assert win.window_for(1, 0, 64) == pytest.approx(2.5e-3)
        # Budget already met (or the backlog meets it): never wait.
        assert win.window_for(64, 0, 64) == 0.0
        assert win.window_for(1, 64, 64) == 0.0
        monkeypatch.setenv("KTPU_ADMISSION_WINDOW", "0")
        assert win.window_for(1, 0, 64) == 0.0

    def test_rate_estimator_tracks_pops(self):
        win = AdmissionWindow()
        t = 100.0
        for _ in range(50):
            win.observe_pop(1, t)
            t += 0.001  # 1000/s trickle of lone pods
        assert win.rate_est == pytest.approx(1000.0, rel=0.1)


class TestFastPathDifferential:
    def test_randomized_single_pod_parity(self):
        """The same lone pod through solve_one-vs-the-fused-chunk must
        land identically, across random request shapes, taints, node
        selectors, and evolving cluster state."""
        cache, snap = _cluster(150, taint_every=7)
        fwk = default_fwk()
        rng = random.Random(0xBEEF)
        b_batch = _backend(chunk=16)
        b_fast = _backend(chunk=16)
        _, fp = _fast(b_fast)
        checked = 0
        for t in range(24):
            kw = {"requests": {
                "cpu": f"{rng.choice([100, 250, 500, 900, 1700])}m",
                "memory": f"{rng.choice([128, 512, 1024])}Mi"}}
            if rng.random() < 0.3:
                kw["tolerations"] = [{"key": "dedicated",
                                      "operator": "Exists"}]
            if rng.random() < 0.25:
                # NodeAffinity static row rides the fast-path base mask.
                kw["node_selector"] = {
                    "kubernetes.io/hostname": f"n{rng.randrange(150)}"}
            pi = PodInfo(make_pod(f"p{t}", uid=f"u{t}", **kw))
            a, _ = b_batch.assign([pi], snap, fwk)
            fast = fp.try_schedule(pi, snap, fwk)
            assert fast == a[pi.key], (t, kw)
            if fast is not None:
                checked += 1
                cache.assume_pod(pi, fast)
                snap = cache.update_snapshot()
        assert checked >= 12  # the differential actually exercised placements
        assert fp.placed == checked

    def test_ineligible_shapes_fall_through(self):
        _, snap = _cluster(20)
        fwk = default_fwk()
        b = _backend()
        _, fp = _fast(b)
        aff = {"podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [{
                "topologyKey": "kubernetes.io/hostname",
                "labelSelector": {"matchLabels": {"app": "x"}}}]}}
        cases = [
            make_pod("affinity", uid="u-aff", affinity=aff,
                     requests={"cpu": "100m"}),
            make_pod("ports", uid="u-port", host_ports=[8080],
                     requests={"cpu": "100m"}),
            make_pod("spread", uid="u-spr", requests={"cpu": "100m"},
                     labels={"app": "x"},
                     topology_spread_constraints=[{
                         "maxSkew": 1,
                         "topologyKey": "kubernetes.io/hostname",
                         "whenUnsatisfiable": "DoNotSchedule",
                         "labelSelector": {"matchLabels": {"app": "x"}}}]),
        ]
        for pod in cases:
            assert fp.try_schedule(PodInfo(pod), snap, fwk) is None, \
                pod["metadata"]["name"]
        assert fp.placed == 0
        assert fp.ineligible == len(cases)
        # A nominated preemptor keeps its nominee-first path.
        pi = PodInfo(make_pod("nom", uid="u-nom", requests={"cpu": "100m"}))
        pi.nominated_node = "n0"
        assert fp.try_schedule(pi, snap, fwk) is None


class TestLightSnapshot:
    def test_light_snapshot_invalidates_cached_full_snapshot(self):
        """light_snapshot()'s clone maintenance clears the dirty set; a
        later update_snapshot() must NOT hand back the pre-mutation
        cached snapshot (its copied lists hold the old clones)."""
        cache, _ = _cluster(4)
        a = cache.update_snapshot()
        pi = PodInfo(make_pod("ls-p0", uid="ls-p0",
                              requests={"cpu": "1"}))
        cache.assume_pod(pi, "n0")
        light = cache.light_snapshot()
        assert light.get("n0").requested.get("cpu") == 1000
        b = cache.update_snapshot()
        assert b is not a
        assert b.get("n0").requested.get("cpu") == 1000
        # clean path still memoizes once no mutation intervenes
        assert cache.update_snapshot() is b


class TestResidentPlaneParity:
    def _fresh_pack(self, ct):
        return np.concatenate(
            [ct.used_q, ct.used_nz_q,
             ct.used_pods.astype(np.int32)[:, None]], axis=1)

    def test_refresh_parity_across_node_lifecycle(self):
        """Mirror + device array must equal a from-scratch upload after
        assumes, node add, node remove, and a cordon (drain prologue) —
        and the fast path must keep agreeing with the batch path."""
        cache, snap = _cluster(40)
        fwk = default_fwk()
        b = _backend()
        res, fp = _fast(b)

        def check(tag):
            ct = b._tensors(cache.update_snapshot())
            res.used_pack(ct)
            fresh = self._fresh_pack(ct)
            assert np.array_equal(res.host_mirror(), fresh), tag
            assert np.array_equal(np.asarray(res._dev), fresh), tag
            pi = PodInfo(make_pod(f"probe-{tag}", uid=f"probe-{tag}",
                                  requests={"cpu": "250m",
                                            "memory": "256Mi"}))
            ref = _backend()
            a, _ = ref.assign([pi], cache.update_snapshot(), fwk)
            assert fp.try_schedule(pi, cache.update_snapshot(), fwk) \
                == a[pi.key], tag

        # assumes drive incremental row refreshes
        for t in range(10):
            pi = PodInfo(make_pod(f"w{t}", uid=f"w{t}",
                                  requests={"cpu": "500m",
                                            "memory": "1Gi"}))
            node = fp.try_schedule(pi, cache.update_snapshot(), fwk)
            assert node is not None
            cache.assume_pod(pi, node)
        check("assume")
        assert res.row_refreshes > 0
        cache.add_node(make_node("extra-0"))
        check("node-add")
        cache.remove_node("n39")
        check("node-remove")
        # Cordon: NodeUnschedulable's static row must flow into the
        # fast-path base mask (and the cordoned node never wins).
        cordoned = make_node("n0", unschedulable=True)
        cache.update_node(cordoned)
        check("cordon")
        ct = b._tensors(cache.update_snapshot())
        pi = PodInfo(make_pod("post-cordon", uid="post-cordon",
                              requests={"cpu": "100m"}))
        node = fp.try_schedule(pi, cache.update_snapshot(), fwk)
        assert node is not None and node != "n0"


def _serving_workload():
    return [make_pod(f"p{t}", uid=f"sp{t}",
                     requests={"cpu": "100m", "memory": "250Mi"})
            for t in range(30)]


async def _run_workload(trickle=0):
    """Schedule the standard workload through a live scheduler; returns
    (assignments dict, SchedulerMetrics, serving tier or None).

    trickle > 0 paces the first `trickle` creates as lone-pod arrivals
    (the fast-path shape); trickle == 0 pre-creates everything BEFORE
    the dispatch loop starts, so the first pop drains one batch — the
    drain shape whose placements the kill-switch parity check compares
    (lone pods deliberately aren't compared across the switch: the
    pre-serving loop routes them through the HOST path, whose seeded
    reservoir tiebreak differs from the device argmax tie rule by
    design — the fast path's parity contract is with the BATCH path,
    pinned in TestFastPathDifferential)."""
    from conftest import start_scheduler
    from kubernetes_tpu.api.meta import namespaced_name
    from kubernetes_tpu.store import install_core_validation, \
        new_cluster_store
    store = new_cluster_store()
    install_core_validation(store)
    for i in range(25):
        await store.create("nodes", make_node(
            f"n{i}", allocatable={"cpu": "4", "memory": "16Gi",
                                  "pods": "32"}))
    sched, factory = await start_scheduler(
        store, backend=TPUBackend(max_batch=16, mesh=None))
    pods = _serving_workload()
    run = None
    if trickle:
        run = asyncio.ensure_future(sched.run(batch_size=64))
    for t, pod in enumerate(pods):
        await store.create("pods", pod)
        if trickle and t < trickle:
            await asyncio.sleep(0.02)  # lone-pod arrivals
    if run is None:
        # Let every informer add land in the queue, then open the loop:
        # the first pop sees the whole batch in both serving modes.
        await asyncio.sleep(0.2)
        run = asyncio.ensure_future(sched.run(batch_size=64))
    try:
        for _ in range(600):
            objs = (await store.list("pods")).items
            if len(objs) == len(pods) and all(
                    p["spec"].get("nodeName") for p in objs):
                break
            await asyncio.sleep(0.05)
        else:
            raise AssertionError("pods never all bound")
        assignments = {namespaced_name(p): p["spec"]["nodeName"]
                       for p in (await store.list("pods")).items}
        return assignments, sched.metrics, sched.serving
    finally:
        await sched.stop()
        run.cancel()
        factory.stop()


class TestServingE2E:
    def test_active_by_default_fast_path_counts(self, monkeypatch):
        monkeypatch.delenv("KTPU_SERVING", raising=False)
        assert serving_enabled()
        _, m, tier = asyncio.run(_run_workload(trickle=8))
        assert tier is not None
        assert m.serving_fast_path_pods.value() > 0
        assert m.resident_plane_refreshes.value() > 0

    def test_kill_switch_structural_degrade_and_parity(self, monkeypatch):
        monkeypatch.delenv("KTPU_SERVING", raising=False)
        a_on, m_on, tier_on = asyncio.run(_run_workload())
        assert tier_on is not None
        assert m_on.resident_plane_refreshes.value() > 0

        monkeypatch.setenv("KTPU_SERVING", "0")
        assert not serving_enabled()
        a_off, m_off, tier_off = asyncio.run(_run_workload())
        # Structural degrade: no tier, no fast-path counts, no resident
        # refreshes — the pre-serving loop shape.
        assert tier_off is None
        assert m_off.serving_fast_path_pods.value() == 0
        assert m_off.resident_plane_refreshes.value() == 0
        # ... and bit-identical batch placements across the switch.
        assert a_on == a_off
