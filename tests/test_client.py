"""Client layer tests: workqueue semantics, informer sync + handlers,
leader election fencing."""

import asyncio

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client import (
    InformerFactory,
    LeaderElector,
    RateLimitingQueue,
    ResourceEventHandler,
    WorkQueue,
)
from kubernetes_tpu.store import MVCCStore


def run(coro):
    return asyncio.run(coro)


class TestWorkQueue:
    def test_dedup_while_queued(self):
        async def body():
            q = WorkQueue()
            await q.add("a")
            await q.add("a")
            await q.add("b")
            assert len(q) == 2
        run(body())

    def test_requeue_while_processing(self):
        async def body():
            q = WorkQueue()
            await q.add("a")
            item, _ = await q.get()
            assert item == "a" and len(q) == 0
            await q.add("a")  # re-add while in flight: goes to dirty, not queue
            assert len(q) == 0
            await q.done("a")  # now it re-enters the queue
            assert len(q) == 1
        run(body())

    def test_shutdown_unblocks_getters(self):
        async def body():
            q = WorkQueue()
            getter = asyncio.ensure_future(q.get())
            await asyncio.sleep(0.01)
            await q.shut_down()
            item, shutdown = await asyncio.wait_for(getter, 1)
            assert shutdown and item is None
        run(body())

    def test_rate_limited_backoff_growth(self):
        async def body():
            q = RateLimitingQueue()
            assert q.rate_limiter.when("x") == 0.005
            assert q.rate_limiter.when("x") == 0.010
            assert q.num_requeues("x") == 2
            q.forget("x")
            assert q.num_requeues("x") == 0
        run(body())

    def test_add_after_earlier_item_not_stuck_behind_long_delay(self):
        async def body():
            q = RateLimitingQueue()
            await q.add_after("slow", 600)
            await q.add_after("fast", 0.01)
            import time
            t0 = time.monotonic()
            item, _ = await asyncio.wait_for(q.get(), 2)
            assert item == "fast"
            assert time.monotonic() - t0 < 1.0
            await q.shut_down()
        run(body())

    def test_add_after_delivers(self):
        async def body():
            q = RateLimitingQueue()
            await q.add_after("late", 0.02)
            await q.add("now")
            first, _ = await q.get()
            assert first == "now"
            second, _ = await asyncio.wait_for(q.get(), 1)
            assert second == "late"
        run(body())


class TestInformer:
    def test_sync_and_live_events(self):
        async def body():
            store = MVCCStore()
            await store.create("nodes", make_node("n1"))
            factory = InformerFactory(store)
            inf = factory.informer("nodes")
            adds, updates, deletes = [], [], []
            inf.add_event_handler(ResourceEventHandler(
                on_add=lambda o: adds.append(o["metadata"]["name"]),
                on_update=lambda old, new: updates.append(new["metadata"]["name"]),
                on_delete=lambda o: deletes.append(o["metadata"]["name"]),
            ))
            factory.start()
            await factory.wait_for_sync()
            assert adds == ["n1"]
            assert len(inf.indexer) == 1

            await store.create("nodes", make_node("n2"))
            n1 = await store.get("nodes", "n1")
            n1["metadata"]["labels"]["zone"] = "a"
            await store.update("nodes", n1)
            await store.delete("nodes", "n2")
            await asyncio.sleep(0.05)
            assert adds == ["n1", "n2"]
            assert updates == ["n1"]
            assert deletes == ["n2"]
            factory.stop()
            store.stop()
        run(body())

    def test_late_handler_gets_synthetic_adds(self):
        async def body():
            store = MVCCStore()
            await store.create("pods", make_pod("p1"))
            factory = InformerFactory(store)
            inf = factory.informer("pods")
            factory.start()
            await factory.wait_for_sync()
            seen = []
            inf.add_event_handler(ResourceEventHandler(
                on_add=lambda o: seen.append(o["metadata"]["name"])))
            assert seen == ["p1"]
            factory.stop()
            store.stop()
        run(body())

    def test_namespace_index(self):
        async def body():
            store = MVCCStore()
            await store.create("pods", make_pod("a", namespace="ns1"))
            await store.create("pods", make_pod("b", namespace="ns2"))
            factory = InformerFactory(store)
            inf = factory.informer("pods")
            factory.start()
            await factory.wait_for_sync()
            assert [o["metadata"]["name"] for o in inf.indexer.by_index("namespace", "ns1")] == ["a"]
            factory.stop()
            store.stop()
        run(body())


class TestLeaderElection:
    def test_single_leader_and_failover(self):
        async def body():
            store = MVCCStore()
            order = []

            def make_payload(tag, hold):
                async def payload():
                    order.append(f"{tag}-start")
                    await asyncio.sleep(hold)
                    order.append(f"{tag}-done")
                return payload

            le1 = LeaderElector(store, "sched", "a", lease_duration=0.2,
                                renew_deadline=0.15, retry_period=0.03)
            le2 = LeaderElector(store, "sched", "b", lease_duration=0.2,
                                renew_deadline=0.15, retry_period=0.03)
            t1 = asyncio.ensure_future(le1.run(make_payload("a", 0.1)))
            await asyncio.sleep(0.02)
            t2 = asyncio.ensure_future(le2.run(make_payload("b", 0.1)))
            await asyncio.wait_for(asyncio.gather(t1, t2), 5)
            # a leads first; b only starts after a's payload finishes + lease expiry
            assert order[0] == "a-start"
            assert "b-start" in order
            assert order.index("a-done") < order.index("b-start")
        run(body())
