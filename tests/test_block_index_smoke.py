"""Tier-1 smoke for the two-level block-sparse node index (ISSUE 20).

Pins: (a) the index is ACTIVE BY DEFAULT at large N — the AdaptiveTuner
block-width row turns on structurally at n_real >= LARGE_N with the
shortlist active, no flag needed; (b) the KTPU_BLOCK_INDEX=0 kill switch
degrades STRUCTURALLY (width 0 → the full-width r18/r21 prefilter call
graph, not a masked no-op), as do KTPU_BLOCK_WIDTH=0 and every shape
guard; (c) at small N the counters must not drift — zero blocks scanned
or pruned when the policy row keeps the index off; (d) the resident
serving planes' per-block aggregate maintenance stays exact across
churn (incremental dirty-block refresh equals a from-scratch recompute,
bit for bit, host and device). The heavy parity battery lives in
test_block_index_solver.py; the perf numbers in bench (BASELINE).
"""

import numpy as np
import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.metrics.registry import SchedulerMetrics
from kubernetes_tpu.ops.backend import AdaptiveTuner, TPUBackend
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.types import PodInfo
from kubernetes_tpu.serving.resident import _BLOCK_BIG, ResidentPlanes
from test_tpu_backend import default_fwk


class TestTunerPolicyRow:
    def test_active_by_default_at_large_n(self):
        """No flags set: the structural row turns the index on at
        n_real >= LARGE_N with a live shortlist — the default width."""
        t = AdaptiveTuner()
        n = AdaptiveTuner.LARGE_N
        assert t.block_width(n, n, 1024) == AdaptiveTuner.BLOCK_WIDTH

    def test_small_n_routes_zero(self):
        t = AdaptiveTuner()
        assert t.block_width(4096, 4096, 256) == 0

    def test_requires_shortlist(self):
        """The index prunes the shortlist prefilter's own O(C·N) pass —
        without a threshold there is nothing to bound against."""
        t = AdaptiveTuner()
        n = AdaptiveTuner.LARGE_N
        assert t.block_width(n, n, 0) == 0

    def test_kill_switch_structural(self, monkeypatch):
        monkeypatch.setenv("KTPU_BLOCK_INDEX", "0")
        t = AdaptiveTuner()
        n = AdaptiveTuner.LARGE_N
        assert t.block_width(n, n, 1024) == 0

    def test_width_override_and_zero_disable(self, monkeypatch):
        t = AdaptiveTuner()
        n = AdaptiveTuner.LARGE_N
        monkeypatch.setenv("KTPU_BLOCK_WIDTH", "64")
        assert t.block_width(n, n, 1024) == 64
        monkeypatch.setenv("KTPU_BLOCK_WIDTH", "0")
        assert t.block_width(n, n, 1024) == 0

    def test_shape_guard_m_plus_one_exceeds_b(self, monkeypatch):
        """A width/N/K combination where selection could not leave one
        block unselected routes 0 — the ValueError stays unreachable."""
        t = AdaptiveTuner()
        monkeypatch.setenv("KTPU_BLOCK_WIDTH", "16")
        monkeypatch.setattr(AdaptiveTuner, "LARGE_N", 1)
        # n_pad=64 → B=4; K=63 → M=2·ceil(64/16)=8 → M+1 > B.
        assert t.block_width(64, 64, 63) == 0
        # Wide enough B passes.
        assert t.block_width(1024, 1024, 63) == 16


class TestCounterHygiene:
    def test_zero_drift_at_small_n(self):
        """Default policy at toy scale: the block counters must stay at
        exactly zero (the kill-switch/off shape is structural — a
        nonzero count here means the policy row leaked)."""
        cache = SchedulerCache()
        for i in range(24):
            cache.add_node(make_node(f"n{i}"))
        snap = cache.update_snapshot()
        pods = [PodInfo(make_pod(f"p{i}", uid=f"u{i}",
                                 requests={"cpu": "100m"}))
                for i in range(12)]
        b = TPUBackend(max_batch=16, mesh=None)
        b.metrics = SchedulerMetrics()
        b.assign(pods, snap, default_fwk())
        assert b.metrics.solver_blocks_scanned.value() == 0
        assert b.metrics.solver_blocks_pruned.value() == 0


class TestResidentBlockAggregates:
    def _cluster(self, n=40):
        cache = SchedulerCache()
        for i in range(n):
            cache.add_node(make_node(
                f"n{i}", allocatable={"cpu": "8", "memory": "32Gi",
                                      "pods": "110"}))
        return cache

    def _recompute(self, res, ct, bw):
        """From-scratch recompute of the five planes off the host
        mirror — the oracle the incremental path must match."""
        n = ct.n_real
        alloc = np.asarray(ct.alloc_q[:n], dtype=np.int32)
        r = alloc.shape[1]
        used_nz = res.host_mirror()[:n, r:2 * r]
        b = -(-n // bw)

        def fold(x, fill):
            pad = b * bw - n
            if pad:
                x = np.concatenate(
                    [x, np.full((pad, r), fill, np.int32)])
            return x.reshape(b, bw, r)

        return {
            "amin_pos": fold(np.where(alloc > 0, alloc, _BLOCK_BIG),
                             _BLOCK_BIG).min(axis=1),
            "amin": fold(alloc, _BLOCK_BIG).min(axis=1),
            "amax": fold(alloc, 0).max(axis=1),
            "umin": fold(used_nz, _BLOCK_BIG).min(axis=1),
            "umax": fold(used_nz, 0).max(axis=1),
        }

    def test_incremental_refresh_matches_recompute(self, monkeypatch):
        """Assume-driven churn dirties a few rows; the dirty-block
        incremental path must leave every plane equal to a from-scratch
        recompute — host AND the packed device mirror — and the refresh
        histogram must see the work."""
        monkeypatch.setenv("KTPU_BLOCK_WIDTH", "8")
        cache = self._cluster()
        b = TPUBackend(max_batch=16, mesh=None)
        m = SchedulerMetrics()
        res = ResidentPlanes(b, metrics=m)
        ct = b._tensors(cache.update_snapshot())
        res.used_pack(ct)
        bw, planes, dev = res.block_aggregates()
        assert bw == 8 and planes is not None
        for key, want in self._recompute(res, ct, bw).items():
            np.testing.assert_array_equal(planes[key], want, err_msg=key)
        # churn: a handful of assumes across distinct blocks
        for t, node in enumerate(("n3", "n3", "n17", "n30")):
            cache.assume_pod(PodInfo(make_pod(
                f"w{t}", uid=f"w{t}",
                requests={"cpu": "500m", "memory": "1Gi"})), node)
            ct = b._tensors(cache.update_snapshot())
            res.used_pack(ct)
        assert res.row_refreshes > 0  # the incremental path actually ran
        bw, planes, dev = res.block_aggregates()
        oracle = self._recompute(res, ct, bw)
        for key, want in oracle.items():
            np.testing.assert_array_equal(planes[key], want, err_msg=key)
        np.testing.assert_array_equal(
            np.asarray(dev),
            np.concatenate([oracle[k] for k in
                            ("amin_pos", "amin", "amax", "umin",
                             "umax")], axis=1))
        assert m.solver_block_refresh.count() > 0

    def test_kill_switch_no_planes(self, monkeypatch):
        """KTPU_BLOCK_INDEX=0: no planes maintained, no histogram
        samples — the serving tier pays nothing for the index."""
        monkeypatch.setenv("KTPU_BLOCK_INDEX", "0")
        cache = self._cluster(12)
        b = TPUBackend(max_batch=16, mesh=None)
        m = SchedulerMetrics()
        res = ResidentPlanes(b, metrics=m)
        res.used_pack(b._tensors(cache.update_snapshot()))
        bw, planes, dev = res.block_aggregates()
        assert bw == 0 and planes is None and dev is None
        assert m.solver_block_refresh.count() == 0

    def test_full_rebuild_on_node_set_change(self, monkeypatch):
        """A node add flips set_epoch → full rebuild path; the planes
        must track the new B and stay exact."""
        monkeypatch.setenv("KTPU_BLOCK_WIDTH", "8")
        cache = self._cluster()
        b = TPUBackend(max_batch=16, mesh=None)
        res = ResidentPlanes(b)
        res.used_pack(b._tensors(cache.update_snapshot()))
        cache.add_node(make_node("extra-0"))
        ct = b._tensors(cache.update_snapshot())
        res.used_pack(ct)
        bw, planes, _ = res.block_aggregates()
        assert planes["amax"].shape[0] == -(-ct.n_real // bw)
        for key, want in self._recompute(res, ct, bw).items():
            np.testing.assert_array_equal(planes[key], want, err_msg=key)
