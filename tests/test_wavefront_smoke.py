"""Tier-1 guard for the speculative wavefront solve (small-N, fast).

Pins: (a) the tuner's wave_width policy row — the swept default, the
KTPU_WAVE_WIDTH override clamp, and the replay-fraction narrowing rule
with its minimum sample; (b) the wavefront being ACTIVE by default
through the backend (wave metrics populated, W > 1) with a bounded
replay fraction on a benign template workload; (c) the KTPU_WAVEFRONT=0
kill switch degrading STRUCTURALLY (wave counters stay zero — the W=1
scan functions run, not one-member waves) with identical assignments.
The heavyweight randomized differential parity lives in
tests/test_wavefront_solver.py.
"""

from kubernetes_tpu.ops.backend import AdaptiveTuner
from kubernetes_tpu.utils import flags


class TestWavePolicy:
    def test_node_count_tiers(self):
        """The swept policy rows (BASELINE r18, 5k/50k/200k): W grows
        with node count — structural, like the large-N chunk row."""
        t = AdaptiveTuner()
        assert t.wave_width(1024) == AdaptiveTuner.WAVE_WIDTH_SMALL == 32
        t.n_nodes = 50_000
        assert t.wave_width(1024) == AdaptiveTuner.WAVE_WIDTH_LARGE == 64
        t.n_nodes = 200_000
        assert t.wave_width(1024) == 64
        # Waves never exceed the chunk (tiny test chunks).
        assert t.wave_width(4) == 4
        assert t.wave_width(1) == 1

    def test_override_pins_width(self):
        t = AdaptiveTuner()
        with flags.scoped_set("KTPU_WAVE_WIDTH", "2"):
            assert t.wave_width(1024) == 2
        with flags.scoped_set("KTPU_WAVE_WIDTH", "4096"):
            assert t.wave_width(1024) == 1024  # clamped to the chunk
        with flags.scoped_set("KTPU_WAVE_WIDTH", "0"):
            assert t.wave_width(1024) == 1

    def test_replay_fraction_narrows_width(self):
        """>25% replays at a decide() boundary halves W — replays are
        exact but serial, so a conflicting workload must narrow (the
        shortlist boost rule, mirrored). The shrink applies across the
        node-count tiers."""
        t = AdaptiveTuner()
        t.n_nodes = 50_000
        t.observe_wave(512, 512)  # 50% replay fraction
        t.decide()
        assert t.wave_width(1024) == 32
        t.observe_wave(0, 1024)   # still conflicting: halve again
        t.decide()
        assert t.wave_width(1024) == 16
        for _ in range(8):        # shrink floors at the serial scan
            t.observe_wave(0, 2048)
            t.decide()
        assert t.wave_width(1024) == 1

    def test_narrowing_needs_sample_and_rate(self):
        t = AdaptiveTuner()
        t.observe_wave(10, 90)    # tiny sample: not trusted yet
        t.decide()
        assert t.wave_width(1024) == 32
        t.observe_wave(900, 124)  # ~12% < 25%: healthy
        t.decide()
        assert t.wave_width(1024) == 32


class TestBackendSmoke:
    def _template_pods(self, n):
        from kubernetes_tpu.api.types import make_pod
        from kubernetes_tpu.scheduler.types import PodInfo
        return [PodInfo(make_pod(
            f"wf-{i}", requests={"cpu": "500m", "memory": "512Mi"},
            uid=f"wf-uid-{i}")) for i in range(n)]

    def _uniform_cluster(self, n):
        from kubernetes_tpu.api.types import make_node
        from kubernetes_tpu.scheduler.cache import SchedulerCache
        cache = SchedulerCache()
        for i in range(n):
            cache.add_node(make_node(
                f"wn{i}", allocatable={"cpu": "8", "memory": "32Gi",
                                       "pods": "110"}))
        return cache.update_snapshot()

    def test_active_by_default_bounded_replays(self):
        """No flags: the wavefront solves every chunk at the policy W,
        and the benign template workload keeps the replay fraction under
        the tuner's own narrowing trigger (beyond it the wavefront would
        be narrowing itself)."""
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.metrics.registry import SchedulerMetrics
        from kubernetes_tpu.ops.backend import TPUBackend
        snap = self._uniform_cluster(120)
        pods = self._template_pods(40)
        b = TPUBackend(max_batch=16, mesh=None)
        b.metrics = SchedulerMetrics()
        assignments, _ = b.assign(pods, snap, default_fwk())
        m = b.metrics
        # Small cluster → the small tier, clamped to the test chunk.
        assert m.solver_wave_width.value() == 16  # min(32, chunk 16)
        com = m.solver_wave_commits.value()
        rep = m.solver_wave_replays.value()
        assert com + rep >= len(pods)
        assert rep <= AdaptiveTuner.WAVE_REPLAY_RATIO * (com + rep), \
            (com, rep)
        assert all(v is not None for v in assignments.values())

    def test_kill_switch_structural_degrade(self):
        """KTPU_WAVEFRONT=0 routes the W=1 scan FUNCTIONS: wave counters
        stay zero (no one-member waves in disguise), wave_width reports
        1, and assignments match the flagless run exactly."""
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.metrics.registry import SchedulerMetrics
        from kubernetes_tpu.ops.backend import TPUBackend
        snap = self._uniform_cluster(100)
        pods = self._template_pods(24)
        fwk = default_fwk()
        on, _ = TPUBackend(max_batch=16, mesh=None).assign(
            pods, snap, fwk)
        b = TPUBackend(max_batch=16, mesh=None)
        b.metrics = SchedulerMetrics()
        with flags.scoped_set("KTPU_WAVEFRONT", "0"):
            off, _ = b.assign(pods, snap, fwk)
        assert off == on
        assert b.metrics.solver_wave_commits.value() == 0
        assert b.metrics.solver_wave_replays.value() == 0
        assert b.metrics.solver_wave_width.value() == 1

    def test_width_override_through_backend(self):
        """KTPU_WAVE_WIDTH pins W end to end (the program key carries
        it) without changing assignments."""
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.metrics.registry import SchedulerMetrics
        from kubernetes_tpu.ops.backend import TPUBackend
        snap = self._uniform_cluster(80)
        pods = self._template_pods(16)
        fwk = default_fwk()
        base, _ = TPUBackend(max_batch=16, mesh=None).assign(
            pods, snap, fwk)
        b = TPUBackend(max_batch=16, mesh=None)
        b.metrics = SchedulerMetrics()
        with flags.scoped_set("KTPU_WAVE_WIDTH", "4"):
            got, _ = b.assign(pods, snap, fwk)
        assert got == base
        assert b.metrics.solver_wave_width.value() == 4
