"""Admission webhooks (mutating + validating, §3.2's HTTPS out-calls)
and CustomResourceDefinition support."""

import asyncio

import pytest
from aiohttp import web

from kubernetes_tpu.api.types import make_pod
from kubernetes_tpu.apiserver.admission import (
    WebhookAdmission,
    apply_json_patch,
    install_crd_support,
    make_crd,
    validate_against_schema,
)
from kubernetes_tpu.apiserver.client import RemoteStore
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.store import install_core_validation, new_cluster_store
from kubernetes_tpu.store.mvcc import Invalid, StoreError


def run(coro):
    return asyncio.run(coro)


async def _webhook_server(handler):
    """Tiny HTTP server playing the webhook sidecar."""
    app = web.Application()
    app.router.add_post("/hook", handler)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}/hook"


class TestJsonPatch:
    def test_add_replace_remove(self):
        obj = {"metadata": {"labels": {"a": "1"}},
               "spec": {"containers": [{"name": "c"}]}}
        out = apply_json_patch(obj, [
            {"op": "add", "path": "/metadata/labels/b", "value": "2"},
            {"op": "replace", "path": "/metadata/labels/a", "value": "9"},
            {"op": "remove", "path": "/spec/containers/0/name"},
            {"op": "add", "path": "/spec/containers/-",
             "value": {"name": "sidecar"}},
        ])
        assert out["metadata"]["labels"] == {"a": "9", "b": "2"}
        assert out["spec"]["containers"] == [{}, {"name": "sidecar"}]


class TestWebhooks:
    def test_mutating_then_validating_over_http(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)

            async def mutate(request):
                review = await request.json()
                assert review["request"]["operation"] == "CREATE"
                return web.json_response({"response": {
                    "allowed": True,
                    "patch": [{"op": "add",
                               "path": "/metadata/labels",
                               "value": {"injected": "true"}}]}})

            async def validate(request):
                review = await request.json()
                meta = review["request"]["object"]["metadata"]
                ok = (meta.get("labels") or {}).get("injected") == "true" \
                    and (meta.get("annotations") or {}).get(
                        "forbidden") != "true"
                return web.json_response({"response": {
                    "allowed": ok,
                    "status": {"message": "forbidden label"}}})

            r1, mutate_url = await _webhook_server(mutate)
            r2, validate_url = await _webhook_server(validate)
            await store.create("mutatingwebhookconfigurations", {
                "kind": "MutatingWebhookConfiguration",
                "metadata": {"name": "m"},
                "webhooks": [{"name": "inject.ktpu.dev",
                              "clientConfig": {"url": mutate_url},
                              "rules": [{"resources": ["pods"],
                                         "operations": ["CREATE"]}]}]})
            await store.create("validatingwebhookconfigurations", {
                "kind": "ValidatingWebhookConfiguration",
                "metadata": {"name": "v"},
                "webhooks": [{"name": "check.ktpu.dev",
                              "clientConfig": {"url": validate_url},
                              "rules": [{"resources": ["pods"],
                                         "operations": ["*"]}]}]})
            srv = APIServer(store, admission=WebhookAdmission(store))
            await srv.start()
            rs = RemoteStore(srv.url)

            created = await rs.create("pods", make_pod("a"))
            # Mutating webhook injected the label; validator passed it.
            assert created["metadata"]["labels"]["injected"] == "true"

            bad = make_pod("b")
            bad["metadata"]["annotations"] = {"forbidden": "true"}
            with pytest.raises(StoreError) as exc:
                await rs.create("pods", bad)
            assert "denied the request" in str(exc.value)

            await rs.close()
            await srv.stop()
            await r1.cleanup()
            await r2.cleanup()
            store.stop()
        run(body())

    def test_failure_policy(self):
        async def body():
            store = new_cluster_store()
            adm = WebhookAdmission(store, timeout=0.5)
            await store.create("validatingwebhookconfigurations", {
                "kind": "ValidatingWebhookConfiguration",
                "metadata": {"name": "down"},
                "webhooks": [{"name": "ignore.ktpu.dev",
                              "clientConfig": {
                                  "url": "http://127.0.0.1:1/hook"},
                              "failurePolicy": "Ignore",
                              "rules": [{"resources": ["pods"]}]}]})
            # Ignore → unreachable webhook is skipped.
            out = await adm.admit(make_pod("a"), "pods", "create")
            assert out["metadata"]["name"] == "a"
            await store.create("validatingwebhookconfigurations", {
                "kind": "ValidatingWebhookConfiguration",
                "metadata": {"name": "hard"},
                "webhooks": [{"name": "fail.ktpu.dev",
                              "clientConfig": {
                                  "url": "http://127.0.0.1:1/hook"},
                              "failurePolicy": "Fail",
                              "rules": [{"resources": ["pods"]}]}]})
            with pytest.raises(Invalid):
                await adm.admit(make_pod("b"), "pods", "create")
            await adm.close()
            store.stop()
        run(body())


class TestCRDs:
    def test_crd_registers_resource_with_schema(self):
        async def body():
            store = new_cluster_store()
            install_crd_support(store)
            await store.create("customresourcedefinitions", make_crd(
                "tpujobs", "TPUJob", schema={
                    "type": "object",
                    "required": ["slices"],
                    "properties": {
                        "slices": {"type": "integer"},
                        "topology": {"type": "string",
                                     "enum": ["2x2", "2x4", "4x4"]},
                    }}))
            # Valid custom object round-trips.
            await store.create("tpujobs", {
                "apiVersion": "ktpu.dev/v1", "kind": "TPUJob",
                "metadata": {"name": "train", "namespace": "default"},
                "spec": {"slices": 4, "topology": "2x4"}})
            got = await store.get("tpujobs", "default/train")
            assert got["spec"]["slices"] == 4
            # Schema violations rejected.
            with pytest.raises(Invalid):
                await store.create("tpujobs", {
                    "kind": "TPUJob",
                    "metadata": {"name": "bad", "namespace": "default"},
                    "spec": {"topology": "2x4"}})   # missing slices
            with pytest.raises(Invalid):
                await store.create("tpujobs", {
                    "kind": "TPUJob",
                    "metadata": {"name": "bad2", "namespace": "default"},
                    "spec": {"slices": 2, "topology": "3x3"}})  # enum
            # The kind→resource mapping is STORE-LOCAL (ADVICE r3): other
            # stores in the process never see this CRD, and the process
            # globals stay untouched.
            from kubernetes_tpu.api.meta import KIND_TO_RESOURCE
            assert store.resource_for_kind("TPUJob") == "tpujobs"
            assert "TPUJob" not in KIND_TO_RESOURCE
            other = new_cluster_store()
            assert other.resource_for_kind("TPUJob") is None
            other.stop()
            store.stop()
        run(body())

    def test_crd_delete_deregisters_and_rescopes(self):
        """Deleting a CRD drops its kind mapping + cluster scoping; a
        re-created Namespaced CRD after a Cluster one must not inherit the
        stale scope (ADVICE r3 finding)."""
        async def body():
            store = new_cluster_store()
            install_crd_support(store)
            crd = make_crd("widgets", "Widget", scope="Cluster")
            await store.create("customresourcedefinitions", crd)
            assert store.resource_for_kind("Widget") == "widgets"
            assert store.is_cluster_scoped("widgets")
            await store.delete("customresourcedefinitions",
                               "widgets.ktpu.dev")
            assert store.resource_for_kind("Widget") is None
            assert not store.is_cluster_scoped("widgets")
            # Re-create as Namespaced: scope follows the live CRD.
            await store.create("customresourcedefinitions",
                               make_crd("widgets", "Widget"))
            assert store.resource_for_kind("Widget") == "widgets"
            assert not store.is_cluster_scoped("widgets")
            store.stop()
        run(body())

    def test_schema_validator_primitives(self):
        validate_against_schema({"a": 1}, {
            "type": "object", "properties": {"a": {"type": "integer"}}})
        with pytest.raises(Invalid):
            validate_against_schema(
                {"a": "x"},
                {"type": "object",
                 "properties": {"a": {"type": "integer"}}}, "t")
        with pytest.raises(Invalid):
            validate_against_schema([1, "x"], {
                "type": "array", "items": {"type": "integer"}}, "t")
