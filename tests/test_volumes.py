"""Volume family: PV binder controller + VolumeBinding/VolumeZone/
NodeVolumeLimits plugins (SURVEY §2.3 volumebinding/, §2.4 pv_controller).

The headline e2e (VERDICT r2 #3): a pod with an unbound
WaitForFirstConsumer PVC schedules only after PreBind's blocking
provisioning; Unreserve releases the claim plan on failure.
"""

import asyncio

from kubernetes_tpu.api.types import (
    make_node,
    make_pod,
    make_pv,
    make_pvc,
    make_storage_class,
)
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.controllers import ControllerManager, PVBinderController
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def run(coro):
    return asyncio.run(coro)


async def wait_for(predicate, timeout=10.0, interval=0.03):
    for _ in range(int(timeout / interval)):
        v = await predicate()
        if v:
            return v
        await asyncio.sleep(interval)
    return await predicate()


def pod_with_pvc(name, claim, **kw):
    pod = make_pod(name, requests={"cpu": "100m"}, **kw)
    pod["spec"]["volumes"] = [
        {"name": "data", "persistentVolumeClaim": {"claimName": claim}}]
    return pod


async def volume_stack(nodes=None):
    store = new_cluster_store()
    install_core_validation(store)
    for n in nodes or [make_node(f"n{i}") for i in range(3)]:
        await store.create("nodes", n)
    mgr = ControllerManager(store, [PVBinderController(store)])
    await mgr.start()
    sched = Scheduler(store, seed=11)
    factory = InformerFactory(store)
    await sched.setup_informers(factory)
    factory.start()
    await factory.wait_for_sync()
    task = asyncio.ensure_future(sched.run())

    async def teardown():
        await sched.stop()
        task.cancel()
        await mgr.stop()
        factory.stop()
        store.stop()
    return store, sched, teardown


class TestPVBinder:
    def test_immediate_binding_static_pv(self):
        async def body():
            store, sched, teardown = await volume_stack()
            await store.create("persistentvolumes", make_pv("pv-a", "10Gi"))
            await store.create("persistentvolumeclaims", make_pvc(
                "data", request="5Gi"))

            async def bound():
                pvc = await store.get("persistentvolumeclaims", "default/data")
                return pvc["status"].get("phase") == "Bound" and \
                    pvc["spec"].get("volumeName") == "pv-a"
            assert await wait_for(bound)
            pv = await store.get("persistentvolumes", "pv-a")
            assert pv["status"]["phase"] == "Bound"
            assert pv["spec"]["claimRef"]["name"] == "data"
            await teardown()
        run(body())

    def test_capacity_and_class_matching(self):
        async def body():
            store, sched, teardown = await volume_stack()
            await store.create("persistentvolumes", make_pv("small", "1Gi"))
            await store.create("persistentvolumes", make_pv(
                "classed", "20Gi", storage_class="fast"))
            await store.create("persistentvolumes", make_pv("big", "20Gi"))
            await store.create("persistentvolumeclaims", make_pvc(
                "data", request="5Gi"))

            async def bound():
                pvc = await store.get("persistentvolumeclaims", "default/data")
                return pvc["spec"].get("volumeName")
            vol = await wait_for(bound)
            assert vol == "big"  # capacity too small / class mismatch skipped
            await teardown()
        run(body())

    def test_pvc_delete_releases_pv(self):
        async def body():
            store, sched, teardown = await volume_stack()
            await store.create("persistentvolumes", make_pv("pv-a", "10Gi"))
            await store.create("persistentvolumeclaims", make_pvc("data"))

            async def bound():
                pv = await store.get("persistentvolumes", "pv-a")
                return pv["status"].get("phase") == "Bound"
            assert await wait_for(bound)
            await store.delete("persistentvolumeclaims", "default/data")

            async def released():
                pv = await store.get("persistentvolumes", "pv-a")
                return pv["status"].get("phase") == "Available" and \
                    not pv["spec"].get("claimRef")
            assert await wait_for(released)
            await teardown()
        run(body())


class TestVolumeBindingE2E:
    def test_wffc_pod_schedules_after_blocking_provision(self):
        """The VERDICT done-criterion: unbound WFFC PVC; the pod's PreBind
        writes selected-node and blocks; the PV controller provisions a PV
        pinned to that node; only then does the pod bind."""
        async def body():
            store, sched, teardown = await volume_stack()
            await store.create("storageclasses", make_storage_class(
                "wffc", binding_mode="WaitForFirstConsumer"))
            await store.create("persistentvolumeclaims", make_pvc(
                "data", storage_class="wffc"))
            await store.create("pods", pod_with_pvc("app", "data"))

            async def pod_bound():
                pod = await store.get("pods", "default/app")
                return pod["spec"].get("nodeName")
            node = await wait_for(pod_bound, timeout=15.0)
            assert node
            pvc = await store.get("persistentvolumeclaims", "default/data")
            assert pvc["status"]["phase"] == "Bound"
            ann = pvc["metadata"]["annotations"][
                "volume.kubernetes.io/selected-node"]
            assert ann == node
            # The provisioned PV is topology-pinned to the selected node.
            pv = await store.get("persistentvolumes",
                                 pvc["spec"]["volumeName"])
            terms = pv["spec"]["nodeAffinity"]["required"][
                "nodeSelectorTerms"]
            assert terms[0]["matchFields"][0]["values"] == [node]
            await teardown()
        run(body())

    def test_bound_pv_node_affinity_constrains_scheduling(self):
        """A pre-bound local PV pinned to n1 forces the pod onto n1."""
        async def body():
            store, sched, teardown = await volume_stack()
            pv = make_pv("local-pv", "10Gi", node_affinity={
                "nodeSelectorTerms": [{"matchFields": [
                    {"key": "metadata.name", "operator": "In",
                     "values": ["n1"]}]}]})
            await store.create("persistentvolumes", pv)
            await store.create("persistentvolumeclaims", make_pvc("data"))

            async def pvc_bound():
                c = await store.get("persistentvolumeclaims", "default/data")
                return c["status"].get("phase") == "Bound"
            assert await wait_for(pvc_bound)
            await store.create("pods", pod_with_pvc("app", "data"))

            async def pod_bound():
                pod = await store.get("pods", "default/app")
                return pod["spec"].get("nodeName")
            node = await wait_for(pod_bound, timeout=15.0)
            assert node == "n1"
            await teardown()
        run(body())

    def test_missing_pvc_is_unschedulable(self):
        async def body():
            store, sched, teardown = await volume_stack()
            await store.create("pods", pod_with_pvc("app", "nope"))
            await asyncio.sleep(0.5)
            pod = await store.get("pods", "default/app")
            assert not pod["spec"].get("nodeName")
            assert sched.queue.stats()["unschedulable"] == 1
            await teardown()
        run(body())

    def test_no_provisioner_class_blocks_until_pv_appears(self):
        """WFFC + no-provisioner (local volumes): pod stays pending until a
        matching PV exists, then schedules onto the PV's node."""
        async def body():
            store, sched, teardown = await volume_stack()
            await store.create("storageclasses", make_storage_class(
                "local", binding_mode="WaitForFirstConsumer",
                provisioner="kubernetes.io/no-provisioner"))
            await store.create("persistentvolumeclaims", make_pvc(
                "data", storage_class="local"))
            await store.create("pods", pod_with_pvc("app", "data"))
            await asyncio.sleep(0.5)
            pod = await store.get("pods", "default/app")
            assert not pod["spec"].get("nodeName")
            # A local PV on n2 appears; the PersistentVolume/Add event
            # registered via VolumeBinding.EVENTS requeues the pod — no
            # manual poke, no 60s flush wait.
            pv = make_pv("local-1", "10Gi", storage_class="local",
                         node_affinity={"nodeSelectorTerms": [{
                             "matchFields": [{"key": "metadata.name",
                                              "operator": "In",
                                              "values": ["n2"]}]}]})
            await store.create("persistentvolumes", pv)

            async def pod_bound():
                p = await store.get("pods", "default/app")
                return p["spec"].get("nodeName")
            node = await wait_for(pod_bound, timeout=15.0)
            assert node == "n2"
            await teardown()
        run(body())

    def test_immediate_pvc_bind_requeues_parked_pod(self):
        """A pod rejected for an unbound immediate PVC re-activates on the
        PersistentVolumeClaim/Update event when the binder binds the claim
        — without waiting for the 60s leftover flush (EventsToRegister
        parity for the volume family)."""
        async def body():
            store, sched, teardown = await volume_stack()
            # Immediate-mode class, but no PV and dynamic provisioning off:
            # the claim stays Pending, the pod parks.
            await store.create("storageclasses", make_storage_class(
                "slow", provisioner="kubernetes.io/no-provisioner"))
            await store.create("persistentvolumeclaims", make_pvc(
                "data", storage_class="slow"))
            await store.create("pods", pod_with_pvc("app", "data"))
            await asyncio.sleep(0.4)
            assert sched.queue.stats()["unschedulable"] == 1
            # A matching PV appears; the binder binds the claim; the
            # PVC/PV informer events must requeue the pod promptly.
            await store.create("persistentvolumes", make_pv(
                "pv-slow", "10Gi", storage_class="slow"))

            async def pod_bound():
                p = await store.get("pods", "default/app")
                return p["spec"].get("nodeName")
            assert await wait_for(pod_bound, timeout=10.0)
            await teardown()
        run(body())


class TestVolumeLimits:
    def test_node_volume_limits_filter(self):
        async def body():
            node = make_node("tiny")
            node["status"]["allocatable"]["attachable-volumes-csi"] = "1"
            store, sched, teardown = await volume_stack(nodes=[node])
            for i in range(2):
                await store.create("persistentvolumes",
                                   make_pv(f"pv{i}", "10Gi"))
                await store.create("persistentvolumeclaims",
                                   make_pvc(f"c{i}"))

            async def claims_bound():
                cs = (await store.list("persistentvolumeclaims")).items
                return all(c["status"].get("phase") == "Bound" for c in cs)
            assert await wait_for(claims_bound)
            await store.create("pods", pod_with_pvc("p0", "c0"))

            async def first():
                p = await store.get("pods", "default/p0")
                return p["spec"].get("nodeName")
            assert await wait_for(first, timeout=15.0)
            await store.create("pods", pod_with_pvc("p1", "c1"))
            await asyncio.sleep(0.6)
            p1 = await store.get("pods", "default/p1")
            assert not p1["spec"].get("nodeName"), \
                "second volume exceeded the node's attach limit"
            await teardown()
        run(body())


from tests.conftest import start_scheduler  # noqa: E402


class TestVolumeRestrictions:
    """volumerestrictions/ parity: ReadWriteOncePod exclusivity and
    ReadWriteOnce single-node attachment."""

    async def _mk_bound_pvc(self, store, name, modes):
        """Pre-bound claim (volumeName set + PV) so VolumeBinding passes
        without running the PV binder controller."""
        from kubernetes_tpu.api.meta import new_object
        await store.create("persistentvolumes", new_object(
            "PersistentVolume", f"pv-{name}", None,
            spec={"capacity": {"storage": "1Gi"}, "accessModes": modes}))
        await store.create("persistentvolumeclaims", new_object(
            "PersistentVolumeClaim", name, "default",
            spec={"accessModes": modes, "volumeName": f"pv-{name}",
                  "resources": {"requests": {"storage": "1Gi"}}}))

    def _pod_with_claim(self, name, claim, node_name=None):
        pod = make_pod(name, node_name=node_name,
                       requests={"cpu": "100m"})
        pod["spec"]["volumes"] = [{
            "name": "v", "persistentVolumeClaim": {"claimName": claim}}]
        return pod

    def test_rwop_claim_admits_one_pod(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            for i in range(2):
                await store.create("nodes", make_node(f"n{i}"))
            await self._mk_bound_pvc(store, "exclusive",
                                          ["ReadWriteOncePod"])
            sched, factory = await start_scheduler(store)
            loop = asyncio.ensure_future(sched.run())
            await store.create("pods",
                               self._pod_with_claim("first", "exclusive"))
            for _ in range(200):
                p = await store.get("pods", "default/first")
                if p["spec"].get("nodeName"):
                    break
                await asyncio.sleep(0.02)
            assert p["spec"].get("nodeName")
            await store.create("pods",
                               self._pod_with_claim("second", "exclusive"))
            await asyncio.sleep(0.4)
            p2 = await store.get("pods", "default/second")
            assert not p2["spec"].get("nodeName"), \
                "RWOP claim admitted a second pod"
            # first pod going away releases the claim
            await store.delete("pods", "default/first")
            for _ in range(300):
                p2 = await store.get("pods", "default/second")
                if p2["spec"].get("nodeName"):
                    break
                await asyncio.sleep(0.02)
            assert p2["spec"].get("nodeName")
            await sched.stop()
            loop.cancel()
            factory.stop()
            store.stop()
        run(body())

    def test_rwo_claim_pins_to_the_attached_node(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            for i in range(3):
                await store.create("nodes", make_node(f"n{i}"))
            await self._mk_bound_pvc(store, "shared",
                                          ["ReadWriteOnce"])
            # a pod already runs with the claim on n1
            await store.create(
                "pods", self._pod_with_claim("holder", "shared",
                                             node_name="n1"))
            sched, factory = await start_scheduler(store)
            loop = asyncio.ensure_future(sched.run())
            await store.create("pods",
                               self._pod_with_claim("joiner", "shared"))
            for _ in range(200):
                p = await store.get("pods", "default/joiner")
                if p["spec"].get("nodeName"):
                    break
                await asyncio.sleep(0.02)
            # RWO is node-scoped: the joiner must co-locate on n1
            assert p["spec"].get("nodeName") == "n1", p["spec"]
            await sched.stop()
            loop.cancel()
            factory.stop()
            store.stop()
        run(body())
