"""Tier-1 guard for the batch-optimal (Sinkhorn) solve mode + descheduler.

Pins: (a) the tuner's solve_mode policy row — the KTPU_SOLVE_MODE=greedy
kill switch, forced-optimal structural degrade (spread / per-pod planes
fall back to greedy WITH the fallback bit), and `auto` routing only
drain-scale and gang chunks to optimal; (b) sinkhorn_plan numerics —
marginals respected, column capacity as an inequality, infeasible and
degenerate inputs sanitized (never NaN); (c) the mode end to end through
the backend: optimal packs, counts solves, and reports the live
KTPU_SINKHORN_ITERS budget, while greedy mode leaves every optimal
counter at zero; (d) the descheduler evicting AT MOST its per-cycle
disruption budget (KTPU_DESCHEDULER_BUDGET) and replacing victims with
unbound twins the scheduler can re-place. The heavyweight randomized
differential parity lives in tests/test_optimal_solver.py; the
KTPU_DESCHEDULER churn-phase wiring is exercised by the perf harness.
"""

import asyncio

import numpy as np
import jax.numpy as jnp

from kubernetes_tpu.ops import solver
from kubernetes_tpu.ops.backend import AdaptiveTuner
from kubernetes_tpu.utils import flags


class TestSolveModePolicy:
    def test_kill_switch_pins_greedy(self):
        """KTPU_SOLVE_MODE=greedy is the kill switch: every chunk keeps
        the r18 call graph, and no fallback is recorded (greedy was
        ASKED for, not degraded to)."""
        t = AdaptiveTuner()
        with flags.scoped_set("KTPU_SOLVE_MODE", "greedy"):
            for p, gang, cls in ((1, False, True), (4096, True, True),
                                 (4096, False, False)):
                assert t.solve_mode(p, has_gang=gang, spread=False,
                                    class_mode=cls) == ("greedy", False)

    def test_forced_optimal_degrades_structurally(self):
        """KTPU_SOLVE_MODE=optimal routes every eligible chunk; spread
        chunks and per-pod (non-class) planes degrade to greedy with the
        fallback bit set so solver_optimal_fallbacks_total records it."""
        t = AdaptiveTuner()
        with flags.scoped_set("KTPU_SOLVE_MODE", "optimal"):
            assert t.solve_mode(2, has_gang=False, spread=False,
                                class_mode=True) == ("optimal", False)
            assert t.solve_mode(2, has_gang=False, spread=True,
                                class_mode=True) == ("greedy", True)
            assert t.solve_mode(2, has_gang=False, spread=False,
                                class_mode=False) == ("greedy", True)

    def test_auto_routes_drain_scale_and_gangs(self):
        """`auto` (the default): serving-scale chunks stay greedy with
        NO fallback recorded (policy chose greedy); drain-scale chunks
        (>= OPTIMAL_MIN_PODS) and gang chunks of any size go optimal."""
        t = AdaptiveTuner()
        small = AdaptiveTuner.OPTIMAL_MIN_PODS - 1
        assert t.solve_mode(small, has_gang=False, spread=False,
                            class_mode=True) == ("greedy", False)
        assert t.solve_mode(AdaptiveTuner.OPTIMAL_MIN_PODS, has_gang=False,
                            spread=False, class_mode=True) \
            == ("optimal", False)
        assert t.solve_mode(2, has_gang=True, spread=False,
                            class_mode=True) == ("optimal", False)
        # an auto-selected chunk still degrades structurally
        assert t.solve_mode(4096, has_gang=False, spread=True,
                            class_mode=True) == ("greedy", True)

    def test_auto_large_n_keeps_greedy_except_gangs(self):
        """The r24 policy row: above the structural large-N signal the
        Sinkhorn plan's fixed dense (C,N) iteration cost IS the
        linear-in-N solve wall, so `auto` keeps non-gang drain chunks
        on the greedy scan (no fallback bit — policy chose greedy, the
        block-sparse prefilter makes it sublinear there). Gang chunks
        still route optimal at any node count, and KTPU_SOLVE_MODE=
        optimal still pins eligible chunks regardless of N."""
        t = AdaptiveTuner()
        t.n_nodes = AdaptiveTuner.LARGE_N
        assert t.solve_mode(AdaptiveTuner.OPTIMAL_MIN_PODS,
                            has_gang=False, spread=False,
                            class_mode=True) == ("greedy", False)
        assert t.solve_mode(2, has_gang=True, spread=False,
                            class_mode=True) == ("optimal", False)
        with flags.scoped_set("KTPU_SOLVE_MODE", "optimal"):
            assert t.solve_mode(AdaptiveTuner.OPTIMAL_MIN_PODS,
                                has_gang=False, spread=False,
                                class_mode=True) == ("optimal", False)
        t.n_nodes = AdaptiveTuner.LARGE_N - 1
        assert t.solve_mode(AdaptiveTuner.OPTIMAL_MIN_PODS,
                            has_gang=False, spread=False,
                            class_mode=True) == ("optimal", False)


class TestSinkhornPlan:
    def test_marginals_and_feasibility(self):
        """Ample capacity: every row places its full count, the column
        inequality holds, and infeasible cells carry no mass."""
        rng = np.random.default_rng(0)
        c, n = 5, 12
        feasible = rng.random((c, n)) > 0.3
        feasible[:, 0] = True  # every row has at least one column
        cost = rng.uniform(0, 4, size=(c, n)).astype(np.float32)
        counts = rng.integers(1, 6, size=(c,)).astype(np.float32)
        cap = np.full((n,), 50.0, np.float32)
        log_plan, plan = solver.sinkhorn_plan(
            jnp.asarray(feasible), jnp.asarray(cost), jnp.asarray(counts),
            jnp.asarray(cap), jnp.int32(48), jnp.float32(0.05))
        plan = np.asarray(plan)
        np.testing.assert_allclose(plan.sum(axis=1), counts, rtol=1e-3)
        assert (plan.sum(axis=0) <= cap + 1e-3).all()
        assert (plan[~feasible] == 0).all()
        assert (np.asarray(log_plan)[~feasible] == -1e30).all()

    def test_column_capacity_binds(self):
        """Tight columns: no node receives more mass than its remaining
        pod slots, even when row mass exceeds total capacity."""
        feasible = np.ones((3, 4), bool)
        cost = np.zeros((3, 4), np.float32)
        counts = np.asarray([4.0, 4.0, 4.0], np.float32)
        cap = np.asarray([2.0, 2.0, 2.0, 2.0], np.float32)
        _, plan = solver.sinkhorn_plan(
            jnp.asarray(feasible), jnp.asarray(cost), jnp.asarray(counts),
            jnp.asarray(cap), jnp.int32(64), jnp.float32(0.05))
        assert (np.asarray(plan).sum(axis=0) <= cap + 1e-3).all()

    def test_degenerate_inputs_stay_finite(self):
        """All-infeasible rows, zero capacity, zero counts: the plan and
        log_plan never go NaN (the scans consume log_plan as scores)."""
        feasible = np.zeros((2, 3), bool)
        z = np.zeros((2, 3), np.float32)
        log_plan, plan = solver.sinkhorn_plan(
            jnp.asarray(feasible), jnp.asarray(z),
            jnp.zeros((2,), np.float32), jnp.zeros((3,), np.float32),
            jnp.int32(8), jnp.float32(0.05))
        assert np.isfinite(np.asarray(plan)).all()
        assert (np.asarray(log_plan) == -1e30).all()


class TestBackendSmoke:
    def _cluster(self, n):
        from kubernetes_tpu.api.types import make_node
        from kubernetes_tpu.scheduler.cache import SchedulerCache
        cache = SchedulerCache()
        for i in range(n):
            cache.add_node(make_node(
                f"on{i}", allocatable={"cpu": "8", "memory": "32Gi",
                                       "pods": "110"}))
        return cache.update_snapshot()

    def _pods(self, n):
        from kubernetes_tpu.api.types import make_pod
        from kubernetes_tpu.scheduler.types import PodInfo
        return [PodInfo(make_pod(
            f"op-{i}", requests={"cpu": "500m", "memory": "512Mi"},
            uid=f"op-uid-{i}")) for i in range(n)]

    def test_optimal_packs_counts_and_reports_iters(self):
        """Forced optimal on a uniform template chunk: every pod places,
        the plan's first-fit rounding PACKS (occupied nodes ≈ the
        capacity bound, not the node count), the chunk is counted, and
        the iterations gauge reports the live KTPU_SINKHORN_ITERS."""
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.metrics.registry import SchedulerMetrics
        from kubernetes_tpu.ops.backend import TPUBackend
        snap = self._cluster(40)
        pods = self._pods(80)
        b = TPUBackend(max_batch=128, mesh=None)
        b.metrics = SchedulerMetrics()
        with flags.scoped_set("KTPU_SOLVE_MODE", "optimal"), \
                flags.scoped_set("KTPU_SINKHORN_ITERS", "16"):
            got, _ = b.assign(pods, snap, default_fwk())
        assert all(v is not None for v in got.values())
        # 80 pods × 500m onto 8-cpu nodes: 16/node → 5 nodes suffice.
        # Packing must land well under the 40-node spread; the exact
        # bound rides the differential suite.
        assert len({v for v in got.values()}) <= 8
        assert b.metrics.solver_optimal_solves.value() >= 1
        assert b.metrics.solver_optimal_fallbacks.value() == 0
        assert b.metrics.solver_sinkhorn_iterations.value() == 16
        # feasibility: per-node cpu within allocatable
        per_node: dict = {}
        for _, node in got.items():
            per_node[node] = per_node.get(node, 0) + 500
        assert all(v <= 8000 for v in per_node.values())

    def test_greedy_mode_keeps_counters_zero(self):
        """KTPU_SOLVE_MODE=greedy through the backend: assignments match
        the default serving-scale run and no optimal counter moves (the
        r18 call graph ran, not a one-iteration Sinkhorn)."""
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.metrics.registry import SchedulerMetrics
        from kubernetes_tpu.ops.backend import TPUBackend
        snap = self._cluster(30)
        pods = self._pods(16)  # < OPTIMAL_MIN_PODS: auto also greedy
        fwk = default_fwk()
        base, _ = TPUBackend(max_batch=16, mesh=None).assign(
            pods, snap, fwk)
        b = TPUBackend(max_batch=16, mesh=None)
        b.metrics = SchedulerMetrics()
        with flags.scoped_set("KTPU_SOLVE_MODE", "greedy"):
            got, _ = b.assign(pods, snap, fwk)
        assert got == base
        assert b.metrics.solver_optimal_solves.value() == 0
        assert b.metrics.solver_optimal_fallbacks.value() == 0

    def test_sinkhorn_temp_is_live(self):
        """KTPU_SINKHORN_TEMP is traced, not a compile key: changing it
        must not mint a new program (the dispatch-count gauge would
        catch a retrace via compile walls; here we just pin that both
        temps solve and place everything)."""
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.ops.backend import TPUBackend
        snap = self._cluster(20)
        pods = self._pods(64)
        fwk = default_fwk()
        b = TPUBackend(max_batch=128, mesh=None)
        with flags.scoped_set("KTPU_SOLVE_MODE", "optimal"), \
                flags.scoped_set("KTPU_SINKHORN_TEMP", "0.5"):
            hot, _ = b.assign(pods, snap, fwk)
        with flags.scoped_set("KTPU_SOLVE_MODE", "optimal"), \
                flags.scoped_set("KTPU_SINKHORN_TEMP", "0.02"):
            cold, _ = b.assign(pods, snap, fwk)
        assert all(v is not None for v in hot.values())
        assert all(v is not None for v in cold.values())


class TestDeschedulerBudget:
    def _run(self, coro):
        return asyncio.run(coro)

    def test_budget_caps_evictions_per_cycle(self):
        """A wide-spread cluster (1 small pod on each of 12 nodes, all
        above the emptiness threshold): one rebalance cycle evicts AT
        MOST the disruption budget, and every eviction is a replace —
        an unbound `-reb` twin in Pending for the scheduler."""
        async def body():
            from kubernetes_tpu.api.types import make_node, make_pod
            from kubernetes_tpu.client import InformerFactory
            from kubernetes_tpu.controllers import DeschedulerController
            from kubernetes_tpu.store import new_cluster_store
            store = new_cluster_store()
            try:
                for i in range(12):
                    await store.create("nodes", make_node(
                        f"dn{i}", allocatable={"cpu": "8", "memory": "32Gi",
                                               "pods": "110"}))
                    await store.create("pods", make_pod(
                        f"dp{i}", requests={"cpu": "500m"},
                        node_name=f"dn{i}", phase="Running",
                        uid=f"dp-uid-{i}"))
                factory = InformerFactory(store)
                # KTPU_DESCHEDULER default-off is the harness contract;
                # the controller itself runs wherever it's constructed.
                assert flags.get("KTPU_DESCHEDULER") is False
                d = DeschedulerController(store, threshold=0.2)
                d.setup(factory)
                factory.start()
                await factory.wait_for_sync()
                with flags.scoped_set("KTPU_DESCHEDULER_BUDGET", "3"):
                    assert d.budget == 3
                    evicted = await d.rebalance_once()
                assert 0 < evicted <= 3
                assert d.evictions == evicted
                pods = (await store.list("pods")).items
                twins = [p for p in pods
                         if "-reb" in p["metadata"]["name"]]
                assert len(twins) == evicted
                for p in twins:
                    assert "nodeName" not in p["spec"]
                    assert p["status"]["phase"] == "Pending"
                # conservation: every eviction deleted exactly one bound
                # pod and created one unbound twin
                assert len(pods) == 12
                factory.stop()
            finally:
                store.stop()
        self._run(body())

    def test_no_eviction_without_headroom(self):
        """A cluster with zero spare capacity never evicts: the
        aggregate-fit admission check refuses to evict into a full
        cluster (the scheduler could not re-place the twins)."""
        async def body():
            from kubernetes_tpu.api.types import make_node, make_pod
            from kubernetes_tpu.client import InformerFactory
            from kubernetes_tpu.controllers import DeschedulerController
            from kubernetes_tpu.store import new_cluster_store
            store = new_cluster_store()
            try:
                for i in range(4):
                    await store.create("nodes", make_node(
                        f"fn{i}", allocatable={"cpu": "1", "memory": "4Gi",
                                               "pods": "110"}))
                    await store.create("pods", make_pod(
                        f"fp{i}", requests={"cpu": "800m"},
                        node_name=f"fn{i}", phase="Running",
                        uid=f"fp-uid-{i}"))
                factory = InformerFactory(store)
                d = DeschedulerController(store, budget=8, threshold=0.1)
                d.setup(factory)
                factory.start()
                await factory.wait_for_sync()
                assert await d.rebalance_once() == 0
                assert d.evictions == 0
                factory.stop()
            finally:
                store.stop()
        self._run(body())
