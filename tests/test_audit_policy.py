"""Audit policy engine (policy/audit.py): rule matching, level-gated
bodies (Metadata vs Request vs RequestResponse), RequestReceived →
ResponseComplete stages on both wires + the gRPC interceptor chain,
RBAC-gated impersonation (allowed and denied), and the bounded sink."""

import asyncio
import json

import pytest

from kubernetes_tpu.api.types import make_pod
from kubernetes_tpu.apiserver.client import RemoteStore
from kubernetes_tpu.apiserver.rbac import RBACAuthorizer
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.apiserver.wire import WireServer, WireStore
from kubernetes_tpu.policy.audit import (
    AuditPipeline,
    AuditPolicy,
    AuditSink,
    LEVEL_METADATA,
    LEVEL_NONE,
    LEVEL_REQUEST_RESPONSE,
)
from kubernetes_tpu.store import install_core_validation, new_cluster_store
from kubernetes_tpu.store.mvcc import StoreError


def run(coro):
    return asyncio.run(coro)


class TestPolicyRules:
    def test_first_match_wins_and_default_none(self):
        pol = AuditPolicy([
            {"level": "None", "users": ["system:kube-proxy"]},
            {"level": "RequestResponse", "verbs": ["create"],
             "resources": ["pods"]},
            {"level": "Metadata", "resources": ["pods", "nodes"]},
        ])
        assert pol.level_for(user="system:kube-proxy", verb="create",
                             resource="pods") == LEVEL_NONE
        assert pol.level_for(user="alice", verb="create",
                             resource="pods") == LEVEL_REQUEST_RESPONSE
        assert pol.level_for(user="alice", verb="get",
                             resource="nodes") == LEVEL_METADATA
        # no rule matches → None (the reference default)
        assert pol.level_for(user="alice", verb="get",
                             resource="secrets") == LEVEL_NONE

    def test_group_and_namespace_rules(self):
        pol = AuditPolicy([
            {"level": "Metadata", "groups": ["system:nodes"]},
            {"level": "Request", "namespaces": ["prod"]},
        ])
        assert pol.level_for(user="u", groups=["system:nodes"],
                             verb="get", resource="pods") == "Metadata"
        assert pol.level_for(user="u", groups=[], verb="get",
                             resource="pods",
                             namespace="prod") == "Request"


class _Cluster:
    """Store + HTTP + wire sharing ONE audit pipeline (for_apiserver)."""

    def __init__(self, policy_rules, **api_kw):
        self.store = new_cluster_store()
        install_core_validation(self.store)
        self.audit = AuditPipeline(AuditPolicy(policy_rules))
        self.api = APIServer(self.store, audit=self.audit, **api_kw)
        self.wire = None

    async def __aenter__(self):
        await self.api.start()
        self.wire = WireServer.for_apiserver(self.api, host="unix:")
        await self.wire.start()
        return self

    async def __aexit__(self, *exc):
        await self.wire.stop()
        await self.api.stop()
        self.store.stop()

    def entries(self, resource="pods"):
        return [e for e in self.audit.sink.entries
                if e["objectRef"]["resource"] == resource]


class TestLevelFiltering:
    def test_metadata_vs_requestresponse_bodies(self):
        """The satellite's level-filtering scenario: a Metadata-level
        rule audits who/what/when with NO bodies; RequestResponse
        carries both the request and response objects."""
        async def body():
            rules = [
                {"level": "RequestResponse", "resources": ["pods"],
                 "namespaces": ["deep"]},
                {"level": "Metadata", "resources": ["pods"]},
            ]
            async with _Cluster(rules) as c:
                rs = RemoteStore(c.api.url)
                await rs.create("pods", make_pod("meta-pod"))
                await rs.create("pods", make_pod("deep-pod",
                                                 namespace="deep"))
                await asyncio.sleep(0.05)
                by_name = {}
                for e in c.entries():
                    by_name.setdefault(
                        e["objectRef"]["name"] or "?", []).append(e)
                meta = [e for e in by_name["meta-pod"]
                        if e["stage"] == "ResponseComplete"][0]
                assert meta["level"] == "Metadata"
                assert "requestObject" not in meta
                assert "responseObject" not in meta
                assert meta["responseStatus"]["code"] == 201
                deep_rr = [e for e in by_name["deep-pod"]
                           if e["stage"] == "RequestReceived"][0]
                assert deep_rr["requestObject"]["metadata"]["name"] == \
                    "deep-pod"
                deep_rc = [e for e in by_name["deep-pod"]
                           if e["stage"] == "ResponseComplete"][0]
                # Response object carries the SERVER-assigned fields.
                assert deep_rc["responseObject"]["metadata"][
                    "resourceVersion"]
                await rs.close()
        run(body())

    def test_level_none_emits_nothing(self):
        async def body():
            rules = [{"level": "None", "users": ["system:anonymous"]},
                     {"level": "Metadata"}]
            async with _Cluster(rules) as c:
                rs = RemoteStore(c.api.url)
                await rs.create("pods", make_pod("quiet"))
                await asyncio.sleep(0.05)
                assert c.entries() == []
                await rs.close()
        run(body())

    def test_stages_on_the_wire_share_audit_id(self):
        async def body():
            async with _Cluster([{"level": "Metadata"}]) as c:
                wc = WireStore(c.wire.target)
                await wc.create("pods", make_pod("w"))
                await wc.get("pods", "default/w")
                await asyncio.sleep(0.05)
                evs = c.entries()
                creates = [e for e in evs
                           if e["verb"] == "create"]
                assert [e["stage"] for e in creates] == \
                    ["RequestReceived", "ResponseComplete"]
                assert creates[0]["auditID"] == creates[1]["auditID"]
                assert creates[1]["responseStatus"]["code"] == 201
                gets = [e for e in evs if e["verb"] == "get"]
                assert {e["stage"] for e in gets} == \
                    {"RequestReceived", "ResponseComplete"}
                await wc.close()
        run(body())

    def test_denied_request_audited_with_failure_code(self):
        async def body():
            authz = RBACAuthorizer()  # empty: deny-by-default
            async with _Cluster([{"level": "Metadata"}],
                                authorizer=authz) as c:
                rs = RemoteStore(c.api.url)
                with pytest.raises(StoreError):
                    await rs.create("pods", make_pod("denied"))
                wc = WireStore(c.wire.target)
                with pytest.raises(StoreError):
                    await wc.create("pods", make_pod("denied2"))
                await asyncio.sleep(0.05)
                codes = [e["responseStatus"]["code"]
                         for e in c.entries()
                         if e["stage"] == "ResponseComplete"]
                assert codes == [403, 403]
                await wc.close()
                await rs.close()
        run(body())


def _imp_authz():
    authz = RBACAuthorizer()
    authz.add_role({"metadata": {"name": "imp"},
                    "rules": [{"verbs": ["impersonate"],
                               "resources": ["users"]}]})
    authz.add_role({"metadata": {"name": "podw"},
                    "rules": [{"verbs": ["*"], "resources": ["pods"]}]})
    authz.add_binding({"roleRef": {"name": "imp"},
                       "subjects": [{"kind": "User", "name": "admin"}]})
    authz.add_binding({"roleRef": {"name": "podw"},
                       "subjects": [{"kind": "User", "name": "bob"}]})
    return authz


class TestImpersonationRBAC:
    def test_http_allowed_denied_and_audited(self):
        async def body():
            tokens = {"ta": "admin", "tm": "mallory"}
            async with _Cluster([{"level": "Metadata"}],
                                bearer_tokens=tokens,
                                authorizer=_imp_authz()) as c:
                # Allowed: admin → bob; attributed to bob, original kept.
                rs = RemoteStore(c.api.url, token="ta",
                                 impersonate="bob")
                await rs.create("pods", make_pod("via-bob"))
                # Denied: mallory lacks the impersonate verb → 403, and
                # bob's pod rights never apply.
                rm = RemoteStore(c.api.url, token="tm",
                                 impersonate="bob")
                with pytest.raises(StoreError) as ei:
                    await rm.create("pods", make_pod("nope"))
                assert "cannot impersonate" in str(ei.value)
                await asyncio.sleep(0.05)
                ok = [e for e in c.entries()
                      if e["objectRef"]["name"] == "via-bob"
                      and e["stage"] == "ResponseComplete"][0]
                assert ok["user"]["username"] == "admin"
                assert ok["impersonatedUser"]["username"] == "bob"
                await rs.close()
                await rm.close()
        run(body())

    def test_wire_allowed_denied(self):
        async def body():
            tokens = {"ta": "admin", "tm": "mallory"}
            async with _Cluster([{"level": "Metadata"}],
                                bearer_tokens=tokens,
                                authorizer=_imp_authz()) as c:
                wc = WireStore(c.wire.target, token="ta",
                               impersonate="bob")
                await wc.create("pods", make_pod("w-bob"))
                wm = WireStore(c.wire.target, token="tm",
                               impersonate="bob")
                with pytest.raises(StoreError) as ei:
                    await wm.create("pods", make_pod("nope"))
                assert "cannot impersonate" in str(ei.value)
                await asyncio.sleep(0.05)
                ok = [e for e in c.entries()
                      if e["objectRef"]["name"] == "w-bob"
                      and e["stage"] == "ResponseComplete"][0]
                assert ok["user"]["username"] == "admin"
                assert ok["impersonatedUser"]["username"] == "bob"
                await wc.close()
                await wm.close()
        run(body())

    def test_impersonate_group_needs_its_own_grant(self):
        """impersonate-on-users must NOT allow self-assigned groups:
        the reference gates each impersonated attribute separately."""
        async def body():
            import aiohttp
            async with _Cluster([{"level": "Metadata"}],
                                bearer_tokens={"ta": "admin"},
                                authorizer=_imp_authz()) as c:
                async with aiohttp.ClientSession() as s:
                    async with s.post(
                            c.api.url + "/api/v1/namespaces/default/pods",
                            json=make_pod("x"),
                            headers={"Authorization": "Bearer ta",
                                     "Impersonate-User": "bob",
                                     "Impersonate-Group":
                                         "cluster-admins"}) as r:
                        assert r.status == 403
                        assert "cannot impersonate groups" in \
                            (await r.json())["message"]
        run(body())

    def test_wire_second_hello_refused(self):
        """One handshake per connection: a second hello must not reset
        the audited principal or re-authenticate the session."""
        async def body():
            async with _Cluster([{"level": "Metadata"}],
                                bearer_tokens={"ta": "admin"},
                                authorizer=_imp_authz()) as c:
                wc = WireStore(c.wire.target, token="ta",
                               impersonate="bob")
                await wc.create("pods", make_pod("first"))
                fut = asyncio.get_event_loop().create_future()
                wc._pending["h2"] = fut
                wc._send(["h2", "hello", {"token": None}])
                with pytest.raises(StoreError) as ei:
                    await asyncio.wait_for(fut, 5)
                assert "already authenticated" in str(ei.value)
                await wc.close()
        run(body())

    def test_grpc_interceptor_chain(self):
        """The third wire: authn → audit → impersonation → authz as a
        grpc.aio server interceptor."""
        async def body():
            from kubernetes_tpu.apiserver.grpc_server import (
                GRPCAPIServer,
                GRPCRemoteStore,
            )
            store = new_cluster_store()
            install_core_validation(store)
            audit = AuditPipeline(AuditPolicy.metadata_for_all())
            srv = GRPCAPIServer(
                store, bearer_tokens={"ta": "admin", "tm": "mallory"},
                authorizer=_imp_authz(), audit=audit)
            await srv.start()
            clients = []
            try:
                ok = GRPCRemoteStore(srv.target, token="ta",
                                     impersonate="bob")
                clients.append(ok)
                created = await ok.create("pods", make_pod("g-bob"))
                assert created["metadata"]["name"] == "g-bob"
                # admin direct: no pod rights → PERMISSION_DENIED maps
                # to StoreError.
                direct = GRPCRemoteStore(srv.target, token="ta")
                clients.append(direct)
                with pytest.raises(StoreError):
                    await direct.create("pods", make_pod("nope"))
                # mallory cannot impersonate.
                bad = GRPCRemoteStore(srv.target, token="tm",
                                      impersonate="bob")
                clients.append(bad)
                with pytest.raises(StoreError) as ei:
                    await bad.create("pods", make_pod("nope2"))
                assert "cannot impersonate" in str(ei.value)
                # bad token → unauthenticated.
                anon = GRPCRemoteStore(srv.target, token="wrong")
                clients.append(anon)
                with pytest.raises(StoreError):
                    await anon.get("pods", "default/g-bob")
                await asyncio.sleep(0.05)
                done = [e for e in audit.sink.entries
                        if e["stage"] == "ResponseComplete"
                        and e["objectRef"]["name"] == "g-bob"]
                assert done and done[0]["user"]["username"] == "admin"
                assert done[0]["impersonatedUser"]["username"] == "bob"
            finally:
                for cli in clients:
                    await cli.close()
                await srv.stop()
                store.stop()
        run(body())


class TestSink:
    def test_bounded_sink_drops_and_counts(self):
        async def body():
            sink = AuditSink()
            sink.MAX_PENDING = 8
            # No drain between emits: everything lands in one tick...
            for i in range(20):
                sink.emit({"stage": "ResponseComplete", "i": i})
            assert sink.events_dropped.value() == 12
            await asyncio.sleep(0.05)
            assert len(sink.entries) == 8
            await sink.close()
        run(body())

    def test_file_sink_writes_json_lines(self, tmp_path):
        async def body():
            path = tmp_path / "audit.log"
            sink = AuditSink(path=str(path))
            pipeline = AuditPipeline(AuditPolicy.metadata_for_all(),
                                     sink=sink)
            ctx = pipeline.begin(user="u", verb="create",
                                 resource="pods", namespace="default",
                                 name="p")
            pipeline.response_complete(ctx, code=201)
            await asyncio.sleep(0.05)
            await pipeline.close()
            lines = [json.loads(ln) for ln in
                     path.read_text().splitlines()]
            assert [e["stage"] for e in lines] == [
                "RequestReceived", "ResponseComplete"]
            assert lines[1]["responseStatus"]["code"] == 201
        run(body())
