"""Audit policy engine (policy/audit.py): rule matching, level-gated
bodies (Metadata vs Request vs RequestResponse), RequestReceived →
ResponseComplete stages on both wires + the gRPC interceptor chain,
RBAC-gated impersonation (allowed and denied), and the bounded sink."""

import asyncio
import json

import pytest

from kubernetes_tpu.api.types import make_pod
from kubernetes_tpu.apiserver.client import RemoteStore
from kubernetes_tpu.apiserver.rbac import RBACAuthorizer
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.apiserver.wire import WireServer, WireStore
from kubernetes_tpu.policy.audit import (
    AuditPipeline,
    AuditPolicy,
    AuditSink,
    LEVEL_METADATA,
    LEVEL_NONE,
    LEVEL_REQUEST_RESPONSE,
    RotatingFileSink,
    WebhookSink,
)
from kubernetes_tpu.store import install_core_validation, new_cluster_store
from kubernetes_tpu.store.mvcc import StoreError


def run(coro):
    return asyncio.run(coro)


class TestPolicyRules:
    def test_first_match_wins_and_default_none(self):
        pol = AuditPolicy([
            {"level": "None", "users": ["system:kube-proxy"]},
            {"level": "RequestResponse", "verbs": ["create"],
             "resources": ["pods"]},
            {"level": "Metadata", "resources": ["pods", "nodes"]},
        ])
        assert pol.level_for(user="system:kube-proxy", verb="create",
                             resource="pods") == LEVEL_NONE
        assert pol.level_for(user="alice", verb="create",
                             resource="pods") == LEVEL_REQUEST_RESPONSE
        assert pol.level_for(user="alice", verb="get",
                             resource="nodes") == LEVEL_METADATA
        # no rule matches → None (the reference default)
        assert pol.level_for(user="alice", verb="get",
                             resource="secrets") == LEVEL_NONE

    def test_group_and_namespace_rules(self):
        pol = AuditPolicy([
            {"level": "Metadata", "groups": ["system:nodes"]},
            {"level": "Request", "namespaces": ["prod"]},
        ])
        assert pol.level_for(user="u", groups=["system:nodes"],
                             verb="get", resource="pods") == "Metadata"
        assert pol.level_for(user="u", groups=[], verb="get",
                             resource="pods",
                             namespace="prod") == "Request"


class _Cluster:
    """Store + HTTP + wire sharing ONE audit pipeline (for_apiserver)."""

    def __init__(self, policy_rules, **api_kw):
        self.store = new_cluster_store()
        install_core_validation(self.store)
        self.audit = AuditPipeline(AuditPolicy(policy_rules))
        self.api = APIServer(self.store, audit=self.audit, **api_kw)
        self.wire = None

    async def __aenter__(self):
        await self.api.start()
        self.wire = WireServer.for_apiserver(self.api, host="unix:")
        await self.wire.start()
        return self

    async def __aexit__(self, *exc):
        await self.wire.stop()
        await self.api.stop()
        self.store.stop()

    def entries(self, resource="pods"):
        return [e for e in self.audit.sink.entries
                if e["objectRef"]["resource"] == resource]


class TestLevelFiltering:
    def test_metadata_vs_requestresponse_bodies(self):
        """The satellite's level-filtering scenario: a Metadata-level
        rule audits who/what/when with NO bodies; RequestResponse
        carries both the request and response objects."""
        async def body():
            rules = [
                {"level": "RequestResponse", "resources": ["pods"],
                 "namespaces": ["deep"]},
                {"level": "Metadata", "resources": ["pods"]},
            ]
            async with _Cluster(rules) as c:
                rs = RemoteStore(c.api.url)
                await rs.create("pods", make_pod("meta-pod"))
                await rs.create("pods", make_pod("deep-pod",
                                                 namespace="deep"))
                await asyncio.sleep(0.05)
                by_name = {}
                for e in c.entries():
                    by_name.setdefault(
                        e["objectRef"]["name"] or "?", []).append(e)
                meta = [e for e in by_name["meta-pod"]
                        if e["stage"] == "ResponseComplete"][0]
                assert meta["level"] == "Metadata"
                assert "requestObject" not in meta
                assert "responseObject" not in meta
                assert meta["responseStatus"]["code"] == 201
                deep_rr = [e for e in by_name["deep-pod"]
                           if e["stage"] == "RequestReceived"][0]
                assert deep_rr["requestObject"]["metadata"]["name"] == \
                    "deep-pod"
                deep_rc = [e for e in by_name["deep-pod"]
                           if e["stage"] == "ResponseComplete"][0]
                # Response object carries the SERVER-assigned fields.
                assert deep_rc["responseObject"]["metadata"][
                    "resourceVersion"]
                await rs.close()
        run(body())

    def test_level_none_emits_nothing(self):
        async def body():
            rules = [{"level": "None", "users": ["system:anonymous"]},
                     {"level": "Metadata"}]
            async with _Cluster(rules) as c:
                rs = RemoteStore(c.api.url)
                await rs.create("pods", make_pod("quiet"))
                await asyncio.sleep(0.05)
                assert c.entries() == []
                await rs.close()
        run(body())

    def test_stages_on_the_wire_share_audit_id(self):
        async def body():
            async with _Cluster([{"level": "Metadata"}]) as c:
                wc = WireStore(c.wire.target)
                await wc.create("pods", make_pod("w"))
                await wc.get("pods", "default/w")
                await asyncio.sleep(0.05)
                evs = c.entries()
                creates = [e for e in evs
                           if e["verb"] == "create"]
                assert [e["stage"] for e in creates] == \
                    ["RequestReceived", "ResponseComplete"]
                assert creates[0]["auditID"] == creates[1]["auditID"]
                assert creates[1]["responseStatus"]["code"] == 201
                gets = [e for e in evs if e["verb"] == "get"]
                assert {e["stage"] for e in gets} == \
                    {"RequestReceived", "ResponseComplete"}
                await wc.close()
        run(body())

    def test_denied_request_audited_with_failure_code(self):
        async def body():
            authz = RBACAuthorizer()  # empty: deny-by-default
            async with _Cluster([{"level": "Metadata"}],
                                authorizer=authz) as c:
                rs = RemoteStore(c.api.url)
                with pytest.raises(StoreError):
                    await rs.create("pods", make_pod("denied"))
                wc = WireStore(c.wire.target)
                with pytest.raises(StoreError):
                    await wc.create("pods", make_pod("denied2"))
                await asyncio.sleep(0.05)
                codes = [e["responseStatus"]["code"]
                         for e in c.entries()
                         if e["stage"] == "ResponseComplete"]
                assert codes == [403, 403]
                await wc.close()
                await rs.close()
        run(body())


def _imp_authz():
    authz = RBACAuthorizer()
    authz.add_role({"metadata": {"name": "imp"},
                    "rules": [{"verbs": ["impersonate"],
                               "resources": ["users"]}]})
    authz.add_role({"metadata": {"name": "podw"},
                    "rules": [{"verbs": ["*"], "resources": ["pods"]}]})
    authz.add_binding({"roleRef": {"name": "imp"},
                       "subjects": [{"kind": "User", "name": "admin"}]})
    authz.add_binding({"roleRef": {"name": "podw"},
                       "subjects": [{"kind": "User", "name": "bob"}]})
    return authz


class TestImpersonationRBAC:
    def test_http_allowed_denied_and_audited(self):
        async def body():
            tokens = {"ta": "admin", "tm": "mallory"}
            async with _Cluster([{"level": "Metadata"}],
                                bearer_tokens=tokens,
                                authorizer=_imp_authz()) as c:
                # Allowed: admin → bob; attributed to bob, original kept.
                rs = RemoteStore(c.api.url, token="ta",
                                 impersonate="bob")
                await rs.create("pods", make_pod("via-bob"))
                # Denied: mallory lacks the impersonate verb → 403, and
                # bob's pod rights never apply.
                rm = RemoteStore(c.api.url, token="tm",
                                 impersonate="bob")
                with pytest.raises(StoreError) as ei:
                    await rm.create("pods", make_pod("nope"))
                assert "cannot impersonate" in str(ei.value)
                await asyncio.sleep(0.05)
                ok = [e for e in c.entries()
                      if e["objectRef"]["name"] == "via-bob"
                      and e["stage"] == "ResponseComplete"][0]
                assert ok["user"]["username"] == "admin"
                assert ok["impersonatedUser"]["username"] == "bob"
                await rs.close()
                await rm.close()
        run(body())

    def test_wire_allowed_denied(self):
        async def body():
            tokens = {"ta": "admin", "tm": "mallory"}
            async with _Cluster([{"level": "Metadata"}],
                                bearer_tokens=tokens,
                                authorizer=_imp_authz()) as c:
                wc = WireStore(c.wire.target, token="ta",
                               impersonate="bob")
                await wc.create("pods", make_pod("w-bob"))
                wm = WireStore(c.wire.target, token="tm",
                               impersonate="bob")
                with pytest.raises(StoreError) as ei:
                    await wm.create("pods", make_pod("nope"))
                assert "cannot impersonate" in str(ei.value)
                await asyncio.sleep(0.05)
                ok = [e for e in c.entries()
                      if e["objectRef"]["name"] == "w-bob"
                      and e["stage"] == "ResponseComplete"][0]
                assert ok["user"]["username"] == "admin"
                assert ok["impersonatedUser"]["username"] == "bob"
                await wc.close()
                await wm.close()
        run(body())

    def test_impersonate_group_needs_its_own_grant(self):
        """impersonate-on-users must NOT allow self-assigned groups:
        the reference gates each impersonated attribute separately."""
        async def body():
            import aiohttp
            async with _Cluster([{"level": "Metadata"}],
                                bearer_tokens={"ta": "admin"},
                                authorizer=_imp_authz()) as c:
                async with aiohttp.ClientSession() as s:
                    async with s.post(
                            c.api.url + "/api/v1/namespaces/default/pods",
                            json=make_pod("x"),
                            headers={"Authorization": "Bearer ta",
                                     "Impersonate-User": "bob",
                                     "Impersonate-Group":
                                         "cluster-admins"}) as r:
                        assert r.status == 403
                        assert "cannot impersonate groups" in \
                            (await r.json())["message"]
        run(body())

    def test_wire_second_hello_refused(self):
        """One handshake per connection: a second hello must not reset
        the audited principal or re-authenticate the session."""
        async def body():
            async with _Cluster([{"level": "Metadata"}],
                                bearer_tokens={"ta": "admin"},
                                authorizer=_imp_authz()) as c:
                wc = WireStore(c.wire.target, token="ta",
                               impersonate="bob")
                await wc.create("pods", make_pod("first"))
                fut = asyncio.get_event_loop().create_future()
                wc._pending["h2"] = fut
                wc._send(["h2", "hello", {"token": None}])
                with pytest.raises(StoreError) as ei:
                    await asyncio.wait_for(fut, 5)
                assert "already authenticated" in str(ei.value)
                await wc.close()
        run(body())

    def test_grpc_interceptor_chain(self):
        """The third wire: authn → audit → impersonation → authz as a
        grpc.aio server interceptor."""
        async def body():
            from kubernetes_tpu.apiserver.grpc_server import (
                GRPCAPIServer,
                GRPCRemoteStore,
            )
            store = new_cluster_store()
            install_core_validation(store)
            audit = AuditPipeline(AuditPolicy.metadata_for_all())
            srv = GRPCAPIServer(
                store, bearer_tokens={"ta": "admin", "tm": "mallory"},
                authorizer=_imp_authz(), audit=audit)
            await srv.start()
            clients = []
            try:
                ok = GRPCRemoteStore(srv.target, token="ta",
                                     impersonate="bob")
                clients.append(ok)
                created = await ok.create("pods", make_pod("g-bob"))
                assert created["metadata"]["name"] == "g-bob"
                # admin direct: no pod rights → PERMISSION_DENIED maps
                # to StoreError.
                direct = GRPCRemoteStore(srv.target, token="ta")
                clients.append(direct)
                with pytest.raises(StoreError):
                    await direct.create("pods", make_pod("nope"))
                # mallory cannot impersonate.
                bad = GRPCRemoteStore(srv.target, token="tm",
                                      impersonate="bob")
                clients.append(bad)
                with pytest.raises(StoreError) as ei:
                    await bad.create("pods", make_pod("nope2"))
                assert "cannot impersonate" in str(ei.value)
                # bad token → unauthenticated.
                anon = GRPCRemoteStore(srv.target, token="wrong")
                clients.append(anon)
                with pytest.raises(StoreError):
                    await anon.get("pods", "default/g-bob")
                await asyncio.sleep(0.05)
                done = [e for e in audit.sink.entries
                        if e["stage"] == "ResponseComplete"
                        and e["objectRef"]["name"] == "g-bob"]
                assert done and done[0]["user"]["username"] == "admin"
                assert done[0]["impersonatedUser"]["username"] == "bob"
            finally:
                for cli in clients:
                    await cli.close()
                await srv.stop()
                store.stop()
        run(body())


class TestSink:
    def test_bounded_sink_drops_and_counts(self):
        async def body():
            sink = AuditSink()
            sink.MAX_PENDING = 8
            # No drain between emits: everything lands in one tick...
            for i in range(20):
                sink.emit({"stage": "ResponseComplete", "i": i})
            assert sink.events_dropped.value() == 12
            await asyncio.sleep(0.05)
            assert len(sink.entries) == 8
            await sink.close()
        run(body())

    def test_rotating_sink_size_rotation(self, tmp_path):
        """Size trigger: events are conserved across segments —
        path.1 holds the rotated-out lines, nothing lost, every line
        valid JSON, rotations counted."""
        async def body():
            path = tmp_path / "audit.log"
            sink = RotatingFileSink(str(path), max_bytes=2048,
                                    backups=3)
            for i in range(200):
                sink.emit({"stage": "ResponseComplete", "i": i,
                           "pad": "x" * 64})
                await asyncio.sleep(0)
            await sink.close()
            segments = [path] + [
                tmp_path / f"audit.log.{k}" for k in range(1, 4)]
            seen = []
            for seg in segments:
                if seg.exists():
                    for ln in seg.read_text().splitlines():
                        seen.append(json.loads(ln)["i"])
            assert sink.rotations.value() >= 1
            assert (tmp_path / "audit.log.1").exists()
            dropped = int(sink.events_dropped.value())
            # Everything emitted is either on disk or counted as
            # dropped (backups past the cap are deleted, counted
            # rotations make the loss visible) — never silent.
            assert len(seen) + dropped <= 200
            assert sorted(seen) == sorted(set(seen))  # no duplicates
            # the newest segment ends with the newest events
            assert json.loads(
                path.read_text().splitlines()[-1])["i"] == 199
        run(body())

    def test_rotating_sink_age_rotation(self, tmp_path):
        async def body():
            path = tmp_path / "audit.log"
            sink = RotatingFileSink(str(path), max_bytes=1 << 20,
                                    max_age_s=0.0, backups=2)
            sink.emit({"stage": "ResponseComplete", "n": 1})
            await asyncio.sleep(0.02)
            sink.emit({"stage": "ResponseComplete", "n": 2})
            await asyncio.sleep(0.02)
            await sink.close()
            assert (tmp_path / "audit.log.1").exists()
            assert sink.rotations.value() >= 1
        run(body())

    def test_webhook_sink_batches_and_delivers(self):
        """One EventList POST carries a whole batch; stage counters and
        batch outcome counters move."""
        async def body():
            from aiohttp import web
            got = []

            async def collect(request):
                got.append(await request.json())
                return web.json_response({})

            app = web.Application()
            app.router.add_post("/audit", collect)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port = site._server.sockets[0].getsockname()[1]
            sink = WebhookSink(f"http://127.0.0.1:{port}/audit")
            for i in range(50):
                sink.emit({"stage": "ResponseComplete", "i": i})
            await sink.close()
            await runner.cleanup()
            assert got and got[0]["kind"] == "EventList"
            items = [e["i"] for batch in got for e in batch["items"]]
            assert sorted(items) == list(range(50))
            assert len(got) < 50  # batched, not one POST per event
            assert sink.webhook_batches.value(outcome="ok") == len(got)
            assert sink.events_dropped.value() == 0
        run(body())

    def test_webhook_sink_retry_backoff_then_delivery(self):
        """A flaky endpoint: the first attempts fail, backoff retries
        land the batch — retries counted, nothing dropped."""
        async def body():
            calls = []

            async def post(url, body):
                calls.append(len(body["items"]))
                if len(calls) <= 2:
                    raise ConnectionError("collector down")

            sink = WebhookSink("http://unused/", post=post,
                               initial_backoff=0.01, max_retries=4)
            sink.emit({"stage": "ResponseComplete", "i": 1})
            await sink.close()
            assert len(calls) == 3  # 2 failures + 1 success
            assert sink.webhook_retries.value() == 2
            assert sink.webhook_batches.value(outcome="ok") == 1
            assert sink.events_dropped.value() == 0
        run(body())

    def test_webhook_sink_exhausted_retries_drop_counted(self):
        async def body():
            async def post(url, body):
                raise ConnectionError("dead collector")

            sink = WebhookSink("http://unused/", post=post,
                               initial_backoff=0.001, max_retries=2)
            for i in range(3):
                sink.emit({"stage": "ResponseComplete", "i": i})
            await sink.close()
            assert sink.events_dropped.value() == 3
            assert sink.webhook_batches.value(outcome="failed") >= 1
        run(body())

    def test_webhook_sink_bounded_queue(self):
        async def body():
            async def post(url, body):
                await asyncio.sleep(3600)  # never completes

            sink = WebhookSink("http://unused/", post=post)
            sink.MAX_PENDING = 8
            emitted = 0
            for i in range(20):
                sink.emit({"stage": "ResponseComplete", "i": i})
                emitted += 1
            # queue bounded: overflow counted immediately, emit never
            # blocked. (first batch is in flight with the hung POST)
            assert sink.events_dropped.value() >= 20 - 8 - sink.batch_max
            assert len(sink._pending) <= 8
        run(body())

    def test_webhook_sink_from_config(self, tmp_path):
        cfg = tmp_path / "webhook.yaml"
        cfg.write_text(
            "url: http://collector:9099/audit\n"
            "batch: {maxSize: 7}\n"
            "retry: {backoff: 0.5, maxAttempts: 2}\n")
        sink = WebhookSink.from_config(str(cfg))
        assert sink.url == "http://collector:9099/audit"
        assert sink.batch_max == 7
        assert sink.initial_backoff == 0.5
        assert sink.max_retries == 2
        with pytest.raises(ValueError):
            bad = tmp_path / "bad.yaml"
            bad.write_text("batch: {}\n")
            WebhookSink.from_config(str(bad))

    def test_pipeline_rides_rotating_sink(self, tmp_path):
        """The production sink plugs into the existing pipeline seam —
        stage events land as JSON lines through RotatingFileSink."""
        async def body():
            sink = RotatingFileSink(str(tmp_path / "a.log"))
            pipeline = AuditPipeline(AuditPolicy.metadata_for_all(),
                                     sink=sink)
            ctx = pipeline.begin(user="u", verb="create",
                                 resource="pods", namespace="default",
                                 name="p")
            pipeline.response_complete(ctx, code=201)
            await asyncio.sleep(0.05)
            await pipeline.close()
            lines = [json.loads(ln) for ln in
                     (tmp_path / "a.log").read_text().splitlines()]
            assert [e["stage"] for e in lines] == [
                "RequestReceived", "ResponseComplete"]
        run(body())

    def test_file_sink_writes_json_lines(self, tmp_path):
        async def body():
            path = tmp_path / "audit.log"
            sink = AuditSink(path=str(path))
            pipeline = AuditPipeline(AuditPolicy.metadata_for_all(),
                                     sink=sink)
            ctx = pipeline.begin(user="u", verb="create",
                                 resource="pods", namespace="default",
                                 name="p")
            pipeline.response_complete(ctx, code=201)
            await asyncio.sleep(0.05)
            await pipeline.close()
            lines = [json.loads(ln) for ln in
                     path.read_text().splitlines()]
            assert [e["stage"] for e in lines] == [
                "RequestReceived", "ResponseComplete"]
            assert lines[1]["responseStatus"]["code"] == 201
        run(body())
