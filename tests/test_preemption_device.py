"""Device-side preemption victim proposal: parity vs the host analog.

SURVEY §7 phase 6 ("solve-with-victim-relaxation"): `solver.propose_victims`
replaces the per-preemptor host candidate search. These tests pin

- PARITY: for a seeded contention scenario, the device-proposed victim set
  matches the host `SelectVictimsOnNode` analog (`_select_victims`) —
  same victims, same minimal count — and the device choice carries the
  host cost-ordering optimum (`_WaveState.candidates`).
- DETERMINISM: identical seeded state → identical proposals.
- SPREADING: a wave's preemptors thread claims on device, so two
  preemptors do not stack on one node.
- The adaptive tuner's flagless picks stay within the documented envelope
  (BASELINE.md r6 "adaptive vs manual").
"""

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.ops.backend import AdaptiveTuner, TPUBackend
from kubernetes_tpu.scheduler.framework import CycleState, Framework
from kubernetes_tpu.scheduler.plugins.defaultpreemption import (
    DefaultPreemption,
    _WaveState,
)
from kubernetes_tpu.scheduler.plugins.noderesources import NodeResourcesFit
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot


def ni(name, cpu="4", pods=()):
    node = NodeInfo(make_node(
        name, allocatable={"cpu": cpu, "memory": "16Gi", "pods": "32"}))
    for p in pods:
        node.add_pod(p)
    return node


def pp(name, cpu="1", priority=0):
    return PodInfo(make_pod(name, requests={"cpu": cpu, "memory": "1Gi"},
                            priority=priority))


def contention_snapshot():
    """3 full nodes; victim priorities ascend differently per node so the
    reference cost ordering (max prio → prio sum → count) is exercised."""
    nodes = [
        ni("n0", pods=[pp("a0", priority=50), pp("a1", priority=60),
                       pp("a2", priority=70), pp("a3", priority=80)]),
        ni("n1", pods=[pp("b0", priority=10), pp("b1", priority=20),
                       pp("b2", priority=90), pp("b3", priority=95)]),
        ni("n2", pods=[pp("c0", priority=30), pp("c1", priority=40),
                       pp("c2", priority=45), pp("c3", priority=85)]),
    ]
    return Snapshot(nodes, generation=1)


def make_plugin(snapshot, seed=0):
    fwk = Framework([NodeResourcesFit()], {"NodeResourcesFit": 1})
    evictions = []
    plug = DefaultPreemption(
        args={"seed": seed}, framework=fwk,
        evict=lambda pod, victims, node: evictions.append(
            (pod.key, tuple(victims), node)))
    return plug, evictions


class TestDeviceHostParity:
    def test_primed_matches_host_minimal_victims(self):
        snap = contention_snapshot()
        preemptor = pp("hi", cpu="1", priority=1000)
        plug, _ = make_plugin(snap)

        # Host analogs computed BEFORE any claim mutates shared state.
        ref_wave = _WaveState(snap, set(), {})
        ranked = ref_wave.candidates(preemptor, set())
        best_n, best_count = ranked[0]
        host_cost = DefaultPreemption._cost_of(ref_wave, ranked[0])
        scan_victims = plug._select_victims(
            CycleState(), preemptor, snap.nodes[best_n])

        plug.prime_wave([preemptor], snap, {})
        assert preemptor.key in plug._primed
        _, dev_n, dev_count = plug._primed[preemptor.key]
        wave = plug._wave
        # Device pick carries the host cost-ordering optimum. (The node
        # itself may differ only under exact cost ties; this scenario has
        # none — assert full identity.)
        assert DefaultPreemption._cost_of(
            wave, (dev_n, dev_count)) == host_cost
        assert (dev_n, dev_count) == (best_n, best_count)
        # Same victim SET and same minimal count as the host
        # SelectVictimsOnNode analog (homogeneous requests, so the
        # minimal ascending-priority prefix IS the reprieve result).
        dev_victims = {v.key for v in wave.victims[dev_n][:dev_count]}
        assert dev_victims == {v.key for v in scan_victims}
        assert dev_count == len(scan_victims)

    def test_post_filter_commits_primed_proposal(self):
        snap = contention_snapshot()
        preemptor = pp("hi", cpu="1", priority=1000)
        plug, evictions = make_plugin(snap)
        plug.prime_wave([preemptor], snap, {})
        primed = dict(plug._primed)
        node, st = plug.post_filter(CycleState(), preemptor, snap, {})
        assert st.is_success()
        _, dev_n, dev_count = primed[preemptor.key]
        assert node == snap.nodes[dev_n].name
        assert len(evictions) == 1
        assert len(evictions[0][1]) == dev_count
        # the proposal was consumed, not left to go stale
        assert preemptor.key not in plug._primed

    def test_deterministic_tiebreak(self):
        results = []
        for _ in range(2):
            snap = contention_snapshot()
            preemptor = pp("hi", cpu="1", priority=1000)
            plug, _ = make_plugin(snap, seed=7)
            plug.prime_wave([preemptor], snap, {})
            results.append(plug._primed[preemptor.key][1:])
        assert results[0] == results[1]

    def test_wave_spreads_across_equal_nodes(self):
        # Two identical single-victim nodes, two preemptors in ONE wave:
        # in-scan claim threading consumes the first choice's only victim
        # (and charges the preemptor), so the second preemptor MUST land
        # on the other node — no host round trip between them.
        nodes = [ni("n0", cpu="1", pods=[pp("a0", priority=1)]),
                 ni("n1", cpu="1", pods=[pp("b0", priority=1)])]
        snap = Snapshot(nodes, generation=1)
        p1 = pp("hi-1", cpu="1", priority=100)
        p2 = pp("hi-2", cpu="1", priority=100)
        plug, _ = make_plugin(snap)
        plug.prime_wave([p1, p2], snap, {})
        assert {plug._primed[p1.key][1],
                plug._primed[p2.key][1]} == {0, 1}

    def test_byte_quantity_resources_do_not_overflow(self):
        # Memory is tracked in BYTES (int64 on host): the device scan is
        # int32, so victim proposal must quantize conservatively instead
        # of clamping/overflowing. 224Gi used of 256Gi, 32Gi freed by one
        # victim, preemptor wants 32Gi → exactly one victim suffices.
        victim = PodInfo(make_pod(
            "big-victim", requests={"cpu": "1", "memory": "32Gi"},
            priority=1))
        filler = PodInfo(make_pod(
            "big-filler", requests={"cpu": "1", "memory": "192Gi"},
            priority=2000))
        node = NodeInfo(make_node("m0", allocatable={
            "cpu": "8", "memory": "256Gi", "pods": "16"}))
        node.add_pod(victim)
        node.add_pod(filler)
        snap = Snapshot([node], generation=1)
        preemptor = PodInfo(make_pod(
            "hi-mem", requests={"cpu": "1", "memory": "32Gi"},
            priority=1000))
        plug, evictions = make_plugin(snap)
        plug.prime_wave([preemptor], snap, {})
        assert preemptor.key in plug._primed
        _, n, count = plug._primed[preemptor.key]
        assert (n, count) == (0, 1)
        node_name, st = plug.post_filter(CycleState(), preemptor, snap, {})
        assert st.is_success() and node_name == "m0"
        assert evictions[0][1] == ("default/big-victim",)

    def test_priority_threshold_and_banned(self):
        snap = contention_snapshot()
        plug, _ = make_plugin(snap)
        # Preemptor below every resident priority: nothing to propose.
        low = pp("low", cpu="1", priority=5)
        plug.prime_wave([low], snap, {})
        assert low.key not in plug._primed

    def test_in_flight_guard_renominates_without_reeviction(self):
        snap = contention_snapshot()
        preemptor = pp("hi", cpu="1", priority=1000)
        plug, evictions = make_plugin(snap)
        node, st = plug.post_filter(CycleState(), preemptor, snap, {})
        assert st.is_success() and len(evictions) == 1
        # Victims are still resident (no informer ran the deletes): a
        # retry must re-nominate the SAME node with NO second eviction.
        node2, st2 = plug.post_filter(CycleState(), preemptor, snap, {})
        assert st2.is_success()
        assert node2 == node
        assert len(evictions) == 1


class TestAdaptiveTunerEnvelope:
    def test_policy_envelope(self):
        # The documented envelope (AdaptiveTuner docstring / BASELINE r6).
        assert AdaptiveTuner.pick(0.020, 0.0) == (2048, 4)
        assert AdaptiveTuner.pick(0.020, 0.5) == (1024, 4)
        assert AdaptiveTuner.pick(0.0002, 0.0) == (1024, 2)
        assert AdaptiveTuner.pick(0.0002, 0.9) == (1024, 2)

    def test_flagless_backend_decides_within_envelope(self):
        backend = TPUBackend()          # flagless: tuner owns both knobs
        assert not backend._chunk_override
        t = backend._tuner
        assert t.decide() is None       # warmup: no decision yet
        for _ in range(t.WARMUP_CHUNKS):
            t.observe_chunk(False)
        chunk, depth = t.decide()       # probes the (local) device
        assert chunk in (512, 1024, 2048)
        assert depth in (2, 4)
        assert t.latency_s is not None

    def test_explicit_chunk_is_an_override(self):
        backend = TPUBackend(max_batch=8)
        assert backend._chunk_override
        assert backend.max_batch == 8


class TestWorkloadResultEventDrops:
    def test_as_dict_reports_drop_rate(self):
        from kubernetes_tpu.perf.scheduler_perf import WorkloadResult
        r = WorkloadResult()
        r.events_emitted_total = 10000
        r.events_dropped_total = 8000
        d = r.as_dict()
        assert d["events_dropped_total"] == 8000
        assert d["events_dropped_pct"] == 80.0
