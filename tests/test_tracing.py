"""End-to-end attempt tracing (SURVEY §5.1): traceparent propagation
across all three wires, threshold-triggered span-tree dumps, and the
Chrome/Perfetto export nesting device-solve chunks under the attempt.
"""

import asyncio
import json
import logging

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.store import install_core_validation, new_cluster_store
from kubernetes_tpu.utils.tracing import (
    DEFAULT_TRACER,
    TRACEPARENT_ANNOTATION,
    Tracer,
    traceparent_of,
)


def run(coro):
    return asyncio.run(coro)


@pytest.fixture
def tracer():
    DEFAULT_TRACER.enabled = True
    DEFAULT_TRACER.clear()
    yield DEFAULT_TRACER
    DEFAULT_TRACER.enabled = False
    DEFAULT_TRACER.clear()


def _span(tracer, name):
    matches = [s for s in tracer.spans if s.name == name]
    assert matches, ([s.name for s in tracer.spans], name)
    return matches[-1]


class TestTraceparentPropagation:
    """(a) one traceparent survives each wire's round-trip: the server's
    request span joins the client's trace instead of opening a new one."""

    def test_http_roundtrip(self, tracer):
        async def body():
            from kubernetes_tpu.apiserver import APIServer, RemoteStore
            backing = new_cluster_store()
            install_core_validation(backing)
            srv = APIServer(backing)
            await srv.start()
            rs = RemoteStore(srv.url)
            try:
                with tracer.span("client.create") as root:
                    created = await rs.create("pods", make_pod("p-http"))
            finally:
                await rs.close()
                await srv.stop()
                backing.stop()
            server_span = _span(tracer, "apiserver.create.pods")
            assert server_span.trace_id == root.trace_id
            assert server_span.parent_id == root.span_id
            # the stored pod carries the request's traceparent for the
            # scheduler to parent to (same trace id)
            tp = traceparent_of(created)
            assert tp and root.trace_id in tp
        run(body())

    def test_wire_roundtrip(self, tracer):
        async def body():
            from kubernetes_tpu.apiserver import APIServer
            from kubernetes_tpu.apiserver.wire import WireServer, WireStore
            backing = new_cluster_store()
            install_core_validation(backing)
            api = APIServer(backing)
            await api.start()
            wire = WireServer.for_apiserver(api, host="unix:")
            await wire.start()
            ws = WireStore(wire.target)
            try:
                with tracer.span("client.create") as root:
                    created = await ws.create("pods", make_pod("p-wire"))
            finally:
                await ws.close()
                await wire.stop()
                await api.stop()
                backing.stop()
            server_span = _span(tracer, "wire.create.pods")
            assert server_span.trace_id == root.trace_id
            assert server_span.parent_id == root.span_id
            tp = traceparent_of(created)
            assert tp and root.trace_id in tp
        run(body())

    def test_wire_multi_members_each_join_the_trace(self, tracer):
        """Ops coalesced into one multi frame are still N requests: each
        member's server span parents to ITS caller's span."""
        async def body():
            from kubernetes_tpu.apiserver import APIServer
            from kubernetes_tpu.apiserver.wire import WireServer, WireStore
            backing = new_cluster_store()
            install_core_validation(backing)
            api = APIServer(backing)
            await api.start()
            wire = WireServer.for_apiserver(api, host="unix:")
            await wire.start()
            ws = WireStore(wire.target)
            try:
                await ws.create("nodes", make_node("warm"))  # connect
                with tracer.span("client.batch") as root:
                    # same-tick gather coalesces into one multi frame
                    await asyncio.gather(
                        ws.create("pods", make_pod("m-0")),
                        ws.create("pods", make_pod("m-1")))
            finally:
                await ws.close()
                await wire.stop()
                await api.stop()
                backing.stop()
            members = [s for s in tracer.spans
                       if s.name == "wire.create.pods"
                       and s.trace_id == root.trace_id]
            assert len(members) == 2, [
                (s.name, s.trace_id) for s in tracer.spans]
        run(body())

    def test_malformed_traced_frame_still_gets_a_reply(self, tracer):
        """A traced wrapper carrying a non-string traceparent must
        degrade to an untraced op, not crash span creation outside the
        error-reply path (which would hang the caller's future)."""
        async def body():
            from kubernetes_tpu.apiserver import APIServer
            from kubernetes_tpu.apiserver.wire import WireServer, WireStore
            from kubernetes_tpu.store.mvcc import NotFound
            backing = new_cluster_store()
            install_core_validation(backing)
            api = APIServer(backing)
            await api.start()
            wire = WireServer.for_apiserver(api, host="unix:")
            await wire.start()
            ws = WireStore(wire.target)
            try:
                await ws.create("nodes", make_node("warm"))  # connect
                fut = asyncio.get_event_loop().create_future()
                ws._pending["rx"] = fut
                ws._send(["rx", "traced", 123, "get", "pods",
                          "default/missing"])
                with pytest.raises(NotFound):  # a real reply, not a hang
                    await asyncio.wait_for(fut, 5.0)
            finally:
                await ws.close()
                await wire.stop()
                await api.stop()
                backing.stop()
        run(body())

    def test_grpc_roundtrip(self, tracer):
        async def body():
            from kubernetes_tpu.apiserver.grpc_server import (
                GRPCAPIServer,
                GRPCRemoteStore,
            )
            backing = new_cluster_store()
            install_core_validation(backing)
            srv = GRPCAPIServer(backing)
            await srv.start()
            client = GRPCRemoteStore(srv.target)
            try:
                with tracer.span("client.create") as root:
                    created = await client.create(
                        "pods", make_pod("p-grpc"))
            finally:
                await client.close()
                await srv.stop()
                backing.stop()
            server_span = _span(tracer, "grpc.create.pods")
            assert server_span.trace_id == root.trace_id
            assert server_span.parent_id == root.span_id
            tp = traceparent_of(created)
            assert tp and root.trace_id in tp
        run(body())

    def test_wire_create_parents_scheduler_attempt(self, tracer):
        """The full journey: a create through the KTPU wire parents the
        scheduler's attempt span (via the stamped annotation), which in
        turn holds the queue-wait and extension-point children; the wire
        span is joinable by audit ID."""
        async def body():
            from kubernetes_tpu.apiserver import APIServer
            from kubernetes_tpu.apiserver.wire import WireServer, WireStore
            from kubernetes_tpu.client import InformerFactory
            from kubernetes_tpu.policy import AuditPipeline, AuditPolicy
            from kubernetes_tpu.scheduler import Scheduler
            backing = new_cluster_store()
            install_core_validation(backing)
            audit = AuditPipeline(AuditPolicy.metadata_for_all())
            api = APIServer(backing, audit=audit)
            await api.start()
            wire = WireServer.for_apiserver(api, host="unix:")
            await wire.start()
            ws = WireStore(wire.target)
            sched = Scheduler(ws, seed=3)
            factory = InformerFactory(ws)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            run_task = asyncio.ensure_future(sched.run(batch_size=1))
            try:
                await ws.create("nodes", make_node("n0"))
                with tracer.span("kubectl.create") as root:
                    await ws.create("pods", make_pod("journey"))
                for _ in range(300):
                    p = await ws.get("pods", "default/journey")
                    if p["spec"].get("nodeName"):
                        break
                    await asyncio.sleep(0.02)
                assert p["spec"].get("nodeName") == "n0"
            finally:
                await sched.stop()
                run_task.cancel()
                factory.stop()
                await ws.close()
                await wire.stop()
                await api.stop()
                await audit.close()
                backing.stop()
            wire_span = next(
                s for s in tracer.spans if s.name == "wire.create.pods"
                and s.trace_id == root.trace_id)
            attempt = next(
                s for s in tracer.spans if s.name == "scheduler.attempt"
                and s.attrs.get("pod") == "default/journey")
            # ONE trace: client span → wire request span → attempt span
            assert attempt.trace_id == root.trace_id
            assert attempt.parent_id == wire_span.span_id
            # queue wait + extension points nest under the attempt
            kids = {s.name for s in tracer.spans
                    if s.parent_id == attempt.span_id}
            assert "scheduler.queue.wait" in kids, kids
            assert "framework.PreFilter" in kids, kids
            assert "framework.Filter" in kids, kids
            # audit ↔ trace join: the wire span carries the auditID and
            # the audit event carries the span's traceparent
            audit_id = wire_span.attrs.get("audit_id")
            assert audit_id
            entry = next(e for e in audit.sink.entries
                         if e["auditID"] == audit_id
                         and e["stage"] == "ResponseComplete")
            assert wire_span.trace_id in \
                entry["annotations"]["traceparent"]
        run(body())


class TestThresholdTreeDump:
    """(b) utiltrace semantics for span trees: only roots slower than the
    threshold log their breakdown."""

    def test_fires_above_threshold(self, caplog):
        t = Tracer(enabled=True, threshold_ms=0.0)
        with caplog.at_level(logging.INFO,
                             logger="kubernetes_tpu.utils.tracing"):
            with t.span("attempt", pod="default/p"):
                with t.span("solve"):
                    pass
        assert len(caplog.records) == 1
        msg = caplog.records[0].message
        assert "Span[attempt{pod=default/p}]" in msg
        assert "solve" in msg

    def test_silent_below_threshold(self, caplog):
        t = Tracer(enabled=True, threshold_ms=10_000.0)
        with caplog.at_level(logging.INFO,
                             logger="kubernetes_tpu.utils.tracing"):
            with t.span("attempt"):
                with t.span("solve"):
                    pass
        assert not caplog.records

    def test_child_spans_never_dump(self, caplog):
        """Only ROOTS trigger the dump — a slow child logs once via its
        root, not once per nesting level."""
        t = Tracer(enabled=True, threshold_ms=0.0)
        with caplog.at_level(logging.INFO,
                             logger="kubernetes_tpu.utils.tracing"):
            with t.span("root"):
                with t.span("mid"):
                    with t.span("leaf"):
                        pass
        assert len(caplog.records) == 1

    def test_env_var_default(self, monkeypatch):
        monkeypatch.setenv("KTPU_TRACE_THRESHOLD_MS", "250")
        assert Tracer().threshold_ms == 250.0
        monkeypatch.delenv("KTPU_TRACE_THRESHOLD_MS")
        assert Tracer().threshold_ms is None


class TestPerfettoExport:
    """(c) schema-valid Chrome trace JSON with device-solve chunks nested
    under the scheduling attempt."""

    def test_solve_spans_nest_under_attempt(self, tracer, monkeypatch):
        # This test pins the CHUNKED solve's span nesting
        # (solver.dispatch/solve under the batch attempt); the serving
        # tier would legitimately fast-drain a 4-pod batch through the
        # pinned single-pod solve (which has no chunk spans) — pin it
        # off for the chunk-path assertion.
        monkeypatch.setenv("KTPU_SERVING", "0")

        async def body():
            from kubernetes_tpu.client import InformerFactory
            from kubernetes_tpu.ops import TPUBackend
            from kubernetes_tpu.scheduler import Scheduler
            store = new_cluster_store()
            install_core_validation(store)
            for i in range(2):
                await store.create("nodes", make_node(f"n{i}"))
            # Pods staged BEFORE the loop starts so one pop drains a
            # multi-pod batch through the device backend.
            for i in range(4):
                await store.create("pods", make_pod(f"p{i}"))
            sched = Scheduler(store, seed=7,
                              backend=TPUBackend(max_batch=8))
            factory = InformerFactory(store)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            run_task = asyncio.ensure_future(sched.run(batch_size=8))
            try:
                for _ in range(600):
                    pods = (await store.list("pods")).items
                    if sum(1 for p in pods
                           if p["spec"].get("nodeName")) == 4:
                        break
                    await asyncio.sleep(0.02)
                assert sum(1 for p in pods
                           if p["spec"].get("nodeName")) == 4
            finally:
                await sched.stop()
                run_task.cancel()
                factory.stop()
                store.stop()

            doc = json.loads(tracer.to_perfetto())
            evs = doc["traceEvents"]
            assert evs
            for e in evs:  # Chrome trace-event schema (complete events)
                assert e["ph"] == "X"
                for field in ("name", "pid", "tid", "ts", "dur", "args"):
                    assert field in e, (field, e)
            by_span = {e["args"]["span_id"]: e for e in evs}
            solve = next(e for e in evs if e["name"] == "solver.solve")
            # walk the parent chain: the solve chunk must nest under a
            # scheduler.attempt span
            seen = set()
            cur = solve
            while cur is not None and cur["name"] != "scheduler.attempt":
                pid = cur["args"].get("parent_id")
                assert pid and pid not in seen, \
                    (solve, [e["name"] for e in evs])
                seen.add(pid)
                cur = by_span.get(pid)
            assert cur is not None and cur["name"] == "scheduler.attempt"
            # dispatch span rides the same tree
            assert any(e["name"] == "solver.dispatch" for e in evs)
            # binds happened and are attributed to pods for trace_for
            assert any(e["name"] == "scheduler.bind" for e in evs)
        run(body())

    def test_queue_wait_covers_only_current_attempt(self, tracer):
        """A retried pod's queue.wait span starts at its LATEST activeQ
        entry, not first-enqueue — prior cycles and backoff windows must
        not inflate the wait."""
        async def body():
            from kubernetes_tpu.scheduler.framework import Framework
            from kubernetes_tpu.scheduler.queue import SchedulingQueue
            from kubernetes_tpu.scheduler.types import PodInfo
            now = [100.0]
            q = SchedulingQueue(Framework([]), initial_backoff=0.0,
                                clock=lambda: now[0])
            pi = PodInfo(make_pod("retry"))
            await q.add(pi)
            assert pi.enqueued_at == 100.0
            now[0] = 101.0
            (popped,) = await q.pop_batch(1)
            assert popped.dequeued_at == 101.0
            now[0] = 150.0  # a long failed cycle...
            await q.move_to_backoff(pi)
            async with q._cond:
                q._flush_backoff_locked()  # ...then re-activation
            assert pi.enqueued_at == 150.0  # re-stamped, not 100.0
            now[0] = 150.5
            (popped,) = await q.pop_batch(1)
            assert popped.dequeued_at - popped.enqueued_at == 0.5
            await q.close()
        run(body())

    def test_retroactive_record_parents_to_current(self, tracer):
        with tracer.span("attempt") as sp:
            tracer.record("queue.wait", 1.0, 2.0, pod="default/x")
        rec = _span(tracer, "queue.wait")
        assert rec.parent_id == sp.span_id
        assert rec.trace_id == sp.trace_id
        assert abs(rec.duration_ms - 1000.0) < 1e-6
        doc = json.loads(tracer.to_perfetto())
        assert any(e["name"] == "queue.wait" for e in doc["traceEvents"])


class TestDisabledOverhead:
    """Tracing off (the default) must leave no trace artifacts anywhere
    on the path — the <2% bench headline guard's functional half."""

    def test_no_annotation_stamped_when_disabled(self):
        async def body():
            from kubernetes_tpu.apiserver import APIServer, RemoteStore
            backing = new_cluster_store()
            install_core_validation(backing)
            srv = APIServer(backing)
            await srv.start()
            rs = RemoteStore(srv.url)
            try:
                created = await rs.create("pods", make_pod("plain"))
            finally:
                await rs.close()
                await srv.stop()
                backing.stop()
            ann = (created["metadata"].get("annotations") or {})
            assert TRACEPARENT_ANNOTATION not in ann
            assert len(DEFAULT_TRACER.spans) == 0
        assert not DEFAULT_TRACER.enabled
        run(body())
