"""ChurnDay tier-1 smoke: a tiny knee sweep + a node-death scenario run
end to end, pinning the bench detail-JSON churn schema (ISSUE r15 CI
satellite). Kept small: ~50 nodes, sub-second open-loop windows."""

import asyncio

from kubernetes_tpu.perf import PerfRunner
from kubernetes_tpu.perf.churn.driver import run_rate_sweep

#: every churn field the bench detail JSON must carry (schema assertion
#: extended to the new battery — sharded/residency smokes pin theirs).
CHURN_DETAIL_KEYS = {
    "churn_offered_rate", "churn_achieved_rate", "churn_arrival_model",
    "churn_arrivals_total", "churn_duration_s", "churn_backlog_peak",
    "churn_backlog_final", "churn_pending_final", "churn_saturated",
    "churn_late_arrivals", "churn_throttled_creates",
    "churn_create_errors", "churn_create_drain_s", "churn_faults",
    "churn_faults_injected", "churn_recovery_seconds_max",
}


class TestChurnSmoke:
    def test_sweep_finds_knee_and_fault_recovers(self):
        """One tiny sweep bracketing the knee (a trickle the host path
        absorbs + a flood it can't) plus a nodeDeath scenario mid-wave:
        knee/p999 fields present, fault injection FIRES, recovery
        measured, and the detail JSON carries the full churn schema."""
        sweep = run_rate_sweep(
            nodes=50, rates=[50.0, 6000.0], duration=0.8, warmup=20,
            seed=11, fault={"kind": "nodeDeath", "at": 0.3},
            fault_rate=40.0, grace=1.0, toleration=0.1,
            recovery_timeout=30.0, timeout=120.0)

        rows = sweep["rows"]
        assert len(rows) == 2
        for row in rows:
            assert CHURN_DETAIL_KEYS <= set(row)
            # p50/p99/p999 are the battery's headline: exact recorder
            # values, present per row.
            assert row["attempt_percentiles_exact"] is True
            for k in ("attempt_p50_ms", "attempt_p99_ms",
                      "attempt_p999_ms"):
                assert row[k] is not None and row[k] > 0
            # Open-loop COUNT invariant: every seeded arrival fired —
            # saturation may slip the clock (self-reported via
            # late_arrivals/achieved_rate) but never drops arrivals.
            from kubernetes_tpu.perf.churn import PoissonArrivals
            expected = len(PoissonArrivals(
                row["churn_offered_rate"], seed=11).timeline(0.8))
            assert row["churn_arrivals_total"] == expected
        # The trickle row also tracks the offered rate in wall time.
        assert rows[0]["churn_achieved_rate"] > \
            0.7 * rows[0]["churn_offered_rate"]

        knee = sweep["knee"]
        assert knee["knee_rate"] == 50.0
        assert knee["knee_p999_ms"] is not None
        assert knee["first_saturated_rate"] == 6000.0
        assert rows[1]["churn_saturated"] is True
        assert rows[1]["churn_backlog_final"] > 16

        fr = sweep["fault_row"]
        assert fr is not None
        assert fr["churn_faults_injected"] == {"nodeDeath": 1}
        (fault,) = fr["churn_faults"]
        assert fault["kind"] == "nodeDeath"
        assert fault["recovered"] is True
        assert fault["recovery_s"] is not None and fault["recovery_s"] > 0
        assert fr["churn_recovery_seconds_max"] == fault["recovery_s"]

    def test_repo_config_has_churn_families(self):
        """ChurnDay ships with ≥3 knee-sweep rows plus a fault family."""
        from kubernetes_tpu.perf.scheduler_perf import load_config
        cfg = load_config(
            "kubernetes_tpu/perf/config/performance-config.yaml")
        fams = {c["name"]: c for c in cfg}
        day = fams["ChurnDay"]
        assert len(day["workloads"]) >= 3
        rates = {w["params"]["rate"] for w in day["workloads"]}
        assert len(rates) >= 3  # a real sweep, not one rate repeated
        ops = [op["opcode"] for op in day["workloadTemplate"]]
        assert "churnOpenLoop" in ops
        faults = fams["ChurnDayFaults"]
        churn_op = next(op for op in faults["workloadTemplate"]
                        if op["opcode"] == "churnOpenLoop")
        assert any(f["kind"] == "nodeDeath" for f in churn_op["faults"])
        # lease renewals must outpace the grace period or healthy nodes
        # flap unreachable (the config bug this battery's bring-up hit).
        lease = next(op for op in faults["workloadTemplate"]
                     if op["opcode"] == "startAgents")["leasePeriod"]
        for w in faults["workloads"]:
            assert w["params"]["grace"] >= 2 * lease

    def test_gang_arrival_fault_collides_with_preemption(self):
        """gangArrival mid-wave: a high-priority gang lands at once on a
        full cluster and must displace filler load (the r6 preemption
        path active inside the open-loop run)."""
        template = [
            {"opcode": "createNodes", "count": 4,
             "nodeTemplate": {"allocatable":
                              {"cpu": "4", "memory": "16Gi",
                               "pods": "32"}}},
            {"opcode": "createPods", "count": 14,
             "podTemplate": {"priority": 0, "requests": {"cpu": "1"}}},
            {"opcode": "barrier"},
            {"opcode": "churnOpenLoop", "collectMetrics": True,
             "arrival": {"model": "poisson", "rate": 10},
             "duration": 1.2, "seed": 5,
             "recoveryTimeout": 30.0,
             "faults": [{"at": 0.2, "kind": "gangArrival", "count": 4,
                         "podTemplate": {"priority": 1000,
                                         "requests": {"cpu": "1"}}}]},
        ]
        res = asyncio.run(PerfRunner().run(template, {}, timeout=90.0))
        d = res.as_dict()
        assert d["churn_faults_injected"] == {"gangArrival": 1}
        (fault,) = d["churn_faults"]
        assert fault["replacements"] == 4
        assert fault["recovered"] is True
