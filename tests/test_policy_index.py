"""O(matching) policy dispatch (policy/vap.py, ISSUE 15): randomized
index-vs-linear differential parity over generated policy sets
(wildcard rules, namespace-selector overlap, matchConditions,
DELETE/object=null, param refs, variables, messageExpression),
mutation invalidation mid-stream, and the tier-1 smoke contract —
index active by default, KTPU_POLICY_INDEX=0 structural degrade,
residue-path non-vacuity, namespace-memo invalidation."""

import asyncio
import random

import pytest

from kubernetes_tpu.api.types import (
    make_config_map,
    make_namespace,
    make_pod,
    make_validating_admission_policy,
    make_vap_binding,
)
from kubernetes_tpu.policy import PolicyEngine
from kubernetes_tpu.policy.vap import PolicyDenied
from kubernetes_tpu.store import install_core_validation, new_cluster_store
from kubernetes_tpu.utils import flags


def run(coro):
    return asyncio.run(coro)


def outcome(engine, obj, resource, op, old=None):
    """None (allowed) or the exact deny message — the bit the
    differential compares."""
    try:
        engine.validate(obj, resource, op, old_object=old)
        return None
    except PolicyDenied as e:
        return str(e)


def evals_total(engine) -> float:
    return sum(engine.evaluations._values.values())


# ---------------------------------------------------------------------------
# generators
# ---------------------------------------------------------------------------

_NS_LABEL_POOL = [
    {"team": "a"}, {"team": "b"}, {"env": "prod"},
    {"env": "prod", "team": "a"}, {},
]

_SELECTOR_POOL = [
    None,
    {"matchLabels": {"team": "a"}},
    {"matchLabels": {"env": "prod"}},
    {"matchLabels": {"team": "b", "env": "prod"}},
    {},  # empty selector: matches every namespace (reference)
]

_EXPR_POOL = [
    # (expression, message) — all compile; some error at runtime on
    # non-pod shapes or missing params (failurePolicy coverage).
    ("size(object.spec.containers) >= 1", "needs containers"),
    ("object.metadata.name != 'deny-me'", "denied by name"),
    ("has(object.spec)", "no spec"),
    ("object.spec.missingField == 1", "runtime error path"),
    ("int(params.data.max) >= 10", "param gate"),
    ("oldObject.metadata.name != 'protected'", "protected"),
]

_CONDITION_POOL = [
    "object.metadata.name != 'skip'",
    "has(object.metadata.labels)",
    "request.operation != 'UPDATE'",
]

_RESOURCE_POOL = [["pods"], ["configmaps"], ["secrets"],
                  ["pods", "configmaps"], ["*"]]
_OP_POOL = [["CREATE"], ["CREATE", "UPDATE"], ["DELETE"], ["*"], None]


async def _seed_cluster(store, rng: random.Random, n_policies: int):
    for i, labels in enumerate(_NS_LABEL_POOL):
        ns = make_namespace(f"ns-{i}")
        if labels:
            ns["metadata"]["labels"] = dict(labels)
        await store.create("namespaces", ns)
    await store.create(
        "configmaps", make_config_map("caps", data={"max": "50"}))
    for i in range(n_policies):
        name = f"pol-{i}"
        expr, msg = rng.choice(_EXPR_POOL)
        constraints = {}
        rules = rng.choice(_RESOURCE_POOL)
        ops = rng.choice(_OP_POOL)
        rule = {"resources": rules}
        if ops is not None:
            rule["operations"] = ops
        if rng.random() < 0.9:
            constraints["resourceRules"] = [rule]
        sel = rng.choice(_SELECTOR_POOL)
        if sel is not None:
            constraints["namespaceSelector"] = sel
        kwargs = {}
        if "params" in expr:
            kwargs["param_kind"] = "ConfigMap"
        policy = make_validating_admission_policy(
            name, [{"expression": expr, "message": msg}],
            failure_policy=rng.choice(["Fail", "Ignore"]),
            match_constraints=constraints or None, **kwargs)
        if rng.random() < 0.3:
            policy["spec"]["matchConditions"] = [
                {"name": "c0", "expression": rng.choice(_CONDITION_POOL)}]
        if rng.random() < 0.3:
            policy["spec"]["variables"] = [
                {"name": "nm", "expression": "object.metadata.name"}]
            policy["spec"]["validations"].append(
                {"expression": "variables.nm != 'var-deny'",
                 "message": "variable deny",
                 "messageExpression":
                     "'variable denied: ' + variables.nm"})
        await store.create("validatingadmissionpolicies", policy)
        if rng.random() < 0.9:  # ~10% stay unbound (inert, reference)
            param_ref = None
            if "params" in expr and rng.random() < 0.8:
                param_ref = {"name": "caps", "namespace": "default"}
            await store.create(
                "validatingadmissionpolicybindings",
                make_vap_binding(f"{name}-b", name,
                                 param_ref=param_ref))


def _rand_request(rng: random.Random):
    name = rng.choice(["ok", "deny-me", "skip", "protected",
                       "var-deny", "plain"])
    ns = rng.choice([f"ns-{i}" for i in range(len(_NS_LABEL_POOL))]
                    + ["default", "ghost-ns"])
    resource = rng.choice(["pods", "configmaps", "secrets", "leases"])
    op = rng.choice(["create", "update", "delete"])
    if resource == "pods":
        obj = make_pod(name, namespace=ns)
    else:
        obj = {"kind": "X", "metadata": {"name": name, "namespace": ns},
               "data": {"k": "v"}}
    if op == "delete":
        return None, resource, op, obj
    old = None
    if op == "update":
        old = {**obj, "metadata": {**obj["metadata"], "old": "1"}}
    return obj, resource, op, old


# ---------------------------------------------------------------------------
# differential parity
# ---------------------------------------------------------------------------

class TestIndexLinearParity:
    @pytest.mark.parametrize("seed", [7, 23, 101])
    def test_randomized_verdict_parity(self, seed):
        """Index-vs-linear verdicts bit-identical (exact deny message)
        over a generated policy set, and the evaluation counters agree
        request-by-request — the shared evaluation core really did run
        the same expressions."""
        async def body():
            rng = random.Random(seed)
            store = new_cluster_store()
            install_core_validation(store)
            await _seed_cluster(store, rng, n_policies=40)
            idx_eng = PolicyEngine(store)
            lin_eng = PolicyEngine(store)
            for _ in range(60):
                obj, resource, op, old = _rand_request(rng)
                with flags.scoped_set("KTPU_POLICY_INDEX", "1"):
                    e0 = evals_total(idx_eng)
                    r_idx = outcome(idx_eng, obj, resource, op, old)
                    d_idx = evals_total(idx_eng) - e0
                with flags.scoped_set("KTPU_POLICY_INDEX", "0"):
                    e0 = evals_total(lin_eng)
                    r_lin = outcome(lin_eng, obj, resource, op, old)
                    d_lin = evals_total(lin_eng) - e0
                assert r_idx == r_lin, (resource, op, r_idx, r_lin)
                assert d_idx == d_lin, (resource, op, d_idx, d_lin)
            # the index really dispatched (non-vacuous differential)
            assert idx_eng.index_rebuilds.value() >= 1
            assert lin_eng.index_rebuilds.value() == 0
            store.stop()
        run(body())

    def test_mutation_invalidation_mid_stream(self):
        """Policy/binding writes and namespace label writes between
        requests: the incremental index must equal a from-scratch
        engine after every mutation."""
        async def body():
            rng = random.Random(99)
            store = new_cluster_store()
            install_core_validation(store)
            await _seed_cluster(store, rng, n_policies=15)
            live = PolicyEngine(store)
            reqs = [_rand_request(rng) for _ in range(10)]
            for step in range(6):
                if step == 1:  # add a new always-matching policy
                    await store.create(
                        "validatingadmissionpolicies",
                        make_validating_admission_policy("mid-add", [
                            {"expression":
                                 "object.metadata.name != 'deny-me'",
                             "message": "mid-add deny"}]))
                    await store.create(
                        "validatingadmissionpolicybindings",
                        make_vap_binding("mid-add-b", "mid-add"))
                elif step == 2:  # unbind it again
                    await store.delete(
                        "validatingadmissionpolicybindings", "mid-add-b")
                elif step == 3:  # flip a namespace's labels
                    ns = await store.get("namespaces", "ns-0")
                    ns["metadata"]["labels"] = {"team": "b"}
                    await store.update("namespaces", ns)
                elif step == 4:  # delete a policy outright
                    await store.delete(
                        "validatingadmissionpolicies", "pol-0")
                elif step == 5:  # restore ns-0
                    ns = await store.get("namespaces", "ns-0")
                    ns["metadata"]["labels"] = {"team": "a"}
                    await store.update("namespaces", ns)
                fresh = PolicyEngine(store)
                for obj, resource, op, old in reqs:
                    with flags.scoped_set("KTPU_POLICY_INDEX", "1"):
                        r_live = outcome(live, obj, resource, op, old)
                    with flags.scoped_set("KTPU_POLICY_INDEX", "0"):
                        r_fresh = outcome(fresh, obj, resource, op, old)
                    assert r_live == r_fresh, (step, resource, op)
            store.stop()
        run(body())


# ---------------------------------------------------------------------------
# tier-1 smoke: structural contracts
# ---------------------------------------------------------------------------

async def _small_cluster():
    store = new_cluster_store()
    install_core_validation(store)
    prod = make_namespace("prod")
    prod["metadata"]["labels"] = {"env": "prod"}
    await store.create("namespaces", prod)
    await store.create(
        "validatingadmissionpolicies",
        make_validating_admission_policy("exact", [
            {"expression": "object.metadata.name != 'deny-me'",
             "message": "exact deny"}],
            match_constraints={"resourceRules": [
                {"resources": ["pods"], "operations": ["CREATE"]}]}))
    await store.create("validatingadmissionpolicybindings",
                       make_vap_binding("exact-b", "exact"))
    await store.create(
        "validatingadmissionpolicies",
        make_validating_admission_policy("wild", [
            {"expression": "object.metadata.name != 'banned'",
             "message": "wildcard deny"}],
            match_constraints={"resourceRules": [
                {"resources": ["*"], "operations": ["CREATE"]}]}))
    await store.create("validatingadmissionpolicybindings",
                       make_vap_binding("wild-b", "wild"))
    return store


class TestIndexSmoke:
    def test_index_active_by_default(self):
        """Flagless: the exact-key index serves pod creates (hits
        counted, structures built) — the O(matching) path is the
        default, not an opt-in."""
        async def body():
            store = await _small_cluster()
            eng = PolicyEngine(store)
            eng.validate(make_pod("fine"), "pods", "create")
            assert eng._index is not None
            assert eng.index_hits.value() >= 1
            assert eng.index_rebuilds.value() == 1
            # a second request reuses the index: no extra rebuild
            eng.validate(make_pod("fine2"), "pods", "create")
            assert eng.index_rebuilds.value() == 1
            store.stop()
        run(body())

    def test_kill_switch_structural_degrade(self):
        """KTPU_POLICY_INDEX=0: verdicts identical, but NO index
        structures exist and no index counters move — the linear scan
        is structural, not an indexed path with extra steps."""
        async def body():
            store = await _small_cluster()
            eng = PolicyEngine(store)
            with flags.scoped_set("KTPU_POLICY_INDEX", "0"):
                with pytest.raises(PolicyDenied) as ei:
                    eng.validate(make_pod("deny-me"), "pods", "create")
                assert "exact deny" in str(ei.value)
                eng.validate(make_pod("fine"), "pods", "create")
            assert eng._index is None
            assert eng.index_rebuilds.value() == 0
            assert eng.index_hits.value() == 0
            assert eng.index_residue_scans.value() == 0
            store.stop()
        run(body())

    def test_residue_path_non_vacuous(self):
        """Wildcard rules land in the residue list and still deny —
        the linear tail is exercised, not just indexed buckets."""
        async def body():
            store = await _small_cluster()
            eng = PolicyEngine(store)
            with pytest.raises(PolicyDenied) as ei:
                eng.validate(make_pod("banned"), "pods", "create")
            assert "wildcard deny" in str(ei.value)
            assert eng.index_residue_scans.value() >= 1
            # a non-pod resource only the wildcard can match: served
            # exclusively from the residue
            hits0 = eng.index_hits.value()
            with pytest.raises(PolicyDenied):
                eng.validate(
                    {"kind": "Secret",
                     "metadata": {"name": "banned",
                                  "namespace": "default"}},
                    "secrets", "create")
            assert eng.index_hits.value() == hits0
            store.stop()
        run(body())

    def test_ns_selector_memo_invalidation(self):
        """The interned-selector memo answers from cache across
        requests and flips correctly when the namespace's labels
        change (the mutator invalidation seam)."""
        async def body():
            store = await _small_cluster()
            await store.create(
                "validatingadmissionpolicies",
                make_validating_admission_policy("prod-only", [
                    {"expression": "has(object.spec.priority)",
                     "message": "prod needs priority"}],
                    match_constraints={
                        "resourceRules": [{"resources": ["pods"],
                                           "operations": ["CREATE"]}],
                        "namespaceSelector": {
                            "matchLabels": {"env": "prod"}}}))
            await store.create("validatingadmissionpolicybindings",
                               make_vap_binding("prod-b", "prod-only"))
            eng = PolicyEngine(store)
            with pytest.raises(PolicyDenied):
                eng.validate(make_pod("p", namespace="prod"),
                             "pods", "create")
            assert eng._ns_memo.get("prod")  # memoized True
            # de-label the namespace: memo entry must invalidate
            ns = await store.get("namespaces", "prod")
            ns["metadata"]["labels"] = {}
            await store.update("namespaces", ns)
            assert "prod" not in eng._ns_memo
            eng.validate(make_pod("p2", namespace="prod"),
                         "pods", "create")  # selector no longer matches
            store.stop()
        run(body())

    def test_sig_tables_bounded_under_selector_churn(self):
        """Policy churn with ever-new selector contents must not grow
        the signature interning tables without bound: each rebuild
        re-interns from the live active set only."""
        async def body():
            store = await _small_cluster()
            eng = PolicyEngine(store)
            for round_ in range(10):
                name = f"churn-{round_}"
                await store.create(
                    "validatingadmissionpolicies",
                    make_validating_admission_policy(name, [
                        {"expression": "1 == 1"}],
                        match_constraints={
                            "resourceRules": [
                                {"resources": ["pods"],
                                 "operations": ["CREATE"]}],
                            "namespaceSelector": {"matchLabels": {
                                "churn": f"v{round_}"}}}))
                await store.create(
                    "validatingadmissionpolicybindings",
                    make_vap_binding(f"{name}-b", name))
                eng.validate(make_pod(f"p{round_}", namespace="prod"),
                             "pods", "create")
                await store.delete(
                    "validatingadmissionpolicybindings", f"{name}-b")
                await store.delete(
                    "validatingadmissionpolicies", name)
            eng.validate(make_pod("last", namespace="prod"),
                         "pods", "create")
            # only the LIVE active set's selectors remain interned
            # (the _small_cluster policies carry none)
            assert len(eng._sig_ids) == 0
            assert len(eng._sig_sel) == 0
            store.stop()
        run(body())

    def test_shared_selector_one_signature(self):
        """Policies carrying the SAME selector content intern to one
        signature — one selector eval per namespace serves all."""
        async def body():
            store = await _small_cluster()
            for i in range(5):
                await store.create(
                    "validatingadmissionpolicies",
                    make_validating_admission_policy(f"shared-{i}", [
                        {"expression": "1 == 1"}],
                        match_constraints={
                            "resourceRules": [
                                {"resources": ["pods"],
                                 "operations": ["CREATE"]}],
                            "namespaceSelector": {
                                "matchLabels": {"env": "prod"}}}))
                await store.create(
                    "validatingadmissionpolicybindings",
                    make_vap_binding(f"shared-{i}-b", f"shared-{i}"))
            eng = PolicyEngine(store)
            eng.validate(make_pod("p", namespace="prod"),
                         "pods", "create")
            assert len(eng._sig_ids) == 1
            assert len(eng._ns_memo["prod"]) == 1
            store.stop()
        run(body())
