"""Server-side apply: field ownership, conflicts, two-owner merges
(SURVEY §2.7 kubectl apply --server-side; structured-merge-diff)."""

import asyncio
import unittest

from kubernetes_tpu.api.types import make_pod
from kubernetes_tpu.apiserver import APIServer, RemoteStore
from kubernetes_tpu.apiserver.wire import WireServer, WireStore
from kubernetes_tpu.store import (
    ApplyConflict,
    install_core_validation,
    new_cluster_store,
)
from kubernetes_tpu.store.mvcc import Conflict


def run(coro):
    return asyncio.run(coro)


def deployment(name="web", **spec):
    return {"apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"replicas": 1, **spec}}


class TestServerSideApply(unittest.TestCase):
    def test_create_records_ownership(self):
        async def body():
            store = new_cluster_store()
            out = await store.apply(
                "deployments", deployment(), field_manager="deploy-tool")
            mf = out["metadata"]["managedFields"]
            self.assertEqual(mf[0]["manager"], "deploy-tool")
            self.assertEqual(mf[0]["operation"], "Apply")
            self.assertIn("f:spec", mf[0]["fieldsV1"])
            store.stop()
        run(body())

    def test_conflict_on_foreign_field_then_force(self):
        async def body():
            store = new_cluster_store()
            await store.apply("deployments", deployment(replicas=1),
                              field_manager="deploy-tool")
            # An autoscaler tries to set replicas: conflict, 409.
            with self.assertRaises(Conflict) as cm:
                await store.apply(
                    "deployments", deployment(replicas=5),
                    field_manager="hpa")
            self.assertIn("deploy-tool", str(cm.exception))
            self.assertIn("spec.replicas", str(cm.exception))
            # force=True takes the field over.
            out = await store.apply(
                "deployments", deployment(replicas=5),
                field_manager="hpa", force=True)
            self.assertEqual(out["spec"]["replicas"], 5)
            owners = {e["manager"]: e["fieldsV1"]
                      for e in out["metadata"]["managedFields"]}
            self.assertIn("f:replicas", owners["hpa"]["f:spec"])
            self.assertNotIn(
                "f:replicas", owners.get("deploy-tool", {})
                .get("f:spec", {}))
            store.stop()
        run(body())

    def test_two_owner_field_merge(self):
        """Judge's 'done' case: two managers own disjoint fields; each
        apply touches only its own, neither clobbers the other."""
        async def body():
            store = new_cluster_store()
            await store.apply(
                "deployments",
                deployment(replicas=2,
                           template={"labels": {"app": "web"}}),
                field_manager="deploy-tool")
            # A second manager owns an annotation + a new spec field.
            patch = {"apiVersion": "apps/v1", "kind": "Deployment",
                     "metadata": {"name": "web", "namespace": "default",
                                  "annotations": {"team": "infra"}},
                     "spec": {"paused": True}}
            out = await store.apply("deployments", patch,
                                    field_manager="annotator")
            self.assertEqual(out["spec"]["replicas"], 2)
            self.assertEqual(out["spec"]["paused"], True)
            self.assertEqual(out["metadata"]["annotations"]["team"],
                             "infra")
            # deploy-tool re-applies WITHOUT the annotation: annotator's
            # fields survive; deploy-tool's dropped field is removed.
            out = await store.apply(
                "deployments", deployment(replicas=3),
                field_manager="deploy-tool")
            self.assertEqual(out["spec"]["replicas"], 3)
            self.assertEqual(out["spec"]["paused"], True)
            self.assertEqual(out["metadata"]["annotations"]["team"],
                             "infra")
            # the template deploy-tool no longer applies is gone
            self.assertNotIn("template", out["spec"])
            store.stop()
        run(body())

    def test_same_value_coownership_no_conflict(self):
        async def body():
            store = new_cluster_store()
            await store.apply("deployments", deployment(replicas=4),
                              field_manager="a")
            out = await store.apply("deployments", deployment(replicas=4),
                                    field_manager="b")  # equal value: ok
            self.assertEqual(out["spec"]["replicas"], 4)
            # a alone dropping the field doesn't remove it (b co-owns)
            out = await store.apply(
                "deployments",
                {"apiVersion": "apps/v1", "kind": "Deployment",
                 "metadata": {"name": "web", "namespace": "default"}},
                field_manager="a")
            self.assertEqual(out["spec"]["replicas"], 4)
            store.stop()
        run(body())

    def test_apply_over_http_and_wire(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            srv = APIServer(store)
            await srv.start()
            wire = WireServer.for_apiserver(srv)
            await wire.start()
            http = RemoteStore(srv.url)
            ws = WireStore(wire.target)
            try:
                out = await http.apply(
                    "pods", make_pod("a", requests={"cpu": "1"}),
                    field_manager="ctl-a")
                self.assertEqual(
                    out["metadata"]["managedFields"][0]["manager"],
                    "ctl-a")
                # conflicting apply over the WIRE gets the 409 mapping
                pod = make_pod("a", requests={"cpu": "2"})
                with self.assertRaises(Conflict):
                    await ws.apply("pods", pod, field_manager="ctl-b")
                out = await ws.apply("pods", pod, field_manager="ctl-b",
                                     force=True)
                self.assertEqual(
                    out["spec"]["containers"][0]["resources"][
                        "requests"]["cpu"], "2")
            finally:
                await http.close()
                await ws.close()
                await wire.stop()
                await srv.stop()
                store.stop()
        run(body())

    def test_kubectl_server_side_flow(self):
        async def body():
            import io
            import tempfile

            from kubernetes_tpu.cli.kubectl import (
                build_parser,
                run_command,
            )
            store = new_cluster_store()
            with tempfile.NamedTemporaryFile(
                    "w", suffix=".yaml", delete=False) as f:
                f.write("apiVersion: apps/v1\nkind: Deployment\n"
                        "metadata: {name: web}\nspec: {replicas: 2}\n")
                path = f.name
            out = io.StringIO()
            args = build_parser().parse_args(
                ["apply", "-f", path, "--server-side",
                 "--field-manager", "ci"])
            rc = await run_command(store, args, out)
            self.assertEqual(rc, 0)
            self.assertIn("serverside-applied", out.getvalue())
            got = await store.get("deployments", "default/web")
            self.assertEqual(
                got["metadata"]["managedFields"][0]["manager"], "ci")
            store.stop()
        run(body())


class TestApplyConflictType(unittest.TestCase):
    def test_is_conflict_subclass(self):
        self.assertTrue(issubclass(ApplyConflict, Conflict))


if __name__ == "__main__":
    unittest.main()


class TestApplyStructuralConflicts(unittest.TestCase):
    """Prefix/extension path overlaps are conflicts (structured-merge-diff
    flags structural overwrites, not just exact-leaf collisions)."""

    def test_scalar_over_foreign_subtree_conflicts(self):
        async def body():
            store = new_cluster_store()
            try:
                await store.apply(
                    "deployments",
                    deployment(strategy={"rollingUpdate": {"maxSurge": 2}}),
                    field_manager="alice")
                # bob applies spec.strategy as a SCALAR — structurally
                # overwrites alice's deeper leaf → conflict, not silent win.
                with self.assertRaises(ApplyConflict):
                    await store.apply(
                        "deployments", deployment(strategy="Recreate"),
                        field_manager="bob")
                # force transfers: alice loses the overlapped deep path.
                out = await store.apply(
                    "deployments", deployment(strategy="Recreate"),
                    field_manager="bob", force=True)
                self.assertEqual(out["spec"]["strategy"], "Recreate")
                mf = {e["manager"]: e for e in
                      out["metadata"]["managedFields"]}
                self.assertNotIn(
                    "f:strategy", mf.get("alice", {}).get(
                        "fieldsV1", {}).get("f:spec", {}))
            finally:
                store.stop()
        run(body())

    def test_deeper_path_under_foreign_leaf_conflicts(self):
        async def body():
            store = new_cluster_store()
            try:
                await store.apply(
                    "deployments", deployment(strategy="Recreate"),
                    field_manager="alice")
                with self.assertRaises(ApplyConflict):
                    await store.apply(
                        "deployments",
                        deployment(strategy={"rollingUpdate":
                                             {"maxSurge": 2}}),
                        field_manager="bob")
            finally:
                store.stop()
        run(body())

    def test_apply_does_not_mutate_caller_input(self):
        async def body():
            store = new_cluster_store()
            try:
                obj = deployment()
                before = {"apiVersion": obj["apiVersion"],
                          "metadata": dict(obj["metadata"])}
                await store.apply("deployments", obj,
                                  field_manager="alice")
                self.assertNotIn("managedFields", obj["metadata"])
                self.assertEqual(obj["metadata"], before["metadata"])
            finally:
                store.stop()
        run(body())
