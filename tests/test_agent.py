"""Hollow-kubelet node agent (kubernetes_tpu/agent): field-filtered
watch source, per-pod workers, DRA device Allocate with checkpoint,
restart recovery.

Reference semantics mirrored: pkg/kubelet syncLoop/pod_workers
(serialized per-pod, latest wins), cm/devicemanager Allocate +
checkpointmanager (allocations survive kubelet restart), kubemark
hollow kubelet (status transitions stand in for a runtime), and the
apiserver's `spec.nodeName=` field selector the kubelet watches with.
"""

import asyncio
import os
import tempfile
import unittest

from kubernetes_tpu.agent import DeviceLedger, NodeAgent
from kubernetes_tpu.api.types import (
    make_device_class,
    make_node,
    make_pod,
    make_resource_claim,
)
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.apiserver.wire import WireServer, WireStore
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def run(coro):
    return asyncio.run(coro)


async def wait_for(pred, timeout=8.0, msg="condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        got = await pred()
        if got:
            return got
        await asyncio.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


class TestFieldSelectors(unittest.TestCase):
    """Store-side field selectors: the kubelet's watch shape."""

    def test_list_by_node_name(self):
        async def body():
            store = new_cluster_store()
            try:
                await store.create("pods", make_pod("a"))
                b = make_pod("b")
                b["spec"]["nodeName"] = "n1"
                await store.create("pods", b)
                lst = await store.list(
                    "pods", fields={"spec.nodeName": "n1"})
                self.assertEqual(
                    [p["metadata"]["name"] for p in lst.items], ["b"])
            finally:
                store.stop()
        run(body())

    def test_bind_enters_field_watch_as_added(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            try:
                await store.create("nodes", make_node("n1"))
                await store.create("pods", make_pod("p"))
                w = await store.watch(
                    "pods", resource_version=store.resource_version,
                    fields={"spec.nodeName": "n1"})
                # Unbound churn is invisible to the node's watch.
                await store.guaranteed_update(
                    "pods", "default/p",
                    lambda o: {**o, "metadata": {
                        **o["metadata"],
                        "labels": {"x": "y"}}})
                await store.subresource(
                    "pods", "default/p", "binding",
                    {"target": {"name": "n1"}})
                ev = await asyncio.wait_for(w.__anext__(), 5)
                self.assertEqual(ev.type, "ADDED")  # enter ⇒ ADDED
                self.assertEqual(ev.object["spec"]["nodeName"], "n1")
                await store.delete("pods", "default/p")
                ev = await asyncio.wait_for(w.__anext__(), 5)
                self.assertEqual(ev.type, "DELETED")
                await w.aclose()
            finally:
                store.stop()
        run(body())


class TestDeviceLedger(unittest.TestCase):
    def test_checkpoint_roundtrip_and_conflict(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "ck.json")
            led = DeviceLedger(path, "n1")
            led.load()
            led.allocate("default/p1", "c0", ["dev-0", "dev-1"])
            led.allocate("default/p2", "c0", ["dev-2"])
            with self.assertRaises(ValueError):
                led.allocate("default/p3", "c0", ["dev-1"])  # taken
            # Restart: a fresh ledger restores the same state.
            led2 = DeviceLedger(path, "n1")
            led2.load()
            self.assertEqual(led2.in_use(), {"dev-0", "dev-1", "dev-2"})
            self.assertEqual(led2.get("default/p1"),
                             {"c0": ["dev-0", "dev-1"]})
            # Reconcile drops departed pods and persists.
            self.assertEqual(led2.reconcile({"default/p1"}), ["default/p2"])
            led3 = DeviceLedger(path, "n1")
            led3.load()
            self.assertEqual(led3.in_use(), {"dev-0", "dev-1"})

    def test_corrupt_checkpoint_starts_empty(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "ck.json")
            with open(path, "w") as f:
                f.write("{truncated")
            led = DeviceLedger(path, "n1")
            led.load()
            self.assertEqual(led.in_use(), set())


class AgentHarness:
    """Store + scheduler + N in-process agents (no kwok)."""

    def __init__(self, agents=2, checkpoint_dir=None, template=None):
        self.n = agents
        self.dir = checkpoint_dir
        self.template = template or {
            "allocatable": {"cpu": "4", "memory": "16Gi", "pods": "32"}}

    async def __aenter__(self):
        self.store = new_cluster_store()
        install_core_validation(self.store)
        self.agents = []
        for i in range(self.n):
            a = NodeAgent(self.store, f"agent-n{i}",
                          checkpoint_dir=self.dir or ".",
                          node_template=self.template)
            await a.start()
            self.agents.append(a)
        self.sched = Scheduler(self.store, seed=7)
        self.factory = InformerFactory(self.store)
        await self.sched.setup_informers(self.factory)
        self.factory.start()
        await self.factory.wait_for_sync()
        self.run_task = asyncio.ensure_future(self.sched.run(batch_size=16))
        return self

    async def __aexit__(self, *exc):
        self.run_task.cancel()
        for a in self.agents:
            await a.stop()
        self.factory.stop()
        self.store.stop()


class TestNodeAgent(unittest.TestCase):
    def test_agents_register_and_run_pods(self):
        async def body():
            with tempfile.TemporaryDirectory() as d:
                async with AgentHarness(agents=2, checkpoint_dir=d) as h:
                    for i in range(6):
                        await h.store.create("pods", make_pod(
                            f"w{i}",
                            requests={"cpu": "100m", "memory": "100Mi"}))

                    async def all_running():
                        lst = await h.store.list("pods")
                        phases = [p.get("status", {}).get("phase")
                                  for p in lst.items]
                        return all(ph == "Running"
                                   for ph in phases) and len(phases) == 6
                    await wait_for(all_running, msg="pods Running via agents")
                    # Every pod landed on an agent node and got an IP.
                    lst = await h.store.list("pods")
                    for p in lst.items:
                        self.assertTrue(
                            p["spec"]["nodeName"].startswith("agent-n"))
                        self.assertTrue(p["status"].get("podIP"))
        run(body())

    def test_dra_allocate_checkpoints_and_survives_restart(self):
        async def body():
            with tempfile.TemporaryDirectory() as d:
                template = {"allocatable": {
                    "cpu": "4", "memory": "16Gi", "pods": "32",
                    "ktpu.io/tpu": "4"}}
                async with AgentHarness(agents=1, checkpoint_dir=d,
                                        template=template) as h:
                    await h.store.create(
                        "deviceclasses",
                        make_device_class("tpu", {"type": "tpu"}))
                    await h.store.create(
                        "resourceclaims", make_resource_claim(
                            "c1", requests=[{
                                "name": "tpus",
                                "deviceClassName": "tpu", "count": 2}]))
                    await h.store.create("pods", make_pod(
                        "dra-pod",
                        requests={"cpu": "100m"},
                        resource_claims=[{
                            "name": "tpus",
                            "resourceClaimName": "c1"}]))

                    agent = h.agents[0]

                    async def allocated():
                        return agent.ledger.get("default/dra-pod") or None
                    alloc = await wait_for(allocated, msg="device Allocate")
                    self.assertEqual(len(alloc["tpus"]), 2)
                    ck = agent.ledger.path
                    self.assertTrue(os.path.exists(ck))

                    # Agent restart: allocations restore from checkpoint
                    # (pod still bound → reconcile keeps it).
                    await agent.stop()
                    a2 = NodeAgent(h.store, agent.node_name,
                                   checkpoint_dir=d,
                                   node_template=template)
                    await a2.start()
                    try:
                        self.assertEqual(
                            a2.ledger.get("default/dra-pod"), alloc)
                        # Deleting the pod releases its devices.
                        await h.store.delete("pods", "default/dra-pod")

                        async def released():
                            return not a2.ledger.in_use() or None
                        await wait_for(released, msg="device release")
                    finally:
                        await a2.stop()
        run(body())



    def test_complete_after_rearms_across_restart(self):
        async def body():
            with tempfile.TemporaryDirectory() as d:
                store = new_cluster_store()
                install_core_validation(store)
                try:
                    a = NodeAgent(store, "ra-n0", checkpoint_dir=d)
                    await a.start()
                    pod = make_pod("job1", requests={"cpu": "100m"})
                    pod["metadata"]["annotations"] = {
                        "kwok.x-k8s.io/complete-after": "0.3"}
                    await store.create("pods", pod)
                    await store.subresource(
                        "pods", "default/job1", "binding",
                        {"target": {"name": "ra-n0"}})

                    async def running():
                        p = await store.get("pods", "default/job1")
                        return (p["status"].get("phase")
                                == "Running") or None
                    await wait_for(running, msg="Running")
                    # Restart BEFORE the completion timer fires.
                    await a.stop()
                    a2 = NodeAgent(store, "ra-n0", checkpoint_dir=d)
                    await a2.start()
                    try:
                        async def succeeded():
                            p = await store.get("pods", "default/job1")
                            return (p["status"].get("phase")
                                    == "Succeeded") or None
                        await wait_for(succeeded,
                                       msg="re-armed completion")
                    finally:
                        await a2.stop()
                finally:
                    store.stop()
        run(body())


class TestAgentOverWire(unittest.TestCase):
    """Agents as wire clients of a real apiserver (the process shape),
    in-process for speed; the subprocess binary is covered below."""

    def test_agent_over_wire_schedules_and_syncs(self):
        async def body():
            backing = new_cluster_store()
            install_core_validation(backing)
            api = APIServer(backing)
            await api.start()
            wire = WireServer.for_apiserver(api, host="unix:")
            await wire.start()
            with tempfile.TemporaryDirectory() as d:
                agent_store = WireStore(wire.target, user_agent="agent")
                sched_store = WireStore(wire.target, user_agent="sched")
                agent = NodeAgent(agent_store, "wire-n0",
                                  checkpoint_dir=d)
                await agent.start()
                sched = Scheduler(sched_store, seed=3)
                factory = InformerFactory(sched_store)
                await sched.setup_informers(factory)
                factory.start()
                await factory.wait_for_sync()
                task = asyncio.ensure_future(sched.run(batch_size=8))
                try:
                    await sched_store.create("pods", make_pod(
                        "wp", requests={"cpu": "100m"}))

                    async def running():
                        p = await sched_store.get("pods", "default/wp")
                        return (p.get("status", {}).get("phase")
                                == "Running") or None
                    await wait_for(running, msg="pod Running over wire")
                finally:
                    task.cancel()
                    await agent.stop()
                    factory.stop()
                    await agent_store.close()
                    await sched_store.close()
                    await wire.stop()
                    await api.stop()
                    backing.stop()
        run(body())


class TestAgentBinary(unittest.TestCase):
    """`python -m kubernetes_tpu.agent` as a REAL subprocess against a
    wire listener — the per-node process shape (SURVEY §2.1 row 14)."""

    def test_subprocess_agent_runs_pod_and_checkpoint_survives(self):
        async def body():
            backing = new_cluster_store()
            install_core_validation(backing)
            api = APIServer(backing)
            await api.start()
            wire = WireServer.for_apiserver(api, host="unix:")
            await wire.start()
            client = WireStore(wire.target)
            with tempfile.TemporaryDirectory() as d:
                import sys
                proc = await asyncio.create_subprocess_exec(
                    sys.executable, "-m", "kubernetes_tpu.agent",
                    "--node", "proc-n0", "--server", wire.target,
                    "--checkpoint-dir", d,
                    "--allocatable", "cpu=4,memory=16Gi,pods=32",
                    stdout=asyncio.subprocess.DEVNULL,
                    stderr=asyncio.subprocess.DEVNULL)
                try:
                    async def node_up():
                        lst = await client.list("nodes")
                        return any(n["metadata"]["name"] == "proc-n0"
                                   for n in lst.items) or None
                    await wait_for(node_up, timeout=15,
                                   msg="subprocess agent registered")
                    # Bind a pod to it directly (no scheduler needed).
                    await client.create("pods", make_pod(
                        "sp", requests={"cpu": "100m"}))
                    await client.subresource(
                        "pods", "default/sp", "binding",
                        {"target": {"name": "proc-n0"}})

                    async def running():
                        p = await client.get("pods", "default/sp")
                        return (p.get("status", {}).get("phase")
                                == "Running") or None
                    await wait_for(running, timeout=15,
                                   msg="subprocess agent ran pod")
                finally:
                    proc.terminate()
                    try:
                        await asyncio.wait_for(proc.wait(), 10)
                    except asyncio.TimeoutError:
                        proc.kill()
                        await proc.wait()
            await client.close()
            await wire.stop()
            await api.stop()
            backing.stop()
        run(body())


if __name__ == "__main__":
    unittest.main()
