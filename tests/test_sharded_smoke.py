"""Tier-1 smoke for the sharded control plane (ROADMAP #5).

Pins the activation contract (the 200k preset rides the sharded path;
5k/50k keep the r12 single store bit-for-bit), the clean S=1
degradation, the incremental host-prep delta build's exactness, and a
small end-to-end run with every shard surface active (sharded store +
per-shard informers + shard metrics + batched agent boot).
"""

from __future__ import annotations

import asyncio

import numpy as np

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.types import PodInfo
from kubernetes_tpu.store import MVCCStore, ShardedNodeStore, \
    control_plane_shards, new_cluster_store


def test_200k_preset_exists_and_activates_sharding(monkeypatch):
    import bench
    assert "200k" in bench.PRESETS
    nodes, warmup, measured = bench.PRESETS["200k"]
    assert nodes == 200_000 and measured >= 5000
    monkeypatch.delenv("KTPU_SHARDS", raising=False)
    monkeypatch.delenv("KTPU_SHARD_THRESHOLD", raising=False)
    assert control_plane_shards(nodes) >= 2, \
        "the 200k preset must ride the sharded path flagless"
    # The 5k/50k guard presets stay single-store bit-for-bit.
    assert control_plane_shards(bench.PRESETS["5k"][0]) == 1
    assert control_plane_shards(bench.PRESETS["50k"][0]) == 1


def test_degrades_cleanly_to_single_store():
    s1 = new_cluster_store(shards=1)
    assert isinstance(s1, MVCCStore) and not isinstance(
        s1, ShardedNodeStore)
    s8 = new_cluster_store(shards=8)
    assert isinstance(s8, ShardedNodeStore) and s8.node_shards == 8
    s8.stop()


def test_incremental_tensor_delta_matches_full_build():
    """The per-shard delta build (tensorize._init_delta) must produce
    arrays bit-identical to a from-scratch build after binds mutate a
    subset of nodes — the exactness contract that keeps sharded
    assignments equal to unsharded ones."""
    from kubernetes_tpu.ops.tensorize import ClusterTensors
    cache = SchedulerCache()
    for i in range(64):
        cache.add_node(make_node(f"n-{i:02d}"))
    snap0 = cache.update_snapshot()
    ct0 = ClusterTensors(snap0)
    assert ct0.shard_rebuilds, "first build rebuilds its shard(s)"
    # Bind a few pods: only their nodes' rows may be rewritten.
    for i, node in enumerate(("n-03", "n-17", "n-42")):
        pod = make_pod(f"p-{i}", requests={"cpu": "500m",
                                           "memory": "1Gi"})
        pod["spec"]["nodeName"] = node
        cache.add_pod(PodInfo(pod))
    snap1 = cache.update_snapshot()
    assert snap1.set_epoch == snap0.set_epoch
    changed = snap1.changed_since(snap0.generation)
    assert changed is not None and len(changed) == 3
    delta = ClusterTensors(snap1, prev=ct0)
    full = ClusterTensors(snap1)
    np.testing.assert_array_equal(delta.used_q, full.used_q)
    np.testing.assert_array_equal(delta.used_nz_q, full.used_nz_q)
    np.testing.assert_array_equal(delta.used_pods, full.used_pods)
    np.testing.assert_array_equal(delta.alloc_q, full.alloc_q)
    assert delta.node_names == full.node_names
    assert delta.node_gens == list(full.node_gens)
    # Static pieces were SHARED, not rebuilt.
    assert delta.alloc_q is ct0.alloc_q
    assert delta.taint_filter_mat is ct0.taint_filter_mat


def test_node_removal_falls_back_to_full_snapshot():
    cache = SchedulerCache()
    for i in range(8):
        cache.add_node(make_node(f"r-{i}"))
    snap0 = cache.update_snapshot()
    cache.remove_node("r-3")
    snap1 = cache.update_snapshot()
    assert len(snap1.nodes) == 7
    assert snap1.set_epoch != snap0.set_epoch
    assert snap1.changed_since(snap0.generation) is None, \
        "positions shifted: consumers must full-rebuild"


def test_sharded_e2e_with_agents_and_metrics():
    """End-to-end at model scale: sharded store through the wire,
    per-shard informers, batched agent fleet boot (NodeAgent.start_many
    via the startAgents opcode), and the shard metrics populated in the
    detail JSON."""
    from kubernetes_tpu.ops import TPUBackend
    from kubernetes_tpu.perf.scheduler_perf import PerfRunner

    template = [
        {"opcode": "startAgents", "count": 12},
        {"opcode": "createNodes", "count": 52},
        {"opcode": "createPods", "count": 40},
        {"opcode": "barrier"},
        {"opcode": "createPods", "count": 120, "collectMetrics": True},
        {"opcode": "barrier"},
    ]
    runner = PerfRunner(backend=TPUBackend(max_batch=64), batch_size=256,
                        through_apiserver="wire", shards=4)
    res = asyncio.run(runner.run(template, {}, timeout=180.0))
    d = res.as_dict()
    assert d["scheduled_total"] == 160
    assert d["shard_count"] == 4
    assert d["shard_tensor_rebuilds_total"] > 0
    assert d["cross_shard_reductions_total"] >= 120
    assert d["agent_start_seconds"] > 0.0
    assert d["shard_solve_seconds"] > 0.0


def test_agent_start_many_batches_phases():
    """start_many registers every agent's Node before any watch
    establishment begins (two wide phases, not per-agent serialized
    handshakes)."""
    from kubernetes_tpu.agent import NodeAgent

    async def go():
        store = new_cluster_store(shards=2)
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            agents = [NodeAgent(store, f"a-{i}", checkpoint_dir=td,
                                lease_period=30.0) for i in range(10)]
            try:
                await NodeAgent.start_many(agents, window=4)
                lst = await store.list("nodes")
                assert len(lst.items) == 10
                # Partitioned across shards, not all on meta.
                assert sum(1 for s in store.shards
                           if s._table("nodes")) >= 2
            finally:
                for a in agents:
                    await a.stop()
                store.stop()
    asyncio.run(go())
