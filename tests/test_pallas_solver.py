"""Randomized differential parity for the fused Pallas wavefront kernel.

The contract under test: `ops/pallas_kernel.wave_solve` — one grid step
fusing plane gather → bit-mask unpack → fit/taint/balanced score →
prefix-distinct wave argmax → pairwise (W,W) conflict re-score →
capacity debit, with the used-state carry resident — produces
assignments BIT-IDENTICAL to the lax.scan reference
(`greedy_assign_rescoring_wave`) it replaces, in interpret mode on CPU:
vs the W=1 serial scan AND the W=64 scan, across tight-capacity
conflict storms, every packing strategy, class-plane indirection with
pinned-column exceptions, multistart permutations with gang
all-or-nothing, and the shard-local `wave_eval` fusion at {1, 4, 8}
shards. Commit/replay counters must match the scan EXACTLY — the
AdaptiveTuner's width policy reads them, so a kernel that assigns
identically but counts differently would still skew W.

The tier-1 activation/kill-switch/fallback-counter pins live in
tests/test_pallas_smoke.py.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from kubernetes_tpu.ops import solver
from test_wavefront_solver import _problem

#: every width exercises a different padding shape (31 is the odd
#: chunk, 64 > P pads a whole trailing wave).
WIDTHS = (2, 8, 31, 64)


def _scan_ref(strategy, w, args):
    a, com, rep = solver.greedy_assign_rescoring_wave(
        strategy=strategy, wave_w=w, **args)
    return np.asarray(a), int(com), int(rep)


class TestPallasWaveParity:
    @pytest.mark.parametrize("strategy",
                             ["LeastAllocated", "MostAllocated",
                              "RequestedToCapacityRatio"])
    def test_conflict_storm_bit_identity(self, strategy):
        """Tight capacity: speculation must conflict and replay through
        the in-kernel fori_loop exactly like the scan's slow path —
        assignments AND the commit/replay split match at every W."""
        for seed in range(2):
            rng = np.random.default_rng(seed)
            args, _ = _problem(rng, n=24, p=31, r=2, tight=True)
            ref = np.asarray(solver.greedy_assign_rescoring(
                strategy=strategy, **args))
            for w in WIDTHS:
                sa, scom, srep = _scan_ref(strategy, w, args)
                np.testing.assert_array_equal(sa, ref)
                a, com, rep = solver.greedy_assign_rescoring_wave_pallas(
                    strategy=strategy, wave_w=w, interpret=True, **args)
                np.testing.assert_array_equal(
                    np.asarray(a), ref, err_msg=f"W={w} {strategy}")
                assert (int(com), int(rep)) == (scom, srep), \
                    f"W={w} {strategy}"

    def test_class_planes_and_exceptions(self):
        """Class-row indirection + pinned-column exceptions ride the
        fused gather/exception gate exactly like the scan."""
        for seed in range(2):
            rng = np.random.default_rng(100 + seed)
            args, _ = _problem(rng, n=40, p=26, r=3, classes=4)
            exc = np.full((26,), -1, np.int32)
            exc[rng.integers(0, 26, size=5)] = \
                rng.integers(0, 40, size=5).astype(np.int32)
            args["exc"] = jnp.asarray(exc)
            ref = np.asarray(solver.greedy_assign_rescoring(
                strategy="LeastAllocated", **args))
            for w in (2, 8):
                a, com, rep = solver.greedy_assign_rescoring_wave_pallas(
                    strategy="LeastAllocated", wave_w=w,
                    interpret=True, **args)
                np.testing.assert_array_equal(np.asarray(a), ref,
                                              err_msg=f"W={w}")
                assert int(com) + int(rep) == 26

    def test_uniform_template_commits_speculatively(self):
        """The template regime (the bench presets' shape): the kernel
        must commit whole waves without replays, like the scan — a
        bit-identical kernel that replays anyway buys nothing."""
        n, p, r = 128, 32, 2
        args = dict(
            req_q=jnp.asarray(np.full((p, r), 500, np.int32)),
            req_nz_q=jnp.asarray(np.full((p, r), 500, np.int32)),
            free_q=jnp.asarray(np.full((n, r), 8000, np.int32)),
            free_pods=jnp.asarray(np.full((n,), 110, np.int32)),
            used_nz_q=jnp.asarray(np.zeros((n, r), np.int32)),
            alloc_q=jnp.asarray(np.full((n, r), 8000, np.int32)),
            mask=jnp.asarray(np.ones((1, n), np.bool_)),
            static_scores=jnp.asarray(np.zeros((1, n), np.float32)),
            fit_col_w=jnp.ones((r,), jnp.float32),
            bal_col_mask=jnp.ones((r,), np.bool_),
            shape_u=jnp.zeros((2,), jnp.float32),
            shape_s=jnp.zeros((2,), jnp.float32),
            w_fit=jnp.float32(1.0), w_bal=jnp.float32(1.0),
            rows=jnp.asarray(np.zeros((p,), np.int32)))
        ref = np.asarray(solver.greedy_assign_rescoring(
            strategy="LeastAllocated", **args))
        a, com, rep = solver.greedy_assign_rescoring_wave_pallas(
            strategy="LeastAllocated", wave_w=8, interpret=True, **args)
        np.testing.assert_array_equal(np.asarray(a), ref)
        assert int(rep) == 0 and int(com) == p


class TestPallasMultistartParity:
    def test_permuted_orders_and_gangs(self):
        """K permuted starts with one unreachable gang quota: the
        poison-aware kernel (always-fast waves + poison OR) must select
        the same winner — and the poisoned rerun path the same full
        multistart — as the scan wrapper."""
        for seed in range(2):
            rng = np.random.default_rng(200 + seed)
            p = 24
            args, _ = _problem(rng, n=48, p=p, r=2, tight=(seed == 0))
            k = 4
            perms = np.tile(np.arange(p, dtype=np.int32), (k, 1))
            for i in range(1, k):
                perms[i] = rng.permutation(p).astype(np.int32)
            gang = np.zeros((p, 16), np.float32)
            gang[:5, 0] = 1.0
            grq = np.zeros((16,), np.float32)
            grq[0] = 5.0
            ref = np.asarray(solver.multistart_greedy_assign(
                strategy="LeastAllocated", perms=jnp.asarray(perms),
                gang_onehot=jnp.asarray(gang),
                gang_required=jnp.asarray(grq), **args))
            for w in (2, 8):
                sa, scom, srep = solver.multistart_greedy_assign_wave(
                    strategy="LeastAllocated", wave_w=w,
                    perms=jnp.asarray(perms), gang_onehot=jnp.asarray(gang),
                    gang_required=jnp.asarray(grq), **args)
                a, com, rep = solver.multistart_greedy_assign_wave_pallas(
                    strategy="LeastAllocated", wave_w=w,
                    perms=jnp.asarray(perms), gang_onehot=jnp.asarray(gang),
                    gang_required=jnp.asarray(grq), interpret=True, **args)
                np.testing.assert_array_equal(np.asarray(a), ref,
                                              err_msg=f"W={w}")
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(sa))
                assert (int(com), int(rep)) == (int(scom), int(srep))
                assert int(com) + int(rep) == p


class TestPallasShardedParity:
    @pytest.mark.parametrize("shards", [1, 4, 8])
    def test_mesh_bit_identity(self, shards):
        """pallas=True fuses each wave's shard-local (W, local_n)
        evaluation (ops/pallas_kernel.wave_eval) under shard_map; the
        ICI reductions are untouched, so assignments match the scan
        reference at every shard count."""
        from kubernetes_tpu.parallel import build_mesh, \
            sharded_greedy_assign
        rng = np.random.default_rng(700 + shards)
        n, p, r = 64, 18, 2
        args, _ = _problem(rng, n=n, p=p, r=r)
        mesh = build_mesh(shards)
        ref = np.asarray(solver.greedy_assign_rescoring(
            strategy="LeastAllocated", **args))
        pos = (args["req_q"], args["req_nz_q"], args["free_q"],
               args["free_pods"], args["used_nz_q"], args["alloc_q"],
               args["mask"], args["static_scores"], args["fit_col_w"],
               args["bal_col_mask"], args["shape_u"], args["shape_s"],
               args["w_fit"], args["w_bal"])
        for w in (2, 8):
            got = np.asarray(sharded_greedy_assign(
                mesh, *pos, "LeastAllocated", wave_w=w, pallas=True))
            np.testing.assert_array_equal(
                got, ref, err_msg=f"shards={shards} W={w}")

    def test_mesh_exceptions_global_coords(self):
        """Pinned columns are GLOBAL node ids: the fused eval receives
        the owner shard's local translation and must gate identically."""
        from kubernetes_tpu.parallel import build_mesh, \
            sharded_greedy_assign
        rng = np.random.default_rng(800)
        n, p, r = 64, 12, 2
        args, _ = _problem(rng, n=n, p=p, r=r)
        exc = np.full((p,), -1, np.int32)
        exc[[1, 5, 9]] = [60, 3, 33]
        ref = np.asarray(solver.greedy_assign_rescoring(
            strategy="LeastAllocated", exc=jnp.asarray(exc), **args))
        pos = (args["req_q"], args["req_nz_q"], args["free_q"],
               args["free_pods"], args["used_nz_q"], args["alloc_q"],
               args["mask"], args["static_scores"], args["fit_col_w"],
               args["bal_col_mask"], args["shape_u"], args["shape_s"],
               args["w_fit"], args["w_bal"])
        got = np.asarray(sharded_greedy_assign(
            build_mesh(4), *pos, "LeastAllocated",
            exc=jnp.asarray(exc), wave_w=4, pallas=True))
        np.testing.assert_array_equal(got, ref)
