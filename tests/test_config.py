"""KubeSchedulerConfiguration loader + TPUScorer feature-gate wiring.

Reference-shaped YAML (kubescheduler.config.k8s.io/v1, the exact field
names of staging/src/k8s.io/kube-scheduler/config/v1) must load unchanged
into running profiles; flipping `TPUScorer` must flip the backend.
"""

import asyncio

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.config import (
    ConfigError,
    build_scheduler,
    load_config,
)
from kubernetes_tpu.store import install_core_validation, new_cluster_store
from kubernetes_tpu.utils.featuregate import ALPHA, FeatureGate


def gates(**kw) -> FeatureGate:
    g = FeatureGate()
    g.add("TPUScorer", ALPHA, False)
    g.add("TPUBatchSolver", ALPHA, False)
    for k, v in kw.items():
        g.set(k, v)
    return g


REFERENCE_YAML = """
apiVersion: kubescheduler.config.k8s.io/v1
kind: KubeSchedulerConfiguration
parallelism: 8
percentageOfNodesToScore: 40
podInitialBackoffSeconds: 2
podMaxBackoffSeconds: 20
leaderElection:
  leaderElect: true
  leaseDuration: 15s
  renewDeadline: 10s
  retryPeriod: 2s
profiles:
- schedulerName: default-scheduler
  plugins:
    score:
      disabled:
      - name: ImageLocality
      enabled:
      - name: NodeResourcesBalancedAllocation
        weight: 5
  pluginConfig:
  - name: NodeResourcesFit
    args:
      scoringStrategy:
        type: MostAllocated
        resources:
        - name: cpu
          weight: 2
        - name: memory
          weight: 1
- schedulerName: gang-scheduler
  plugins:
    multiPoint:
      enabled:
      - name: Coscheduling
    filter:
      disabled:
      - name: PodTopologySpread
extenders:
- urlPrefix: http://127.0.0.1:9999/scheduler
  filterVerb: filter
  prioritizeVerb: prioritize
  weight: 2
  nodeCacheCapable: true
  ignorable: true
  managedResources:
  - name: example.com/foo
    ignoredByScheduler: true
"""


class TestLoader:
    def test_reference_yaml_loads_unchanged(self):
        cfg = load_config(REFERENCE_YAML)
        assert cfg.parallelism == 8
        assert cfg.percentage_of_nodes_to_score == 40
        assert cfg.pod_initial_backoff == 2
        assert cfg.pod_max_backoff == 20
        assert cfg.leader_elect and cfg.leader_lease_duration == 15.0
        assert len(cfg.profiles) == 2
        assert len(cfg.extenders) == 1

        default = cfg.profiles[0]
        assert default.scheduler_name == "default-scheduler"
        assert "ImageLocality" not in default.active["Score"]
        assert default.weights["NodeResourcesBalancedAllocation"] == 5
        fit_args = default.plugin_config["NodeResourcesFit"]
        assert fit_args["scoringStrategy"]["type"] == "MostAllocated"

        gang = cfg.profiles[1]
        assert "Coscheduling" in gang.active["Permit"]
        assert "Coscheduling" in gang.active["PreEnqueue"]
        assert "PodTopologySpread" not in gang.active["Filter"]
        assert "PodTopologySpread" in gang.active["Score"]  # only Filter off

    def test_frameworks_built_with_typed_args(self):
        cfg = load_config(REFERENCE_YAML)
        fwk = cfg.profiles[0].build_framework()
        fit = next(p for p in fwk.score_plugins if p.NAME == "NodeResourcesFit")
        assert fit.strategy_type == "MostAllocated"
        assert fit.score_resources[0] == {"name": "cpu", "weight": 2}
        assert all(p.NAME != "ImageLocality" for p in fwk.score_plugins)
        assert fwk.score_weights["NodeResourcesBalancedAllocation"] == 5

        gang = cfg.profiles[1].build_framework()
        assert any(p.NAME == "Coscheduling" for p in gang.permit_plugins)
        assert all(p.NAME != "PodTopologySpread" for p in gang.filter_plugins)
        assert any(p.NAME == "PodTopologySpread" for p in gang.score_plugins)

    def test_disable_star_clears_point(self):
        cfg = load_config({
            "profiles": [{"plugins": {
                "score": {"disabled": [{"name": "*"}],
                          "enabled": [{"name": "TaintToleration",
                                       "weight": 7}]}}}],
        })
        prof = cfg.profiles[0]
        assert prof.active["Score"] == ["TaintToleration"]
        assert prof.weights["TaintToleration"] == 7
        # Other points keep their defaults.
        assert "NodeResourcesFit" in prof.active["Filter"]

    def test_errors(self):
        with pytest.raises(ConfigError):
            load_config({"apiVersion": "nope/v1"})
        with pytest.raises(ConfigError):
            load_config({"kind": "Banana"})
        with pytest.raises(ConfigError):
            load_config({"profiles": [{"plugins": {
                "filter": {"enabled": [{"name": "NoSuchPlugin"}]}}}]})
        with pytest.raises(ConfigError):
            load_config({"profiles": [
                {"schedulerName": "a"}, {"schedulerName": "a"}]})
        with pytest.raises(ConfigError):
            # PrioritySort implements QueueSort, not Filter.
            load_config({"profiles": [{"plugins": {
                "filter": {"enabled": [{"name": "PrioritySort"}]}}}]})

    def test_disable_star_multipoint_empties_everything(self):
        cfg = load_config({"profiles": [{"plugins": {
            "multiPoint": {"disabled": [{"name": "*"}]}}}]})
        fwk = cfg.profiles[0].build_framework()
        assert not fwk.plugins
        assert not fwk.filter_plugins and not fwk.score_plugins

    def test_per_profile_percentage_scoped(self):
        store = new_cluster_store()
        sched = build_scheduler(store, {
            "percentageOfNodesToScore": 100,
            "profiles": [
                {"schedulerName": "a"},
                {"schedulerName": "b", "percentageOfNodesToScore": 10},
            ]}, feature_gates=gates())
        assert sched._num_feasible_nodes_to_find(
            5000, getattr(sched.profiles["a"],
                          "percentage_of_nodes_to_score", None)) == 5000
        assert sched._num_feasible_nodes_to_find(
            5000, sched.profiles["b"].percentage_of_nodes_to_score) == 500
        store.stop()

    def test_config_gates_do_not_leak_between_builds(self):
        g = gates()
        store = new_cluster_store()
        s1 = build_scheduler(store, {"featureGates": {"TPUScorer": True}},
                             feature_gates=g)
        s2 = build_scheduler(store, None, feature_gates=g)
        assert s1.backend is not None
        assert s2.backend is None, "gate leaked into the shared default set"
        store.stop()

    def test_unknown_feature_gate_tolerated(self):
        store = new_cluster_store()
        sched = build_scheduler(
            store, {"featureGates": {"DynamicResourceAllocation": True}},
            feature_gates=gates())
        assert sched.backend is None
        store.stop()

    def test_load_from_file(self, tmp_path):
        p = tmp_path / "sched.yaml"
        p.write_text(REFERENCE_YAML)
        cfg = load_config(str(p))
        assert cfg.percentage_of_nodes_to_score == 40


class TestTPUScorerGate:
    def test_gate_off_means_host_path(self):
        store = new_cluster_store()
        sched = build_scheduler(store, None, feature_gates=gates())
        assert sched.backend is None
        store.stop()

    def test_gate_on_selects_batched_backend(self):
        from kubernetes_tpu.ops import TPUBackend
        store = new_cluster_store()
        sched = build_scheduler(store, None,
                                feature_gates=gates(TPUScorer=True))
        assert isinstance(sched.backend, TPUBackend)
        assert sched.backend_profiles == {"default-scheduler"}
        store.stop()

    def test_config_feature_gates_key_flips_backend(self):
        store = new_cluster_store()
        sched = build_scheduler(
            store, {"featureGates": {"TPUScorer": True}},
            feature_gates=gates())
        assert sched.backend is not None
        store.stop()

    def test_per_profile_override_removes_gate(self):
        cfg = {
            "profiles": [
                {"schedulerName": "default-scheduler"},
                {"schedulerName": "host-only",
                 "pluginConfig": [{"name": "TPUScorer",
                                   "args": {"enabled": False}}]},
            ],
        }
        store = new_cluster_store()
        sched = build_scheduler(store, cfg,
                                feature_gates=gates(TPUScorer=True))
        assert sched.backend_profiles == {"default-scheduler"}
        store.stop()

    def test_gate_on_schedules_through_backend_e2e(self, monkeypatch):
        # This test probes the gate's BATCH wiring (assign_stream); the
        # serving tier would legitimately fast-drain a 12-pod workload
        # through the pinned single-pod solve instead — pin it off.
        monkeypatch.setenv("KTPU_SERVING", "0")

        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            for i in range(4):
                await store.create("nodes", make_node(f"n{i}"))
            sched = build_scheduler(store, None,
                                    feature_gates=gates(TPUScorer=True),
                                    seed=42)
            calls = []
            orig = sched.backend.assign_stream

            async def spy(pods, snapshot, fwk):
                calls.append(len(pods))
                async for item in orig(pods, snapshot, fwk):
                    yield item

            sched.backend.assign_stream = spy
            factory = InformerFactory(store)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            for i in range(12):
                await store.create("pods", make_pod(
                    f"p{i}", requests={"cpu": "100m"}))
            loop = asyncio.ensure_future(sched.run(batch_size=64))
            for _ in range(100):
                await asyncio.sleep(0.05)
                pods = (await store.list("pods")).items
                if sum(1 for p in pods if p["spec"].get("nodeName")) == 12:
                    break
            pods = (await store.list("pods")).items
            assert sum(1 for p in pods if p["spec"].get("nodeName")) == 12
            assert calls, "batched backend was never used with the gate on"
            await sched.stop()
            loop.cancel()
            factory.stop()
            store.stop()
        asyncio.run(body())

    def test_mixed_profiles_route_by_gate(self):
        """Pods of a host-only profile schedule via the host path while the
        gated profile uses the backend — in one batch."""
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            for i in range(3):
                await store.create("nodes", make_node(f"n{i}"))
            cfg = {
                "profiles": [
                    {"schedulerName": "default-scheduler"},
                    {"schedulerName": "host-only",
                     "pluginConfig": [{"name": "TPUScorer",
                                       "args": {"enabled": False}}]},
                ],
            }
            sched = build_scheduler(store, cfg,
                                    feature_gates=gates(TPUScorer=True),
                                    seed=42)
            backend_pods = []
            orig = sched.backend.assign_stream

            async def spy(pods, snapshot, fwk):
                backend_pods.extend(p.key for p in pods)
                async for item in orig(pods, snapshot, fwk):
                    yield item

            sched.backend.assign_stream = spy
            factory = InformerFactory(store)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            for i in range(6):
                await store.create("pods", make_pod(
                    f"tpu{i}", requests={"cpu": "10m"}))
                await store.create("pods", make_pod(
                    f"host{i}", requests={"cpu": "10m"},
                    scheduler_name="host-only"))
            loop = asyncio.ensure_future(sched.run(batch_size=64))
            for _ in range(120):
                await asyncio.sleep(0.05)
                pods = (await store.list("pods")).items
                if sum(1 for p in pods if p["spec"].get("nodeName")) == 12:
                    break
            pods = (await store.list("pods")).items
            assert sum(1 for p in pods if p["spec"].get("nodeName")) == 12
            assert backend_pods and all("tpu" in k for k in backend_pods)
            await sched.stop()
            loop.cancel()
            factory.stop()
            store.stop()
        asyncio.run(body())
