"""API core tests: quantities, selectors, pod/node accessors."""

import pytest

from kubernetes_tpu.api.labels import (
    Requirement,
    from_label_selector,
    match_label_selector,
    match_node_selector_terms,
    parse_selector,
)
from kubernetes_tpu.api.meta import (
    deep_copy,
    namespaced_name,
    new_controller_ref,
    new_object,
)
from kubernetes_tpu.api.resource import Quantity, format_quantity, parse_quantity
from kubernetes_tpu.api.types import (
    find_untolerated_taint,
    make_node,
    make_pod,
    pod_requests,
    node_allocatable,
    toleration_tolerates_taint,
)


class TestQuantity:
    @pytest.mark.parametrize(
        "s,milli",
        [
            ("1", 1000),
            ("500m", 500),
            ("0.5", 500),
            ("2Gi", 2 * 2**30 * 1000),
            ("1Ki", 1024 * 1000),
            ("100k", 100_000_000),
            ("2e3", 2_000_000),
            ("0", 0),
            ("", 0),
            (None, 0),
            (4, 4000),
            (1.5, 1500),
            ("250u", 0),  # rounds to 0 milli — sub-milli resolution saturates
        ],
    )
    def test_parse(self, s, milli):
        assert parse_quantity(s) == milli

    @pytest.mark.parametrize("bad", ["abc", "1Qi", "--3", "1.2.3"])
    def test_parse_errors(self, bad):
        with pytest.raises(ValueError):
            parse_quantity(bad)

    def test_format_roundtrip(self):
        assert format_quantity(parse_quantity("2")) == "2"
        assert format_quantity(parse_quantity("1500m")) == "1500m"
        assert parse_quantity(format_quantity(parse_quantity("2Gi"))) == parse_quantity("2Gi")

    def test_quantity_arith(self):
        assert (Quantity("1") + Quantity("500m")) == Quantity("1500m")
        assert Quantity("2Gi") > Quantity("1Gi")
        assert Quantity("100m") - Quantity("100m") == Quantity(0)


class TestSelectors:
    def test_match_labels(self):
        sel = {"matchLabels": {"app": "web"}}
        assert match_label_selector(sel, {"app": "web", "tier": "fe"})
        assert not match_label_selector(sel, {"app": "db"})
        assert not match_label_selector(sel, None)

    def test_match_expressions(self):
        sel = {
            "matchExpressions": [
                {"key": "env", "operator": "In", "values": ["prod", "staging"]},
                {"key": "canary", "operator": "DoesNotExist"},
            ]
        }
        assert match_label_selector(sel, {"env": "prod"})
        assert not match_label_selector(sel, {"env": "dev"})
        assert not match_label_selector(sel, {"env": "prod", "canary": "1"})

    def test_notin_absent_key_matches(self):
        r = Requirement("zone", "NotIn", ["a"])
        assert r.matches({})  # reference semantics: absent key passes NotIn
        assert not r.matches({"zone": "a"})
        assert r.matches({"zone": "b"})

    def test_gt_lt(self):
        assert Requirement("n", "Gt", ["5"]).matches({"n": "6"})
        assert not Requirement("n", "Gt", ["5"]).matches({"n": "5"})
        assert Requirement("n", "Lt", ["5"]).matches({"n": "4"})
        assert not Requirement("n", "Lt", ["5"]).matches({"n": "x"})

    def test_parse_selector_grammar(self):
        sel = parse_selector("a=b, c != d, e in (x, y), f, !g")
        labels_ok = {"a": "b", "e": "x", "f": "1", "c": "z"}
        assert sel.matches(labels_ok)
        assert not sel.matches({**labels_ok, "g": "1"})
        assert not sel.matches({**labels_ok, "c": "d"})
        assert parse_selector("").matches({"anything": "yes"})

    def test_empty_label_selector_matches_all(self):
        assert from_label_selector({}).matches({"x": "y"})

    def test_node_selector_terms_or_semantics(self):
        terms = [
            {"matchExpressions": [{"key": "zone", "operator": "In", "values": ["a"]}]},
            {"matchExpressions": [{"key": "zone", "operator": "In", "values": ["b"]}]},
        ]
        assert match_node_selector_terms(terms, {"zone": "b"})
        assert not match_node_selector_terms(terms, {"zone": "c"})
        assert not match_node_selector_terms([], {"zone": "a"})


class TestPodNode:
    def test_pod_requests_init_container_max(self):
        pod = make_pod("p", requests={"cpu": "200m", "memory": "1Gi"})
        pod["spec"]["initContainers"] = [
            {"name": "init", "resources": {"requests": {"cpu": "1"}}}
        ]
        req = pod_requests(pod)
        assert req["cpu"] == 1000  # init container max dominates 200m
        assert req["memory"] == parse_quantity("1Gi")

    def test_pod_requests_nonzero_defaults(self):
        pod = make_pod("p")
        req = pod_requests(pod, non_zero=True)
        assert req["cpu"] == 100
        assert req["memory"] == parse_quantity("200Mi")
        assert pod_requests(pod) == {}

    def test_node_allocatable(self):
        node = make_node("n1", allocatable={"cpu": "4", "memory": "8Gi", "pods": "110"})
        alloc = node_allocatable(node)
        assert alloc["cpu"] == 4000
        assert alloc["pods"] == 110_000

    def test_namespaced_name(self):
        pod = make_pod("p", namespace="ns1")
        assert namespaced_name(pod) == "ns1/p"
        node = make_node("n1")
        assert namespaced_name(node) == "n1"

    def test_controller_ref(self):
        owner = new_object("ReplicaSet", "rs1", "default")
        ref = new_controller_ref(owner)
        assert ref["controller"] and ref["uid"] == owner["metadata"]["uid"]

    def test_deep_copy_isolation(self):
        pod = make_pod("p", labels={"a": "b"})
        cp = deep_copy(pod)
        cp["metadata"]["labels"]["a"] = "mutated"
        assert pod["metadata"]["labels"]["a"] == "b"


class TestTaints:
    def test_exists_tolerates(self):
        taint = {"key": "gpu", "value": "true", "effect": "NoSchedule"}
        assert toleration_tolerates_taint({"operator": "Exists"}, taint)
        assert toleration_tolerates_taint({"key": "gpu", "operator": "Exists"}, taint)
        assert not toleration_tolerates_taint(
            {"key": "gpu", "operator": "Exists", "effect": "NoExecute"}, taint
        )

    def test_equal_default_op(self):
        taint = {"key": "k", "value": "v", "effect": "NoSchedule"}
        assert toleration_tolerates_taint({"key": "k", "value": "v"}, taint)
        assert not toleration_tolerates_taint({"key": "k", "value": "w"}, taint)

    def test_find_untolerated(self):
        taints = [
            {"key": "a", "value": "1", "effect": "PreferNoSchedule"},
            {"key": "b", "value": "2", "effect": "NoSchedule"},
        ]
        t = find_untolerated_taint(taints, [], ("NoSchedule", "NoExecute"))
        assert t["key"] == "b"
        t = find_untolerated_taint(
            taints, [{"key": "b", "value": "2"}], ("NoSchedule", "NoExecute")
        )
        assert t is None
