"""Randomized sharded-vs-unsharded scheduling parity (ROADMAP #5).

The sharded control plane reorganizes WHERE node state lives (per-shard
stores, per-shard informer streams, per-shard host prep) but must not
move a single assignment: the merged initial LIST hands both paths the
same key-sorted node order, the shared RV counter keeps event order
globally comparable, and the host prep's delta path rewrites rows in
place — so the solver sees bit-identical tensors and the r10 stable
index tie rule lands every pod on the same node. These tests run the
same randomized workload through a single MVCCStore and through
ShardedNodeStores at shard counts {1, 2, 4, 8} (1 = the structural
degradation: `new_cluster_store(shards=1)` IS the single store) and
require the assignment maps to be equal, not merely equivalent.
"""

from __future__ import annotations

import asyncio
import random
import time

import pytest

from kubernetes_tpu.api.meta import namespaced_name
from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client import InformerFactory, ResourceEventHandler
from kubernetes_tpu.metrics.registry import SchedulerMetrics
from kubernetes_tpu.ops import TPUBackend
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.store import install_core_validation, new_cluster_store

ZONES = ("a", "b", "c")


def _random_cluster(seed: int, n_nodes: int = 48, n_pods: int = 96):
    """Deterministic random workload: heterogeneous capacities, zone
    labels, a fraction of selector-carrying pods. Total capacity is
    plentiful so every pod schedules (pending pods would make the
    comparison depend on when the watcher looks)."""
    rng = random.Random(seed)
    nodes = []
    for i in range(n_nodes):
        nodes.append(dict(
            name=f"n-{i:03d}",
            allocatable={"cpu": str(rng.choice((4, 8, 16))),
                         "memory": rng.choice(("16Gi", "32Gi", "64Gi")),
                         "pods": "110"},
            labels={"zone": rng.choice(ZONES)}))
    pods = []
    for i in range(n_pods):
        spec = dict(
            name=f"p-{i:03d}",
            requests={"cpu": f"{rng.choice((100, 250, 500))}m",
                      "memory": rng.choice(("128Mi", "256Mi", "512Mi"))})
        if rng.random() < 0.3:
            spec["node_selector"] = {"zone": rng.choice(ZONES)}
        pods.append(spec)
    return nodes, pods


async def _schedule(store, nodes, pods, batch: int = 64) -> dict:
    """Create nodes → sync informers (sorted initial LIST on every
    path) → create pods → drain; returns {pod key: node name}."""
    install_core_validation(store)
    for spec in nodes:
        await store.create("nodes", make_node(**spec))
    metrics = SchedulerMetrics()
    sched = Scheduler(store, seed=42, backend=TPUBackend(max_batch=batch),
                      metrics=metrics)
    factory = InformerFactory(store)
    await sched.setup_informers(factory)
    bound: dict[str, str] = {}

    def track(obj):
        node = obj.get("spec", {}).get("nodeName")
        if node:
            bound[namespaced_name(obj)] = node

    factory.informer("pods").add_event_handler(ResourceEventHandler(
        on_add=track, on_update=lambda old, new: track(new)))
    factory.start()
    await factory.wait_for_sync()
    run_task = asyncio.ensure_future(sched.run(batch_size=batch))
    try:
        for spec in pods:
            await store.create("pods", make_pod(**spec))
        deadline = time.monotonic() + 60
        while len(bound) < len(pods):
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {len(bound)}/{len(pods)} pods bound")
            await asyncio.sleep(0.01)
    finally:
        await sched.stop()
        run_task.cancel()
        factory.stop()
        store.stop()
    return dict(bound)


@pytest.mark.parametrize("seed", [11, 23])
def test_sharded_assignment_parity(seed):
    async def go():
        nodes, pods = _random_cluster(seed)
        reference = await _schedule(new_cluster_store(), nodes, pods)
        assert len(reference) == len(pods)
        for shards in (1, 2, 4, 8):
            got = await _schedule(
                new_cluster_store(shards=shards), nodes, pods)
            assert got == reference, (
                f"shards={shards}: "
                f"{sum(1 for k in got if got[k] != reference.get(k))} "
                f"assignments diverged")
    asyncio.run(go())


def test_sharded_informer_is_active_in_parity_runs():
    """The parity above must not pass because the sharded path silently
    degraded: the node informer on a sharded store runs S shard loops."""
    async def go():
        nodes, pods = _random_cluster(5, n_nodes=24, n_pods=24)
        store = new_cluster_store(shards=4)
        install_core_validation(store)
        for spec in nodes:
            await store.create("nodes", make_node(**spec))
        factory = InformerFactory(store)
        inf = factory.informer("nodes")
        inf.start()
        await inf.wait_for_sync()
        await asyncio.sleep(0.05)
        assert getattr(inf, "_shard_count", 0) == 4
        factory.stop()
        store.stop()
    asyncio.run(go())
