"""Scheduler core tests: cache assume/expire, queue tiers, framework points."""

import asyncio

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.scheduler import (
    Framework,
    PodInfo,
    SchedulerCache,
    SchedulingQueue,
    Status,
)
from kubernetes_tpu.scheduler.framework import CycleState
from kubernetes_tpu.scheduler.plugins.core import PrioritySort, SchedulingGates
from kubernetes_tpu.scheduler.queue import ClusterEvent, QUEUE, QUEUE_SKIP
from kubernetes_tpu.scheduler.types import Snapshot


def run(coro):
    return asyncio.run(coro)


def pi(name, priority=0, node=None, requests=None, gates=None):
    return PodInfo(make_pod(name, priority=priority, node_name=node,
                            requests=requests, scheduling_gates=gates))


class TestCache:
    def test_assume_confirm_lifecycle(self):
        c = SchedulerCache()
        c.add_node(make_node("n1", allocatable={"cpu": "4", "memory": "8Gi", "pods": "10"}))
        p = pi("a", requests={"cpu": "1"})
        c.assume_pod(p, "n1")
        assert c.is_assumed("default/a")
        snap = c.update_snapshot()
        assert snap.get("n1").requested.get("cpu") == 1000

        # informer confirms
        bound = PodInfo(make_pod("a", requests={"cpu": "1"}, node_name="n1"))
        c.add_pod(bound)
        assert not c.is_assumed("default/a")
        assert c.update_snapshot().get("n1").requested.get("cpu") == 1000

    def test_assume_expire(self):
        c = SchedulerCache(assumed_pod_ttl=10)
        c.add_node(make_node("n1"))
        p = pi("a", requests={"cpu": "1"})
        c.assume_pod(p, "n1")
        c.finish_binding("default/a", now=100.0)
        assert c.cleanup_expired(now=105.0) == []
        assert c.cleanup_expired(now=111.0) == ["default/a"]
        assert c.update_snapshot().get("n1").requested.get("cpu") == 0

    def test_forget_restores_resources(self):
        c = SchedulerCache()
        c.add_node(make_node("n1"))
        p = pi("a", requests={"cpu": "2"})
        c.assume_pod(p, "n1")
        c.forget_pod("default/a")
        snap = c.update_snapshot()
        assert snap.get("n1").requested.get("cpu") == 0
        assert snap.get("n1").requested.pods == 0

    def test_incremental_snapshot_reuses_unchanged_nodes(self):
        c = SchedulerCache()
        for i in range(4):
            c.add_node(make_node(f"n{i}"))
        s1 = c.update_snapshot()
        c.assume_pod(pi("a", requests={"cpu": "1"}), "n2")
        s2 = c.update_snapshot()
        # unchanged nodes are the same cloned object; changed node re-cloned
        assert s1.get("n0") is s2.get("n0")
        assert s1.get("n2") is not s2.get("n2")

    def test_double_assume_raises(self):
        c = SchedulerCache()
        c.add_node(make_node("n1"))
        p = pi("a")
        c.assume_pod(p, "n1")
        with pytest.raises(ValueError):
            c.assume_pod(pi("a"), "n1")


class TestQueue:
    def _mk(self, **kw):
        fwk = Framework([PrioritySort(), SchedulingGates()])
        return SchedulingQueue(fwk, **kw)

    def test_priority_order(self):
        async def body():
            q = self._mk()
            await q.add(pi("low", priority=1))
            await q.add(pi("high", priority=100))
            await q.add(pi("mid", priority=50))
            got = [p.name for p in await q.pop_batch(3)]
            assert got == ["high", "mid", "low"]
        run(body())

    def test_gated_pods_stay_out(self):
        async def body():
            q = self._mk()
            await q.add(pi("gated", gates=["wait-for-quota"]))
            await q.add(pi("free"))
            got = await q.pop_batch(5)
            assert [p.name for p in got] == ["free"]
            assert q.stats()["gated"] == 1
            # gate removal → update re-evaluates PreEnqueue
            await q.update(pi("gated"))
            got = await q.pop_batch(5)
            assert [p.name for p in got] == ["gated"]
        run(body())

    def test_unschedulable_event_move(self):
        async def body():
            clock = [0.0]
            q = self._mk(clock=lambda: clock[0], initial_backoff=0.0)
            p = pi("a")
            await q.add(p)
            (popped,) = await q.pop_batch(1)
            popped.unschedulable_plugins = {"NodeResourcesFit"}
            await q.add_unschedulable(popped)
            assert q.stats()["unschedulable"] == 1
            q.register_hint("Node/Add", "NodeResourcesFit", lambda pi, ev: QUEUE)
            moved = await q.move_all(ClusterEvent("Node", "Add"))
            assert moved == 1
            got = await q.pop_batch(1)
            assert got[0].name == "a"
        run(body())

    def test_hint_skip_keeps_parked(self):
        async def body():
            q = self._mk()
            p = pi("a")
            await q.add(p)
            (popped,) = await q.pop_batch(1)
            popped.unschedulable_plugins = {"NodeResourcesFit"}
            await q.add_unschedulable(popped)
            q.register_hint("Node/Add", "NodeResourcesFit",
                            lambda pi, ev: QUEUE_SKIP)
            moved = await q.move_all(ClusterEvent("Node", "Add"))
            assert moved == 0
            assert q.stats()["unschedulable"] == 1
        run(body())

    def test_backoff_flush_by_clock(self):
        async def body():
            clock = [100.0]
            q = self._mk(clock=lambda: clock[0], initial_backoff=2.0)
            p = pi("a")
            await q.add(p)
            (popped,) = await q.pop_batch(1)
            await q.move_to_backoff(popped)
            assert q.stats()["backoff"] == 1
            clock[0] = 103.0  # past 2s backoff
            got = await asyncio.wait_for(q.pop_batch(1), 2)
            assert got[0].name == "a"
        run(body())

    def test_leftover_flush(self):
        async def body():
            clock = [0.0]
            q = self._mk(clock=lambda: clock[0], initial_backoff=0.0,
                         unschedulable_flush_interval=60.0)
            p = pi("a")
            await q.add(p)
            (popped,) = await q.pop_batch(1)
            await q.add_unschedulable(popped)
            clock[0] = 30.0
            assert await q.flush_unschedulable_leftover() == 0
            clock[0] = 61.0
            assert await q.flush_unschedulable_leftover() == 1
        run(body())

    def test_event_during_in_flight_cycle_goes_to_backoff(self):
        """moveRequestCycle semantics: a pod that fails while a cluster event
        fired mid-cycle must land in backoff (prompt retry), not the
        unschedulable pool (60s stall)."""
        async def body():
            clock = [0.0]
            q = self._mk(clock=lambda: clock[0], initial_backoff=1.0)
            await q.add(pi("a"))
            (popped,) = await q.pop_batch(1)  # cycle in flight
            await q.move_all(ClusterEvent("Node", "Add"))  # event mid-cycle
            await q.add_unschedulable(popped)  # cycle fails afterwards
            stats = q.stats()
            assert stats["backoff"] == 1 and stats["unschedulable"] == 0
        run(body())

    def test_batch_pop(self):
        async def body():
            q = self._mk()
            for i in range(10):
                await q.add(pi(f"p{i}", priority=i))
            batch = await q.pop_batch(4)
            assert [p.name for p in batch] == ["p9", "p8", "p7", "p6"]
            assert q.stats()["active"] == 6
        run(body())


class _AlwaysFilter:
    pass


class TestFramework:
    def test_prefilter_skip_suppresses_filter(self):
        from kubernetes_tpu.scheduler.plugins.nodeaffinity import NodeAffinity
        fwk = Framework([NodeAffinity()])
        state = CycleState()
        pod = pi("plain")  # no affinity → PreFilter returns Skip
        snap = Snapshot([])
        assert fwk.run_pre_filter(state, pod, snap).is_success()
        assert "NodeAffinity" in state.skip_filter_plugins

    def test_reserve_failure_unwinds(self):
        from kubernetes_tpu.scheduler import Plugin

        events = []

        class R1(Plugin):
            NAME = "R1"
            EXTENSION_POINTS = ("Reserve",)

            def reserve(self, state, pod, node):
                events.append("r1-reserve")
                return Status.success()

            def unreserve(self, state, pod, node):
                events.append("r1-unreserve")

        class R2(Plugin):
            NAME = "R2"
            EXTENSION_POINTS = ("Reserve",)

            def reserve(self, state, pod, node):
                events.append("r2-reserve")
                return Status.unschedulable("nope")

        fwk = Framework([R1(), R2()])
        st = fwk.run_reserve(CycleState(), pi("a"), "n1")
        assert not st.is_success()
        assert events == ["r1-reserve", "r2-reserve", "r1-unreserve"]

    def test_permit_wait_aggregation(self):
        from kubernetes_tpu.scheduler import Plugin

        class W(Plugin):
            NAME = "W"
            EXTENSION_POINTS = ("Permit",)

            def permit(self, state, pod, node):
                return Status.wait(), 5.0

        fwk = Framework([W()])
        st, timeout = fwk.run_permit(CycleState(), pi("a"), "n1")
        assert st.is_wait() and timeout == 5.0
