"""Randomized differential suite for the batch-optimal (Sinkhorn) solve.

The contract under test: the optimal mode is a SCORING change, never a
feasibility change — the transport plan's log-mass replaces the greedy
static scores and the same capacity-debiting scan rounds it, so every
assignment it emits is valid against the filter planes by construction.
On top of that: occupied-node fragmentation under optimal must not
exceed greedy on adversarial bin-packing fixtures (the headline r20
metric), `KTPU_SOLVE_MODE=greedy` must be bit-identical to the flagless
default at every wave width and shard count (the kill switch restores
the r18 call graph, it doesn't approximate it), the sharded shard_map
Sinkhorn must match the single-device plan at {1, 4, 8} devices, and
gang chunks routed through optimal keep all-or-nothing placement. The
tier-1 policy/NaN/budget pins live in tests/test_optimal_smoke.py.
"""

import asyncio

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import solver
from kubernetes_tpu.utils import flags

WIDTHS = (1, 4, 8)


def _class_problem(rng, n, c, p, r, tight=False):
    """Random class-plane problem: per-class request rows, shared mask
    and score planes — the shape the optimal mode requires."""
    if tight:
        alloc_q = rng.integers(2, 6, size=(n, r)).astype(np.int32) * 1000
        class_req = rng.integers(500, 2500, size=(c, r)).astype(np.int32)
        free_pods = rng.integers(1, 3, size=(n,)).astype(np.int32)
    else:
        alloc_q = rng.integers(20, 60, size=(n, r)).astype(np.int32) * 1000
        class_req = rng.integers(100, 3000, size=(c, r)).astype(np.int32)
        free_pods = rng.integers(2, 8, size=(n,)).astype(np.int32)
    used_q = (alloc_q * rng.uniform(0, 0.4, size=(n, r))).astype(np.int32)
    rows = rng.integers(0, c, size=(p,)).astype(np.int32)
    req_q = class_req[rows]
    mask = rng.random((c, n)) > 0.2
    mask[:, 0] = True
    scores = rng.uniform(0, 4, size=(c, n)).astype(np.float32)
    return dict(alloc_q=alloc_q, used_q=used_q, free_pods=free_pods,
                rows=rows, req_q=req_q, mask=mask, scores=scores)


def _optimal_scores(pr, iters=32, temp=0.05):
    """The optimal path's scoring stage, solver-level: transport plan
    over the class planes, log-mass as the scan's static scores."""
    c = pr["mask"].shape[0]
    row_counts = np.bincount(pr["rows"], minlength=c).astype(np.float32)
    log_plan, plan = solver.sinkhorn_plan(
        jnp.asarray(pr["mask"]), jnp.asarray(pr["scores"]),
        jnp.asarray(row_counts), jnp.asarray(pr["free_pods"]),
        jnp.int32(iters), jnp.float32(temp))
    return np.asarray(log_plan), np.asarray(plan)


def _scan_args(pr, static_scores, zero_weights):
    r = pr["alloc_q"].shape[1]
    w = 0.0 if zero_weights else 1.0
    return dict(
        req_q=jnp.asarray(pr["req_q"]), req_nz_q=jnp.asarray(pr["req_q"]),
        free_q=jnp.asarray(pr["alloc_q"] - pr["used_q"]),
        free_pods=jnp.asarray(pr["free_pods"]),
        used_nz_q=jnp.asarray(pr["used_q"]),
        alloc_q=jnp.asarray(pr["alloc_q"]),
        mask=jnp.asarray(pr["mask"]),
        static_scores=jnp.asarray(static_scores.astype(np.float32)),
        fit_col_w=jnp.ones((r,), jnp.float32),
        bal_col_mask=jnp.ones((r,), np.bool_),
        shape_u=jnp.asarray([0.0, 100.0], jnp.float32),
        shape_s=jnp.asarray([0.0, 10.0], jnp.float32),
        w_fit=jnp.float32(w), w_bal=jnp.float32(w),
        rows=jnp.asarray(pr["rows"]))


def _check_feasible(pr, assign):
    """Replay the assignment sequentially against the filter planes:
    mask row, quantity capacity, pod-slot capacity — every placement
    must have been valid AT ITS TURN (the scan debits in pod order)."""
    free = (pr["alloc_q"] - pr["used_q"]).astype(np.int64)
    slots = pr["free_pods"].copy()
    for k, node in enumerate(np.asarray(assign)):
        if node < 0:
            continue
        cls = pr["rows"][k]
        assert pr["mask"][cls, node], (k, node)
        assert (pr["req_q"][k] <= free[node]).all(), (k, node)
        assert slots[node] > 0, (k, node)
        free[node] -= pr["req_q"][k]
        slots[node] -= 1


class TestOptimalFeasibility:
    @pytest.mark.parametrize("tight", [False, True])
    def test_rounding_respects_filter_planes(self, tight):
        """Random problems, loose and contested: every optimal-mode
        assignment replays cleanly against mask + capacity + slots."""
        for seed in range(4):
            rng = np.random.default_rng(seed)
            pr = _class_problem(rng, n=24, c=5, p=31, r=2, tight=tight)
            log_plan, _ = _optimal_scores(pr)
            a = solver.greedy_assign_rescoring(
                strategy="LeastAllocated",
                **_scan_args(pr, log_plan, zero_weights=True))
            _check_feasible(pr, a)

    def test_places_no_fewer_than_plan_mass_suggests(self):
        """Ample capacity: the rounding places every pod the greedy
        baseline places (the plan is a re-ranking, not a filter)."""
        rng = np.random.default_rng(7)
        pr = _class_problem(rng, n=32, c=4, p=24, r=2, tight=False)
        log_plan, _ = _optimal_scores(pr)
        a_opt = np.asarray(solver.greedy_assign_rescoring(
            strategy="LeastAllocated",
            **_scan_args(pr, log_plan, zero_weights=True)))
        a_greedy = np.asarray(solver.greedy_assign_rescoring(
            strategy="LeastAllocated",
            **_scan_args(pr, pr["scores"], zero_weights=False)))
        assert (a_opt >= 0).sum() >= (a_greedy >= 0).sum()


class TestFragmentationHeadline:
    def _assign(self, n_nodes, pods, mode, alloc=None):
        import sys
        sys.path.insert(0, "tests")
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.api.types import make_node
        from kubernetes_tpu.ops.backend import TPUBackend
        from kubernetes_tpu.scheduler.cache import SchedulerCache
        cache = SchedulerCache()
        alloc = alloc or {"cpu": "8", "memory": "32Gi", "pods": "110"}
        for i in range(n_nodes):
            cache.add_node(make_node(f"fr{i}", allocatable=alloc))
        snap = cache.update_snapshot()
        b = TPUBackend(max_batch=256, mesh=None)
        with flags.scoped_set("KTPU_SOLVE_MODE", mode):
            got, _ = b.assign(pods, snap, default_fwk())
        return got

    @staticmethod
    def _occupied_frag(got, pods_by_name, n_nodes, cpu_alloc_m):
        used = {}
        for name, node in got.items():
            if node is None:
                continue
            used[node] = used.get(node, 0) \
                + pods_by_name[name.rsplit("/", 1)[-1]]
        if not used:
            return 0.0
        return 100.0 * sum(
            (cpu_alloc_m - u) / cpu_alloc_m for u in used.values()) \
            / len(used)

    def _pods(self, sizes):
        from kubernetes_tpu.api.types import make_pod
        from kubernetes_tpu.scheduler.types import PodInfo
        return [PodInfo(make_pod(
            f"bp-{i}", requests={"cpu": f"{m}m", "memory": "256Mi"},
            uid=f"bp-uid-{i}")) for i, m in enumerate(sizes)]

    def test_uniform_template_packs_strictly_tighter(self):
        """The adversarial spread fixture: uniform small pods on a wide
        cluster. LeastAllocated greedy spreads one pod per node (max
        occupied fragmentation); the transport plan's first-fit rounding
        packs — strictly lower occupied fragmentation."""
        sizes = [500] * 80
        pods = self._pods(sizes)
        by_name = {f"bp-{i}": m for i, m in enumerate(sizes)}
        f = {}
        for mode in ("greedy", "optimal"):
            got = self._assign(40, pods, mode)
            assert all(v is not None for v in got.values())
            f[mode] = self._occupied_frag(got, by_name, 40, 8000)
        assert f["optimal"] < f["greedy"]
        # the pack side must be near the capacity bound (5 nodes × 16)
        assert f["optimal"] < 20.0

    def test_mixed_classes_no_worse(self):
        """Two interleaved size classes (the bin-packing shape greedy
        fragments): optimal occupied fragmentation ≤ greedy."""
        sizes = [500 if i % 2 else 1500 for i in range(72)]
        pods = self._pods(sizes)
        by_name = {f"bp-{i}": m for i, m in enumerate(sizes)}
        f = {}
        for mode in ("greedy", "optimal"):
            got = self._assign(30, pods, mode)
            assert all(v is not None for v in got.values())
            f[mode] = self._occupied_frag(got, by_name, 30, 8000)
        assert f["optimal"] <= f["greedy"] + 1e-9


class TestKillSwitchBitIdentity:
    def _workload(self, seed, n_pods=48):
        from kubernetes_tpu.api.types import make_node, make_pod
        from kubernetes_tpu.scheduler.cache import SchedulerCache
        from kubernetes_tpu.scheduler.types import PodInfo
        rng = np.random.default_rng(seed)
        cache = SchedulerCache()
        for i in range(36):
            cache.add_node(make_node(
                f"kn{i}", allocatable={
                    "cpu": str(int(rng.choice((4, 8, 16)))),
                    "memory": "32Gi", "pods": "110"}))
        snap = cache.update_snapshot()
        pods = [PodInfo(make_pod(
            f"kp-{i}",
            requests={"cpu": f"{int(rng.choice((100, 250, 500)))}m",
                      "memory": "256Mi"},
            uid=f"kp-uid-{i}")) for i in range(n_pods)]
        return snap, pods

    def test_greedy_flag_matches_flagless_at_every_width(self):
        """KTPU_SOLVE_MODE=greedy vs the flagless default (auto routes
        these sub-threshold chunks to greedy): identical assignment maps
        at W ∈ {1, 4, 8} — the kill switch re-pins the exact r18 call
        graph, wave speculation and all."""
        import sys
        sys.path.insert(0, "tests")
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.ops.backend import TPUBackend
        snap, pods = self._workload(3)
        fwk = default_fwk()
        for w in WIDTHS:
            with flags.scoped_set("KTPU_WAVE_WIDTH", str(w)):
                base, _ = TPUBackend(max_batch=64, mesh=None).assign(
                    pods, snap, fwk)
                with flags.scoped_set("KTPU_SOLVE_MODE", "greedy"):
                    got, _ = TPUBackend(max_batch=64, mesh=None).assign(
                        pods, snap, fwk)
            assert got == base, f"W={w}"

    @pytest.mark.parametrize("n_devices", [4, 8])
    def test_greedy_flag_matches_on_mesh(self, n_devices):
        """Same identity on the sharded backend: the solve-mode static
        rides the program key identically at every device count."""
        if len(jax.devices()) < n_devices:
            pytest.skip("not enough devices")
        import sys
        sys.path.insert(0, "tests")
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.ops.backend import TPUBackend
        from kubernetes_tpu.parallel import build_mesh
        snap, pods = self._workload(11, n_pods=32)
        fwk = default_fwk()
        mesh = build_mesh(n_devices)
        base, _ = TPUBackend(max_batch=32, mesh=mesh).assign(
            pods, snap, fwk)
        with flags.scoped_set("KTPU_SOLVE_MODE", "greedy"):
            got, _ = TPUBackend(max_batch=32, mesh=mesh).assign(
                pods, snap, fwk)
        assert got == base

    def test_optimal_ignores_wave_width(self):
        """Optimal mode pins W=0 at dispatch: KTPU_WAVE_WIDTH must not
        change a single optimal-mode assignment."""
        import sys
        sys.path.insert(0, "tests")
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.ops.backend import TPUBackend
        snap, pods = self._workload(5, n_pods=72)
        fwk = default_fwk()
        outs = []
        for w in (1, 8):
            with flags.scoped_set("KTPU_SOLVE_MODE", "optimal"), \
                    flags.scoped_set("KTPU_WAVE_WIDTH", str(w)):
                got, _ = TPUBackend(max_batch=128, mesh=None).assign(
                    pods, snap, fwk)
            outs.append(got)
        assert outs[0] == outs[1]


class TestShardedSinkhornParity:
    @pytest.mark.parametrize("n_devices", [1, 4, 8])
    def test_matches_single_device_plan(self, n_devices):
        """shard_map Sinkhorn (column axis sharded, psum'd row
        marginals) vs the single-device plan: same plan, same sanitized
        log-plan, at every shard count."""
        if len(jax.devices()) < n_devices:
            pytest.skip("not enough devices")
        from kubernetes_tpu.parallel import build_mesh, \
            sharded_sinkhorn_plan
        rng = np.random.default_rng(n_devices)
        c, n = 6, 64
        feasible = rng.random((c, n)) > 0.25
        feasible[:, 0] = True
        cost = rng.uniform(0, 4, size=(c, n)).astype(np.float32)
        counts = rng.integers(1, 8, size=(c,)).astype(np.float32)
        cap = rng.integers(0, 6, size=(n,)).astype(np.float32)
        args = (jnp.asarray(feasible), jnp.asarray(cost),
                jnp.asarray(counts), jnp.asarray(cap),
                jnp.int32(32), jnp.float32(0.05))
        ref_log, ref_plan = solver.sinkhorn_plan(*args)
        mesh = build_mesh(n_devices)
        got_log, got_plan = sharded_sinkhorn_plan(mesh, *args)
        np.testing.assert_allclose(np.asarray(got_plan),
                                   np.asarray(ref_plan),
                                   rtol=1e-4, atol=1e-5)
        # sanitization must agree exactly where it clamps
        np.testing.assert_array_equal(
            np.asarray(got_log) == -1e30, np.asarray(ref_log) == -1e30)


class TestGangAllOrNothing:
    def _run(self, coro):
        return asyncio.run(coro)

    def test_feasible_gang_binds_together_infeasible_never_partially(self):
        """Under forced optimal: a gang that fits binds every member; a
        gang that can NEVER assemble binds none (the transport plan
        feeds the same gang-atomic rounding, so all-or-nothing
        survives the mode switch)."""
        async def body():
            from kubernetes_tpu.api.types import make_node
            from kubernetes_tpu.ops import TPUBackend
            from kubernetes_tpu.scheduler.plugins.coscheduling import (
                make_pod_group,
            )
            from kubernetes_tpu.store import (
                install_core_validation,
                new_cluster_store,
            )
            from test_coscheduling import bound_names, gang_pod, make_sched
            store = new_cluster_store()
            install_core_validation(store)
            try:
                # 2 nodes × 8 cpu: a 3×3cpu gang fits (2+1); a 3×7cpu
                # gang can never assemble (one member per node, max 2).
                for i in range(2):
                    await store.create("nodes", make_node(
                        f"gn{i}", allocatable={"cpu": "8",
                                               "memory": "32Gi",
                                               "pods": "110"}))
                await store.create("podgroups", make_pod_group(
                    "fits", min_member=3, schedule_timeout_seconds=5.0))
                await store.create("podgroups", make_pod_group(
                    "never", min_member=3, schedule_timeout_seconds=0.6))
                sched, factory = await make_sched(
                    store, backend=TPUBackend(max_batch=8))
                task = asyncio.ensure_future(sched.run())
                for i in range(3):
                    await store.create("pods", gang_pod(
                        f"ok-{i}", "fits", cpu="3"))
                for _ in range(200):
                    bound = await bound_names(store)
                    if {"ok-0", "ok-1", "ok-2"} <= bound:
                        break
                    await asyncio.sleep(0.05)
                assert {"ok-0", "ok-1", "ok-2"} <= await bound_names(store)
                # Now the impossible gang: with the cluster down to
                # <2cpu per node it can never assemble — no member may
                # EVER bind (a partial bind would strand resources).
                for i in range(3):
                    await store.create("pods", gang_pod(
                        f"no-{i}", "never", cpu="7"))
                await asyncio.sleep(1.2)
                bound = await bound_names(store)
                assert {"ok-0", "ok-1", "ok-2"} <= bound
                assert not bound & {"no-0", "no-1", "no-2"}
                await sched.stop()
                task.cancel()
                factory.stop()
            finally:
                store.stop()
        with flags.scoped_set("KTPU_SOLVE_MODE", "optimal"):
            self._run(body())
