"""Plugin semantics tests — the CPU oracle's correctness fixture suite.

Mirrors the reference's plugin unit style: build NodeInfo/PodInfo fixtures
directly, no API server (pkg/scheduler/framework/plugins/*/
*_test.go table-driven tests)."""

from kubernetes_tpu.api.resource import parse_quantity
from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.scheduler.framework import CycleState, Framework
from kubernetes_tpu.scheduler.plugins.interpodaffinity import InterPodAffinity
from kubernetes_tpu.scheduler.plugins.nodeaffinity import (
    NodeAffinity,
    NodeName,
    NodePorts,
    NodeUnschedulable,
    TaintToleration,
)
from kubernetes_tpu.scheduler.plugins.noderesources import (
    BalancedAllocation,
    NodeResourcesFit,
    insufficient_resources,
)
from kubernetes_tpu.scheduler.plugins.podtopologyspread import PodTopologySpread
from kubernetes_tpu.scheduler.types import NodeInfo, PodInfo, Snapshot


def ni(name, allocatable=None, labels=None, taints=None, unschedulable=False,
       pods=()):
    node = NodeInfo(make_node(name, allocatable=allocatable, labels=labels,
                              taints=taints, unschedulable=unschedulable))
    for p in pods:
        node.add_pod(p)
    return node


def pp(name, **kw):
    return PodInfo(make_pod(name, **kw))


class TestNodeResourcesFit:
    def test_filter_insufficient_cpu(self):
        node = ni("n1", allocatable={"cpu": "2", "memory": "4Gi", "pods": "10"})
        node.add_pod(pp("existing", requests={"cpu": "1500m"}))
        plug = NodeResourcesFit()
        st = plug.filter(CycleState(), pp("new", requests={"cpu": "1"}), node)
        assert not st.is_success()
        assert "Insufficient cpu" in st.reasons

    def test_filter_max_pods(self):
        node = ni("n1", allocatable={"cpu": "64", "memory": "256Gi", "pods": "2"})
        node.add_pod(pp("a"))
        node.add_pod(pp("b"))
        st = NodeResourcesFit().filter(CycleState(), pp("c"), node)
        assert "Too many pods" in st.reasons

    def test_filter_extended_resource(self):
        node = ni("n1", allocatable={"cpu": "8", "memory": "16Gi",
                                     "google.com/tpu": "4", "pods": "110"})
        plug = NodeResourcesFit()
        ok = plug.filter(CycleState(), pp("a", requests={"google.com/tpu": "4"}), node)
        assert ok.is_success()
        node.add_pod(pp("holder", requests={"google.com/tpu": "2"}))
        bad = plug.filter(CycleState(), pp("b", requests={"google.com/tpu": "3"}), node)
        assert "Insufficient google.com/tpu" in bad.reasons

    def test_least_allocated_score(self):
        plug = NodeResourcesFit()
        empty = ni("empty", allocatable={"cpu": "10", "memory": "10Gi", "pods": "110"})
        half = ni("half", allocatable={"cpu": "10", "memory": "10Gi", "pods": "110"})
        half.add_pod(pp("filler", requests={"cpu": "5", "memory": "5Gi"}))
        pod = pp("new", requests={"cpu": "1", "memory": "1Gi"})
        s_empty = plug.score(CycleState(), pod, empty)
        s_half = plug.score(CycleState(), pod, half)
        assert s_empty > s_half  # LeastAllocated prefers the empty node
        assert abs(s_empty - 90.0) < 1e-6  # (10-1)/10 * 100

    def test_most_allocated_prefers_packed(self):
        plug = NodeResourcesFit({"scoringStrategy": {"type": "MostAllocated"}})
        empty = ni("empty", allocatable={"cpu": "10", "memory": "10Gi", "pods": "110"})
        half = ni("half", allocatable={"cpu": "10", "memory": "10Gi", "pods": "110"})
        half.add_pod(pp("filler", requests={"cpu": "5", "memory": "5Gi"}))
        pod = pp("new", requests={"cpu": "1", "memory": "1Gi"})
        assert plug.score(CycleState(), pod, half) > plug.score(CycleState(), pod, empty)

    def test_requested_to_capacity_ratio_shape(self):
        plug = NodeResourcesFit({"scoringStrategy": {
            "type": "RequestedToCapacityRatio",
            "requestedToCapacityRatio": {
                "shape": [{"utilization": 0, "score": 10},
                          {"utilization": 100, "score": 0}]},
        }})
        empty = ni("e", allocatable={"cpu": "10", "memory": "10Gi", "pods": "110"})
        pod = pp("p", requests={"cpu": "5", "memory": "5Gi"})
        # 50% utilization on both → raw 5 → scaled 50
        assert abs(plug.score(CycleState(), pod, empty) - 50.0) < 1e-6

    def test_insufficient_reasons_list(self):
        node = ni("n1", allocatable={"cpu": "1", "memory": "1Gi", "pods": "110"})
        reasons = insufficient_resources(
            pp("big", requests={"cpu": "2", "memory": "2Gi"}), node)
        assert set(reasons) == {"Insufficient cpu", "Insufficient memory"}


class TestBalancedAllocation:
    def test_balanced_beats_skewed(self):
        plug = BalancedAllocation()
        balanced = ni("b", allocatable={"cpu": "10", "memory": "10Gi", "pods": "110"})
        balanced.add_pod(pp("x", requests={"cpu": "5", "memory": "5Gi"}))
        skewed = ni("s", allocatable={"cpu": "10", "memory": "10Gi", "pods": "110"})
        skewed.add_pod(pp("y", requests={"cpu": "9", "memory": "1Gi"}))
        pod = pp("new", requests={"cpu": "500m", "memory": "512Mi"})
        assert plug.score(CycleState(), pod, balanced) > plug.score(CycleState(), pod, skewed)


class TestNodePredicates:
    def test_node_name(self):
        assert NodeName().filter(CycleState(), pp("a", node_name=None), ni("n1")).is_success()
        pod = PodInfo(make_pod("a", node_name="n2"))
        assert not NodeName().filter(CycleState(), pod, ni("n1")).is_success()

    def test_node_unschedulable(self):
        assert not NodeUnschedulable().filter(
            CycleState(), pp("a"), ni("n1", unschedulable=True)).is_success()
        tolerant = pp("b", tolerations=[
            {"key": "node.kubernetes.io/unschedulable", "operator": "Exists"}])
        assert NodeUnschedulable().filter(
            CycleState(), tolerant, ni("n1", unschedulable=True)).is_success()

    def test_node_selector(self):
        node = ni("n1", labels={"disk": "ssd"})
        ok = pp("a", node_selector={"disk": "ssd"})
        bad = pp("b", node_selector={"disk": "hdd"})
        assert NodeAffinity().filter(CycleState(), ok, node).is_success()
        assert not NodeAffinity().filter(CycleState(), bad, node).is_success()

    def test_required_node_affinity(self):
        node = ni("n1", labels={"zone": "us-a"})
        affinity = {"nodeAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": {
            "nodeSelectorTerms": [
                {"matchExpressions": [
                    {"key": "zone", "operator": "In", "values": ["us-a", "us-b"]}]}]}}}
        assert NodeAffinity().filter(
            CycleState(), pp("a", affinity=affinity), node).is_success()
        node2 = ni("n2", labels={"zone": "eu-a"})
        assert not NodeAffinity().filter(
            CycleState(), pp("b", affinity=affinity), node2).is_success()

    def test_preferred_node_affinity_score(self):
        affinity = {"nodeAffinity": {"preferredDuringSchedulingIgnoredDuringExecution": [
            {"weight": 80, "preference": {"matchExpressions": [
                {"key": "zone", "operator": "In", "values": ["us-a"]}]}},
            {"weight": 20, "preference": {"matchExpressions": [
                {"key": "disk", "operator": "In", "values": ["ssd"]}]}},
        ]}}
        plug = NodeAffinity()
        pod = pp("a", affinity=affinity)
        both = ni("n1", labels={"zone": "us-a", "disk": "ssd"})
        one = ni("n2", labels={"zone": "us-a"})
        neither = ni("n3", labels={"zone": "eu"})
        assert plug.score(CycleState(), pod, both) == 100.0
        assert plug.score(CycleState(), pod, one) == 80.0
        assert plug.score(CycleState(), pod, neither) == 0.0

    def test_taint_filter_and_score(self):
        taints = [{"key": "dedicated", "value": "gpu", "effect": "NoSchedule"}]
        node = ni("n1", taints=taints)
        assert not TaintToleration().filter(CycleState(), pp("a"), node).is_success()
        tolerant = pp("b", tolerations=[{"key": "dedicated", "value": "gpu"}])
        assert TaintToleration().filter(CycleState(), tolerant, node).is_success()

    def test_taint_prefer_noschedule_normalize(self):
        plug = TaintToleration()
        soft = ni("soft", taints=[
            {"key": "a", "value": "1", "effect": "PreferNoSchedule"}])
        clean = ni("clean")
        pod = pp("p")
        scores = {"soft": plug.score(CycleState(), pod, soft),
                  "clean": plug.score(CycleState(), pod, clean)}
        plug.normalize_scores(CycleState(), pod, scores)
        assert scores["clean"] == 100.0 and scores["soft"] == 0.0

    def test_node_ports_conflict(self):
        node = ni("n1")
        node.add_pod(pp("existing", host_ports=[8080]))
        st = NodePorts().filter(CycleState(), pp("new", host_ports=[8080]), node)
        assert not st.is_success()
        assert NodePorts().filter(
            CycleState(), pp("other", host_ports=[9090]), node).is_success()


class TestInterPodAffinity:
    def _snap(self):
        web = pp("web-1", labels={"app": "web"})
        n1 = ni("n1", labels={"zone": "a", "kubernetes.io/hostname": "n1"}, pods=[web])
        n2 = ni("n2", labels={"zone": "b", "kubernetes.io/hostname": "n2"})
        return Snapshot([n1, n2])

    def _required_anti(self, topology_key="kubernetes.io/hostname"):
        return {"podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [
            {"labelSelector": {"matchLabels": {"app": "web"}},
             "topologyKey": topology_key}]}}

    def test_anti_affinity_blocks_same_host(self):
        snap = self._snap()
        plug = InterPodAffinity()
        pod = pp("web-2", labels={"app": "web"}, affinity=self._required_anti())
        state = CycleState()
        assert plug.pre_filter(state, pod, snap).is_success()
        assert not plug.filter(state, pod, snap.get("n1")).is_success()
        assert plug.filter(state, pod, snap.get("n2")).is_success()

    def test_anti_affinity_zone_wide(self):
        snap = self._snap()
        plug = InterPodAffinity()
        pod = pp("web-2", labels={"app": "web"},
                 affinity=self._required_anti("zone"))
        state = CycleState()
        plug.pre_filter(state, pod, snap)
        assert not plug.filter(state, pod, snap.get("n1")).is_success()
        assert plug.filter(state, pod, snap.get("n2")).is_success()

    def test_affinity_requires_colocation(self):
        snap = self._snap()
        plug = InterPodAffinity()
        affinity = {"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [
            {"labelSelector": {"matchLabels": {"app": "web"}},
             "topologyKey": "zone"}]}}
        pod = pp("sidecar", labels={"role": "cache"}, affinity=affinity)
        state = CycleState()
        plug.pre_filter(state, pod, snap)
        assert plug.filter(state, pod, snap.get("n1")).is_success()
        assert not plug.filter(state, pod, snap.get("n2")).is_success()

    def test_first_pod_in_group_rule(self):
        """A pod whose affinity matches itself can schedule when no pod in the
        cluster matches (otherwise deployments could never bootstrap)."""
        empty = Snapshot([ni("n1", labels={"zone": "a"})])
        plug = InterPodAffinity()
        affinity = {"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [
            {"labelSelector": {"matchLabels": {"app": "db"}}, "topologyKey": "zone"}]}}
        pod = pp("db-0", labels={"app": "db"}, affinity=affinity)
        state = CycleState()
        plug.pre_filter(state, pod, empty)
        assert plug.filter(state, pod, empty.get("n1")).is_success()

    def test_existing_anti_affinity_symmetry(self):
        """Existing pod's required anti-affinity keeps matching new pods out."""
        guard = pp("guard", labels={"app": "solo"},
                   affinity={"podAntiAffinity": {
                       "requiredDuringSchedulingIgnoredDuringExecution": [
                           {"labelSelector": {"matchLabels": {"tier": "batch"}},
                            "topologyKey": "kubernetes.io/hostname"}]}})
        n1 = ni("n1", labels={"kubernetes.io/hostname": "n1"}, pods=[guard])
        n2 = ni("n2", labels={"kubernetes.io/hostname": "n2"})
        snap = Snapshot([n1, n2])
        plug = InterPodAffinity()
        pod = pp("batch-1", labels={"tier": "batch"})
        state = CycleState()
        plug.pre_filter(state, pod, snap)
        assert not plug.filter(state, pod, snap.get("n1")).is_success()
        assert plug.filter(state, pod, snap.get("n2")).is_success()

    def test_preferred_affinity_scoring(self):
        snap = self._snap()
        plug = InterPodAffinity()
        pod = pp("friend", labels={"role": "cache"}, affinity={
            "podAffinity": {"preferredDuringSchedulingIgnoredDuringExecution": [
                {"weight": 100, "podAffinityTerm": {
                    "labelSelector": {"matchLabels": {"app": "web"}},
                    "topologyKey": "zone"}}]}})
        state = CycleState()
        plug.pre_score(state, pod, snap.nodes)
        scores = {n.name: plug.score(state, pod, n) for n in snap.nodes}
        plug.normalize_scores(state, pod, scores)
        assert scores["n1"] == 100.0 and scores["n2"] == 0.0


class TestPodTopologySpread:
    def test_do_not_schedule_skew(self):
        sel = {"matchLabels": {"app": "web"}}
        cons = [{"maxSkew": 1, "topologyKey": "zone",
                 "whenUnsatisfiable": "DoNotSchedule", "labelSelector": sel}]
        w = lambda i: pp(f"w{i}", labels={"app": "web"})
        n1 = ni("n1", labels={"zone": "a"}, pods=[w(1), w(2)])
        n2 = ni("n2", labels={"zone": "b"}, pods=[w(3)])
        n3 = ni("n3", labels={"zone": "c"})
        snap = Snapshot([n1, n2, n3])
        plug = PodTopologySpread()
        pod = pp("w4", labels={"app": "web"}, topology_spread_constraints=cons)
        state = CycleState()
        assert plug.pre_filter(state, pod, snap).is_success()
        # zone a has 2, min is 0 (zone c) → adding to a gives skew 3 > 1
        assert not plug.filter(state, pod, n1).is_success()
        # zone b: 1+1-0 = 2 > 1 → also blocked
        assert not plug.filter(state, pod, n2).is_success()
        # zone c: 0+1-0 = 1 ≤ 1 → allowed
        assert plug.filter(state, pod, n3).is_success()

    def test_self_match_excluded_when_selector_misses_pod(self):
        """filtering.go selfMatchNum: a pod whose spread selector does NOT
        match its own labels is not counted as +1 on placement."""
        sel = {"matchLabels": {"app": "web"}}
        cons = [{"maxSkew": 1, "topologyKey": "zone",
                 "whenUnsatisfiable": "DoNotSchedule", "labelSelector": sel}]
        w = lambda i: pp(f"w{i}", labels={"app": "web"})
        n1 = ni("n1", labels={"zone": "a"}, pods=[w(1)])
        n2 = ni("n2", labels={"zone": "b"})
        snap = Snapshot([n1, n2])
        plug = PodTopologySpread()
        # Pod labeled "other": selector doesn't match it, selfMatch = 0.
        pod = pp("x", labels={"app": "other"},
                 topology_spread_constraints=cons)
        state = CycleState()
        assert plug.pre_filter(state, pod, snap).is_success()
        # zone a: 1 + 0 - min(0) = 1 ≤ 1 → allowed (was wrongly blocked
        # when the incoming pod was counted unconditionally).
        assert plug.filter(state, pod, n1).is_success()
        assert plug.filter(state, pod, n2).is_success()

    def test_missing_topology_key_unresolvable(self):
        cons = [{"maxSkew": 1, "topologyKey": "zone",
                 "whenUnsatisfiable": "DoNotSchedule",
                 "labelSelector": {"matchLabels": {"app": "web"}}}]
        nolabel = ni("bare")
        snap = Snapshot([nolabel])
        plug = PodTopologySpread()
        pod = pp("w", labels={"app": "web"}, topology_spread_constraints=cons)
        state = CycleState()
        plug.pre_filter(state, pod, snap)
        st = plug.filter(state, pod, nolabel)
        assert not st.is_success()

    def test_schedule_anyway_scores_spread(self):
        sel = {"matchLabels": {"app": "web"}}
        cons = [{"maxSkew": 1, "topologyKey": "zone",
                 "whenUnsatisfiable": "ScheduleAnyway", "labelSelector": sel}]
        w = lambda i: pp(f"w{i}", labels={"app": "web"})
        n1 = ni("n1", labels={"zone": "a"}, pods=[w(1), w(2), w(3)])
        n2 = ni("n2", labels={"zone": "b"})
        plug = PodTopologySpread()
        pod = pp("w4", labels={"app": "web"}, topology_spread_constraints=cons)
        state = CycleState()
        plug.pre_score(state, pod, [n1, n2])
        scores = {n.name: plug.score(state, pod, n) for n in (n1, n2)}
        plug.normalize_scores(state, pod, scores)
        assert scores["n2"] > scores["n1"]
