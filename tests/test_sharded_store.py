"""ShardedNodeStore differential + semantics suite (ROADMAP #5).

The sharded control plane must be INVISIBLE to correct clients: every
read the facade serves — merged LISTs, pinned pagination, per-shard and
multiplexed watches — is pinned bit-equal (same items, same order, same
RV semantics) to a single MVCCStore fed the same writes. Randomized
differential cases cover the merge paths; directed cases pin the
routing, the shared-RV contract, and Expired behavior.
"""

from __future__ import annotations

import asyncio
import random

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.store import (
    MVCCStore,
    ShardedNodeStore,
    control_plane_shards,
    install_core_validation,
    new_cluster_store,
    shard_of,
)
from kubernetes_tpu.store.mvcc import Expired, NotFound


def run(coro):
    return asyncio.run(coro)


def _names(lst):
    return [o["metadata"]["name"] for o in lst.items]


async def _populated_pair(shards: int, n: int = 40, seed: int = 0):
    rng = random.Random(seed)
    plain, sharded = new_cluster_store(), ShardedNodeStore(shards)
    names = [f"node-{rng.randrange(10_000)}-{i}" for i in range(n)]
    for s in (plain, sharded):
        for name in names:
            await s.create("nodes", make_node(
                name, labels={"bucket": str(hash(name) % 3)}))
    return plain, sharded, names


# -- construction / activation policy ------------------------------------


def test_new_cluster_store_shards_param():
    assert isinstance(new_cluster_store(), MVCCStore)
    s = new_cluster_store(shards=4)
    assert isinstance(s, ShardedNodeStore)
    assert s.node_shards == 4
    # S=1 degrades STRUCTURALLY: no facade at all.
    assert isinstance(new_cluster_store(shards=1), MVCCStore)


def test_env_override(monkeypatch):
    monkeypatch.setenv("KTPU_SHARDS", "3")
    s = new_cluster_store()
    assert isinstance(s, ShardedNodeStore) and s.node_shards == 3
    monkeypatch.setenv("KTPU_SHARDS", "1")
    assert isinstance(new_cluster_store(), MVCCStore)


def test_control_plane_shards_policy(monkeypatch):
    monkeypatch.delenv("KTPU_SHARDS", raising=False)
    assert control_plane_shards(5_000) == 1
    assert control_plane_shards(50_000) == 1
    assert control_plane_shards(200_000) == 8
    assert control_plane_shards(200_000, override=4) == 4
    assert control_plane_shards(100, override=2) == 2
    monkeypatch.setenv("KTPU_SHARDS", "6")
    assert control_plane_shards(100) == 6
    monkeypatch.setenv("KTPU_SHARD_THRESHOLD", "50")
    monkeypatch.delenv("KTPU_SHARDS")
    assert control_plane_shards(100) == 8


def test_shard_of_stable_and_spread():
    names = [f"node-{i}" for i in range(1000)]
    ids = [shard_of(n, 8) for n in names]
    assert ids == [shard_of(n, 8) for n in names]  # deterministic
    for s in range(8):  # crc32 spreads template names reasonably
        assert ids.count(s) > 50


# -- routing --------------------------------------------------------------


def test_partitioned_routing_and_meta():
    async def go():
        s = ShardedNodeStore(4)
        await s.create("nodes", make_node("n-a"))
        await s.create("pods", make_pod("p-a"))
        # The node landed on exactly its hash shard; the pod on meta.
        owner = shard_of("n-a", 4)
        for i, shard in enumerate(s.shards):
            has = "n-a" in shard._table("nodes")
            assert has == (i == owner)
        assert "default/p-a" in s.meta._table("pods")
        # Reads route back.
        assert (await s.get("nodes", "n-a"))["metadata"]["name"] == "n-a"
        with pytest.raises(NotFound):
            await s.get("nodes", "n-missing")
        # guaranteed_update + delete route too.
        def mut(o):
            o["metadata"].setdefault("labels", {})["x"] = "1"
            return o
        got = await s.guaranteed_update("nodes", "n-a", mut)
        assert got["metadata"]["labels"]["x"] == "1"
        await s.delete("nodes", "n-a")
        with pytest.raises(NotFound):
            await s.get("nodes", "n-a")
        s.stop()
    run(go())


def test_shared_rv_is_globally_monotonic():
    async def go():
        s = ShardedNodeStore(4)
        rvs = []
        for i in range(32):
            obj = await s.create("nodes", make_node(f"n-{i}"))
            rvs.append(int(obj["metadata"]["resourceVersion"]))
        assert rvs == sorted(rvs) and len(set(rvs)) == len(rvs)
        assert s.resource_version == rvs[-1]
        s.stop()
    run(go())


def test_binding_subresource_through_facade():
    async def go():
        s = new_cluster_store(shards=4)
        install_core_validation(s)
        await s.create("nodes", make_node("n-0"))
        await s.create("pods", make_pod("p"))
        out = await s.subresource("pods", "default/p", "binding",
                                  {"target": {"name": "n-0"}})
        assert out["status"] == "Success"
        assert (await s.get("pods", "default/p"))["spec"]["nodeName"] \
            == "n-0"
        s.stop()
    run(go())


# -- differential: merged reads vs the single store -----------------------


@pytest.mark.parametrize("shards", [2, 4, 8])
def test_merged_list_bit_equal(shards):
    async def go():
        plain, sharded, _ = await _populated_pair(shards, n=60,
                                                  seed=shards)
        lp = await plain.list("nodes")
        ls = await sharded.list("nodes")
        assert _names(lp) == _names(ls)

        def strip_uid(o):
            # uid is a process-global sequence and creationTimestamp is
            # wall-clock seconds: both can differ between the two
            # populations without any semantic divergence.
            o = dict(o)
            o["metadata"] = {k: v for k, v in o["metadata"].items()
                             if k not in ("uid", "creationTimestamp")}
            return o
        assert [strip_uid(o) for o in lp.items] == \
            [strip_uid(o) for o in ls.items]
        assert ls.resource_version == sharded.resource_version
        # Selector + fields filtering parity.
        from kubernetes_tpu.api.labels import parse_selector
        sel = parse_selector("bucket=1")
        assert _names(await plain.list("nodes", selector=sel)) == \
            _names(await sharded.list("nodes", selector=sel))
        plain.stop(); sharded.stop()
    run(go())


@pytest.mark.parametrize("shards", [2, 4, 8])
def test_paginated_list_parity(shards):
    async def go():
        plain, sharded, _ = await _populated_pair(shards, n=53,
                                                  seed=7 * shards)

        async def pages(store, limit):
            out, cont = [], None
            while True:
                r = await store.list("nodes", limit=limit,
                                     continue_key=cont)
                out.extend(_names(r))
                cont = r.cont
                if not cont:
                    return out
        for limit in (1, 7, 20, 60):
            assert await pages(plain, limit) == await pages(sharded, limit)
        plain.stop(); sharded.stop()
    run(go())


def test_pinned_pagination_spans_writes():
    """A paginated LIST started before concurrent writes serves every
    page at the FIRST page's snapshot RV — across shards, because the
    shared RV counter makes the pin a global snapshot."""
    async def go():
        s = ShardedNodeStore(4)
        names = sorted(f"n-{i:03d}" for i in range(30))
        for n in names:
            await s.create("nodes", make_node(n))
        first = await s.list("nodes", limit=10)
        assert first.cont
        # Writes land between pages: adds, plus an update of a later key.
        await s.create("nodes", make_node("a-before-everything"))
        await s.guaranteed_update(
            "nodes", names[-1],
            lambda o: (o["metadata"].setdefault(
                "labels", {}).update({"late": "1"}) or o))
        got, cont = _names(first), first.cont
        while cont:
            r = await s.list("nodes", limit=10, continue_key=cont)
            got.extend(_names(r))
            for item in r.items:
                assert "late" not in (
                    item["metadata"].get("labels") or {}), \
                    "page leaked post-snapshot state"
            cont = r.cont
        assert got == names  # the late add is not in the pinned LIST
        s.stop()
    run(go())


# -- watches --------------------------------------------------------------


def test_per_shard_watch_streams_partition_events():
    async def go():
        s = ShardedNodeStore(4)
        seen: dict[int, list[str]] = {i: [] for i in range(4)}

        async def consume(i, w):
            async for ev in w:
                if ev.type == "BOOKMARK":
                    continue
                seen[i].append(ev.object["metadata"]["name"])

        watches = [await s.watch("nodes", shard=i) for i in range(4)]
        tasks = [asyncio.ensure_future(consume(i, w))
                 for i, w in enumerate(watches)]
        names = [f"w-{i}" for i in range(24)]
        for n in names:
            await s.create("nodes", make_node(n))
        await asyncio.sleep(0.1)
        for i in range(4):
            assert seen[i] == [n for n in names if shard_of(n, 4) == i]
        for t in tasks:
            t.cancel()
        s.stop()
    run(go())


def test_multiplexed_watch_replay_and_live():
    """The unsharded-client path (HTTP/gRPC wires): one merged stream
    replays history from a global RV and then streams live events."""
    async def go():
        s = ShardedNodeStore(3)
        for i in range(12):
            await s.create("nodes", make_node(f"m-{i}"))
        mark = s.resource_version
        for i in range(12, 18):
            await s.create("nodes", make_node(f"m-{i}"))
        w = await s.watch("nodes", resource_version=mark)
        got = []

        async def consume():
            async for ev in w:
                if ev.type == "BOOKMARK":
                    continue
                got.append(ev.object["metadata"]["name"])
                if len(got) >= 8:
                    return
        live = asyncio.ensure_future(consume())
        await asyncio.sleep(0.05)
        await s.create("nodes", make_node("m-live-0"))
        await s.create("nodes", make_node("m-live-1"))
        await asyncio.wait_for(live, 5)
        assert set(got) == {f"m-{i}" for i in range(12, 18)} | \
            {"m-live-0", "m-live-1"}
        # RVs in the merged stream are globally valid and > mark.
        s.stop()
    run(go())


def test_watch_expired_parity():
    async def go():
        s = ShardedNodeStore(2)
        await s.create("nodes", make_node("x-0"))
        with pytest.raises(Expired):
            await s.watch("nodes", resource_version=10_000, shard=0)
        with pytest.raises(Expired):
            await s.watch("nodes", resource_version=10_000)
        s.stop()
    run(go())


def test_shared_observability_surfaces():
    async def go():
        s = ShardedNodeStore(4)
        for i in range(8):
            await s.create("nodes", make_node(f"o-{i}"))
        # Every shard's cacher reports into ONE metrics object.
        assert s.cacher is s.meta.cacher
        for shard in s.shards:
            assert shard.cacher.metrics is s.cacher.metrics
            assert shard.watch_metrics is s.watch_metrics
        h0 = s.cacher.metrics.hits.value()
        await s.list("nodes")
        assert s.cacher.metrics.hits.value() >= h0 + s.node_shards
        assert isinstance(s.list_direct_total, dict)
        s.stop()
    run(go())


def test_event_sinks_fan_to_all_shards():
    async def go():
        s = ShardedNodeStore(3)
        events = []
        s.add_event_sink(lambda res, ev: events.append(
            (res, ev.object["metadata"]["name"])))
        for i in range(9):
            await s.create("nodes", make_node(f"sink-{i}"))
        await s.create("pods", make_pod("sink-pod"))
        assert len([e for e in events if e[0] == "nodes"]) == 9
        assert ("pods", "sink-pod") in events
        s.stop()
    run(go())
