"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so mesh/sharding code paths are
exercised without TPU hardware (the driver separately dry-runs the multi-chip
path; real-chip perf runs happen only in bench.py).

Must set XLA_FLAGS/JAX_PLATFORMS before jax initializes, hence top of conftest.
"""

import os

# Force CPU: the session's axon sitecustomize pins
# jax.config jax_platforms="axon,cpu" (the one real TPU) at interpreter
# start, overriding the env var — so override the *config* after import.
# KTPU_TEST_PLATFORM runs the suite against real hardware instead.
# Enforce the "handlers never mutate delivered/stored objects" convention in
# tests: watch events share the stored dict, so a violating handler must fail
# loudly here rather than silently corrupt the store (see store/mvcc.py).
os.environ.setdefault("KTPU_DEBUG_FREEZE", "1")

_platform = os.environ.get("KTPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)

import asyncio  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def event_loop_policy():
    return asyncio.DefaultEventLoopPolicy()


def run_async(coro):
    """Run a coroutine to completion on a fresh loop (test helper)."""
    return asyncio.run(coro)


async def start_scheduler(store, seed=42, **kw):
    """Shared scheduler bootstrap for e2e-style tests."""
    from kubernetes_tpu.client import InformerFactory
    from kubernetes_tpu.scheduler import Scheduler
    sched = Scheduler(store, seed=seed, **kw)
    factory = InformerFactory(store)
    await sched.setup_informers(factory)
    factory.start()
    await factory.wait_for_sync()
    return sched, factory
