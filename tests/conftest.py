"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so mesh/sharding code paths are
exercised without TPU hardware (the driver separately dry-runs the multi-chip
path; real-chip perf runs happen only in bench.py).

Must set XLA_FLAGS/JAX_PLATFORMS before jax initializes, hence top of conftest.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import asyncio  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def event_loop_policy():
    return asyncio.DefaultEventLoopPolicy()


def run_async(coro):
    """Run a coroutine to completion on a fresh loop (test helper)."""
    return asyncio.run(coro)
