"""Resilience wiring (SURVEY §5.2/§5.3): leader-elected scheduler/KCM,
TPU-device-loss → host fallback, and hypothesis state-machine tests for
the queue/cache invariants the Go race detector enforced structurally."""

import asyncio

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.client.leaderelection import LeaderElector
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def run(coro):
    return asyncio.run(coro)


async def wait_for(predicate, timeout=10.0, interval=0.02):
    for _ in range(int(timeout / interval)):
        v = await predicate()
        if v:
            return v
        await asyncio.sleep(interval)
    return await predicate()


class TestLeaderElectedScheduler:
    def test_standby_takes_over_when_leader_dies(self):
        """Two schedulers, one lease: only the leader schedules; killing it
        lets the standby acquire and continue (§5.3 active/passive HA)."""
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            for i in range(3):
                await store.create("nodes", make_node(f"n{i}"))

            async def make_sched(seed):
                s = Scheduler(store, seed=seed)
                f = InformerFactory(store)
                await s.setup_informers(f)
                f.start()
                await f.wait_for_sync()
                return s, f

            s1, f1 = await make_sched(1)
            s2, f2 = await make_sched(2)
            e1 = LeaderElector(store, "kube-scheduler", "a",
                               lease_duration=0.8, renew_deadline=0.6,
                               retry_period=0.1)
            e2 = LeaderElector(store, "kube-scheduler", "b",
                               lease_duration=0.8, renew_deadline=0.6,
                               retry_period=0.1)
            t1 = asyncio.ensure_future(
                s1.run_with_leader_election(e1, batch_size=4))
            t2 = asyncio.ensure_future(
                s2.run_with_leader_election(e2, batch_size=4))
            await asyncio.sleep(0.3)
            assert e1.is_leader != e2.is_leader  # exactly one leads

            await store.create("pods", make_pod("p1", requests={"cpu": "1"}))

            async def p1_bound():
                p = await store.get("pods", "default/p1")
                return p["spec"].get("nodeName")
            assert await wait_for(p1_bound)

            # Kill the leader (hard cancel: no graceful lease release).
            leader_task, standby_e = (t1, e2) if e1.is_leader else (t2, e1)
            leader_task.cancel()
            await asyncio.gather(leader_task, return_exceptions=True)

            # Standby must acquire after the lease expires and schedule.
            assert await wait_for(
                lambda: asyncio.sleep(0, standby_e.is_leader), timeout=5.0)
            await store.create("pods", make_pod("p2", requests={"cpu": "1"}))

            async def p2_bound():
                p = await store.get("pods", "default/p2")
                return p["spec"].get("nodeName")
            assert await wait_for(p2_bound, timeout=5.0)

            for t in (t1, t2):
                t.cancel()
            await asyncio.gather(t1, t2, return_exceptions=True)
            await s1.stop()
            await s2.stop()
            f1.stop()
            f2.stop()
            store.stop()
        run(body())


class _ExplodingBackend:
    """Backend double that fails N times, then works (by delegating)."""

    def __init__(self, failures: int):
        self.failures = failures
        self.calls = 0

    def assign(self, pods, snapshot, fwk):
        self.calls += 1
        raise RuntimeError("device lost (injected)")


class TestDeviceLossFallback:
    def test_backend_crash_falls_back_to_host_path(self):
        """An exploding backend must not fail the cycle: pods schedule via
        the host path, and 3 consecutive crashes open the circuit."""
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            for i in range(3):
                await store.create("nodes", make_node(f"n{i}"))
            backend = _ExplodingBackend(failures=99)
            sched = Scheduler(store, seed=3, backend=backend)
            factory = InformerFactory(store)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            task = asyncio.ensure_future(sched.run(batch_size=4))
            # Drive waves until 3 backend calls happened (a size-1 pop
            # bypasses the backend, so waves aren't guaranteed one call
            # each) — the circuit must then be open.
            total = 0
            for wave in range(10):
                for i in range(4):
                    await store.create("pods", make_pod(
                        f"p{wave}-{i}", requests={"cpu": "100m"}))
                total += 4

                async def bound(want=total):
                    pods = (await store.list("pods")).items
                    return sum(1 for p in pods
                               if p["spec"].get("nodeName")) == want
                assert await wait_for(bound, timeout=10.0)
                if backend.calls >= 3:
                    break
            # Circuit opened after 3 consecutive failures.
            assert backend.calls >= 3
            assert sched.backend is None
            assert sched.metrics.schedule_attempts.value(
                result="backend_fallback",
                profile="default-scheduler") >= 3
            await sched.stop()
            task.cancel()
            factory.stop()
            store.stop()
        run(body())
