"""Mesh-sharded path: differential vs the single-chip solver on the 8-device
virtual CPU mesh (conftest forces xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import solver
from kubernetes_tpu.parallel import (
    build_mesh,
    build_mesh_2d,
    sharded_greedy_assign,
    sharded_masks_scores,
)


def synthetic(P=12, N=64, R=2, seed=3):
    rng = np.random.default_rng(seed)
    alloc_q = rng.integers(4_000, 64_000, size=(N, R)).astype(np.int32)
    used_q = (alloc_q * rng.uniform(0, 0.5, size=(N, R))).astype(np.int32)
    alloc_pods = np.full((N,), 110, np.int32)
    used_pods = rng.integers(0, 30, size=(N,)).astype(np.int32)
    req_q = rng.integers(100, 9_000, size=(P, R)).astype(np.int32)
    mask = rng.random((P, N)) < 0.9
    static_sc = rng.uniform(0, 10, size=(P, N)).astype(np.float32)
    col_w = np.ones((R,), np.float32)
    col_mask = np.ones((R,), np.bool_)
    return alloc_q, used_q, alloc_pods, used_pods, req_q, mask, static_sc, \
        col_w, col_mask


class TestShardedSolver:
    @pytest.mark.parametrize("n_devices", [1, 2, 8])
    def test_matches_single_chip(self, n_devices):
        if len(jax.devices()) < n_devices:
            pytest.skip("not enough devices")
        (alloc_q, used_q, alloc_pods, used_pods, req_q, mask, static_sc,
         col_w, col_mask) = synthetic()
        single = np.asarray(solver.greedy_assign_rescoring(
            jnp.asarray(req_q), jnp.asarray(req_q),
            jnp.asarray(alloc_q - used_q), jnp.asarray(alloc_pods - used_pods),
            jnp.asarray(used_q), jnp.asarray(alloc_q), jnp.asarray(mask),
            jnp.asarray(static_sc), jnp.asarray(col_w), jnp.asarray(col_mask),
            jnp.zeros((2,), jnp.float32), jnp.zeros((2,), jnp.float32),
            1.0, 1.0, strategy="LeastAllocated"))
        mesh = build_mesh(n_devices)
        sharded = np.asarray(sharded_greedy_assign(
            mesh, jnp.asarray(req_q), jnp.asarray(req_q),
            jnp.asarray(alloc_q - used_q), jnp.asarray(alloc_pods - used_pods),
            jnp.asarray(used_q), jnp.asarray(alloc_q), jnp.asarray(mask),
            jnp.asarray(static_sc), jnp.asarray(col_w), jnp.asarray(col_mask),
            np.zeros((2,), np.float32), np.zeros((2,), np.float32),
            1.0, 1.0, "LeastAllocated"))
        np.testing.assert_array_equal(single, sharded)

    def test_capacity_never_overcommitted(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        (alloc_q, used_q, alloc_pods, used_pods, req_q, mask, static_sc,
         col_w, col_mask) = synthetic(P=40, N=16, seed=9)
        mesh = build_mesh(8)
        assign = np.asarray(sharded_greedy_assign(
            mesh, jnp.asarray(req_q), jnp.asarray(req_q),
            jnp.asarray(alloc_q - used_q), jnp.asarray(alloc_pods - used_pods),
            jnp.asarray(used_q), jnp.asarray(alloc_q), jnp.asarray(mask),
            jnp.asarray(static_sc), jnp.asarray(col_w), jnp.asarray(col_mask),
            np.zeros((2,), np.float32), np.zeros((2,), np.float32),
            1.0, 1.0, "LeastAllocated"))
        spent = np.zeros_like(alloc_q)
        for i, n in enumerate(assign):
            if n >= 0:
                assert mask[i, n]
                spent[n] += req_q[i]
        assert (used_q + spent <= alloc_q).all()


class TestMasksScores2D:
    def test_2d_mesh_phase_runs(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        mesh = build_mesh_2d(8)
        P = 4 * mesh.shape["pods"]
        N = 16 * mesh.shape["nodes"]
        (alloc_q, used_q, alloc_pods, used_pods, req_q, _, _, col_w,
         col_mask) = synthetic(P=P, N=N)
        static_mask = np.ones((P, N), np.bool_)
        taint = np.zeros((N, 1), np.bool_)
        untol = np.zeros((P, 1), np.bool_)
        host_scores = np.zeros((P, N), np.float32)
        mask, feasible, static_sc = sharded_masks_scores(
            mesh, jnp.asarray(alloc_q), jnp.asarray(used_q),
            jnp.asarray(used_q), jnp.asarray(alloc_pods),
            jnp.asarray(used_pods), jnp.asarray(req_q), jnp.asarray(req_q),
            jnp.asarray(untol), jnp.asarray(untol), jnp.asarray(taint),
            jnp.asarray(taint), jnp.asarray(static_mask),
            jnp.asarray(host_scores), 3.0, True, "LeastAllocated")
        assert np.asarray(mask).shape == (P, N)
        assert np.asarray(static_sc).shape == (P, N)
        assert np.isfinite(np.asarray(static_sc)[np.asarray(feasible)]).all()


class TestGraftEntry:
    def test_entry_compiles(self):
        import sys
        sys.path.insert(0, "/root/repo")
        import __graft_entry__ as ge
        fn, args = ge.entry()
        out = np.asarray(jax.jit(fn)(*args))
        assert out.shape == (16,)

    def test_dryrun_multichip(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        import __graft_entry__ as ge
        ge.dryrun_multichip(8)


class TestMultisliceSolver:
    """Config #5: (slice × nodes) mesh with hierarchical ICI→DCN argmax."""

    @pytest.mark.parametrize("shape", [(2, 4), (4, 2), (2, 2)])
    def test_matches_single_chip(self, shape):
        from kubernetes_tpu.parallel import (
            build_multislice_mesh,
            sharded_greedy_assign_multislice,
        )
        s, c = shape
        if len(jax.devices()) < s * c:
            pytest.skip("not enough devices")
        (alloc_q, used_q, alloc_pods, used_pods, req_q, mask, static_sc,
         col_w, col_mask) = synthetic(P=16, N=128, seed=7)
        single = np.asarray(solver.greedy_assign_rescoring(
            jnp.asarray(req_q), jnp.asarray(req_q),
            jnp.asarray(alloc_q - used_q), jnp.asarray(alloc_pods - used_pods),
            jnp.asarray(used_q), jnp.asarray(alloc_q), jnp.asarray(mask),
            jnp.asarray(static_sc), jnp.asarray(col_w), jnp.asarray(col_mask),
            jnp.zeros((2,), jnp.float32), jnp.zeros((2,), jnp.float32),
            1.0, 1.0, strategy="LeastAllocated"))
        mesh = build_multislice_mesh(s, c)
        ms = np.asarray(sharded_greedy_assign_multislice(
            mesh, jnp.asarray(req_q), jnp.asarray(req_q),
            jnp.asarray(alloc_q - used_q), jnp.asarray(alloc_pods - used_pods),
            jnp.asarray(used_q), jnp.asarray(alloc_q), jnp.asarray(mask),
            jnp.asarray(static_sc), jnp.asarray(col_w), jnp.asarray(col_mask),
            np.zeros((2,), np.float32), np.zeros((2,), np.float32),
            1.0, 1.0, "LeastAllocated"))
        np.testing.assert_array_equal(single, ms)

    def test_50k_node_width(self):
        """The 50k-node problem width (config #5) solves on the (2×4)
        virtual multi-slice mesh: 51200 node rows, capacity respected."""
        from kubernetes_tpu.parallel import (
            build_multislice_mesh,
            sharded_greedy_assign_multislice,
        )
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        (alloc_q, used_q, alloc_pods, used_pods, req_q, mask, static_sc,
         col_w, col_mask) = synthetic(P=32, N=51_200, seed=11)
        mesh = build_multislice_mesh(2, 4)
        assign = np.asarray(sharded_greedy_assign_multislice(
            mesh, jnp.asarray(req_q), jnp.asarray(req_q),
            jnp.asarray(alloc_q - used_q), jnp.asarray(alloc_pods - used_pods),
            jnp.asarray(used_q), jnp.asarray(alloc_q), jnp.asarray(mask),
            jnp.asarray(static_sc), jnp.asarray(col_w), jnp.asarray(col_mask),
            np.zeros((2,), np.float32), np.zeros((2,), np.float32),
            1.0, 1.0, "LeastAllocated"))
        assert (assign >= 0).all()  # plenty of room at this width
        spent = np.zeros_like(alloc_q)
        for i, n in enumerate(assign):
            spent[n] += req_q[i]
        assert (used_q + spent <= alloc_q).all()

    def test_backend_on_multislice_mesh(self):
        """TPUBackend accepts a (slice × nodes) mesh: the fused program
        auto-partitions the node dimension over both axes."""
        from kubernetes_tpu.api.types import make_node, make_pod
        from kubernetes_tpu.ops import TPUBackend
        from kubernetes_tpu.parallel import build_multislice_mesh
        from kubernetes_tpu.scheduler.cache import SchedulerCache
        from kubernetes_tpu.scheduler.framework import Framework
        from kubernetes_tpu.scheduler.plugins.registry import (
            DEFAULT_SCORE_WEIGHTS, build_plugins)
        from kubernetes_tpu.scheduler.types import PodInfo
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        cache = SchedulerCache()
        for i in range(16):
            cache.add_node(make_node(f"n{i}"))
        snapshot = cache.update_snapshot()
        pods = [PodInfo(make_pod(f"p{i}", requests={"cpu": "500m"},
                                 uid=f"u{i}")) for i in range(12)]
        fwk = Framework(build_plugins(), DEFAULT_SCORE_WEIGHTS)
        backend = TPUBackend(max_batch=16,
                             mesh=build_multislice_mesh(2, 4))
        assignments, _ = backend.assign(pods, snapshot, fwk)
        assert all(assignments[p.key] for p in pods)
