"""Tier-1 guard for the watch-cache serving tier (store/cacher.py).

Three promises the tier must keep, at toy scale, on every commit:

- the cacher is ACTIVE BY DEFAULT — a plain MVCCStore serves LISTs and
  exact-RV snapshot reads from the tier, never scanning its table;
- a 500-agent cold-start relist storm (every agent tears down its watch
  and full-LISTs at once) costs the mvcc core at most ONE direct LIST
  per resource, not one per agent — the storm rides the shared snapshot;
- the `KTPU_WATCH_CACHE=0` kill switch degrades cleanly to the
  direct-mvcc path: LIST/watch/legacy paging all work, only historical
  exact-RV reads (which need the ring) turn into Expired.
"""

import asyncio

import pytest

from kubernetes_tpu.agent.agent import NodeAgent
from kubernetes_tpu.store.mvcc import Expired, MVCCStore
from kubernetes_tpu.utils import locking


@pytest.fixture(autouse=True)
def _lock_check(monkeypatch):
    """Tier-1 rides the runtime lock/dispatch-hygiene detector (see
    tests/test_serving_smoke.py): locks built during this suite are
    instrumented, inversions and held-across-dispatch raise."""
    monkeypatch.setenv("KTPU_LOCK_CHECK", "1")
    locking.reset_observed()
    yield
    locking.reset_observed()


def run(coro):
    return asyncio.run(coro)


async def _mk_pods(s: MVCCStore, n: int, node: str = "node-0",
                   start: int = 0):
    for i in range(start, start + n):
        await s.create("pods", {
            "metadata": {"name": f"p{i}", "namespace": "default"},
            "spec": {"nodeName": node}, "status": {"phase": "Running"}})


class TestActiveByDefault:
    def test_plain_store_serves_from_the_tier(self):
        async def body():
            s = MVCCStore()
            assert s.cacher is not None
            await _mk_pods(s, 5)
            rv0 = s.resource_version
            await _mk_pods(s, 3, node="node-1", start=5)
            lst = await s.list("pods")
            assert len(lst.items) == 8
            # Historical exact-RV snapshot — only the ring can serve it.
            old = await s.list("pods", resource_version=rv0,
                               resource_version_match="Exact")
            assert old.resource_version == rv0
            assert len(old.items) == 5
            # NONE of that scanned the table.
            assert s.list_direct_total == {}
            assert s.cacher.metrics.hits.value() >= 2
            s.stop()
        run(body())


class TestColdStartRelistStorm:
    def test_500_agents_cost_one_store_read_per_resource(self, tmp_path):
        async def body():
            s = MVCCStore()
            await _mk_pods(s, 10)
            agents = [
                NodeAgent(s, f"node-{i}", checkpoint_dir=str(tmp_path),
                          lease_period=60.0)
                for i in range(500)]
            try:
                await asyncio.gather(*(a.start() for a in agents))
                # Boot alone is 500 field-filtered LISTs + 500 watches:
                # all served off the shared snapshot.
                assert all(n <= 1 for n in s.list_direct_total.values()), \
                    s.list_direct_total
                base = dict(s.list_direct_total)
                h0 = s.cacher.metrics.hits.value()
                await asyncio.gather(*(a.force_relist() for a in agents))
                # The storm: 500 cold relists + rewatches, ZERO new
                # direct scans — N reads of one snapshot, not N scans.
                for res, n in s.list_direct_total.items():
                    assert n - base.get(res, 0) == 0, (res, n)
                assert s.cacher.metrics.hits.value() - h0 >= 500
            finally:
                await asyncio.gather(*(a.stop() for a in agents))
                s.stop()
        run(body())


class TestKillSwitch:
    def test_direct_mvcc_path_degrades_cleanly(self, monkeypatch):
        monkeypatch.setenv("KTPU_WATCH_CACHE", "0")

        async def body():
            s = MVCCStore()
            assert s.cacher is None
            await _mk_pods(s, 6)
            rv0 = s.resource_version
            lst = await s.list("pods")
            assert len(lst.items) == 6
            assert s.list_direct_total.get("pods") == 1
            # Legacy bare-key paging still works end to end.
            page = await s.list("pods", limit=4)
            assert page.cont is None  # pinned tokens are a cacher thing
            rest = await s.list("pods", limit=4,
                                continue_key="default/p3")
            assert [p["metadata"]["name"] for p in rest.items] == \
                ["p4", "p5"]
            # Current-RV exact works; historical exact is honestly 410.
            cur = await s.list("pods", resource_version=rv0,
                               resource_version_match="Exact")
            assert cur.resource_version == rv0
            await s.create("pods", {
                "metadata": {"name": "late", "namespace": "default"},
                "spec": {}})
            with pytest.raises(Expired):
                await s.list("pods", resource_version=rv0,
                             resource_version_match="Exact")
            # Watch backfill rides the store's global-history scan.
            gen = await s.watch("pods", resource_version=rv0)
            ev = await asyncio.wait_for(gen.__anext__(), 2.0)
            assert ev.type == "ADDED"
            assert ev.object["metadata"]["name"] == "late"
            await gen.aclose()
            s.stop()
        run(body())
