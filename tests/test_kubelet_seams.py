"""Kubelet seams (kubernetes_tpu/agent): merged config sources and the
read-only server.

Pins: (a) config precedence is defaults < file < apiserver <
constructor override, FIELD-BY-FIELD (a layer overrides only the keys
it sets), with per-field source attribution; (b) unknown keys and
malformed values degrade to the lower layer with a warning, never a
crash; (c) the apiserver layer is the node-named `kubeletconfigs`
object falling back to the cluster-wide `default`; (d) the read-only
server answers /healthz, /pods (the agent's LOCAL resident view) and
/configz (resolved values + attribution) with no mutating route.
"""

import asyncio
import json
import os
import tempfile
import unittest

from kubernetes_tpu.agent import NodeAgent, merge_config
from kubernetes_tpu.agent.config import (
    DEFAULTS,
    fetch_apiserver_source,
    load_file_source,
    resolve_config,
)
from kubernetes_tpu.agent.server import AgentServer
from kubernetes_tpu.api.meta import new_object
from kubernetes_tpu.api.types import make_pod
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def run(coro):
    return asyncio.run(coro)


async def wait_for(pred, timeout=8.0, msg="condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        got = await pred()
        if got:
            return got
        await asyncio.sleep(0.02)
    raise AssertionError(f"timeout waiting for {msg}")


class TestConfigMerge(unittest.TestCase):
    def test_defaults_only(self):
        cfg = merge_config()
        self.assertEqual(cfg.values, DEFAULTS)
        self.assertTrue(all(s == "default" for s in cfg.sources.values()))

    def test_precedence_field_by_field(self):
        # file sets lease, apiserver sets zones: each field keeps the
        # HIGHEST layer that actually set it — apiserver does not reset
        # the file's lease, the file does not shadow apiserver zones.
        cfg = merge_config(
            ("file", {"leasePeriodSeconds": 7.5, "deviceZones": 4}),
            ("apiserver", {"deviceZones": 8}),
        )
        self.assertEqual(cfg["leasePeriodSeconds"], 7.5)
        self.assertEqual(cfg["deviceZones"], 8)
        self.assertEqual(cfg["deviceDriver"], DEFAULTS["deviceDriver"])
        self.assertEqual(cfg.sources["leasePeriodSeconds"], "file")
        self.assertEqual(cfg.sources["deviceZones"], "apiserver")
        self.assertEqual(cfg.sources["deviceDriver"], "default")

    def test_override_layer_wins(self):
        cfg = merge_config(
            ("file", {"leasePeriodSeconds": 7.5}),
            ("apiserver", {"leasePeriodSeconds": 9.0}),
            ("override", {"leasePeriodSeconds": 0.25}),
        )
        self.assertEqual(cfg["leasePeriodSeconds"], 0.25)
        self.assertEqual(cfg.sources["leasePeriodSeconds"], "override")

    def test_unknown_and_malformed_degrade(self):
        with self.assertLogs("kubernetes_tpu.agent.config",
                             level="WARNING"):
            cfg = merge_config(
                ("file", {"notAField": 1, "leasePeriodSeconds": "nope"}))
        # Unknown key ignored, bad value falls back to the default.
        self.assertEqual(cfg["leasePeriodSeconds"],
                         DEFAULTS["leasePeriodSeconds"])
        self.assertNotIn("notAField", cfg.values)

    def test_coercion(self):
        # Hand-edited files carry strings; fields coerce per-type.
        cfg = merge_config(("file", {"leasePeriodSeconds": "5",
                                     "deviceZones": "4"}))
        self.assertEqual(cfg["leasePeriodSeconds"], 5.0)
        self.assertEqual(cfg["deviceZones"], 4)

    def test_configz_payload(self):
        cfg = merge_config(("file", {"deviceDriver": "dra.other"}))
        z = cfg.as_configz()
        self.assertEqual(z["kubeletconfig"]["deviceDriver"], "dra.other")
        self.assertEqual(z["sources"]["deviceDriver"], "file")

    def test_file_source_missing_and_malformed(self):
        self.assertEqual(load_file_source(None), {})
        self.assertEqual(load_file_source("/does/not/exist.json"), {})
        with tempfile.NamedTemporaryFile(
                "w", suffix=".json", delete=False) as f:
            f.write("{not json")
            path = f.name
        try:
            with self.assertLogs("kubernetes_tpu.agent.config",
                                 level="WARNING"):
                self.assertEqual(load_file_source(path), {})
        finally:
            os.unlink(path)

    def test_apiserver_source_node_beats_default(self):
        async def body():
            store = new_cluster_store()
            try:
                await store.create("kubeletconfigs", new_object(
                    "KubeletConfiguration", "default", "default",
                    spec={"deviceZones": 2}))
                await store.create("kubeletconfigs", new_object(
                    "KubeletConfiguration", "nodeA", "default",
                    spec={"deviceZones": 6}))
                self.assertEqual(
                    await fetch_apiserver_source(store, "nodeA"),
                    {"deviceZones": 6})
                # No node-named object → the cluster-wide default.
                self.assertEqual(
                    await fetch_apiserver_source(store, "nodeB"),
                    {"deviceZones": 2})
                # Neither existing is normal: empty layer.
                await store.delete("kubeletconfigs", "default/default")
                await store.delete("kubeletconfigs", "default/nodeA")
                self.assertEqual(
                    await fetch_apiserver_source(store, "nodeB"), {})
            finally:
                store.stop()
        run(body())

    def test_resolve_full_stack(self):
        async def body():
            store = new_cluster_store()
            with tempfile.NamedTemporaryFile(
                    "w", suffix=".json", delete=False) as f:
                json.dump({"leasePeriodSeconds": 6.0,
                           "deviceDriver": "dra.file"}, f)
                path = f.name
            try:
                await store.create("kubeletconfigs", new_object(
                    "KubeletConfiguration", "n0", "default",
                    spec={"deviceDriver": "dra.api"}))
                cfg = await resolve_config(
                    store, "n0", config_file=path,
                    overrides={"deviceZones": 3})
                self.assertEqual(cfg["leasePeriodSeconds"], 6.0)   # file
                self.assertEqual(cfg["deviceDriver"], "dra.api")   # api
                self.assertEqual(cfg["deviceZones"], 3)            # kwarg
                self.assertEqual(cfg.sources["leasePeriodSeconds"], "file")
                self.assertEqual(cfg.sources["deviceDriver"], "apiserver")
                self.assertEqual(cfg.sources["deviceZones"], "override")
            finally:
                os.unlink(path)
                store.stop()
        run(body())


class TestAgentAppliesConfig(unittest.TestCase):
    def test_apiserver_layer_reaches_running_agent(self):
        """An agent started with NO kwargs resolves its lease period
        from the apiserver's node-named config object."""
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            tmp = tempfile.mkdtemp(prefix="ktpu-seams-")
            try:
                await store.create("kubeletconfigs", new_object(
                    "KubeletConfiguration", "n0", "default",
                    spec={"leasePeriodSeconds": 0.123}))
                agent = NodeAgent(store, "n0", checkpoint_dir=tmp)
                await agent.start()
                try:
                    self.assertEqual(agent.lease_period, 0.123)
                    self.assertEqual(
                        agent.kubelet_config.sources["leasePeriodSeconds"],
                        "apiserver")
                finally:
                    await agent.stop()
            finally:
                store.stop()
        run(body())

    def test_coord_label_stamped_on_preexisting_node(self):
        """Restart / pre-staged Node: create raced AlreadyExists, but
        the coordinate label must still land on the surviving object."""
        async def body():
            from kubernetes_tpu.api.types import make_node
            from kubernetes_tpu.topology import MESH_COORD_LABEL
            store = new_cluster_store()
            install_core_validation(store)
            tmp = tempfile.mkdtemp(prefix="ktpu-seams-")
            try:
                await store.create("nodes", make_node("n0"))
                agent = NodeAgent(store, "n0", checkpoint_dir=tmp,
                                  topology_coord="3,1")
                await agent.start()
                try:
                    node = await store.get("nodes", "n0")
                    self.assertEqual(
                        node["metadata"]["labels"][MESH_COORD_LABEL],
                        "3,1")
                finally:
                    await agent.stop()
            finally:
                store.stop()
        run(body())

    def test_constructor_kwarg_beats_apiserver(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            tmp = tempfile.mkdtemp(prefix="ktpu-seams-")
            try:
                await store.create("kubeletconfigs", new_object(
                    "KubeletConfiguration", "n0", "default",
                    spec={"leasePeriodSeconds": 0.123}))
                agent = NodeAgent(store, "n0", checkpoint_dir=tmp,
                                  lease_period=9.0)
                await agent.start()
                try:
                    self.assertEqual(agent.lease_period, 9.0)
                finally:
                    await agent.stop()
            finally:
                store.stop()
        run(body())


class TestAgentServer(unittest.TestCase):
    """Read-endpoint smoke: /healthz, /pods, /configz over real HTTP."""

    def test_read_endpoints(self):
        async def body():
            import aiohttp
            store = new_cluster_store()
            install_core_validation(store)
            tmp = tempfile.mkdtemp(prefix="ktpu-seams-")
            agent = NodeAgent(store, "n0", checkpoint_dir=tmp,
                              topology_coord="1,2")
            await agent.start()
            server = AgentServer(agent)
            await server.start()
            base = f"http://127.0.0.1:{server.port}"
            try:
                # Bind a pod onto the node; the agent's local view
                # (via its field-filtered watch) backs /pods.
                await store.create("pods", make_pod(
                    "resident", uid="resident"))
                await store.subresource(
                    "pods", "default/resident", "binding",
                    {"target": {"name": "n0"}})
                await wait_for(
                    lambda: asyncio.sleep(0, bool(agent.resident_pods())),
                    msg="agent observed its pod")
                async with aiohttp.ClientSession() as http:
                    async with http.get(base + "/healthz") as r:
                        self.assertEqual(r.status, 200)
                        self.assertEqual(await r.text(), "ok")
                    async with http.get(base + "/pods") as r:
                        self.assertEqual(r.status, 200)
                        pods = await r.json()
                        self.assertEqual(pods["kind"], "PodList")
                        names = [p["metadata"]["name"]
                                 for p in pods["items"]]
                        self.assertEqual(names, ["resident"])
                    async with http.get(base + "/configz") as r:
                        self.assertEqual(r.status, 200)
                        z = await r.json()
                        self.assertEqual(
                            z["kubeletconfig"]["topologyCoord"], "1,2")
                        self.assertEqual(
                            z["sources"]["topologyCoord"], "override")
                        self.assertEqual(
                            z["sources"]["leasePeriodSeconds"], "default")
                # Registration stamped the mesh coordinate label.
                node = await store.get("nodes", "n0")
                from kubernetes_tpu.topology import MESH_COORD_LABEL
                self.assertEqual(
                    node["metadata"]["labels"][MESH_COORD_LABEL], "1,2")
            finally:
                await server.stop()
                await agent.stop()
                store.stop()
        run(body())

    def test_healthz_reports_stopped(self):
        async def body():
            import aiohttp
            store = new_cluster_store()
            install_core_validation(store)
            tmp = tempfile.mkdtemp(prefix="ktpu-seams-")
            agent = NodeAgent(store, "n0", checkpoint_dir=tmp)
            await agent.start()
            server = AgentServer(agent)
            await server.start()
            base = f"http://127.0.0.1:{server.port}"
            try:
                await agent.stop()
                async with aiohttp.ClientSession() as http:
                    async with http.get(base + "/healthz") as r:
                        self.assertEqual(r.status, 500)
            finally:
                await server.stop()
                store.stop()
        run(body())
