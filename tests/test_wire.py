"""KTPU wire transport (apiserver/wire.py): the multiplexed framed
core-component wire — CRUD parity with the store, watch push semantics,
same-tick multi batching, authn/authz, and informer integration.

Reference semantics being mirrored: client-go's HTTP/2 transport (one
connection, multiplexed streams), watch.Interface event delivery, and
the apiserver handler chain (authn → APF → authz) — see wire.py header.
"""

import asyncio
import unittest

from kubernetes_tpu.api.labels import parse_selector
from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.apiserver.rbac import RBACAuthorizer
from kubernetes_tpu.apiserver.wire import WireServer, WireStore
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.store import install_core_validation, new_cluster_store
from kubernetes_tpu.store.mvcc import (
    AlreadyExists,
    Conflict,
    Expired,
    NotFound,
)


def run(coro):
    return asyncio.run(coro)


class WireHarness:
    """One store + wire server + connected client per test."""

    def __init__(self, **server_kw):
        self.store = new_cluster_store()
        install_core_validation(self.store)
        self.server = WireServer(self.store, **server_kw)
        self.client: WireStore | None = None

    async def __aenter__(self) -> "WireHarness":
        await self.server.start()
        self.client = WireStore(self.server.target)
        return self

    async def __aexit__(self, *exc):
        if self.client is not None:
            await self.client.close()
        await self.server.stop()
        self.store.stop()


class TestWireCRUD(unittest.TestCase):
    def test_create_get_update_delete(self):
        async def body():
            async with WireHarness() as h:
                c = h.client
                created = await c.create("pods", make_pod("a"))
                self.assertEqual(created["metadata"]["name"], "a")
                self.assertTrue(created["metadata"]["resourceVersion"])
                got = await c.get("pods", "default/a")
                self.assertEqual(got["metadata"]["uid"],
                                 created["metadata"]["uid"])
                got["metadata"]["labels"] = {"x": "1"}
                updated = await c.update("pods", got)
                self.assertGreater(
                    int(updated["metadata"]["resourceVersion"]),
                    int(created["metadata"]["resourceVersion"]))
                await c.delete("pods", "default/a")
                with self.assertRaises(NotFound):
                    await c.get("pods", "default/a")
        run(body())

    def test_error_mapping(self):
        async def body():
            async with WireHarness() as h:
                c = h.client
                await c.create("pods", make_pod("a"))
                with self.assertRaises(AlreadyExists):
                    await c.create("pods", make_pod("a"))
                stale = await c.get("pods", "default/a")
                await c.update("pods", dict(stale))
                with self.assertRaises(Conflict):
                    await c.update("pods", stale)  # old resourceVersion
        run(body())

    def test_guaranteed_update_and_subresource(self):
        async def body():
            async with WireHarness() as h:
                c = h.client
                await c.create("pods", make_pod("a"))

                def label(obj):
                    obj["metadata"].setdefault("labels", {})["k"] = "v"
                    return obj

                out = await c.guaranteed_update("pods", "default/a", label)
                self.assertEqual(out["metadata"]["labels"]["k"], "v")
                await c.create("nodes", make_node("n1"))
                st = await c.subresource("pods", "default/a", "binding", {
                    "target": {"kind": "Node", "name": "n1"}})
                self.assertEqual(st.get("status"), "Success")
                bound = await c.get("pods", "default/a")
                self.assertEqual(bound["spec"]["nodeName"], "n1")
        run(body())

    def test_list_with_selector_and_paging(self):
        async def body():
            async with WireHarness() as h:
                c = h.client
                for i in range(5):
                    await c.create("pods", make_pod(
                        f"p{i}", labels={"odd": str(i % 2)}))
                lst = await c.list(
                    "pods", selector=parse_selector("odd=1"))
                self.assertEqual(
                    sorted(p["metadata"]["name"] for p in lst.items),
                    ["p1", "p3"])
                page = await c.list("pods", limit=2)
                self.assertEqual(len(page.items), 2)
        run(body())

    def test_multi_batches_same_tick_ops(self):
        async def body():
            async with WireHarness() as h:
                c = h.client
                await c.create("nodes", make_node("warm"))  # connect first
                results = await asyncio.gather(*(
                    c.create("pods", make_pod(f"m{i}")) for i in range(64)))
                self.assertEqual(len(results), 64)
                self.assertEqual(len({r["metadata"]["uid"]
                                      for r in results}), 64)
                # Mixed outcomes resolve positionally: dup fails, new works.
                out = await asyncio.gather(
                    c.create("pods", make_pod("m0")),
                    c.create("pods", make_pod("fresh")),
                    return_exceptions=True)
                self.assertIsInstance(out[0], AlreadyExists)
                self.assertEqual(out[1]["metadata"]["name"], "fresh")
        run(body())


class TestWireWatch(unittest.TestCase):
    def test_watch_delivers_events_and_resume(self):
        async def body():
            async with WireHarness() as h:
                c = h.client
                first = await c.create("pods", make_pod("a"))
                rv = int(first["metadata"]["resourceVersion"])
                watch = await c.watch("pods", resource_version=rv)
                await c.create("pods", make_pod("b"))
                await c.delete("pods", "default/b")
                got = []
                async for ev in watch:
                    if ev.type == "BOOKMARK":
                        continue
                    got.append((ev.type, ev.object["metadata"]["name"]))
                    if len(got) == 2:
                        break
                self.assertEqual(got, [("ADDED", "b"), ("DELETED", "b")])
        run(body())

    def test_watch_expired_rv_raises(self):
        async def body():
            store = new_cluster_store()
            store._event_window = 2  # force compaction
            server = WireServer(store)
            await server.start()
            c = WireStore(server.target)
            try:
                for i in range(8):
                    await c.create("pods", make_pod(f"p{i}"))
                with self.assertRaises(Expired):
                    watch = await c.watch("pods", resource_version=1)
                    async for _ev in watch:
                        break
            finally:
                await c.close()
                await server.stop()
                store.stop()
        run(body())

    def test_watch_selector_transitions(self):
        async def body():
            async with WireHarness() as h:
                c = h.client
                base = await c.create(
                    "pods", make_pod("a", labels={"app": "web"}))
                watch = await c.watch(
                    "pods",
                    resource_version=int(
                        base["metadata"]["resourceVersion"]),
                    selector=parse_selector("app=web"))

                def drop_label(obj):
                    obj["metadata"]["labels"] = {}
                    return obj

                await c.guaranteed_update("pods", "default/a", drop_label)
                async for ev in watch:
                    if ev.type == "BOOKMARK":
                        continue
                    # Transition out of the selector set synthesizes
                    # DELETED (cacher prevObject semantics).
                    self.assertEqual(ev.type, "DELETED")
                    self.assertEqual(ev.object["metadata"]["name"], "a")
                    break
        run(body())

    def test_informers_run_over_wire(self):
        async def body():
            async with WireHarness() as h:
                c = h.client
                factory = InformerFactory(c)
                inf = factory.informer("pods")
                factory.start()
                await factory.wait_for_sync()
                await c.create("pods", make_pod("x"))
                for _ in range(100):
                    if inf.indexer.get("default/x") is not None:
                        break
                    await asyncio.sleep(0.01)
                self.assertIsNotNone(inf.indexer.get("default/x"))
                factory.stop()
        run(body())


class TestWireAuth(unittest.TestCase):
    def test_token_authn_and_rbac(self):
        async def body():
            authz = RBACAuthorizer()
            authz.add_role({"metadata": {"name": "reader"},
                            "rules": [{"verbs": ["get", "list", "watch"],
                                       "resources": ["pods"]}]})
            authz.add_binding({
                "roleRef": {"kind": "ClusterRole", "name": "reader"},
                "subjects": [{"kind": "User", "name": "alice"}]})
            store = new_cluster_store()
            install_core_validation(store)
            server = WireServer(store, bearer_tokens={"t-alice": "alice"},
                                authorizer=authz)
            await server.start()
            alice = WireStore(server.target, token="t-alice")
            try:
                await store.create("pods", make_pod("a"))
                got = await alice.get("pods", "default/a")
                self.assertEqual(got["metadata"]["name"], "a")
                from kubernetes_tpu.store.mvcc import StoreError
                with self.assertRaises(StoreError) as cm:
                    await alice.create("pods", make_pod("b"))
                self.assertIn("cannot create", str(cm.exception))
                # Multi path enforces per-op authz identically.
                out = await asyncio.gather(
                    alice.get("pods", "default/a"),
                    alice.create("pods", make_pod("c")),
                    return_exceptions=True)
                self.assertEqual(out[0]["metadata"]["name"], "a")
                self.assertIsInstance(out[1], StoreError)
            finally:
                await alice.close()
                await server.stop()
                store.stop()
        run(body())

    def test_bad_token_rejected(self):
        async def body():
            store = new_cluster_store()
            server = WireServer(store, bearer_tokens={"good": "u"})
            await server.start()
            bad = WireStore(server.target, token="evil")
            try:
                from kubernetes_tpu.store.mvcc import StoreError
                with self.assertRaises(StoreError):
                    await bad.get("pods", "default/a")
            finally:
                await bad.close()
                await server.stop()
                store.stop()
        run(body())


class TestWireUnixSocket(unittest.TestCase):
    def test_uds_roundtrip(self):
        async def body():
            async with WireHarness(host="unix:") as h:
                self.assertTrue(h.server.target.startswith("unix:"))
                created = await h.client.create("pods", make_pod("a"))
                self.assertEqual(created["metadata"]["name"], "a")
        run(body())


if __name__ == "__main__":
    unittest.main()


class TestWireCodec(unittest.TestCase):
    """msgpack is the default frame codec; JSON remains interoperable on
    the same server, detected per frame (wire.py `_decode_frame`)."""

    def test_json_client_interops_with_msgpack_default_server(self):
        async def body():
            async with WireHarness() as h:
                jc = WireStore(h.server.target, enc="json")
                try:
                    created = await jc.create("pods", make_pod("j"))
                    self.assertEqual(created["metadata"]["name"], "j")
                    # msgpack client sees the same object.
                    got = await h.client.get("pods", "default/j")
                    self.assertEqual(got["metadata"]["uid"],
                                     created["metadata"]["uid"])
                finally:
                    await jc.close()
        run(body())

    def test_msgpack_watch_push_and_bookmarkless_resume(self):
        async def body():
            async with WireHarness() as h:
                c = h.client
                w = await c.watch("pods", resource_version=0)
                await c.create("pods", make_pod("m1"))
                ev = await asyncio.wait_for(w.__anext__(), 5)
                self.assertEqual(ev.type, "ADDED")
                self.assertEqual(ev.object["metadata"]["name"], "m1")
                await w.aclose()
        run(body())

    def test_client_watch_queue_bounded_expires_slow_consumer(self):
        async def body():
            async with WireHarness() as h:
                c = h.client
                w = await c.watch("pods", resource_version=0)
                # Find the client-side watch record and shrink its bound
                # so the overflow path triggers without 8k writes.
                wid, rec = next(iter(c._watches.items()))
                rec.MAX_BUFFERED = 4
                for i in range(8):
                    await c.create("pods", make_pod(f"ov-{i}"))
                await asyncio.sleep(0.05)  # let pushes land unconsumed
                # Consumer resumes: sees a few events then the Expired
                # overflow signal; the watch is deregistered client-side.
                with self.assertRaises(Exception) as ctx:
                    for _ in range(10):
                        await asyncio.wait_for(w.__anext__(), 5)
                self.assertIn("overflow", str(ctx.exception))
                self.assertNotIn(wid, c._watches)
        run(body())
