"""Multi-process control plane (ISSUE r22 tentpole): spawn e2e,
quiesced merged-LIST parity against the in-process facade,
restart-under-load with ZERO lost scheduled pods, and kill-the-leader
scheduler failover.

Flags exercised here (the FL304 registry gate greps these names):
KTPU_PROCESSES (process count / `1` kill switch), KTPU_WAL (WAL kill
switch), KTPU_WAL_FSYNC (fsync policy), KTPU_LEASE_DURATION (leader
lease → failover detection time).
"""

import asyncio
import os
import tempfile
import time
import unittest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.multiproc import MultiProcessControlPlane
from kubernetes_tpu.store.mvcc import StoreError
from kubernetes_tpu.utils import flags


def run(coro):
    return asyncio.run(coro)


async def _wait_bound(store, want, timeout_s=90.0):
    """Poll until >= `want` pods carry spec.nodeName; returns the count
    seen last. Tolerates transient wire errors (shard restart windows)."""
    deadline = time.monotonic() + timeout_s
    bound = 0
    while time.monotonic() < deadline:
        try:
            pods = (await store.list("pods")).items
        except StoreError:
            await asyncio.sleep(0.1)
            continue
        bound = sum(1 for p in pods if p["spec"].get("nodeName"))
        if bound >= want:
            return bound
        await asyncio.sleep(0.1)
    return bound


class TestProcessControlPlane(unittest.TestCase):
    def test_spawn_e2e_bind(self):
        """KTPU_PROCESSES=2 spawn path end to end: shard apiserver
        processes boot, the leader-elected scheduler pair binds pods
        through the wire, and the merged LIST sees every shard."""
        async def body():
            with flags.scoped_set("KTPU_PROCESSES", 2):
                nproc = flags.get("KTPU_PROCESSES")
                cp = MultiProcessControlPlane(nproc)
                store = None
                try:
                    await cp.start()
                    await cp.start_schedulers(2)
                    store = cp.client()
                    for i in range(4):
                        await store.create("nodes", make_node(f"n{i}"))
                    for i in range(6):
                        await store.create("pods", make_pod(f"p{i}"))
                    self.assertEqual(await _wait_bound(store, 6), 6)
                    topo = await store.control_topology()
                    self.assertEqual(topo["nodeShards"], 2)
                    nodes = await store.list("nodes")
                    self.assertEqual(
                        sorted(n["metadata"]["name"] for n in nodes.items),
                        [f"n{i}" for i in range(4)])
                finally:
                    if store is not None:
                        await store.close()
                    await cp.stop()
        run(body())

    def test_quiesced_merged_list_parity(self):
        """The cross-process differential: on a QUIESCED store (no
        in-flight writes) the weaker merged-LIST contract coincides
        with the in-process facade's — same routing, same merged sort
        order, same per-shard membership, same merged RV."""
        async def body():
            from kubernetes_tpu.store.sharded import ShardedNodeStore
            inproc = ShardedNodeStore(2)
            cp = MultiProcessControlPlane(2)
            store = None
            try:
                await cp.start()
                store = cp.client()
                for i in range(17):
                    node = f"node-{i:03d}"
                    await inproc.create("nodes", make_node(node))
                    await store.create("nodes", make_node(node))
                for i in range(9):
                    pod = f"pod-{i:03d}"
                    await inproc.create("pods", make_pod(pod))
                    await store.create("pods", make_pod(pod))
                for resource in ("nodes", "pods"):
                    a = await inproc.list(resource)
                    b = await store.list(resource)
                    self.assertEqual(
                        [o["metadata"]["name"] for o in a.items],
                        [o["metadata"]["name"] for o in b.items])
                    self.assertEqual(a.resource_version,
                                     b.resource_version)
                # per-shard membership matches the crc32 routing table
                for shard in range(2):
                    a = await inproc.list("nodes", shard=shard)
                    b = await store.list("nodes", shard=shard)
                    self.assertEqual(
                        [o["metadata"]["name"] for o in a.items],
                        [o["metadata"]["name"] for o in b.items])
            finally:
                if store is not None:
                    await store.close()
                await cp.stop()
        run(body())

    def test_restart_under_load_zero_lost_pods(self):
        """The tier-1 restart smoke: SIGKILL the meta shard (pods +
        bindings) mid-churn with KTPU_WAL fsync=always, restart it on
        the same data dir, and prove ZERO acknowledged pods were lost
        and recovery stayed bounded."""
        async def body():
            d = tempfile.mkdtemp()
            with flags.scoped_set("KTPU_WAL", 1), \
                    flags.scoped_set("KTPU_WAL_FSYNC", "always"):
                cp = MultiProcessControlPlane(2, data_dir=d)
                store = None
                try:
                    await cp.start()
                    await cp.start_schedulers(2)
                    store = cp.client()
                    for i in range(3):
                        await store.create("nodes", make_node(f"n{i}"))

                    acked = []
                    stop_churn = asyncio.Event()

                    async def churn():
                        i = 0
                        while not stop_churn.is_set():
                            name = f"c{i}"
                            i += 1
                            try:
                                await store.create(
                                    "pods", make_pod(name))
                            except StoreError:
                                # shard-down window: this create was
                                # never acknowledged — not counted.
                                await asyncio.sleep(0.05)
                                continue
                            acked.append(name)
                            await asyncio.sleep(0.01)

                    task = asyncio.ensure_future(churn())
                    await asyncio.sleep(0.6)     # pods flowing
                    await cp.kill_shard(0)       # SIGKILL: no flush
                    await asyncio.sleep(0.3)     # churn hits the hole
                    t0 = time.monotonic()
                    await cp.restart_shard(0)    # snapshot + WAL replay
                    recovery_s = time.monotonic() - t0
                    await asyncio.sleep(0.6)     # churn resumes
                    stop_churn.set()
                    await task

                    self.assertLess(recovery_s, 30.0,
                                    "recovery not bounded")
                    self.assertGreater(len(acked), 10,
                                       "churn never got going")
                    survivors = {p["metadata"]["name"]
                                 for p in (await store.list("pods")).items}
                    lost = [n for n in acked if n not in survivors]
                    self.assertEqual(lost, [],
                                     f"acknowledged pods lost: {lost}")
                    # every surviving pod ends up scheduled
                    want = len(survivors)
                    self.assertEqual(
                        await _wait_bound(store, want), want)
                finally:
                    if store is not None:
                        await store.close()
                    await cp.stop()
        run(body())

    def test_leader_failover_and_post_failover_binding(self):
        """Kill the lease-holding scheduler replica: the standby takes
        over on lease EXPIRY (KTPU_LEASE_DURATION sets the detection
        floor) and keeps binding."""
        async def body():
            with flags.scoped_set("KTPU_LEASE_DURATION", 2.0):
                cp = MultiProcessControlPlane(1)
                store = None
                try:
                    await cp.start()
                    await cp.start_schedulers(2)
                    store = cp.client()
                    await store.create("nodes", make_node("n0"))
                    await store.create("pods", make_pod("before"))
                    self.assertEqual(await _wait_bound(store, 1), 1)

                    leader = None
                    for _ in range(300):
                        leader = await cp.leader_identity()
                        if leader:
                            break
                        await asyncio.sleep(0.1)
                    self.assertIsNotNone(leader, "no leader elected")

                    t0 = time.monotonic()
                    killed = await cp.kill_leader()
                    self.assertEqual(killed, leader)
                    new = None
                    while time.monotonic() - t0 < 60.0:
                        new = await cp.leader_identity()
                        if new and new != killed:
                            break
                        await asyncio.sleep(0.1)
                    ttr = time.monotonic() - t0
                    self.assertTrue(new and new != killed,
                                    "standby never took over")
                    self.assertLess(ttr, 60.0)

                    await store.create("pods", make_pod("after-failover"))
                    self.assertEqual(await _wait_bound(store, 2), 2)
                finally:
                    if store is not None:
                        await store.close()
                    await cp.stop()
        run(body())


if __name__ == "__main__":
    unittest.main()
