"""Randomized differential parity: class-dictionary device planes vs the
per-pod plane fallback (ISSUE r14 acceptance: bit-identical assignments).

The class format reorganizes WHAT the solve pipeline ships and computes
— (C, N) equivalence-class planes + a (P,) index + a sparse exception
column instead of per-pod (P, N) planes — but must not move a single
assignment: the class rows carry exactly the rows every member pod would
have carried, exceptions intersect exactly the single-column host rows
they replace, and the shortlist's exactness bound covers the pinned-pod
corner (a pin outside its class shortlist falls back to the full row).
These tests run the same randomized workloads through both formats
(KTPU_CLASS_PLANES=0 is the structural per-pod degrade) and require the
assignment maps to be EQUAL, including the None (unschedulable) entries,
across tight-capacity contention, affinity/score families, hard spread,
the shortlist regime, control-plane shards {1, 4, 8}, and the two
adversarial extremes (every pod its own class; one class for all).
"""

from __future__ import annotations

import asyncio
import random
import time

import pytest

from kubernetes_tpu.api.meta import namespaced_name
from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client import InformerFactory, ResourceEventHandler
from kubernetes_tpu.metrics.registry import SchedulerMetrics
from kubernetes_tpu.ops import TPUBackend
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.types import PodInfo
from kubernetes_tpu.store import install_core_validation, new_cluster_store

from test_tpu_backend import default_fwk, random_cluster, random_pending

ZONES = ("a", "b", "c")


def _class_env(monkeypatch, on: bool, pad: int | None = None) -> None:
    if on:
        monkeypatch.delenv("KTPU_CLASS_PLANES", raising=False)
        if pad is None:
            monkeypatch.delenv("KTPU_CLASS_PAD", raising=False)
        else:
            monkeypatch.setenv("KTPU_CLASS_PAD", str(pad))
    else:
        monkeypatch.setenv("KTPU_CLASS_PLANES", "0")


def _assign(pods, snap, fwk, monkeypatch, on: bool, pad=None, chunk=32):
    _class_env(monkeypatch, on, pad)
    b = TPUBackend(max_batch=chunk, mesh=None)
    b.metrics = SchedulerMetrics()
    assignments, _diags = b.assign(pods, snap, fwk)
    return assignments, b.metrics


def _parity(pods, snap, monkeypatch, chunk=32, pad=None):
    fwk = default_fwk()
    dense, _ = _assign(pods, snap, fwk, monkeypatch, on=False, chunk=chunk)
    got, m = _assign(pods, snap, fwk, monkeypatch, on=True, pad=pad,
                     chunk=chunk)
    assert got == dense, {
        k: (got[k], dense[k]) for k in got if got[k] != dense[k]}
    return dense, m


def _labeled_cluster(seed: int, n_nodes: int = 40):
    """Zone-labeled nodes via the real cache (honest aggregates)."""
    from kubernetes_tpu.scheduler.cache import SchedulerCache
    rng = random.Random(seed)
    cache = SchedulerCache()
    for i in range(n_nodes):
        cache.add_node(make_node(
            f"n{i}",
            allocatable={"cpu": str(rng.choice((4, 8, 16))),
                         "memory": rng.choice(("16Gi", "64Gi")),
                         "pods": "110"},
            labels={"zone": rng.choice(ZONES), "disk": "ssd"}))
    return cache.update_snapshot()


class TestBackendParity:
    @pytest.mark.parametrize("seed", [3, 17])
    def test_tight_capacity_contention(self, seed, monkeypatch):
        rng = random.Random(seed)
        snap = random_cluster(rng, 32, resident_per_node=4)
        pods = random_pending(rng, 96)
        _parity(pods, snap, monkeypatch, chunk=32)

    def test_affinity_and_score_rows(self, monkeypatch):
        snap = _labeled_cluster(7)
        rng = random.Random(7)
        pods = []
        for i in range(48):
            kw = dict(requests={"cpu": "250m", "memory": "256Mi"},
                      labels={"app": rng.choice(("web", "db"))},
                      uid=f"uid-{i}")
            roll = rng.random()
            if roll < 0.3:
                kw["node_selector"] = {"zone": rng.choice(ZONES)}
            elif roll < 0.6:
                kw["affinity"] = {"podAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution": [{
                        "weight": 50,
                        "podAffinityTerm": {
                            "topologyKey": "zone",
                            "labelSelector": {"matchLabels": {
                                "app": kw["labels"]["app"]}}}}]}}
            elif roll < 0.8:
                kw["affinity"] = {"nodeAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution": [{
                        "weight": 10,
                        "preference": {"matchExpressions": [{
                            "key": "zone", "operator": "In",
                            "values": [rng.choice(ZONES)]}]}}]}}
            pods.append(PodInfo(make_pod(f"pend-{i}", **kw)))
        dense, m = _parity(pods, snap, monkeypatch, chunk=16)
        assert any(v is not None for v in dense.values())
        # The run really exercised multi-class dirty planes.
        assert m.plane_classes.value() >= 2
        assert m.plane_bytes.value() > 0

    def test_hard_spread(self, monkeypatch):
        snap = _labeled_cluster(11, n_nodes=24)
        cons = [{"maxSkew": 1, "topologyKey": "zone",
                 "whenUnsatisfiable": "DoNotSchedule",
                 "labelSelector": {"matchLabels": {"app": "spread"}}}]
        pods = [PodInfo(make_pod(
            f"sp-{i}", requests={"cpu": "100m", "memory": "128Mi"},
            labels={"app": "spread"}, topology_spread_constraints=cons,
            uid=f"su-{i}")) for i in range(30)]
        # Interleave unconstrained pods so contribute-only chunks and the
        # spread scan both run under class planes.
        pods += [PodInfo(make_pod(
            f"pl-{i}", requests={"cpu": "200m", "memory": "128Mi"},
            labels={"app": "spread"}, uid=f"pu-{i}")) for i in range(10)]
        _parity(pods, snap, monkeypatch, chunk=16)

    def test_shortlist_regime(self, monkeypatch):
        """Above the activation threshold the class path prunes (dense
        fallback keeps the full scan) — assignments still identical."""
        from kubernetes_tpu.scheduler.cache import SchedulerCache
        cache = SchedulerCache()
        for i in range(160):
            cache.add_node(make_node(
                f"n{i}", allocatable={"cpu": "8", "memory": "32Gi",
                                      "pods": "110"}))
        snap = cache.update_snapshot()
        pods = [PodInfo(make_pod(
            f"pend-{i}", requests={"cpu": "500m", "memory": "512Mi"},
            uid=f"uid-{i}")) for i in range(40)]
        fwk = default_fwk()
        dense, md = _assign(pods, snap, fwk, monkeypatch, on=False,
                            chunk=16)
        got, mc = _assign(pods, snap, fwk, monkeypatch, on=True, chunk=16)
        assert got == dense
        assert mc.solver_shortlist_pods.value() == len(pods)
        assert md.solver_shortlist_pods.value() == 0

    def test_all_pods_distinct_c_equals_p(self, monkeypatch):
        """Adversarial extreme: every pod a distinct request shape. With
        a big pad the class build carries C == P real classes; past the
        pad it falls back per-pod — all three agree."""
        rng = random.Random(29)
        snap = random_cluster(rng, 24, resident_per_node=2)
        pods = [PodInfo(make_pod(
            f"pend-{i}", requests={"cpu": f"{100 + 7 * i}m",
                                   "memory": f"{64 + 3 * i}Mi"},
            uid=f"uid-{i}")) for i in range(40)]
        fwk = default_fwk()
        dense, _ = _assign(pods, snap, fwk, monkeypatch, on=False, chunk=64)
        wide, mw = _assign(pods, snap, fwk, monkeypatch, on=True, pad=64,
                           chunk=64)
        over, mo = _assign(pods, snap, fwk, monkeypatch, on=True, pad=8,
                           chunk=64)
        assert wide == dense and over == dense
        assert mw.plane_classes.value() == len(pods)          # C == P
        assert mo.class_split_fallbacks.value() == len(pods)  # overflow

    def test_single_class_c_equals_1(self, monkeypatch):
        rng = random.Random(31)
        snap = random_cluster(rng, 24, resident_per_node=2)
        pods = [PodInfo(make_pod(
            f"pend-{i}", requests={"cpu": "300m", "memory": "256Mi"},
            uid=f"uid-{i}")) for i in range(48)]
        _, m = _parity(pods, snap, monkeypatch, chunk=16)
        assert m.plane_classes.value() == 1

    def test_pinned_pods_with_scores_share_class(self, monkeypatch):
        """Pins × score plugins: a pinned pod's normalized score row is
        computed over its pin-restricted feasible set (per-pod unique),
        but a single-column argmax is score-invariant — so its parts
        are dropped from the class key and pinned pods coalesce into
        ONE scoreless class per template instead of one class per pin
        (no overflow fallback), still bit-identical to per-pod planes."""
        snap = _labeled_cluster(19, n_nodes=36)
        pods = []
        for i in range(36):
            kw = dict(requests={"cpu": "250m", "memory": "256Mi"},
                      uid=f"uid-{i}",
                      affinity={"nodeAffinity": {
                          "preferredDuringSchedulingIgnoredDuringExecution":
                          [{"weight": 10,
                            "preference": {"matchExpressions": [{
                                "key": "zone", "operator": "In",
                                "values": ["a"]}]}}]}})
            if i % 3 == 0:
                kw["node_name"] = f"n{i}"
            pods.append(PodInfo(make_pod(f"pend-{i}", **kw)))
        dense, m = _parity(pods, snap, monkeypatch, chunk=36)
        # One scored class + one pinned scoreless class, NOT 12 pin
        # classes and NOT a per-pod fallback.
        assert m.plane_classes.value() == 2
        assert m.class_split_fallbacks.value() == 0
        for i in range(0, 36, 3):
            assert dense[pods[i].key] == f"n{i}"

    def test_exception_pins_share_class(self, monkeypatch):
        """NodeName single-column rows ride the exception vector: pinned
        pods keep their template's class (C stays 1), land exactly on
        the named node, and match the per-pod fallback bit for bit."""
        from kubernetes_tpu.scheduler.cache import SchedulerCache
        cache = SchedulerCache()
        for i in range(160):
            cache.add_node(make_node(
                f"n{i}", allocatable={"cpu": "8", "memory": "32Gi",
                                      "pods": "110"}))
        snap = cache.update_snapshot()
        pods = []
        for i in range(32):
            kw = dict(requests={"cpu": "500m", "memory": "512Mi"},
                      uid=f"uid-{i}")
            if i % 4 == 0:
                kw["node_name"] = f"n{100 + i}"
            pods.append(PodInfo(make_pod(f"pend-{i}", **kw)))
        dense, m = _parity(pods, snap, monkeypatch, chunk=16)
        assert m.plane_classes.value() == 1  # pins did NOT split classes
        for i in range(0, 32, 4):
            assert dense[pods[i].key] == f"n{100 + i}"


class TestShardedSolverClassPlanes:
    @pytest.mark.parametrize("shortlist_k", [0, 4])
    def test_class_planes_match_per_pod_reference(self, shortlist_k):
        """parallel/sharded.py's class form (rows/exc/row_req) against
        the single-chip per-pod reference: pods gather class rows, the
        exception column translates to shard-local coordinates (the
        pinned column lives on a non-zero shard), and the shard-local
        prefilter runs over C class rows."""
        import numpy as np
        import jax.numpy as jnp
        from kubernetes_tpu.ops import solver
        from kubernetes_tpu.parallel import build_mesh, sharded_greedy_assign

        rng = np.random.default_rng(23)
        N, P, C, R = 32, 8, 2, 2
        alloc_q = rng.integers(8_000, 32_000, size=(N, R)).astype(np.int32)
        used_q = (alloc_q * 0.2).astype(np.int32)
        free_pods = np.full((N,), 110, np.int32)
        c_req = rng.integers(500, 4_000, size=(C, R)).astype(np.int32)
        cls = (np.arange(P) % C).astype(np.int32)
        req_q = c_req[cls]
        mask_c = rng.random((C, N)) < 0.9
        sc_c = rng.uniform(0, 5, size=(C, N)).astype(np.float32)
        exc = np.full((P,), -1, np.int32)
        exc[3] = 27   # pinned into the last shard of a 4-way mesh
        exc[5] = 2
        # Per-pod reference: gather class rows, fold pins into the mask.
        mask_p = mask_c[cls].copy()
        sc_p = sc_c[cls]
        for i, e in enumerate(exc):
            if e >= 0:
                keep = mask_p[i, e]
                mask_p[i, :] = False
                mask_p[i, e] = keep
        shape = (np.zeros((2,), np.float32), np.zeros((2,), np.float32))
        col_w = np.ones((R,), np.float32)
        col_m = np.ones((R,), np.bool_)
        single = np.asarray(solver.greedy_assign_rescoring(
            jnp.asarray(req_q), jnp.asarray(req_q),
            jnp.asarray(alloc_q - used_q), jnp.asarray(free_pods),
            jnp.asarray(used_q), jnp.asarray(alloc_q),
            jnp.asarray(mask_p), jnp.asarray(sc_p),
            jnp.asarray(col_w), jnp.asarray(col_m),
            jnp.asarray(shape[0]), jnp.asarray(shape[1]),
            jnp.float32(1.0), jnp.float32(1.0),
            strategy="LeastAllocated"))
        sharded = np.asarray(sharded_greedy_assign(
            build_mesh(4), jnp.asarray(req_q), jnp.asarray(req_q),
            jnp.asarray(alloc_q - used_q), jnp.asarray(free_pods),
            jnp.asarray(used_q), jnp.asarray(alloc_q),
            jnp.asarray(mask_c), jnp.asarray(sc_c),
            jnp.asarray(col_w), jnp.asarray(col_m),
            shape[0], shape[1], 1.0, 1.0, "LeastAllocated",
            shortlist_k=shortlist_k, rows=cls, exc=exc,
            row_req_q=c_req, row_req_nz_q=c_req))
        np.testing.assert_array_equal(single, sharded)
        assert sharded[3] in (27, -1)
        if sharded[3] >= 0:
            assert sharded[3] == 27


async def _schedule_e2e(store, nodes, pods, batch: int = 64) -> dict:
    """End-to-end through store + informers + scheduler (the
    test_sharded_parity driver): returns {pod key: node name}."""
    install_core_validation(store)
    for spec in nodes:
        await store.create("nodes", make_node(**spec))
    sched = Scheduler(store, seed=42, backend=TPUBackend(max_batch=batch),
                      metrics=SchedulerMetrics())
    factory = InformerFactory(store)
    await sched.setup_informers(factory)
    bound: dict[str, str] = {}

    def track(obj):
        node = obj.get("spec", {}).get("nodeName")
        if node:
            bound[namespaced_name(obj)] = node

    factory.informer("pods").add_event_handler(ResourceEventHandler(
        on_add=track, on_update=lambda old, new: track(new)))
    factory.start()
    await factory.wait_for_sync()
    run_task = asyncio.ensure_future(sched.run(batch_size=batch))
    try:
        for spec in pods:
            await store.create("pods", make_pod(**spec))
        deadline = time.monotonic() + 60
        while len(bound) < len(pods):
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {len(bound)}/{len(pods)} pods bound")
            await asyncio.sleep(0.01)
    finally:
        await sched.stop()
        run_task.cancel()
        factory.stop()
        store.stop()
    return dict(bound)


def _sharded_workload(seed: int, n_nodes: int = 48, n_pods: int = 96):
    rng = random.Random(seed)
    nodes = [dict(
        name=f"n-{i:03d}",
        allocatable={"cpu": str(rng.choice((4, 8, 16))),
                     "memory": rng.choice(("16Gi", "32Gi", "64Gi")),
                     "pods": "110"},
        labels={"zone": rng.choice(ZONES)}) for i in range(n_nodes)]
    pods = []
    for i in range(n_pods):
        spec = dict(
            name=f"p-{i:03d}",
            requests={"cpu": f"{rng.choice((100, 250, 500))}m",
                      "memory": rng.choice(("128Mi", "256Mi", "512Mi"))})
        if rng.random() < 0.3:
            spec["node_selector"] = {"zone": rng.choice(ZONES)}
        pods.append(spec)
    return nodes, pods


def test_sharded_control_plane_parity(monkeypatch):
    """Class planes vs per-pod planes, end to end through the sharded
    control plane at shard counts {1, 4, 8}: every configuration must
    produce the SAME assignment map as the unsharded per-pod reference."""
    async def go():
        nodes, pods = _sharded_workload(13)
        _class_env(monkeypatch, on=False)
        reference = await _schedule_e2e(new_cluster_store(), nodes, pods)
        assert len(reference) == len(pods)
        _class_env(monkeypatch, on=True)
        for shards in (1, 4, 8):
            got = await _schedule_e2e(
                new_cluster_store(shards=shards), nodes, pods)
            assert got == reference, (
                f"shards={shards}: "
                f"{sum(1 for k in got if got[k] != reference.get(k))} "
                f"assignments diverged")
    asyncio.run(go())
