"""Prometheus exposition lint (tier-1): the full default registry must
render valid text format line-by-line — HELP/TYPE pairing, label
escaping, sample-name/metric-name agreement, no duplicate registration
across the WatchMetrics/SchedulerMetrics/APIServerMetrics/audit/policy
register_into paths — plus the Gauge TYPE-line regression and the exact
windowed-percentile recorder.
"""

import asyncio
import math
import re

from kubernetes_tpu.metrics.registry import (
    APIServerMetrics,
    Counter,
    Gauge,
    Histogram,
    Registry,
    SchedulerMetrics,
    WatchMetrics,
    WindowedLatencyRecorder,
)

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_HELP_RE = re.compile(rf"^# HELP ({_NAME})(?: (.*))?$")
_TYPE_RE = re.compile(rf"^# TYPE ({_NAME}) (counter|gauge|histogram|summary)$")
_SAMPLE_RE = re.compile(
    rf"^({_NAME})(?:\{{(.*)\}})? (-?[0-9.e+-]+|NaN|[+-]Inf)$")
_LABEL_RE = re.compile(rf'({_NAME})="((?:[^"\\\n]|\\\\|\\"|\\n)*)"')


def validate_exposition(text: str) -> list[str]:
    """Line-by-line Prometheus text-format check. Returns the metric
    names seen (in order), raising AssertionError with the offending
    line on any violation."""
    seen_types: dict[str, str] = {}
    current: str | None = None
    pending_help: str | None = None
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        m = _HELP_RE.match(line)
        if m:
            assert pending_help is None, \
                f"line {lineno}: HELP {m.group(1)} follows unpaired HELP"
            pending_help = m.group(1)
            continue
        m = _TYPE_RE.match(line)
        if m:
            name = m.group(1)
            # HELP must immediately precede TYPE for the same metric
            assert pending_help == name, \
                f"line {lineno}: TYPE {name} not preceded by its HELP " \
                f"(got {pending_help!r})"
            pending_help = None
            assert name not in seen_types, \
                f"line {lineno}: duplicate TYPE for {name} " \
                "(double registration)"
            seen_types[name] = m.group(2)
            current = name
            continue
        assert pending_help is None, \
            f"line {lineno}: HELP {pending_help} not followed by TYPE"
        m = _SAMPLE_RE.match(line)
        assert m, f"line {lineno}: unparseable sample line {line!r}"
        sample, labels = m.group(1), m.group(2)
        assert current is not None, \
            f"line {lineno}: sample before any TYPE"
        allowed = {current}
        if seen_types[current] == "histogram":
            allowed = {f"{current}_bucket", f"{current}_sum",
                       f"{current}_count"}
        assert sample in allowed, \
            f"line {lineno}: sample {sample!r} under metric {current!r}"
        if labels:
            # the whole label body must be well-formed pairs (catches
            # unescaped quotes/newlines/backslashes)
            stripped = _LABEL_RE.sub("", labels).replace(",", "")
            assert stripped == "", \
                f"line {lineno}: malformed labels {labels!r}"
    assert pending_help is None, f"dangling HELP {pending_help}"
    return list(seen_types)


class TestGaugeRender:
    def test_type_line_is_gauge_even_when_help_mentions_counter(self):
        """Regression: the old render derived TYPE by replacing the first
        'counter' substring — corrupting the HELP line whenever the help
        text itself contained the word."""
        g = Gauge("queue_depth", "a counter of queued items")
        g.set(3.0)
        out = g.render()
        assert "# HELP queue_depth a counter of queued items" in out
        assert "# TYPE queue_depth gauge" in out
        assert "counter" not in out.splitlines()[1]

    def test_plain_gauge(self):
        g = Gauge("g", "help", labels=("k",))
        g.set(1.5, k="v")
        validate_exposition(g.render())


class TestLabelEscaping:
    def test_quotes_backslashes_newlines_escape(self):
        c = Counter("c_total", "help", labels=("sel",))
        c.inc(sel='app="x",\\tier\nblue')
        out = c.render()
        validate_exposition(out)
        line = out.splitlines()[-1]
        assert '\\"x\\"' in line and "\\\\tier" in line and "\\n" in line
        assert "\n" not in line

    def test_help_newline_escapes(self):
        c = Counter("c_total", "line one\nline two")
        out = c.render()
        assert out.splitlines()[0] == "# HELP c_total line one\\nline two"
        validate_exposition(out)

    def test_histogram_label_escaping(self):
        h = Histogram("h_seconds", "help", labels=("who",))
        h.observe(0.01, who='say "hi"')
        validate_exposition(h.render())


class TestExpositionLint:
    def _full_registry(self) -> Registry:
        """Every register_into path the servers actually compose onto one
        /metrics endpoint."""
        from kubernetes_tpu.policy.audit import AuditSink
        from kubernetes_tpu.policy.vap import PolicyEngine
        from kubernetes_tpu.store import new_cluster_store
        r = Registry()
        sm = SchedulerMetrics(r)
        sm.observe_attempt("scheduled", "default-scheduler", 0.004)
        sm.observe_plugin("NodeResourcesFit", "Filter", 0.0001)
        sm.set_pending({"active": 1, "backoff": 0})
        sm.solve_duration.observe(0.002)
        wm = WatchMetrics()
        wm.events_dispatched.inc()
        wm.register_into(r)
        am = APIServerMetrics()
        am.observe("create", "pods", 201, 0.001)
        am.inc_inflight("create")
        am.dec_inflight("create")
        am.register_into(r)
        sink = AuditSink()
        sink.events_total.inc(stage="ResponseComplete")
        sink.register_into(r)
        store = new_cluster_store()
        engine = PolicyEngine(store)
        engine.register_into(r)
        store.stop()
        return r

    def test_full_default_registry_renders_clean(self):
        names = validate_exposition(self._full_registry().render())
        # the families this PR's contract names must all be present
        for want in ("scheduler_scheduling_attempt_duration_seconds",
                     "scheduler_tpu_solve_seconds",
                     "watch_events_dispatched_total",
                     "apiserver_request_duration_seconds",
                     "apiserver_current_inflight_requests",
                     "audit_events_total",
                     "policy_evaluations_total"):
            assert want in names, (want, names)

    def test_register_into_is_idempotent(self):
        """Registering the same family twice (both wires share one
        registry) must not duplicate HELP/TYPE blocks."""
        r = self._full_registry()
        WatchMetrics().register_into(r)  # same names, different objects
        am = APIServerMetrics()
        am.register_into(r)
        validate_exposition(r.render())  # duplicate TYPE would assert

    def test_apiserver_metrics_on_both_wires(self):
        """The request-duration family observes from the HTTP middleware
        AND the KTPU wire into one shared instance at /metrics."""
        from kubernetes_tpu.api.types import make_pod
        from kubernetes_tpu.apiserver import APIServer, RemoteStore
        from kubernetes_tpu.apiserver.wire import WireServer, WireStore
        from kubernetes_tpu.store import (
            install_core_validation,
            new_cluster_store,
        )

        async def body():
            backing = new_cluster_store()
            install_core_validation(backing)
            registry = Registry()
            api = APIServer(backing, metrics_registry=registry)
            await api.start()
            wire = WireServer.for_apiserver(api, host="unix:")
            await wire.start()
            rs = RemoteStore(api.url)
            ws = WireStore(wire.target)
            try:
                await rs.create("pods", make_pod("via-http"))
                await ws.create("pods", make_pod("via-wire"))
                await ws.get("pods", "default/via-wire")
                # rendered through the server's /metrics endpoint
                import aiohttp
                async with aiohttp.ClientSession() as s:
                    async with s.get(f"{api.url}/metrics") as resp:
                        text = await resp.text()
            finally:
                await rs.close()
                await ws.close()
                await wire.stop()
                await api.stop()
                backing.stop()
            validate_exposition(text)
            m = api.request_metrics
            assert m.request_duration.count(
                verb="create", resource="pods", code="201") == 2
            assert m.request_duration.count(
                verb="get", resource="pods", code="200") == 1
            # inflight settles back to zero on both kinds
            assert m.inflight.value(request_kind="mutating") == 0
            assert ('apiserver_request_duration_seconds_bucket'
                    in text)
            assert 'apiserver_current_inflight_requests' in text
        asyncio.run(body())


class TestWindowedLatencyRecorder:
    def test_exact_percentiles(self):
        w = WindowedLatencyRecorder(capacity=4096)
        mark = w.mark()
        for i in range(1, 1001):  # 1..1000 ms
            w.observe(i / 1000.0)
        got = w.percentiles_since(mark, (0.50, 0.99, 0.999))
        assert got[0.50] == 0.500   # exact, not a bucket edge
        assert got[0.99] == 0.990
        assert got[0.999] == 0.999

    def test_window_isolation(self):
        """Observations before the mark never leak into the window."""
        w = WindowedLatencyRecorder(capacity=64)
        for _ in range(10):
            w.observe(100.0)  # warmup junk
        mark = w.mark()
        for v in (1.0, 2.0, 3.0):
            w.observe(v)
        got = w.percentiles_since(mark, (0.5, 1.0))
        assert got[0.5] == 2.0
        assert got[1.0] == 3.0

    def test_empty_window_is_nan(self):
        w = WindowedLatencyRecorder()
        got = w.percentiles_since(w.mark(), (0.5, 0.999))
        assert math.isnan(got[0.5]) and math.isnan(got[0.999])

    def test_overflow_keeps_newest_tail(self):
        w = WindowedLatencyRecorder(capacity=8)
        mark = w.mark()
        for i in range(100):
            w.observe(float(i))
        got = w.percentiles_since(mark, (0.0, 1.0))
        # window larger than capacity degrades to the newest 8 values
        assert got[0.0] == 92.0
        assert got[1.0] == 99.0

    def test_rides_observe_attempt(self):
        sm = SchedulerMetrics()
        mark = sm.attempt_window().mark()
        for ms in (1, 2, 3, 4, 5):
            sm.observe_attempt("scheduled", "default-scheduler",
                               ms / 1000.0)
        sm.observe_attempt("unschedulable", "default-scheduler", 9.0)
        got = sm.attempt_window().percentiles_since(mark, (1.0,))
        assert got[1.0] == 0.005  # failures ride their own window
        assert sm.attempt_window("unschedulable").count_since(0) == 1
