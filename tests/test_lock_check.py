"""Runtime lock-order / dispatch-hygiene detector (utils/locking.py).

Pins: (a) `new_lock` is a plain `threading.Lock` with the flag off —
zero overhead, no bookkeeping; (b) with `KTPU_LOCK_CHECK=1` a
deliberately inverted two-lock pattern raises `LockOrderError` on the
FIRST inversion (no unlucky interleaving needed); (c) the sanctioned
dispatch seams raise when entered with an instrumented lock held;
(d) the metrics registry rides the detector cleanly (its single-lock
discipline produces no false positives under render-vs-inc load).
"""

import threading

import pytest

from kubernetes_tpu.utils import locking
from kubernetes_tpu.utils.locking import (
    InstrumentedLock,
    LockHeldAcrossDispatchError,
    LockOrderError,
    new_lock,
)


@pytest.fixture(autouse=True)
def _clean_graph():
    locking.reset_observed()
    yield
    locking.reset_observed()


class TestZeroOverheadOff:
    def test_plain_lock_when_disabled(self, monkeypatch):
        monkeypatch.delenv("KTPU_LOCK_CHECK", raising=False)
        lk = new_lock("anything")
        assert not isinstance(lk, InstrumentedLock)
        assert type(lk) is type(threading.Lock())
        with lk:
            # a plain lock never participates in seam checks
            locking.check_dispatch_seam("test.seam")

    def test_explicit_zero_disables(self, monkeypatch):
        monkeypatch.setenv("KTPU_LOCK_CHECK", "0")
        assert not isinstance(new_lock("x"), InstrumentedLock)


class TestInversionDetection:
    def test_inverted_two_lock_pattern_raises(self, monkeypatch):
        monkeypatch.setenv("KTPU_LOCK_CHECK", "1")
        a, b = new_lock("A"), new_lock("B")
        assert isinstance(a, InstrumentedLock)
        with a:
            with b:
                pass
        # the deliberate inversion: B then A
        with b:
            with pytest.raises(LockOrderError) as exc:
                with a:
                    pass  # pragma: no cover - acquire raises first
            assert "A" in str(exc.value) and "B" in str(exc.value)

    def test_consistent_order_never_raises(self, monkeypatch):
        monkeypatch.setenv("KTPU_LOCK_CHECK", "1")
        a, b = new_lock("A"), new_lock("B")
        for _ in range(3):
            with a:
                with b:
                    pass

    def test_same_name_instances_exempt(self, monkeypatch):
        # Counter instances all share the name "metrics.<name>": nesting
        # two interchangeable instances is not an ordering fact.
        monkeypatch.setenv("KTPU_LOCK_CHECK", "1")
        a1, a2 = new_lock("metrics.same"), new_lock("metrics.same")
        with a1:
            with a2:
                pass
        with a2:
            with a1:
                pass

    def test_inversion_detected_across_threads(self, monkeypatch):
        monkeypatch.setenv("KTPU_LOCK_CHECK", "1")
        a, b = new_lock("A"), new_lock("B")
        with a:
            with b:
                pass
        errors = []

        def invert():
            try:
                b.acquire()
                try:
                    a.acquire()
                    a.release()
                finally:
                    b.release()
            except LockOrderError as e:
                errors.append(e)

        t = threading.Thread(target=invert)
        t.start()
        t.join()
        assert len(errors) == 1


class TestDispatchSeam:
    def test_raises_while_holding(self, monkeypatch):
        monkeypatch.setenv("KTPU_LOCK_CHECK", "1")
        lk = new_lock("store.cacher")
        with lk:
            with pytest.raises(LockHeldAcrossDispatchError) as exc:
                locking.check_dispatch_seam("backend.fetch_assign")
            assert "store.cacher" in str(exc.value)
        # released: the seam is clean again
        locking.check_dispatch_seam("backend.fetch_assign")

    def test_held_locks_introspection(self, monkeypatch):
        monkeypatch.setenv("KTPU_LOCK_CHECK", "1")
        a, b = new_lock("A"), new_lock("B")
        assert locking.held_locks() == ()
        with a:
            with b:
                assert locking.held_locks() == ("A", "B")
        assert locking.held_locks() == ()


class TestMetricsIntegration:
    def test_registry_rides_the_detector(self, monkeypatch):
        """Counter/Histogram under KTPU_LOCK_CHECK=1: instrumented locks,
        no false positives from inc-vs-render (the LK205 fix snapshots
        under the lock, never nests)."""
        monkeypatch.setenv("KTPU_LOCK_CHECK", "1")
        from kubernetes_tpu.metrics.registry import Counter, Histogram
        c = Counter("test_lockcheck_total", "t", labels=("k",))
        assert isinstance(c._lock, InstrumentedLock)
        h = Histogram("test_lockcheck_seconds", "t")
        done = []

        def writer():
            for i in range(500):
                c.inc(k=str(i % 7))
                h.observe(0.001 * i)
            done.append(True)

        threads = [threading.Thread(target=writer) for _ in range(3)]
        for t in threads:
            t.start()
        # render concurrently with the writers — the pre-fix registry
        # raised "dictionary changed size during iteration" here.
        for _ in range(50):
            c.render()
            h.render()
            h.snapshot()
        for t in threads:
            t.join()
        assert len(done) == 3
        assert c.render().count("test_lockcheck_total") >= 7

    def test_fetch_seam_clean_after_observe(self, monkeypatch):
        monkeypatch.setenv("KTPU_LOCK_CHECK", "1")
        from kubernetes_tpu.metrics.registry import Histogram
        h = Histogram("test_seam_seconds", "t")
        h.observe(0.5)
        # observe released its lock — the solve-fetch seam must be clean
        locking.check_dispatch_seam("backend.fetch_assign")
