"""Shortlist-pruned solve: randomized differential parity vs the full
N-wide scans (ops/solver.py), including adversarial cases engineered to
force the exactness fallback (tight capacity, score ties at the K
boundary), the spread scan, the sharded path on the 8-virtual-device CPU
mesh, and the backend end to end.

The contract under test is absolute: shortlist and full solves must
produce IDENTICAL assignments (and therefore identical fragmentation) —
the shortlist is a pruning of the same argmax, never an approximation.
"""

import asyncio
import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import kernels, solver


def run(coro):
    return asyncio.run(coro)


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

def synthetic(rng, P=16, N=96, R=2, score_levels=None, tight=False,
              mask_p=0.9):
    alloc_q = rng.integers(4_000, 64_000, size=(N, R)).astype(np.int32)
    used_frac = rng.uniform(0, 0.9 if tight else 0.5, size=(N, R))
    used_q = (alloc_q * used_frac).astype(np.int32)
    alloc_pods = np.full((N,), 6 if tight else 110, np.int32)
    used_pods = rng.integers(0, 5 if tight else 30, size=(N,)).astype(np.int32)
    lo, hi = (2_000, 24_000) if tight else (100, 9_000)
    req_q = rng.integers(lo, hi, size=(P, R)).astype(np.int32)
    mask = rng.random((P, N)) < mask_p
    if score_levels is None:
        static_sc = rng.uniform(0, 10, size=(P, N)).astype(np.float32)
    else:
        # Quantized scores: exact ties everywhere, including at the K
        # boundary — the tie rule's adversarial case.
        static_sc = rng.integers(
            0, score_levels, size=(P, N)).astype(np.float32)
    col_w = np.ones((R,), np.float32)
    col_mask = np.ones((R,), np.bool_)
    shp = np.array([0.0, 100.0], np.float32), np.array([0.0, 10.0], np.float32)
    return dict(alloc_q=alloc_q, used_q=used_q, alloc_pods=alloc_pods,
                used_pods=used_pods, req_q=req_q, mask=mask,
                static_sc=static_sc, col_w=col_w, col_mask=col_mask,
                shape_u=shp[0], shape_s=shp[1])


def solver_args(d, w_fit=1.0, w_bal=1.0):
    free_q = d["alloc_q"] - d["used_q"]
    free_pods = d["alloc_pods"] - d["used_pods"]
    return [jnp.asarray(x) for x in (
        d["req_q"], d["req_q"], free_q, free_pods, d["used_q"],
        d["alloc_q"], d["mask"], d["static_sc"], d["col_w"], d["col_mask"],
        d["shape_u"], d["shape_s"])] + [jnp.float32(w_fit),
                                        jnp.float32(w_bal)]


def prefilter(d, k, strategy, w_fit=1.0, w_bal=1.0):
    """Per-pod shortlist args, the way the backend builds them (here with
    one class per pod — the class sharing is exercised separately)."""
    free_q = d["alloc_q"] - d["used_q"]
    free_pods = d["alloc_pods"] - d["used_pods"]
    sc0 = kernels.chunk_start_scores(
        jnp.asarray(d["alloc_q"]), jnp.asarray(d["used_q"]),
        jnp.asarray(d["req_q"]), jnp.asarray(d["static_sc"]),
        jnp.asarray(d["col_w"]), jnp.asarray(d["col_mask"]),
        jnp.asarray(d["shape_u"]), jnp.asarray(d["shape_s"]),
        jnp.float32(w_fit), jnp.float32(w_bal), strategy)
    fits0 = np.all(d["req_q"][:, None, :] <= free_q[None], axis=-1) \
        & (free_pods >= 1)[None]
    cand, th = solver.shortlist_prefilter(
        jnp.asarray(d["mask"] & fits0), sc0, k)
    P = d["req_q"].shape[0]
    return (sc0, jnp.arange(P, dtype=jnp.int32), cand, th,
            jnp.asarray(d["mask"].any(axis=1)))


# ---------------------------------------------------------------------------
# identity-order scan
# ---------------------------------------------------------------------------

class TestIdentityParity:
    @pytest.mark.parametrize("strategy", ["LeastAllocated", "MostAllocated"])
    def test_randomized(self, strategy):
        total_fallbacks = 0
        for seed in range(6):
            rng = np.random.default_rng(seed)
            d = synthetic(rng)
            args = solver_args(d)
            full = np.asarray(solver.greedy_assign_rescoring(
                *args, strategy=strategy))
            sl, nfall = solver.greedy_assign_rescoring_shortlist(
                *args, strategy, *prefilter(d, 6, strategy))
            np.testing.assert_array_equal(full, np.asarray(sl))
            total_fallbacks += int(nfall)
        # The suite must actually exercise BOTH paths across its seeds
        # (fallback traffic is strategy-dependent; LeastAllocated's
        # decreasing scores are the reliable generator).
        if strategy == "LeastAllocated":
            assert total_fallbacks > 0

    def test_tight_capacity_forces_fallback(self):
        """Capacity debits exhaust shortlists → the full-row fallback
        must fire AND stay bit-identical."""
        hit = 0
        for seed in range(4):
            rng = np.random.default_rng(100 + seed)
            d = synthetic(rng, P=20, N=48, tight=True)
            args = solver_args(d)
            full = np.asarray(solver.greedy_assign_rescoring(
                *args, strategy="LeastAllocated"))
            sl, nfall = solver.greedy_assign_rescoring_shortlist(
                *args, "LeastAllocated", *prefilter(d, 4, "LeastAllocated"))
            np.testing.assert_array_equal(full, np.asarray(sl))
            hit += int(nfall)
        assert hit > 0

    def test_score_ties_at_k_boundary(self):
        """Quantized scores (exact float ties straddling the shortlist
        boundary) — the untouched-winner tie rule must match the full
        scan's lowest-index tie-break exactly."""
        for seed in range(6):
            rng = np.random.default_rng(200 + seed)
            d = synthetic(rng, score_levels=2)
            # Zero score weights: ONLY tied static scores decide, so the
            # (K+1)-th bound equals the winner's score at nearly every
            # step — maximal pressure on the tie logic.
            args = solver_args(d, w_fit=0.0, w_bal=0.0)
            for k in (1, 4, 9):
                full = np.asarray(solver.greedy_assign_rescoring(
                    *args, strategy="LeastAllocated"))
                sl, _ = solver.greedy_assign_rescoring_shortlist(
                    *args, "LeastAllocated",
                    *prefilter(d, k, "LeastAllocated",
                               w_fit=0.0, w_bal=0.0))
                np.testing.assert_array_equal(full, np.asarray(sl))

    def test_uniform_cluster_round_robin_no_fallback(self):
        """The 50k-preset shape: identical nodes + template pods round-
        robin one fresh node per pod. With K ≥ P the whole chunk's
        winners sit in the shortlist — zero fallbacks, same assigns."""
        rng = np.random.default_rng(7)
        N, P, R = 128, 16, 2
        d = synthetic(rng, P=P, N=N)
        d["alloc_q"][:] = 32_000
        d["used_q"][:] = 0
        d["used_pods"][:] = 0
        d["req_q"][:] = 900
        d["mask"][:] = True
        d["static_sc"][:] = 0.0
        args = solver_args(d)
        full = np.asarray(solver.greedy_assign_rescoring(
            *args, strategy="LeastAllocated"))
        sl, nfall = solver.greedy_assign_rescoring_shortlist(
            *args, "LeastAllocated", *prefilter(d, P, "LeastAllocated"))
        np.testing.assert_array_equal(full, np.asarray(sl))
        assert int(nfall) == 0
        assert len(set(full.tolist())) == P  # it did round-robin


# ---------------------------------------------------------------------------
# multistart (vmapped orders, poisoned-chunk fallback)
# ---------------------------------------------------------------------------

class TestMultistartParity:
    def _perms(self, d, K=3):
        P = d["req_q"].shape[0]
        perms = np.tile(np.arange(P, dtype=np.int32), (K, 1))
        sizes = d["req_q"].sum(axis=1)
        perms[1] = np.argsort(-sizes, kind="stable").astype(np.int32)
        if K > 2:
            perms[2] = np.argsort(sizes, kind="stable").astype(np.int32)
        return jnp.asarray(perms)

    @pytest.mark.parametrize("tight", [False, True])
    def test_randomized(self, tight):
        poisoned = clean = 0
        for seed in range(5):
            rng = np.random.default_rng(300 + seed)
            d = synthetic(rng, tight=tight)
            args = solver_args(d)
            P = d["req_q"].shape[0]
            perms = self._perms(d)
            gz = jnp.zeros((P, 4), jnp.float32)
            gr = jnp.zeros((4,), jnp.float32)
            full = np.asarray(solver.multistart_greedy_assign(
                *args, "LeastAllocated", perms, gz, gr))
            sl, nf = solver.multistart_greedy_assign_shortlist(
                *args, "LeastAllocated", perms, gz, gr,
                *prefilter(d, 6, "LeastAllocated"))
            np.testing.assert_array_equal(full, np.asarray(sl))
            if int(nf):
                poisoned += 1
            else:
                clean += 1
        # Across both regimes the suite sees clean chunks AND whole-chunk
        # fallbacks (the vmapped scans can't repair per step).
        assert (poisoned + clean) == 5

    def test_gangs_ride_both_paths(self):
        rng = np.random.default_rng(42)
        d = synthetic(rng, P=12, N=64)
        args = solver_args(d)
        P = 12
        gang = np.zeros((P, 4), np.float32)
        gang[:4, 0] = 1.0  # one 4-member gang
        req = np.zeros((4,), np.float32)
        req[0] = 4.0
        perms = self._perms(d)
        full = np.asarray(solver.multistart_greedy_assign(
            *args, "LeastAllocated", perms,
            jnp.asarray(gang), jnp.asarray(req)))
        sl, _ = solver.multistart_greedy_assign_shortlist(
            *args, "LeastAllocated", perms,
            jnp.asarray(gang), jnp.asarray(req),
            *prefilter(d, 6, "LeastAllocated"))
        np.testing.assert_array_equal(full, np.asarray(sl))


# ---------------------------------------------------------------------------
# spread scan
# ---------------------------------------------------------------------------

class TestSpreadParity:
    def _spread(self, rng, N, P, D=4, C=2):
        dom_of = rng.integers(0, D, size=(N,))
        dom_onehot = np.zeros((N, D), np.float32)
        dom_onehot[np.arange(N), dom_of] = 1.0
        cid = np.zeros((D, C), np.float32)
        cid[: D // 2, 0] = 1.0
        cid[D // 2:, 1] = 1.0
        applies = (rng.random((P, C)) < 0.6).astype(np.float32)
        contrib = np.maximum(
            applies, (rng.random((P, C)) < 0.3)).astype(np.float32)
        return [jnp.asarray(x) for x in (
            dom_onehot, cid,
            rng.integers(0, 2, size=(D,)).astype(np.float32),
            np.array([1.0, 2.0], np.float32),       # max_skew
            np.ones((C,), np.float32),              # min_ok
            np.ones((N, C), np.float32),            # has_key
            applies, contrib)]

    def test_randomized(self):
        total_fallbacks = 0
        for seed in range(6):
            rng = np.random.default_rng(400 + seed)
            N, P = 48, 12
            d = synthetic(rng, P=P, N=N)
            args = solver_args(d)
            sp = self._spread(rng, N, P)
            full, dc_full = solver.greedy_assign_rescoring_spread(
                *args, "LeastAllocated", *sp)
            sl, dc_sl, nfall = \
                solver.greedy_assign_rescoring_spread_shortlist(
                    *args, "LeastAllocated", *sp,
                    *prefilter(d, 5, "LeastAllocated"))
            np.testing.assert_array_equal(
                np.asarray(full), np.asarray(sl))
            np.testing.assert_allclose(
                np.asarray(dc_full), np.asarray(dc_sl))
            total_fallbacks += int(nfall)
        # Spread gating is prefilter-blind, so skew-blocked score heads
        # must route through the fallback somewhere in the suite.
        assert total_fallbacks > 0

    def test_tight_skew_forces_fallback(self):
        """maxSkew=1 over few domains: the score head saturates its
        domain quickly and the allowed set moves away from the shortlist
        — heavy fallback traffic, still bit-identical (incl. the chained
        domain counts)."""
        rng = np.random.default_rng(77)
        N, P, D, C = 32, 16, 2, 1
        d = synthetic(rng, P=P, N=N, mask_p=1.0)
        d["static_sc"][:] = 0.0
        args = solver_args(d)
        dom_onehot = np.zeros((N, D), np.float32)
        dom_onehot[np.arange(N), np.arange(N) % D] = 1.0
        sp = [jnp.asarray(x) for x in (
            dom_onehot, np.ones((D, C), np.float32),
            np.zeros((D,), np.float32), np.array([1.0], np.float32),
            np.ones((C,), np.float32), np.ones((N, C), np.float32),
            np.ones((P, C), np.float32), np.ones((P, C), np.float32))]
        full, dc_full = solver.greedy_assign_rescoring_spread(
            *args, "LeastAllocated", *sp)
        sl, dc_sl, nfall = solver.greedy_assign_rescoring_spread_shortlist(
            *args, "LeastAllocated", *sp,
            *prefilter(d, 4, "LeastAllocated"))
        np.testing.assert_array_equal(np.asarray(full), np.asarray(sl))
        np.testing.assert_allclose(np.asarray(dc_full), np.asarray(dc_sl))


# ---------------------------------------------------------------------------
# sharded path (8-virtual-device CPU mesh, conftest-forced)
# ---------------------------------------------------------------------------

class TestShardedParity:
    @pytest.mark.parametrize("n_devices", [1, 2, 8])
    @pytest.mark.parametrize("k", [3, 8])
    def test_matches_single_chip(self, n_devices, k):
        if len(jax.devices()) < n_devices:
            pytest.skip("not enough devices")
        from kubernetes_tpu.parallel import build_mesh, sharded_greedy_assign
        rng = np.random.default_rng(11)
        d = synthetic(rng, P=12, N=64)
        args = solver_args(d)
        single = np.asarray(solver.greedy_assign_rescoring(
            *args, strategy="LeastAllocated"))
        sharded = np.asarray(sharded_greedy_assign(
            build_mesh(n_devices), *args, "LeastAllocated", shortlist_k=k))
        np.testing.assert_array_equal(single, sharded)

    def test_multislice_with_shortlist(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        from kubernetes_tpu.parallel import build_multislice_mesh
        from kubernetes_tpu.parallel.sharded import (
            sharded_greedy_assign_multislice,
        )
        rng = np.random.default_rng(13)
        d = synthetic(rng, P=12, N=64)
        args = solver_args(d)
        single = np.asarray(solver.greedy_assign_rescoring(
            *args, strategy="LeastAllocated"))
        ms = np.asarray(sharded_greedy_assign_multislice(
            build_multislice_mesh(2, 4), *args, "LeastAllocated",
            shortlist_k=4))
        np.testing.assert_array_equal(single, ms)


# ---------------------------------------------------------------------------
# backend end to end: forced-on vs forced-off must agree, classes shared
# ---------------------------------------------------------------------------

class TestBackendParity:
    def _cluster_and_pods(self, seed, n_nodes=128, n_pods=48):
        from test_tpu_backend import TOL_POOL, random_cluster
        from kubernetes_tpu.api.types import make_pod
        from kubernetes_tpu.scheduler.types import PodInfo
        rng = random.Random(seed)
        snap = random_cluster(rng, n_nodes)
        # Template pods (two classes) — the row-sharing case the class
        # key must get right; heterogeneous chunks are covered above.
        pods = [PodInfo(make_pod(
            f"pend-{i}",
            requests={"cpu": "500m", "memory": "512Mi"} if i % 2
            else {"cpu": "1", "memory": "2Gi"},
            tolerations=TOL_POOL if i % 2 else None,
            uid=f"uid-{i}")) for i in range(n_pods)]
        return snap, pods

    def test_forced_on_off_identical(self, monkeypatch):
        import kubernetes_tpu.ops.backend as backend_mod
        from test_tpu_backend import default_fwk
        from kubernetes_tpu.metrics.registry import SchedulerMetrics
        # 50 pods over 16-wide chunks: the last chunk is PARTIAL, so the
        # padding rows ride the scan (all-false masks must resolve to -1
        # with no fallback and no poisoning).
        snap, pods = self._cluster_and_pods(9, n_pods=50)
        fwk = default_fwk()
        # The override is a LIVE env read now (utils/flags.py), so the
        # sweep knob is the flag itself — no module-state patching.
        monkeypatch.setenv("KTPU_SHORTLIST_K", "0")
        full, _ = backend_mod.TPUBackend(
            max_batch=16, mesh=None).assign(pods, snap, fwk)
        monkeypatch.setenv("KTPU_SHORTLIST_K", "16")
        b = backend_mod.TPUBackend(max_batch=16, mesh=None)
        b.metrics = SchedulerMetrics()
        sl, _ = b.assign(pods, snap, fwk)
        assert full == sl
        # The forced run must actually have taken the shortlist path.
        assert b.metrics.solver_shortlist_pods.value() == len(pods)
        assert b.metrics.solve_duration.count() > 0
