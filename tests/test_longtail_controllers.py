"""Controller long tail (SURVEY §2.4 bottom rows): EndpointSlice,
ResourceQuota + admission, Disruption/PDB + eviction API, TTL-after-
finished, HPA."""

import asyncio

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.controllers import (
    ControllerManager,
    DisruptionController,
    EndpointSliceController,
    HorizontalPodAutoscalerController,
    KwokController,
    ResourceQuotaController,
    TTLAfterFinishedController,
    install_eviction_subresource,
    install_quota_admission,
    make_hpa,
    make_pdb,
    make_resource_quota,
    make_service,
)
from kubernetes_tpu.store import install_core_validation, new_cluster_store
from kubernetes_tpu.store.mvcc import Conflict, Invalid


def run(coro):
    return asyncio.run(coro)


async def wait_for(predicate, timeout=10.0, interval=0.03):
    for _ in range(int(timeout / interval)):
        v = await predicate()
        if v:
            return v
        await asyncio.sleep(interval)
    return await predicate()


async def stack(controllers, *, kwok=False, scheduler=False):
    store = new_cluster_store()
    install_core_validation(store)
    ctrls = [c(store) for c in controllers]
    kw = None
    if kwok:
        kw = KwokController(store, node_count=3, lease_period=0.5)
        await kw.register_nodes()
        ctrls.append(kw)
    else:
        for i in range(3):
            await store.create("nodes", make_node(f"n{i}"))
    mgr = ControllerManager(store, ctrls)
    await mgr.start()
    sched_task = None
    sched = None
    factory = None
    if scheduler:
        from kubernetes_tpu.client import InformerFactory
        from kubernetes_tpu.scheduler import Scheduler
        sched = Scheduler(store, seed=2)
        factory = InformerFactory(store)
        await sched.setup_informers(factory)
        factory.start()
        await factory.wait_for_sync()
        sched_task = asyncio.ensure_future(sched.run())

    async def teardown():
        if sched is not None:
            await sched.stop()
            sched_task.cancel()
            factory.stop()
        await mgr.stop()
        store.stop()
    return store, teardown


class TestEndpointSlice:
    def test_service_gets_ready_endpoints(self):
        async def body():
            store, teardown = await stack(
                [EndpointSliceController], kwok=True, scheduler=True)
            await store.create("services", make_service(
                "web", {"app": "web"}))
            for i in range(3):
                await store.create("pods", make_pod(
                    f"w{i}", labels={"app": "web"},
                    requests={"cpu": "100m"}))
            await store.create("pods", make_pod(
                "other", labels={"app": "db"}, requests={"cpu": "100m"}))

            async def three_ready():
                try:
                    eps = await store.get("endpointslices", "default/web")
                except Exception:
                    return False
                eps_list = eps.get("endpoints") or []
                return len(eps_list) == 3 and all(
                    e["conditions"]["ready"] for e in eps_list)
            assert await wait_for(three_ready)
            eps = await store.get("endpointslices", "default/web")
            names = {e["targetRef"]["name"] for e in eps["endpoints"]}
            assert names == {"w0", "w1", "w2"}
            assert all(e["addresses"][0].startswith("10.")
                       for e in eps["endpoints"])
            # Pod deletion shrinks the slice.
            await store.delete("pods", "default/w0")

            async def two():
                eps = await store.get("endpointslices", "default/web")
                return len(eps.get("endpoints") or []) == 2
            assert await wait_for(two)
            await teardown()
        run(body())


class TestResourceQuota:
    def test_admission_rejects_over_quota(self):
        async def body():
            store, teardown = await stack([ResourceQuotaController])
            install_quota_admission(store)
            await store.create("resourcequotas", make_resource_quota(
                "team", {"pods": "2", "cpu": "1"}))
            await store.create("pods", make_pod(
                "a", requests={"cpu": "400m"}))
            await store.create("pods", make_pod(
                "b", requests={"cpu": "400m"}))
            # third pod: over the pods=2 limit
            with pytest.raises(Invalid):
                await store.create("pods", make_pod(
                    "c", requests={"cpu": "100m"}))
            # cpu limit binds even under the pod count
            await store.delete("pods", "default/b")
            with pytest.raises(Invalid):
                await store.create("pods", make_pod(
                    "big", requests={"cpu": "700m"}))
            # status.used is published by the controller
            async def used():
                rq = await store.get("resourcequotas", "default/team")
                return (rq.get("status") or {}).get("used", {}).get("pods") \
                    == "1"
            assert await wait_for(used)
            await teardown()
        run(body())


class TestDisruption:
    def test_eviction_respects_pdb(self):
        async def body():
            store, teardown = await stack(
                [DisruptionController], kwok=True, scheduler=True)
            install_eviction_subresource(store)
            await store.create("poddisruptionbudgets", make_pdb(
                "web-pdb", {"matchLabels": {"app": "web"}},
                min_available=2))
            for i in range(3):
                await store.create("pods", make_pod(
                    f"w{i}", labels={"app": "web"},
                    requests={"cpu": "100m"}))

            async def budget_ready():
                pdb = await store.get(
                    "poddisruptionbudgets", "default/web-pdb")
                st = pdb.get("status") or {}
                return st.get("currentHealthy") == 3 and \
                    st.get("disruptionsAllowed") == 1
            assert await wait_for(budget_ready)
            # First eviction allowed (3 healthy, min 2)...
            await store.subresource("pods", "default/w0", "eviction", {})

            async def one_allowed_gone():
                pdb = await store.get(
                    "poddisruptionbudgets", "default/web-pdb")
                return (pdb.get("status") or {}).get(
                    "disruptionsAllowed") == 0
            assert await wait_for(one_allowed_gone)
            # ...second refused: budget exhausted.
            with pytest.raises(Conflict):
                await store.subresource("pods", "default/w1", "eviction", {})
            await teardown()
        run(body())


class TestEvictionRace:
    def test_back_to_back_evictions_cannot_break_budget(self):
        """The eviction handler recounts LIVE state, so a tight eviction
        loop (ktpuctl drain) cannot overshoot the budget while the
        controller's status lags."""
        async def body():
            store, teardown = await stack([], kwok=True, scheduler=True)
            install_eviction_subresource(store)
            await store.create("poddisruptionbudgets", make_pdb(
                "pdb", {"matchLabels": {"app": "web"}}, min_available=2))
            for i in range(3):
                await store.create("pods", make_pod(
                    f"w{i}", labels={"app": "web"},
                    requests={"cpu": "100m"}))

            async def all_ready():
                pods = (await store.list("pods")).items
                return sum(1 for p in pods
                           if p["status"].get("phase") == "Running") == 3
            assert await wait_for(all_ready)
            # NO DisruptionController running: status is absent/stale.
            # First eviction OK, second must refuse (2 healthy left).
            await store.subresource("pods", "default/w0", "eviction", {})
            with pytest.raises(Conflict):
                await store.subresource("pods", "default/w1", "eviction", {})
            pods = (await store.list("pods")).items
            assert len(pods) == 2
            await teardown()
        run(body())


class TestTTLAfterFinished:
    def test_finished_job_deleted_after_ttl(self):
        async def body():
            store, teardown = await stack([TTLAfterFinishedController])
            from kubernetes_tpu.api.meta import now_iso
            job = {
                "apiVersion": "batch/v1", "kind": "Job",
                "metadata": {"name": "done", "namespace": "default"},
                "spec": {"ttlSecondsAfterFinished": 0},
                "status": {"conditions": [{
                    "type": "Complete", "status": "True",
                    "lastTransitionTime": now_iso()}]},
            }
            await store.create("jobs", job)

            async def gone():
                return not (await store.list("jobs")).items
            assert await wait_for(gone)
            await teardown()
        run(body())


class TestHPA:
    def test_scales_up_on_load(self):
        async def body():
            store, teardown = await stack(
                [HorizontalPodAutoscalerController])
            await store.create("deployments", {
                "apiVersion": "apps/v1", "kind": "Deployment",
                "metadata": {"name": "web", "namespace": "default"},
                "spec": {"replicas": 2,
                         "selector": {"matchLabels": {"app": "web"}}}})
            for i in range(2):
                pod = make_pod(f"w{i}", labels={"app": "web"},
                               requests={"cpu": "100m"}, phase="Running")
                pod["metadata"]["annotations"] = {"ktpu.dev/load": "160"}
                await store.create("pods", pod)
            await store.create(
                "horizontalpodautoscalers",
                make_hpa("web-hpa", "deployments/web", max_replicas=8,
                         target_utilization=80))

            async def scaled():
                d = await store.get("deployments", "default/web")
                return d["spec"]["replicas"] == 4  # ceil(2 * 160/80)
            assert await wait_for(scaled)
            await teardown()
        run(body())


class TestKubeProxy:
    def test_vip_rules_follow_endpoints(self):
        """Service gets a clusterIP at admission; the proxier compiles
        (VIP, port) -> ready backends and re-compiles on endpoint churn;
        lookup round-robins like the kernel DNAT would."""
        async def body():
            from kubernetes_tpu.controllers import (
                KubeProxyController,
                install_service_ip_allocator,
            )
            store, teardown = await stack([], kwok=True, scheduler=True)
            install_service_ip_allocator(store)
            eps_ctrl = EndpointSliceController(store)
            proxy = KubeProxyController(store, min_sync_period=0.01)
            from kubernetes_tpu.controllers import ControllerManager
            mgr2 = ControllerManager(store, [eps_ctrl, proxy])
            await mgr2.start()

            svc = await store.create("services", make_service(
                "web", {"app": "web"}, port=80))
            vip = svc["spec"]["clusterIP"]
            assert vip.startswith("10.96.")
            for i in range(2):
                await store.create("pods", make_pod(
                    f"w{i}", labels={"app": "web"},
                    requests={"cpu": "100m"}))

            async def two_backends():
                return len(proxy.rules.get((vip, 80)) or []) == 2
            assert await wait_for(two_backends)
            # Round-robin across both backends.
            seen = {proxy.lookup(vip, 80) for _ in range(4)}
            assert len(seen) == 2
            # Endpoint churn recompiles: delete one pod.
            await store.delete("pods", "default/w0")

            async def one_backend():
                return len(proxy.rules.get((vip, 80)) or []) == 1
            assert await wait_for(one_backend)
            # Service deletion drops the VIP rules entirely.
            await store.delete("services", "default/web")

            async def gone():
                return (vip, 80) not in proxy.rules
            assert await wait_for(gone)
            await mgr2.stop()
            await teardown()
        run(body())


class TestNodeAgent:
    def test_readiness_failure_drops_endpoint(self):
        """A staged readiness failure flips the Ready condition; the
        EndpointSlice marks the endpoint not-ready and the proxier drops
        it from rotation — the full probe → rotation chain."""
        async def body():
            from kubernetes_tpu.controllers import (
                EndpointSliceController,
                ProberController,
                install_service_ip_allocator,
            )
            store, teardown = await stack(
                [EndpointSliceController, ProberController],
                kwok=True, scheduler=True)
            install_service_ip_allocator(store)
            svc = await store.create("services", make_service(
                "web", {"app": "web"}))
            pod = make_pod("w0", labels={"app": "web"},
                           requests={"cpu": "100m"})
            pod["metadata"]["annotations"] = {
                "kwok.x-k8s.io/fail-readiness-after": "0.2"}
            await store.create("pods", pod)

            async def not_ready():
                try:
                    eps = await store.get("endpointslices", "default/web")
                except Exception:
                    return False
                endpoints = eps.get("endpoints") or []
                return len(endpoints) == 1 and \
                    not endpoints[0]["conditions"]["ready"]
            assert await wait_for(not_ready, timeout=10.0)
            await teardown()
        run(body())

    def test_liveness_failure_restarts(self):
        async def body():
            from kubernetes_tpu.controllers import ProberController
            store, teardown = await stack(
                [ProberController], kwok=True, scheduler=True)
            pod = make_pod("crashy", requests={"cpu": "100m"})
            pod["metadata"]["annotations"] = {
                "kwok.x-k8s.io/fail-liveness-after": "0.2"}
            await store.create("pods", pod)

            async def restarted():
                p = await store.get("pods", "default/crashy")
                return (p.get("status") or {}).get("restartCount", 0) >= 1
            assert await wait_for(restarted, timeout=10.0)
            p = await store.get("pods", "default/crashy")
            ready = next(c for c in p["status"]["conditions"]
                         if c["type"] == "Ready")
            assert ready["status"] == "True"  # restarted, back Ready
            await teardown()
        run(body())

    def test_node_pressure_evicts_lowest_priority(self):
        async def body():
            from kubernetes_tpu.controllers import (
                NodePressureEvictionController,
            )
            store, teardown = await stack([])
            # Single 8Gi node; threshold 0.9 → pressure above ~7.2Gi.
            await store.delete("nodes", "n1")
            await store.delete("nodes", "n2")
            mgr_node = await store.get("nodes", "n0")
            mgr_node["status"]["allocatable"]["memory"] = "8Gi"
            await store.update("nodes", mgr_node)
            from kubernetes_tpu.controllers import ControllerManager
            ctrl = NodePressureEvictionController(store, threshold=0.9)
            mgr2 = ControllerManager(store, [ctrl])
            await mgr2.start()
            # 4Gi high-prio + 4Gi low-prio = 8Gi > 7.2Gi threshold.
            await store.create("pods", make_pod(
                "hi", node_name="n0", priority=100,
                requests={"memory": "4Gi"}, phase="Running"))
            await store.create("pods", make_pod(
                "lo", node_name="n0", priority=0,
                requests={"memory": "4Gi"}, phase="Running"))

            async def evicted():
                pods = {p["metadata"]["name"]
                        for p in (await store.list("pods")).items}
                return pods == {"hi"}  # lowest priority went first
            assert await wait_for(evicted, timeout=10.0)

            # The memory-pressure taint is transient (applied while over
            # threshold, lifted once eviction clears it) — assert the
            # durable end state: pressure gone, taint gone.
            async def untainted():
                node = await store.get("nodes", "n0")
                return not any(
                    t.get("key") == "node.kubernetes.io/memory-pressure"
                    for t in node.get("spec", {}).get("taints") or [])
            assert await wait_for(untainted, timeout=10.0)
            await mgr2.stop()
            await teardown()
        run(body())
