"""Job / DaemonSet / StatefulSet controllers — the workload tier
(SURVEY §2.4 rows 44-46), incl. full chains through scheduler + kwok
mirroring test_full_chain_deployment_to_running_pods."""

import asyncio

from kubernetes_tpu.api.meta import namespaced_name
from kubernetes_tpu.api.types import make_node, make_storage_class
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.controllers import (
    ControllerManager,
    DaemonSetController,
    JobController,
    KwokController,
    PVBinderController,
    StatefulSetController,
    make_daemonset,
    make_job,
    make_statefulset,
)
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def run(coro):
    return asyncio.run(coro)


async def wait_for(predicate, timeout=10.0, interval=0.03):
    for _ in range(int(timeout / interval)):
        v = await predicate()
        if v:
            return v
        await asyncio.sleep(interval)
    return await predicate()


async def full_stack(controllers, node_count=3):
    """store + kwok nodes + controllers + scheduler, all wired."""
    store = new_cluster_store()
    install_core_validation(store)
    # Default StorageClass so StatefulSet volumeClaimTemplates provision
    # (the DefaultStorageClass admission mutator picks it up).
    await store.create("storageclasses", make_storage_class(
        "standard", binding_mode="WaitForFirstConsumer", is_default=True))
    kwok = KwokController(store, node_count=node_count, lease_period=0.5)
    await kwok.register_nodes()
    # PV binder always runs (it is part of kube-controller-manager in the
    # reference); StatefulSet volumeClaimTemplates need it to provision.
    mgr = ControllerManager(
        store,
        [c(store) for c in controllers] + [PVBinderController(store), kwok])
    await mgr.start()
    sched = Scheduler(store, seed=7)
    factory = InformerFactory(store)
    await sched.setup_informers(factory)
    factory.start()
    await factory.wait_for_sync()
    sched_task = asyncio.ensure_future(sched.run())

    async def teardown():
        await sched.stop()
        sched_task.cancel()
        await mgr.stop()
        factory.stop()
        store.stop()
    return store, kwok, teardown


JOB_TEMPLATE = {
    "metadata": {"labels": {"app": "batch"},
                 "annotations": {"kwok.x-k8s.io/complete-after": "0.1"}},
    "spec": {"containers": [{"name": "main", "image": "batch:v1",
                             "resources": {"requests": {"cpu": "100m"}}}]},
}


class TestJob:
    def test_parallelism_completions_to_complete(self):
        """6 completions at parallelism 2: never more than 2 active, ends
        Complete with succeeded=6."""
        async def body():
            store, kwok, teardown = await full_stack([JobController])
            max_active = 0

            async def poll():
                nonlocal max_active
                pods = (await store.list("pods")).items
                active = sum(1 for p in pods
                             if p["status"].get("phase") in ("Pending", "Running"))
                max_active = max(max_active, active)
                job = await store.get("jobs", "default/sum")
                conds = (job.get("status") or {}).get("conditions") or []
                return any(c["type"] == "Complete" and c["status"] == "True"
                           for c in conds)

            await store.create("jobs", make_job(
                "sum", parallelism=2, completions=6, template=JOB_TEMPLATE))
            assert await wait_for(poll, timeout=20.0)
            job = await store.get("jobs", "default/sum")
            assert job["status"]["succeeded"] == 6
            assert job["status"]["active"] == 0
            assert job["status"].get("completionTime")
            assert max_active <= 2, f"parallelism exceeded: {max_active}"
            await teardown()
        run(body())

    def test_indexed_mode_stable_identities(self):
        async def body():
            store, kwok, teardown = await full_stack([JobController])
            await store.create("jobs", make_job(
                "train", parallelism=4, completions=4,
                completion_mode="Indexed", template=JOB_TEMPLATE))

            async def complete():
                job = await store.get("jobs", "default/train")
                conds = (job.get("status") or {}).get("conditions") or []
                return any(c["type"] == "Complete" for c in conds)
            assert await wait_for(complete, timeout=20.0)
            pods = (await store.list("pods")).items
            names = {p["metadata"]["name"] for p in pods}
            assert names == {"train-0", "train-1", "train-2", "train-3"}
            idx = {p["metadata"]["annotations"]
                   ["batch.kubernetes.io/job-completion-index"] for p in pods}
            assert idx == {"0", "1", "2", "3"}
            await teardown()
        run(body())

    def test_succeeded_count_survives_terminal_pod_deletion(self):
        """GC/eviction deleting a finished pod must not regress
        status.succeeded or re-run completed indexed work (cumulative
        uncountedTerminatedPods semantics)."""
        async def body():
            store, kwok, teardown = await full_stack([JobController])
            await store.create("jobs", make_job(
                "persist", parallelism=1, completions=2,
                completion_mode="Indexed", template=JOB_TEMPLATE))

            async def first_done():
                job = await store.get("jobs", "default/persist")
                return (job["status"].get("succeeded") or 0) >= 1
            assert await wait_for(first_done, timeout=20.0)
            # Simulate PodGC: delete every Succeeded pod.
            for p in (await store.list("pods")).items:
                if p["status"].get("phase") == "Succeeded":
                    await store.delete("pods", namespaced_name(p))

            async def complete():
                job = await store.get("jobs", "default/persist")
                conds = (job.get("status") or {}).get("conditions") or []
                return any(c["type"] == "Complete" for c in conds)
            assert await wait_for(complete, timeout=20.0)
            job = await store.get("jobs", "default/persist")
            assert job["status"]["succeeded"] == 2
            assert sorted(job["status"]["completedIndexes"]) == ["0", "1"]
            await teardown()
        run(body())

    def test_backoff_limit_fails_job(self):
        async def body():
            store, kwok, teardown = await full_stack([JobController])
            await store.create("jobs", make_job(
                "doomed", parallelism=1, completions=3, backoff_limit=1,
                template=JOB_TEMPLATE))

            # Fail pods as they appear (kubelet-sim of a crashing container).
            async def fail_pods():
                pods = (await store.list("pods")).items
                for p in pods:
                    if p["status"].get("phase") in ("Pending", "Running"):
                        def to_failed(obj):
                            if obj["status"].get("phase") == "Succeeded":
                                return None
                            obj["status"]["phase"] = "Failed"
                            return obj
                        await store.guaranteed_update(
                            "pods", namespaced_name(p), to_failed)
                job = await store.get("jobs", "default/doomed")
                conds = (job.get("status") or {}).get("conditions") or []
                return any(c["type"] == "Failed" and
                           c.get("reason") == "BackoffLimitExceeded"
                           for c in conds)
            assert await wait_for(fail_pods, timeout=20.0)
            await teardown()
        run(body())


class TestDaemonSet:
    def test_one_pod_per_node_via_node_affinity(self):
        async def body():
            store, kwok, teardown = await full_stack(
                [DaemonSetController], node_count=4)
            await store.create("daemonsets", make_daemonset(
                "agent", {"matchLabels": {"app": "agent"}},
                {"metadata": {"labels": {"app": "agent"}},
                 "spec": {"containers": [{"name": "a", "image": "agent"}]}}))

            async def all_running():
                pods = (await store.list("pods")).items
                return len(pods) == 4 and all(
                    p["status"].get("phase") == "Running" for p in pods) \
                    and pods
            pods = await wait_for(all_running, timeout=15.0)
            assert pods
            # Scheduler placed each exactly on its pinned node (NodeAffinity
            # matchFields metadata.name — the reference's post-1.12 path).
            for p in pods:
                terms = (p["spec"]["affinity"]["nodeAffinity"]
                         ["requiredDuringSchedulingIgnoredDuringExecution"]
                         ["nodeSelectorTerms"])
                pinned = terms[0]["matchFields"][0]["values"][0]
                assert p["spec"]["nodeName"] == pinned
            nodes_covered = {p["spec"]["nodeName"] for p in pods}
            assert len(nodes_covered) == 4
            # Status sync is its own controller pass — all pods Running
            # does not mean the daemonset status caught up yet, so wait
            # for it like the pods above (racy direct asserts flaked
            # under a loaded full-suite run).
            async def status_synced():
                ds = await store.get("daemonsets", "default/agent")
                return ds["status"]["desiredNumberScheduled"] == 4 \
                    and ds["status"]["numberReady"] == 4
            assert await wait_for(status_synced, timeout=15.0)
            await teardown()
        run(body())

    def test_new_node_gets_pod_and_node_selector_respected(self):
        async def body():
            store, kwok, teardown = await full_stack(
                [DaemonSetController], node_count=2)
            await store.create("daemonsets", make_daemonset(
                "gpu-agent", {"matchLabels": {"app": "ga"}},
                {"metadata": {"labels": {"app": "ga"}},
                 "spec": {"nodeSelector": {"accel": "tpu"},
                          "containers": [{"name": "a", "image": "agent"}]}}))
            await asyncio.sleep(0.3)
            assert (await store.list("pods")).items == []  # no node matches
            node = make_node("kwok-node-99", labels={"accel": "tpu"})
            await store.create("nodes", node)
            kwok._managed.add("kwok-node-99")

            async def one():
                pods = (await store.list("pods")).items
                return pods if len(pods) == 1 else None
            pods = await wait_for(one, timeout=15.0)
            assert pods and pods[0]["spec"].get("nodeName") == "kwok-node-99"
            await teardown()
        run(body())


class TestStatefulSet:
    def test_ordered_creation_and_identity(self):
        async def body():
            store, kwok, teardown = await full_stack([StatefulSetController])
            await store.create("statefulsets", make_statefulset(
                "db", 3, {"matchLabels": {"app": "db"}},
                {"metadata": {"labels": {"app": "db"}},
                 "spec": {"containers": [{"name": "d", "image": "db"}]}},
                volume_claim_templates=[
                    {"metadata": {"name": "data"},
                     "spec": {"resources": {"requests": {"storage": "1Gi"}}}}]))

            creation_order = []

            async def all_up():
                pods = (await store.list("pods")).items
                for p in pods:
                    if p["metadata"]["name"] not in creation_order:
                        creation_order.append(p["metadata"]["name"])
                return len(pods) == 3 and all(
                    p["status"].get("phase") == "Running" for p in pods)
            assert await wait_for(all_up, timeout=15.0)
            # Ordinal names, ordered creation.
            assert sorted(creation_order) == ["db-0", "db-1", "db-2"]
            assert creation_order == ["db-0", "db-1", "db-2"]
            pods = (await store.list("pods")).items
            for p in pods:
                assert p["metadata"]["labels"][
                    "statefulset.kubernetes.io/pod-name"] == \
                    p["metadata"]["name"]
            # One PVC per pod from the claim template.
            pvcs = (await store.list("persistentvolumeclaims")).items
            assert {c["metadata"]["name"] for c in pvcs} == \
                {"data-db-0", "data-db-1", "data-db-2"}
            await teardown()
        run(body())

    def test_scale_down_removes_highest_ordinal_keeps_pvc(self):
        async def body():
            store, kwok, teardown = await full_stack([StatefulSetController])
            await store.create("statefulsets", make_statefulset(
                "db", 3, {"matchLabels": {"app": "db"}},
                {"metadata": {"labels": {"app": "db"}},
                 "spec": {"containers": [{"name": "d", "image": "db"}]}},
                volume_claim_templates=[
                    {"metadata": {"name": "data"},
                     "spec": {"resources": {"requests": {"storage": "1Gi"}}}}]))

            async def three():
                pods = (await store.list("pods")).items
                return len(pods) == 3 and all(
                    p["status"].get("phase") == "Running" for p in pods)
            assert await wait_for(three, timeout=15.0)
            await store.guaranteed_update(
                "statefulsets", "default/db",
                lambda o: (o["spec"].__setitem__("replicas", 1), o)[1])

            async def one():
                pods = (await store.list("pods")).items
                return len(pods) == 1 and pods
            pods = await wait_for(one, timeout=15.0)
            assert pods[0]["metadata"]["name"] == "db-0"
            # PVCs survive scale-down (stable identity).
            pvcs = (await store.list("persistentvolumeclaims")).items
            assert len(pvcs) == 3
            await teardown()
        run(body())
