"""Store durability: WAL + snapshot + crash recovery (SURVEY §5.4).

The contract proved here: a killed-and-restarted control plane resumes
with resourceVersion continuity, watches resume across the restart for
rvs newer than the last snapshot, and older rvs get 410 Expired (the
informer relist signal).
"""

import asyncio
import json
import os
import tempfile
import unittest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.store import (
    DurabilityManager,
    Expired,
    install_core_validation,
    new_cluster_store,
    recover_store,
)


def run(coro):
    return asyncio.run(coro)


class TestWALRecovery(unittest.TestCase):
    def test_crash_recovery_rv_continuity_and_watch_resume(self):
        async def body():
            d = tempfile.mkdtemp()
            store = new_cluster_store()
            install_core_validation(store)
            mgr = DurabilityManager(store, d, fsync="always",
                                    snapshot_interval_s=3600)
            await store.create("nodes", make_node("n0"))
            for i in range(5):
                await store.create("pods", make_pod(f"p{i}"))
            snap_rv = mgr.wal.snapshot()          # checkpoint mid-history
            created = await store.create("pods", make_pod("after-snap"))
            rv_before_crash = int(created["metadata"]["resourceVersion"])
            await store.create("pods", make_pod("last"))
            uid_last = (await store.get("pods", "default/last"))[
                "metadata"]["uid"]
            final_rv = store.resource_version
            # CRASH: no clean close, no final snapshot — the WAL alone
            # must carry the post-snapshot writes (fsync="always").
            del store, mgr

            re_store = recover_store(d)
            install_core_validation(re_store)
            # state + rv continuity
            self.assertEqual(re_store.resource_version, final_rv)
            pods = (await re_store.list("pods")).items
            self.assertEqual(len(pods), 7)
            self.assertEqual(
                (await re_store.get("pods", "default/last"))[
                    "metadata"]["uid"], uid_last)
            fresh = await re_store.create("pods", make_pod("post-restart"))
            self.assertEqual(int(fresh["metadata"]["resourceVersion"]),
                             final_rv + 1)
            # watch resumes exactly where the crashed watcher stopped
            watch = await re_store.watch(
                "pods", resource_version=rv_before_crash)
            got = []
            async for ev in watch:
                if ev.type == "BOOKMARK":
                    continue
                got.append((ev.type, ev.object["metadata"]["name"]))
                if len(got) == 2:
                    break
            self.assertEqual(got, [("ADDED", "last"),
                                   ("ADDED", "post-restart")])
            # pre-snapshot rvs are compacted -> 410 Expired (relist)
            with self.assertRaises(Expired):
                await re_store.watch("pods", resource_version=snap_rv - 3)
            re_store.stop()
        run(body())

    def test_deletes_and_updates_survive(self):
        async def body():
            d = tempfile.mkdtemp()
            store = new_cluster_store()
            install_core_validation(store)
            DurabilityManager(store, d, fsync="always",
                              snapshot_interval_s=3600)
            await store.create("pods", make_pod("keep"))
            await store.create("pods", make_pod("gone"))
            await store.delete("pods", "default/gone")

            def label(obj):
                obj["metadata"].setdefault("labels", {})["x"] = "1"
                return obj
            await store.guaranteed_update("pods", "default/keep", label)
            del store

            re_store = recover_store(d)
            pods = (await re_store.list("pods")).items
            self.assertEqual([p["metadata"]["name"] for p in pods],
                             ["keep"])
            self.assertEqual(pods[0]["metadata"]["labels"]["x"], "1")
            re_store.stop()
        run(body())

    def test_torn_tail_truncates_not_corrupts(self):
        async def body():
            d = tempfile.mkdtemp()
            store = new_cluster_store()
            DurabilityManager(store, d, fsync="always",
                              snapshot_interval_s=3600)
            await store.create("pods", make_pod("a"))
            await store.create("pods", make_pod("b"))
            # simulate a torn write at the tail
            wal = [f for f in os.listdir(d) if f.startswith("wal-")][0]
            with open(os.path.join(d, wal), "a") as f:
                f.write('[9999,"ADDED","po')  # no newline, truncated JSON
            del store
            re_store = recover_store(d)
            names = sorted(p["metadata"]["name"]
                           for p in (await re_store.list("pods")).items)
            self.assertEqual(names, ["a", "b"])
            self.assertLess(re_store.resource_version, 9999)
            re_store.stop()
        run(body())

    def test_periodic_snapshot_compacts_and_recovers(self):
        async def body():
            d = tempfile.mkdtemp()
            store = new_cluster_store()
            mgr = DurabilityManager(store, d, fsync="batch",
                                    flush_interval_s=0.01,
                                    snapshot_interval_s=0.05)
            mgr.start()
            for i in range(30):
                await store.create("pods", make_pod(f"p{i}"))
                await asyncio.sleep(0.005)
            await asyncio.sleep(0.1)  # let a snapshot land
            snaps = [f for f in os.listdir(d) if f.startswith("snapshot-")]
            self.assertTrue(snaps, "no periodic snapshot written")
            await mgr.stop()
            del store
            re_store = recover_store(d)
            self.assertEqual(
                len((await re_store.list("pods")).items), 30)
            re_store.stop()
        run(body())

    def test_selector_watch_transition_survives_restart(self):
        """prev_labels ride the WAL: a selector watcher resuming across
        the restart sees the synthesized DELETED for a label transition
        that happened while it was down (cacher prevObject semantics)."""
        async def body():
            import tempfile
            from kubernetes_tpu.api.labels import parse_selector
            d = tempfile.mkdtemp()
            store = new_cluster_store()
            install_core_validation(store)
            DurabilityManager(store, d, fsync="always",
                              snapshot_interval_s=3600)
            created = await store.create(
                "pods", make_pod("a", labels={"app": "web"}))
            rv0 = int(created["metadata"]["resourceVersion"])

            def drop(obj):
                obj["metadata"]["labels"] = {}
                return obj
            await store.guaranteed_update("pods", "default/a", drop)
            del store  # crash

            re_store = recover_store(d)
            watch = await re_store.watch(
                "pods", resource_version=rv0,
                selector=parse_selector("app=web"))
            async for ev in watch:
                if ev.type == "BOOKMARK":
                    continue
                self.assertEqual(ev.type, "DELETED")
                self.assertEqual(ev.object["metadata"]["name"], "a")
                break
            re_store.stop()
        run(body())

    def test_control_plane_restart_e2e(self):
        """Full loop: scheduler binds pods, the process 'dies', a new
        control plane recovers the store and keeps scheduling — bound
        pods stay bound, pending pods get scheduled."""
        async def body():
            d = tempfile.mkdtemp()
            store = new_cluster_store()
            install_core_validation(store)
            DurabilityManager(store, d, fsync="always",
                              snapshot_interval_s=3600)
            for i in range(3):
                await store.create("nodes", make_node(f"n{i}"))
            sched = Scheduler(store, seed=1)
            factory = InformerFactory(store)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            loop = asyncio.ensure_future(sched.run(batch_size=8))
            for i in range(4):
                await store.create("pods", make_pod(f"p{i}"))
            for _ in range(200):
                pods = (await store.list("pods")).items
                if sum(1 for p in pods
                       if p["spec"].get("nodeName")) == 4:
                    break
                await asyncio.sleep(0.02)
            await sched.stop()
            loop.cancel()
            factory.stop()
            # crash + restart
            del store
            re_store = recover_store(d)
            install_core_validation(re_store)
            pods = (await re_store.list("pods")).items
            bound = {p["metadata"]["name"]: p["spec"].get("nodeName")
                     for p in pods}
            self.assertEqual(sum(1 for v in bound.values() if v), 4)
            sched2 = Scheduler(re_store, seed=2)
            factory2 = InformerFactory(re_store)
            await sched2.setup_informers(factory2)
            factory2.start()
            await factory2.wait_for_sync()
            loop2 = asyncio.ensure_future(sched2.run(batch_size=8))
            await re_store.create("pods", make_pod("new-after-restart"))
            ok = False
            for _ in range(200):
                p = await re_store.get("pods", "default/new-after-restart")
                if p["spec"].get("nodeName"):
                    ok = True
                    break
                await asyncio.sleep(0.02)
            self.assertTrue(ok, "recovered control plane failed to bind")
            # bindings persisted before the crash are untouched
            for name, node in bound.items():
                cur = await re_store.get("pods", f"default/{name}")
                self.assertEqual(cur["spec"].get("nodeName"), node)
            await sched2.stop()
            loop2.cancel()
            factory2.stop()
            re_store.stop()
        run(body())


class TestServerDurabilityBootstrap(unittest.TestCase):
    """The KTPU_DATA_DIR / data_dir bootstrap (ISSUE 12 satellite):
    persistence reachable END TO END through the server, not just from
    tests — APIServer(data_dir=...) recovers on construction, runs the
    background snapshotter for its lifetime, and a restarted server
    serves the previous run's objects over the wire."""

    def test_server_data_dir_recover_on_restart(self):
        async def body():
            from kubernetes_tpu.apiserver import APIServer, RemoteStore
            d = tempfile.mkdtemp()
            srv = APIServer(data_dir=d, fsync="always")
            await srv.start()
            rs = RemoteStore(srv.url)
            await rs.create("nodes", make_node("dur-n0"))
            await rs.create("pods", make_pod("dur-p0"))
            rv_before = srv.store.resource_version
            await rs.close()
            await srv.stop()  # final snapshot on clean shutdown
            snaps = [f for f in os.listdir(d) if f.startswith("snapshot-")]
            self.assertTrue(snaps, "clean stop left no snapshot")

            srv2 = APIServer(data_dir=d)
            await srv2.start()
            self.assertGreaterEqual(srv2.store.resource_version, rv_before)
            rs2 = RemoteStore(srv2.url)
            pods = (await rs2.list("pods")).items
            self.assertEqual([p["metadata"]["name"] for p in pods],
                             ["dur-p0"])
            nodes = (await rs2.list("nodes")).items
            self.assertEqual([n["metadata"]["name"] for n in nodes],
                             ["dur-n0"])
            # RV continuity: the next write rides the recovered counter,
            # and the recovered server keeps committing to the WAL.
            created = await rs2.create("pods", make_pod("dur-p1"))
            self.assertGreater(
                int(created["metadata"]["resourceVersion"]), rv_before)
            await rs2.close()
            await srv2.stop()
        run(body())

    def test_env_bootstrap(self):
        async def body():
            from kubernetes_tpu.apiserver import APIServer
            d = tempfile.mkdtemp()
            os.environ["KTPU_DATA_DIR"] = d
            try:
                srv = APIServer()
                await srv.start()
                self.assertIsNotNone(srv.durability)
                await srv.store.create("pods", make_pod("env-p0"))
                await srv.stop()
            finally:
                os.environ.pop("KTPU_DATA_DIR", None)
            re_store = recover_store(d)
            self.assertEqual(
                (await re_store.get("pods", "default/env-p0"))[
                    "metadata"]["name"], "env-p0")
            # No store, no dir → explicit error, not a silent
            # in-memory server masquerading as durable.
            with self.assertRaises(ValueError):
                APIServer()
        run(body())


if __name__ == "__main__":
    unittest.main()
