"""Store durability: WAL + snapshot + crash recovery (SURVEY §5.4).

The contract proved here: a killed-and-restarted control plane resumes
with resourceVersion continuity, watches resume across the restart for
rvs newer than the last snapshot, and older rvs get 410 Expired (the
informer relist signal).
"""

import asyncio
import json
import os
import tempfile
import unittest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.store import (
    DurabilityManager,
    Expired,
    install_core_validation,
    new_cluster_store,
    recover_store,
)


def run(coro):
    return asyncio.run(coro)


class TestWALRecovery(unittest.TestCase):
    def test_crash_recovery_rv_continuity_and_watch_resume(self):
        async def body():
            d = tempfile.mkdtemp()
            store = new_cluster_store()
            install_core_validation(store)
            mgr = DurabilityManager(store, d, fsync="always",
                                    snapshot_interval_s=3600)
            await store.create("nodes", make_node("n0"))
            for i in range(5):
                await store.create("pods", make_pod(f"p{i}"))
            snap_rv = mgr.wal.snapshot()          # checkpoint mid-history
            created = await store.create("pods", make_pod("after-snap"))
            rv_before_crash = int(created["metadata"]["resourceVersion"])
            await store.create("pods", make_pod("last"))
            uid_last = (await store.get("pods", "default/last"))[
                "metadata"]["uid"]
            final_rv = store.resource_version
            # CRASH: no clean close, no final snapshot — the WAL alone
            # must carry the post-snapshot writes (fsync="always").
            del store, mgr

            re_store = recover_store(d)
            install_core_validation(re_store)
            # state + rv continuity
            self.assertEqual(re_store.resource_version, final_rv)
            pods = (await re_store.list("pods")).items
            self.assertEqual(len(pods), 7)
            self.assertEqual(
                (await re_store.get("pods", "default/last"))[
                    "metadata"]["uid"], uid_last)
            fresh = await re_store.create("pods", make_pod("post-restart"))
            self.assertEqual(int(fresh["metadata"]["resourceVersion"]),
                             final_rv + 1)
            # watch resumes exactly where the crashed watcher stopped
            watch = await re_store.watch(
                "pods", resource_version=rv_before_crash)
            got = []
            async for ev in watch:
                if ev.type == "BOOKMARK":
                    continue
                got.append((ev.type, ev.object["metadata"]["name"]))
                if len(got) == 2:
                    break
            self.assertEqual(got, [("ADDED", "last"),
                                   ("ADDED", "post-restart")])
            # pre-snapshot rvs are compacted -> 410 Expired (relist)
            with self.assertRaises(Expired):
                await re_store.watch("pods", resource_version=snap_rv - 3)
            re_store.stop()
        run(body())

    def test_deletes_and_updates_survive(self):
        async def body():
            d = tempfile.mkdtemp()
            store = new_cluster_store()
            install_core_validation(store)
            DurabilityManager(store, d, fsync="always",
                              snapshot_interval_s=3600)
            await store.create("pods", make_pod("keep"))
            await store.create("pods", make_pod("gone"))
            await store.delete("pods", "default/gone")

            def label(obj):
                obj["metadata"].setdefault("labels", {})["x"] = "1"
                return obj
            await store.guaranteed_update("pods", "default/keep", label)
            del store

            re_store = recover_store(d)
            pods = (await re_store.list("pods")).items
            self.assertEqual([p["metadata"]["name"] for p in pods],
                             ["keep"])
            self.assertEqual(pods[0]["metadata"]["labels"]["x"], "1")
            re_store.stop()
        run(body())

    def test_torn_tail_truncates_not_corrupts(self):
        async def body():
            d = tempfile.mkdtemp()
            store = new_cluster_store()
            DurabilityManager(store, d, fsync="always",
                              snapshot_interval_s=3600)
            await store.create("pods", make_pod("a"))
            await store.create("pods", make_pod("b"))
            # simulate a torn write at the tail
            wal = [f for f in os.listdir(d) if f.startswith("wal-")][0]
            with open(os.path.join(d, wal), "a") as f:
                f.write('[9999,"ADDED","po')  # no newline, truncated JSON
            del store
            re_store = recover_store(d)
            names = sorted(p["metadata"]["name"]
                           for p in (await re_store.list("pods")).items)
            self.assertEqual(names, ["a", "b"])
            self.assertLess(re_store.resource_version, 9999)
            re_store.stop()
        run(body())

    def test_periodic_snapshot_compacts_and_recovers(self):
        async def body():
            d = tempfile.mkdtemp()
            store = new_cluster_store()
            mgr = DurabilityManager(store, d, fsync="batch",
                                    flush_interval_s=0.01,
                                    snapshot_interval_s=0.05)
            mgr.start()
            for i in range(30):
                await store.create("pods", make_pod(f"p{i}"))
                await asyncio.sleep(0.005)
            await asyncio.sleep(0.1)  # let a snapshot land
            snaps = [f for f in os.listdir(d) if f.startswith("snapshot-")]
            self.assertTrue(snaps, "no periodic snapshot written")
            await mgr.stop()
            del store
            re_store = recover_store(d)
            self.assertEqual(
                len((await re_store.list("pods")).items), 30)
            re_store.stop()
        run(body())

    def test_selector_watch_transition_survives_restart(self):
        """prev_labels ride the WAL: a selector watcher resuming across
        the restart sees the synthesized DELETED for a label transition
        that happened while it was down (cacher prevObject semantics)."""
        async def body():
            import tempfile
            from kubernetes_tpu.api.labels import parse_selector
            d = tempfile.mkdtemp()
            store = new_cluster_store()
            install_core_validation(store)
            DurabilityManager(store, d, fsync="always",
                              snapshot_interval_s=3600)
            created = await store.create(
                "pods", make_pod("a", labels={"app": "web"}))
            rv0 = int(created["metadata"]["resourceVersion"])

            def drop(obj):
                obj["metadata"]["labels"] = {}
                return obj
            await store.guaranteed_update("pods", "default/a", drop)
            del store  # crash

            re_store = recover_store(d)
            watch = await re_store.watch(
                "pods", resource_version=rv0,
                selector=parse_selector("app=web"))
            async for ev in watch:
                if ev.type == "BOOKMARK":
                    continue
                self.assertEqual(ev.type, "DELETED")
                self.assertEqual(ev.object["metadata"]["name"], "a")
                break
            re_store.stop()
        run(body())

    def test_control_plane_restart_e2e(self):
        """Full loop: scheduler binds pods, the process 'dies', a new
        control plane recovers the store and keeps scheduling — bound
        pods stay bound, pending pods get scheduled."""
        async def body():
            d = tempfile.mkdtemp()
            store = new_cluster_store()
            install_core_validation(store)
            DurabilityManager(store, d, fsync="always",
                              snapshot_interval_s=3600)
            for i in range(3):
                await store.create("nodes", make_node(f"n{i}"))
            sched = Scheduler(store, seed=1)
            factory = InformerFactory(store)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            loop = asyncio.ensure_future(sched.run(batch_size=8))
            for i in range(4):
                await store.create("pods", make_pod(f"p{i}"))
            for _ in range(200):
                pods = (await store.list("pods")).items
                if sum(1 for p in pods
                       if p["spec"].get("nodeName")) == 4:
                    break
                await asyncio.sleep(0.02)
            await sched.stop()
            loop.cancel()
            factory.stop()
            # crash + restart
            del store
            re_store = recover_store(d)
            install_core_validation(re_store)
            pods = (await re_store.list("pods")).items
            bound = {p["metadata"]["name"]: p["spec"].get("nodeName")
                     for p in pods}
            self.assertEqual(sum(1 for v in bound.values() if v), 4)
            sched2 = Scheduler(re_store, seed=2)
            factory2 = InformerFactory(re_store)
            await sched2.setup_informers(factory2)
            factory2.start()
            await factory2.wait_for_sync()
            loop2 = asyncio.ensure_future(sched2.run(batch_size=8))
            await re_store.create("pods", make_pod("new-after-restart"))
            ok = False
            for _ in range(200):
                p = await re_store.get("pods", "default/new-after-restart")
                if p["spec"].get("nodeName"):
                    ok = True
                    break
                await asyncio.sleep(0.02)
            self.assertTrue(ok, "recovered control plane failed to bind")
            # bindings persisted before the crash are untouched
            for name, node in bound.items():
                cur = await re_store.get("pods", f"default/{name}")
                self.assertEqual(cur["spec"].get("nodeName"), node)
            await sched2.stop()
            loop2.cancel()
            factory2.stop()
            re_store.stop()
        run(body())


class TestSnapshotCrashAtomicity(unittest.TestCase):
    """ISSUE r22 satellite: snapshot writes are crash-atomic — written
    to `snapshot-<rv>.json.tmp`, fsynced, then `os.replace`d — so a
    crash mid-snapshot can never leave a half-written file that
    recovery would load as truth."""

    def test_no_tmp_after_snapshot_and_orphan_ignored(self):
        async def body():
            d = tempfile.mkdtemp()
            store = new_cluster_store()
            install_core_validation(store)
            mgr = DurabilityManager(store, d, fsync="always",
                                    snapshot_interval_s=3600)
            for i in range(4):
                await store.create("pods", make_pod(f"p{i}"))
            mgr.wal.snapshot()
            self.assertFalse(
                [f for f in os.listdir(d) if f.endswith(".tmp")],
                "normal snapshot left a .tmp behind")
            # A crash between the tmp write and os.replace leaves an
            # orphan — even one claiming a FUTURE rv with garbage in it.
            orphan = os.path.join(d, "snapshot-999999.json.tmp")
            with open(orphan, "w") as f:
                f.write('{"rv": 999999, "tables": {"pods"')
            await store.create("pods", make_pod("after"))
            final_rv = store.resource_version
            del store, mgr  # crash

            re_store = recover_store(d)
            self.assertEqual(re_store.resource_version, final_rv)
            self.assertEqual(
                len((await re_store.list("pods")).items), 5)
            # the next snapshot's GC reclaims the orphan
            mgr2 = DurabilityManager(re_store, d, fsync="always",
                                     snapshot_interval_s=3600)
            mgr2.wal.snapshot()
            self.assertFalse(os.path.exists(orphan),
                             "snapshot GC left the .tmp orphan")
            await mgr2.stop()
            re_store.stop()
        run(body())

    def test_crash_between_rotate_and_snapshot_write(self):
        """Phase A (capture + segment rotation) landed, phase B (the
        disk write) never did: recovery must fall back to the OLD
        snapshot and replay BOTH WAL segments — no committed write
        lost."""
        async def body():
            d = tempfile.mkdtemp()
            store = new_cluster_store()
            install_core_validation(store)
            mgr = DurabilityManager(store, d, fsync="always",
                                    snapshot_interval_s=3600)
            for i in range(3):
                await store.create("pods", make_pod(f"p{i}"))
            mgr.wal.snapshot()
            await store.create("pods", make_pod("in-old-segment"))
            # crash window: rotate happens, write_snapshot never runs
            mgr.wal.begin_snapshot()
            await store.create("pods", make_pod("in-new-segment"))
            final_rv = store.resource_version
            del store, mgr  # crash

            re_store = recover_store(d)
            names = sorted(p["metadata"]["name"]
                           for p in (await re_store.list("pods")).items)
            self.assertEqual(names, sorted(
                ["p0", "p1", "p2", "in-old-segment", "in-new-segment"]))
            self.assertEqual(re_store.resource_version, final_rv)
            re_store.stop()
        run(body())

    def test_stop_serializes_with_inflight_background_snapshot(self):
        """stop() awaits the background write_snapshot worker thread
        before taking its own final snapshot — two writers interleaving
        segment rotation + GC was the corruption window."""
        async def body():
            import time as _time
            d = tempfile.mkdtemp()
            store = new_cluster_store()
            install_core_validation(store)
            mgr = DurabilityManager(store, d, fsync="batch",
                                    flush_interval_s=0.01,
                                    snapshot_interval_s=0.05)
            orig = mgr.wal.write_snapshot

            def slow_write(data, rv):
                _time.sleep(0.3)   # widen the in-flight window
                orig(data, rv)
            mgr.wal.write_snapshot = slow_write
            mgr.start()
            for i in range(10):
                await store.create("pods", make_pod(f"p{i}"))
            for _ in range(400):   # wait for a background snapshot
                if mgr._snap_inflight is not None:
                    break
                await asyncio.sleep(0.01)
            self.assertIsNotNone(mgr._snap_inflight)
            await mgr.stop(final_snapshot=True)  # races the worker

            self.assertFalse(
                [f for f in os.listdir(d) if f.endswith(".tmp")])
            final_rv = store.resource_version
            del store, mgr
            re_store = recover_store(d)
            self.assertEqual(re_store.resource_version, final_rv)
            self.assertEqual(
                len((await re_store.list("pods")).items), 10)
            re_store.stop()
        run(body())

    def test_wal_kill_switch_snapshot_only(self):
        """KTPU_WAL=0 degrades to snapshot-only durability (the r16
        shape): writes after the last snapshot are legitimately lost on
        crash, and the log file stays empty. KTPU_WAL_FSYNC routes the
        fsync policy when no explicit argument is given."""
        async def body():
            from kubernetes_tpu.utils import flags
            d = tempfile.mkdtemp()
            with flags.scoped_set("KTPU_WAL", False), \
                    flags.scoped_set("KTPU_WAL_FSYNC", "always"):
                store = new_cluster_store()
                mgr = DurabilityManager(store, d,
                                        snapshot_interval_s=3600)
                self.assertEqual(mgr.wal.fsync, "always")
                self.assertFalse(mgr.wal.enabled)
                await store.create("pods", make_pod("durable"))
                mgr.wal.snapshot()
                await store.create("pods", make_pod("volatile"))
                del store, mgr  # crash: post-snapshot write unlogged
            wals = [f for f in os.listdir(d) if f.startswith("wal-")]
            self.assertTrue(all(
                os.path.getsize(os.path.join(d, f)) == 0 for f in wals))
            re_store = recover_store(d)
            names = [p["metadata"]["name"]
                     for p in (await re_store.list("pods")).items]
            self.assertEqual(names, ["durable"])
            re_store.stop()
        run(body())


class TestWALReplayDifferential(unittest.TestCase):
    """ISSUE r22 satellite: randomized differential — a seeded random
    create/update/delete stream with snapshots interleaved, crash,
    recover, then compare the FULL recovered dump (every table, every
    object, the rv counter) against the live store's final dump."""

    def test_randomized_stream_parity(self):
        async def body():
            import random
            for seed in (7, 23, 101):
                rng = random.Random(seed)
                d = tempfile.mkdtemp()
                store = new_cluster_store()
                install_core_validation(store)
                mgr = DurabilityManager(store, d, fsync="always",
                                        snapshot_interval_s=3600)
                alive = {"pods": [], "nodes": []}
                serial = 0
                for _ in range(120):
                    resource = rng.choice(("pods", "nodes"))
                    roll = rng.random()
                    if roll < 0.5 or not alive[resource]:
                        serial += 1
                        name = f"s{seed}-{resource[:-1]}-{serial}"
                        obj = (make_pod(name) if resource == "pods"
                               else make_node(name))
                        await store.create(resource, obj)
                        ns = obj["metadata"].get("namespace", "")
                        alive[resource].append(
                            f"{ns}/{name}" if ns else name)
                    elif roll < 0.8:
                        key = rng.choice(alive[resource])
                        stamp = str(rng.randrange(10_000))

                        def label(obj, stamp=stamp):
                            obj["metadata"].setdefault(
                                "labels", {})["stamp"] = stamp
                            return obj
                        await store.guaranteed_update(
                            resource, key, label)
                    else:
                        key = rng.choice(alive[resource])
                        alive[resource].remove(key)
                        await store.delete(resource, key)
                    if rng.random() < 0.05:
                        mgr.wal.snapshot()  # checkpoint mid-stream
                live = json.loads(store.dump())
                del store, mgr  # crash

                re_store = recover_store(d)
                recovered = json.loads(re_store.dump())
                self.assertEqual(recovered, live,
                                 f"replay diverged for seed {seed}")
                re_store.stop()
        run(body())


class TestServerDurabilityBootstrap(unittest.TestCase):
    """The KTPU_DATA_DIR / data_dir bootstrap (ISSUE 12 satellite):
    persistence reachable END TO END through the server, not just from
    tests — APIServer(data_dir=...) recovers on construction, runs the
    background snapshotter for its lifetime, and a restarted server
    serves the previous run's objects over the wire."""

    def test_server_data_dir_recover_on_restart(self):
        async def body():
            from kubernetes_tpu.apiserver import APIServer, RemoteStore
            d = tempfile.mkdtemp()
            srv = APIServer(data_dir=d, fsync="always")
            await srv.start()
            rs = RemoteStore(srv.url)
            await rs.create("nodes", make_node("dur-n0"))
            await rs.create("pods", make_pod("dur-p0"))
            rv_before = srv.store.resource_version
            await rs.close()
            await srv.stop()  # final snapshot on clean shutdown
            snaps = [f for f in os.listdir(d) if f.startswith("snapshot-")]
            self.assertTrue(snaps, "clean stop left no snapshot")

            srv2 = APIServer(data_dir=d)
            await srv2.start()
            self.assertGreaterEqual(srv2.store.resource_version, rv_before)
            rs2 = RemoteStore(srv2.url)
            pods = (await rs2.list("pods")).items
            self.assertEqual([p["metadata"]["name"] for p in pods],
                             ["dur-p0"])
            nodes = (await rs2.list("nodes")).items
            self.assertEqual([n["metadata"]["name"] for n in nodes],
                             ["dur-n0"])
            # RV continuity: the next write rides the recovered counter,
            # and the recovered server keeps committing to the WAL.
            created = await rs2.create("pods", make_pod("dur-p1"))
            self.assertGreater(
                int(created["metadata"]["resourceVersion"]), rv_before)
            await rs2.close()
            await srv2.stop()
        run(body())

    def test_env_bootstrap(self):
        async def body():
            from kubernetes_tpu.apiserver import APIServer
            d = tempfile.mkdtemp()
            os.environ["KTPU_DATA_DIR"] = d
            try:
                srv = APIServer()
                await srv.start()
                self.assertIsNotNone(srv.durability)
                await srv.store.create("pods", make_pod("env-p0"))
                await srv.stop()
            finally:
                os.environ.pop("KTPU_DATA_DIR", None)
            re_store = recover_store(d)
            self.assertEqual(
                (await re_store.get("pods", "default/env-p0"))[
                    "metadata"]["name"], "env-p0")
            # No store, no dir → explicit error, not a silent
            # in-memory server masquerading as durable.
            with self.assertRaises(ValueError):
                APIServer()
        run(body())


if __name__ == "__main__":
    unittest.main()
