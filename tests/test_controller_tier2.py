"""CronJob, ServiceAccount/token, attach-detach controllers (SURVEY §2.4
long tail — the round-4 controller-tier completion)."""

import asyncio
import unittest
from datetime import datetime, timezone

from kubernetes_tpu.api.meta import new_object
from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.apiserver.wire import WireServer, WireStore
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.controllers import (
    AttachDetachController,
    CronJobController,
    CronSchedule,
    ServiceAccountAuthenticator,
    ServiceAccountController,
    TokenController,
    make_cronjob,
)
from kubernetes_tpu.store import install_core_validation, new_cluster_store
from kubernetes_tpu.store.mvcc import StoreError


def run(coro):
    return asyncio.run(coro)


def ts(s: str) -> datetime:
    return datetime.fromisoformat(s).replace(tzinfo=timezone.utc)


class TestCronSchedule(unittest.TestCase):
    def test_every_minute(self):
        s = CronSchedule("* * * * *")
        self.assertEqual(s.next_after(ts("2026-07-30T10:00:30")),
                         ts("2026-07-30T10:01:00"))

    def test_specific_minute_hour(self):
        s = CronSchedule("30 2 * * *")
        self.assertEqual(s.next_after(ts("2026-07-30T10:00:00")),
                         ts("2026-07-31T02:30:00"))
        self.assertEqual(s.next_after(ts("2026-07-30T01:00:00")),
                         ts("2026-07-30T02:30:00"))

    def test_step_and_list(self):
        s = CronSchedule("*/15 8-10 * * *")
        self.assertEqual(s.next_after(ts("2026-07-30T08:20:00")),
                         ts("2026-07-30T08:30:00"))
        self.assertEqual(s.next_after(ts("2026-07-30T10:46:00")),
                         ts("2026-07-31T08:00:00"))

    def test_day_of_week(self):
        s = CronSchedule("0 9 * * 1")  # Mondays 09:00
        # 2026-07-30 is a Thursday; next Monday is 2026-08-03.
        self.assertEqual(s.next_after(ts("2026-07-30T12:00:00")),
                         ts("2026-08-03T09:00:00"))

    def test_month_rollover(self):
        s = CronSchedule("0 0 1 * *")  # first of the month
        self.assertEqual(s.next_after(ts("2026-12-15T00:00:00")),
                         ts("2027-01-01T00:00:00"))

    def test_bad_spec_rejected(self):
        with self.assertRaises(ValueError):
            CronSchedule("61 * * * *")
        with self.assertRaises(ValueError):
            CronSchedule("* * *")


class ControllerHarness:
    def __init__(self, controllers):
        self.controllers = controllers

    async def __aenter__(self):
        self.store = new_cluster_store()
        install_core_validation(self.store)
        self.factory = InformerFactory(self.store)
        self.built = [ctor(self.store) for ctor in self.controllers]
        for c in self.built:
            c.setup(self.factory)
        self.factory.start()
        await self.factory.wait_for_sync()
        for c in self.built:
            c.start()
        return self

    async def __aexit__(self, *exc):
        for c in self.built:
            await c.stop()
        self.factory.stop()
        self.store.stop()

    async def wait_for(self, pred, timeout=5.0, msg="condition"):
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            got = await pred()
            if got:
                return got
            await asyncio.sleep(0.02)
        raise AssertionError(f"timeout waiting for {msg}")


class TestCronJobController(unittest.TestCase):
    def test_schedule_spawns_job_and_records_last_schedule(self):
        async def body():
            clock = [ts("2026-07-30T10:00:30")]

            def build(store):
                return CronJobController(store, now=lambda: clock[0])

            async with ControllerHarness([build]) as h:
                cj = make_cronjob("tick", "* * * * *")
                cj["metadata"]["creationTimestamp"] = \
                    "2026-07-30T10:00:00Z"
                await h.store.create("cronjobs", cj)
                clock[0] = ts("2026-07-30T10:01:10")  # minute boundary hit

                async def job_exists():
                    jobs = (await h.store.list("jobs")).items
                    return jobs or None
                jobs = await h.wait_for(job_exists, msg="job spawn")
                self.assertEqual(len(jobs), 1)
                ref = jobs[0]["metadata"]["ownerReferences"][0]
                self.assertEqual(ref["kind"], "CronJob")
                cj = await h.store.get("cronjobs", "default/tick")
                self.assertEqual(cj["status"]["lastScheduleTime"],
                                 "2026-07-30T10:01:00Z")
                # same tick never double-fires
                await asyncio.sleep(0.2)
                self.assertEqual(
                    len((await h.store.list("jobs")).items), 1)
                # next minute fires the second job
                clock[0] = ts("2026-07-30T10:02:05")

                async def two_jobs():
                    return len((await h.store.list("jobs")).items) == 2 \
                        or None
                await h.wait_for(two_jobs, msg="second spawn")
        run(body())

    def test_forbid_policy_skips_while_active(self):
        async def body():
            clock = [ts("2026-07-30T10:00:30")]

            def build(store):
                return CronJobController(store, now=lambda: clock[0])

            async with ControllerHarness([build]) as h:
                cj = make_cronjob("solo", "* * * * *",
                                  concurrency_policy="Forbid")
                cj["metadata"]["creationTimestamp"] = \
                    "2026-07-30T10:00:00Z"
                await h.store.create("cronjobs", cj)
                clock[0] = ts("2026-07-30T10:01:10")

                async def one_job():
                    jobs = (await h.store.list("jobs")).items
                    return jobs or None
                await h.wait_for(one_job, msg="first spawn")
                # job still active; the next tick must NOT spawn
                clock[0] = ts("2026-07-30T10:02:10")
                await asyncio.sleep(0.3)
                self.assertEqual(
                    len((await h.store.list("jobs")).items), 1)
        run(body())

    def test_suspend_blocks_spawning(self):
        async def body():
            clock = [ts("2026-07-30T10:00:30")]

            def build(store):
                return CronJobController(store, now=lambda: clock[0])

            async with ControllerHarness([build]) as h:
                await h.store.create("cronjobs", make_cronjob(
                    "paused", "* * * * *", suspend=True))
                clock[0] = ts("2026-07-30T10:05:00")
                await asyncio.sleep(0.3)
                self.assertEqual((await h.store.list("jobs")).items, [])
        run(body())


class TestServiceAccounts(unittest.TestCase):
    def test_default_sa_and_token_lifecycle(self):
        async def body():
            async with ControllerHarness(
                    [ServiceAccountController, TokenController]) as h:
                await h.store.create("namespaces", new_object(
                    "Namespace", "team-a", None))

                async def sa_ready():
                    try:
                        return await h.store.get(
                            "serviceaccounts", "team-a/default")
                    except StoreError:
                        return None
                await h.wait_for(sa_ready, msg="default SA")

                async def token_ready():
                    secrets = (await h.store.list(
                        "secrets", namespace="team-a")).items
                    return secrets or None
                secrets = await h.wait_for(token_ready, msg="token secret")
                token = secrets[0]["data"]["token"]
                self.assertTrue(token.startswith("sa-"))
                # deleting the SA removes its token; the default SA is
                # then recreated with a fresh one
                await h.store.delete("serviceaccounts", "team-a/default")

                async def rotated():
                    secrets = (await h.store.list(
                        "secrets", namespace="team-a")).items
                    if len(secrets) == 1 and \
                            secrets[0]["data"]["token"] != token:
                        return secrets
                    return None
                await h.wait_for(rotated, msg="token rotation")
        run(body())

    def test_issued_token_authenticates_and_rbac_binds(self):
        async def body():
            from kubernetes_tpu.apiserver.rbac import RBACAuthorizer
            async with ControllerHarness(
                    [ServiceAccountController, TokenController]) as h:
                authn = ServiceAccountAuthenticator(h.factory)
                await h.store.create("namespaces", new_object(
                    "Namespace", "ci", None))

                async def token_ready():
                    secrets = (await h.store.list(
                        "secrets", namespace="ci")).items
                    return secrets or None
                secrets = await h.wait_for(token_ready, msg="token")
                token = secrets[0]["data"]["token"]
                authz = RBACAuthorizer()
                authz.add_role({"metadata": {"name": "podreader"},
                                "rules": [{"verbs": ["get", "list"],
                                           "resources": ["pods"]}]})
                authz.add_binding({
                    "roleRef": {"kind": "ClusterRole",
                                "name": "podreader"},
                    "subjects": [{"kind": "ServiceAccount",
                                  "name": "default",
                                  "namespace": "ci"}]})
                server = WireServer(h.store, token_authenticator=authn,
                                    authorizer=authz)
                await server.start()
                client = WireStore(server.target, token=token)
                try:
                    await h.store.create("pods", make_pod("a"))
                    got = await client.get("pods", "default/a")
                    self.assertEqual(got["metadata"]["name"], "a")
                    with self.assertRaises(StoreError):
                        await client.create("pods", make_pod("b"))
                    bad = WireStore(server.target, token="sa-forged")
                    with self.assertRaises(StoreError):
                        await bad.get("pods", "default/a")
                    await bad.close()
                finally:
                    await client.close()
                    await server.stop()
        run(body())


class TestAttachDetach(unittest.TestCase):
    def test_attach_on_schedule_detach_on_delete(self):
        async def body():
            async with ControllerHarness([AttachDetachController]) as h:
                await h.store.create("nodes", make_node("n0"))
                await h.store.create("persistentvolumes", new_object(
                    "PersistentVolume", "pv-1", None,
                    spec={"capacity": {"storage": "10Gi"}}))
                pvc = new_object("PersistentVolumeClaim", "data", "default",
                                 spec={"volumeName": "pv-1"})
                await h.store.create("persistentvolumeclaims", pvc)
                pod = make_pod("user", node_name="n0")
                pod["spec"]["volumes"] = [{
                    "name": "data",
                    "persistentVolumeClaim": {"claimName": "data"}}]
                await h.store.create("pods", pod)

                async def attached():
                    vas = (await h.store.list("volumeattachments")).items
                    for va in vas:
                        if va["spec"]["source"][
                                "persistentVolumeName"] == "pv-1" \
                                and va["spec"]["nodeName"] == "n0" \
                                and va.get("status", {}).get("attached"):
                            return va
                    return None
                await h.wait_for(attached, msg="attach")
                # second pod on the same node/PV: attachment is shared
                pod2 = make_pod("user2", node_name="n0")
                pod2["spec"]["volumes"] = [{
                    "name": "data",
                    "persistentVolumeClaim": {"claimName": "data"}}]
                await h.store.create("pods", pod2)
                await asyncio.sleep(0.2)
                self.assertEqual(
                    len((await h.store.list("volumeattachments")).items),
                    1)
                # detach only after the LAST user leaves
                await h.store.delete("pods", "default/user")
                await asyncio.sleep(0.2)
                self.assertEqual(
                    len((await h.store.list("volumeattachments")).items),
                    1)
                await h.store.delete("pods", "default/user2")

                async def detached():
                    vas = (await h.store.list("volumeattachments")).items
                    return True if not vas else None
                await h.wait_for(detached, msg="detach")
        run(body())


if __name__ == "__main__":
    unittest.main()


class TestTokenSquattedName(unittest.TestCase):
    """A foreign secret squatting `<sa>-token` must not wedge the token
    controller: it falls back to a suffixed name and only mirrors names
    that actually authenticate (advisor r4)."""

    def test_foreign_secret_squat_falls_back_to_suffixed_name(self):
        async def body():
            async with ControllerHarness(
                    [ServiceAccountController, TokenController]) as h:
                squat = new_object("Secret", "robot-token", "default",
                                   type="Opaque", data={"x": "y"})
                await h.store.create("secrets", squat)
                await h.store.create(
                    "serviceaccounts",
                    new_object("ServiceAccount", "robot", "default"))

                async def sa_has_live_token():
                    sa = await h.store.get(
                        "serviceaccounts", "default/robot")
                    for ref in sa.get("secrets") or []:
                        try:
                            s = await h.store.get(
                                "secrets", f"default/{ref['name']}")
                        except StoreError:
                            continue
                        ann = (s.get("metadata") or {}).get(
                            "annotations") or {}
                        if (s.get("type") ==
                                "kubernetes.io/service-account-token"
                                and ann.get(
                                    "kubernetes.io/service-account.name")
                                == "robot"):
                            return s
                    return None
                tok = await h.wait_for(sa_has_live_token,
                                       msg="suffixed token secret")
                self.assertNotEqual(tok["metadata"]["name"], "robot-token")
                self.assertTrue(
                    tok["metadata"]["name"].startswith("robot-token-"))
                # The squatter is untouched.
                squatted = await h.store.get("secrets",
                                             "default/robot-token")
                self.assertEqual(squatted.get("type"), "Opaque")
        run(body())

    def test_double_squat_warns_and_emits_event(self):
        """BOTH candidate names squatted by foreign secrets (ADVICE
        r5): sync must not return silently — it logs a warning and
        emits a Warning Event on the SA so the dead-end is observable —
        and it never mirrors a dead name into sa.secrets."""
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            try:
                sa = new_object("ServiceAccount", "wedged", "default")
                await store.create("serviceaccounts", sa)
                stored = await store.get(
                    "serviceaccounts", "default/wedged")
                uid = stored["metadata"]["uid"]
                suffix = uid.replace("-", "")[:6]
                for name in ("wedged-token",
                             f"wedged-token-{suffix}"):
                    await store.create("secrets", new_object(
                        "Secret", name, "default",
                        type="Opaque", data={"x": "y"}))
                # No workers: sync() runs by hand, so the squats are
                # guaranteed in place before the controller looks.
                factory = InformerFactory(store)
                tc = TokenController(store)
                tc.setup(factory)
                factory.start()
                await factory.wait_for_sync()
                with self.assertLogs(
                        "kubernetes_tpu.controllers.serviceaccount",
                        level="WARNING") as logs:
                    await tc.sync("default/wedged")
                self.assertTrue(any("wedged-token" in ln
                                    for ln in logs.output))

                async def squat_event():
                    evs = (await store.list(
                        "events", namespace="default")).items
                    return [e for e in evs
                            if e.get("reason") == "TokenSecretSquatted"]
                deadline = asyncio.get_event_loop().time() + 5.0
                evs = []
                while asyncio.get_event_loop().time() < deadline:
                    evs = await squat_event()
                    if evs:
                        break
                    await asyncio.sleep(0.02)
                self.assertTrue(evs, "no TokenSecretSquatted Event")
                self.assertEqual(evs[0]["type"], "Warning")
                self.assertEqual(
                    evs[0]["involvedObject"]["name"], "wedged")
                # resyncs dead-end identically: same warning, and the
                # SA never mirrors a dead name
                await tc.sync("default/wedged")
                sa_now = await store.get(
                    "serviceaccounts", "default/wedged")
                self.assertFalse(sa_now.get("secrets"))
                factory.stop()
            finally:
                store.stop()
        run(body())
