"""End-to-end scheduler tests: store → informers → cycles → Binding in store.

The scheduler_perf trick (SURVEY §3.5): pods "run" because nothing contradicts
Bind — no kubelet needed.
"""

import asyncio

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def run(coro):
    return asyncio.run(coro)


async def make_cluster(num_nodes=5, node_kw=None):
    store = new_cluster_store()
    install_core_validation(store)
    for i in range(num_nodes):
        await store.create("nodes", make_node(f"node-{i}", **(node_kw or {})))
    return store


from tests.conftest import start_scheduler  # noqa: E402


async def wait_bound(store, n, timeout=5.0):
    for _ in range(int(timeout / 0.05)):
        pods = (await store.list("pods")).items
        bound = [p for p in pods if p["spec"].get("nodeName")]
        if len(bound) >= n:
            return bound
        await asyncio.sleep(0.05)
    return [p for p in (await store.list("pods")).items if p["spec"].get("nodeName")]


class TestE2E:
    def test_schedules_pending_pods(self):
        async def body():
            store = await make_cluster(5)
            sched, factory = await start_scheduler(store)
            for i in range(20):
                await store.create("pods", make_pod(
                    f"p{i}", requests={"cpu": "100m", "memory": "128Mi"}))
            loop = asyncio.ensure_future(sched.run())
            bound = await wait_bound(store, 20)
            assert len(bound) == 20
            # spread across nodes (LeastAllocated should balance)
            nodes_used = {p["spec"]["nodeName"] for p in bound}
            assert len(nodes_used) == 5
            await sched.stop()
            loop.cancel()
            factory.stop()
            store.stop()
        run(body())

    def test_unschedulable_then_node_added(self):
        async def body():
            store = await make_cluster(1, node_kw={
                "allocatable": {"cpu": "1", "memory": "1Gi", "pods": "110"}})
            sched, factory = await start_scheduler(store)
            await store.create("pods", make_pod("big", requests={"cpu": "4"}))
            loop = asyncio.ensure_future(sched.run())
            await asyncio.sleep(0.3)
            assert sched.queue.stats()["unschedulable"] == 1
            events = (await store.list("events")).items
            assert any(e.get("reason") == "FailedScheduling" for e in events)
            # Node/Add event moves the pod back; it then schedules.
            await store.create("nodes", make_node(
                "bignode", allocatable={"cpu": "8", "memory": "8Gi", "pods": "110"}))
            bound = await wait_bound(store, 1, timeout=8)
            assert len(bound) == 1 and bound[0]["spec"]["nodeName"] == "bignode"
            await sched.stop()
            loop.cancel()
            factory.stop()
            store.stop()
        run(body())

    def test_batched_pop_resolves_contention(self):
        """With batch>1 and the host fallback path, pods later in the batch
        see earlier assumes (no double-booking the same free slot)."""
        async def body():
            store = await make_cluster(2, node_kw={
                "allocatable": {"cpu": "2", "memory": "4Gi", "pods": "110"}})
            sched, factory = await start_scheduler(store)
            for i in range(4):
                await store.create("pods", make_pod(
                    f"p{i}", requests={"cpu": "1"}))
            loop = asyncio.ensure_future(sched.run(batch_size=4))
            bound = await wait_bound(store, 4)
            assert len(bound) == 4
            per_node = {}
            for p in bound:
                per_node.setdefault(p["spec"]["nodeName"], 0)
                per_node[p["spec"]["nodeName"]] += 1
            assert all(v == 2 for v in per_node.values()), per_node
            await sched.stop()
            loop.cancel()
            factory.stop()
            store.stop()
        run(body())

    def test_preemption_evicts_lower_priority(self):
        async def body():
            store = await make_cluster(1, node_kw={
                "allocatable": {"cpu": "2", "memory": "4Gi", "pods": "110"}})
            sched, factory = await start_scheduler(store)
            loop = asyncio.ensure_future(sched.run())
            await store.create("pods", make_pod(
                "victim", requests={"cpu": "2"}, priority=0))
            await wait_bound(store, 1)
            await store.create("pods", make_pod(
                "preemptor", requests={"cpu": "2"}, priority=1000))
            # victim gets API-deleted; preemptor eventually binds
            for _ in range(100):
                pods = {p["metadata"]["name"]: p
                        for p in (await store.list("pods")).items}
                if ("victim" not in pods
                        and pods.get("preemptor", {}).get("spec", {}).get("nodeName")):
                    break
                await asyncio.sleep(0.05)
            pods = {p["metadata"]["name"]: p
                    for p in (await store.list("pods")).items}
            assert "victim" not in pods
            assert pods["preemptor"]["spec"].get("nodeName") == "node-0"
            await sched.stop()
            loop.cancel()
            factory.stop()
            store.stop()
        run(body())

    def test_affinity_e2e(self):
        async def body():
            store = await make_cluster(0)
            for zone, name in (("a", "za-1"), ("a", "za-2"), ("b", "zb-1")):
                await store.create("nodes", make_node(
                    name, labels={"topology.kubernetes.io/zone": zone}))
            sched, factory = await start_scheduler(store)
            loop = asyncio.ensure_future(sched.run())
            await store.create("pods", make_pod(
                "db", labels={"app": "db"},
                node_selector={"topology.kubernetes.io/zone": "a"}))
            await wait_bound(store, 1)
            anti = {"podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"labelSelector": {"matchLabels": {"app": "db"}},
                     "topologyKey": "topology.kubernetes.io/zone"}]}}
            await store.create("pods", make_pod(
                "db2", labels={"app": "db"}, affinity=anti))
            bound = await wait_bound(store, 2)
            by_name = {p["metadata"]["name"]: p["spec"]["nodeName"] for p in bound}
            assert by_name["db"].startswith("za")
            assert by_name["db2"] == "zb-1"  # anti-affinity forced zone b
            await sched.stop()
            loop.cancel()
            factory.stop()
            store.stop()
        run(body())

    def test_namespace_selector_affinity_e2e(self):
        """namespaceSelector terms resolve against live Namespace objects
        (reference PreFilter namespace merge): a pod in ns `web` requires
        co-zone with hub pods in any namespace labeled team=infra."""
        async def body():
            from kubernetes_tpu.api.meta import new_object
            store = await make_cluster(0)
            for name, labels in (("web", {"team": "app"}),
                                 ("infra-a", {"team": "infra"})):
                await store.create("namespaces", new_object(
                    "Namespace", name, None, labels=labels))
            for zone, name in (("a", "za-1"), ("b", "zb-1")):
                await store.create("nodes", make_node(
                    name, labels={"topology.kubernetes.io/zone": zone}))
            sched, factory = await start_scheduler(store)
            loop = asyncio.ensure_future(sched.run())
            await store.create("pods", make_pod(
                "hub", namespace="infra-a", labels={"app": "hub"},
                node_selector={"topology.kubernetes.io/zone": "b"}))
            await wait_bound(store, 1)
            aff = {"podAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"labelSelector": {"matchLabels": {"app": "hub"}},
                     "namespaceSelector": {"matchLabels": {"team": "infra"}},
                     "topologyKey": "topology.kubernetes.io/zone"}]}}
            await store.create("pods", make_pod(
                "w1", namespace="web", affinity=aff))
            bound = await wait_bound(store, 2)
            by_name = {p["metadata"]["name"]: p["spec"].get("nodeName")
                       for p in bound}
            # cross-namespace affinity pulled w1 into the hub's zone
            assert by_name.get("w1") == "zb-1", by_name
            await sched.stop()
            loop.cancel()
            factory.stop()
            store.stop()
        run(body())

    def test_heterogeneous_spread_templates_exact_on_backend(self):
        """Two DoNotSchedule spread templates + a cross-matching plain pod
        in ONE batch: the union-table scan must satisfy BOTH templates'
        skew exactly (no verify/requeue churn), counting the plain pod
        where its labels match."""
        async def body():
            from kubernetes_tpu.ops import TPUBackend
            store = await make_cluster(0)
            for zone in ("a", "b", "c"):
                for i in range(2):
                    await store.create("nodes", make_node(
                        f"z{zone}-{i}",
                        labels={"topology.kubernetes.io/zone":
                                f"zone-{zone}"}))
            sched, factory = await start_scheduler(
                store, backend=TPUBackend(max_batch=64))
            loop = asyncio.ensure_future(sched.run(batch_size=64))

            def spread(name, app, skew):
                return make_pod(
                    name, labels={"app": app}, requests={"cpu": "100m"},
                    topology_spread_constraints=[{
                        "maxSkew": skew,
                        "topologyKey": "topology.kubernetes.io/zone",
                        "whenUnsatisfiable": "DoNotSchedule",
                        "labelSelector": {"matchLabels": {"app": app}}}])
            # one batch: 9 of template A (skew 1), 6 of template B
            # (skew 2), and one PLAIN pod whose labels match template A.
            plain = make_pod("plain-a", labels={"app": "a"},
                             requests={"cpu": "100m"})
            await store.create("pods", plain)
            for i in range(9):
                await store.create("pods", spread(f"a{i}", "a", 1))
            for i in range(6):
                await store.create("pods", spread(f"b{i}", "b", 2))
            bound = await wait_bound(store, 16, timeout=30.0)
            assert len(bound) == 16, len(bound)
            zones = {"a": {}, "b": {}}
            node_zone = {n["metadata"]["name"]:
                         n["metadata"]["labels"][
                             "topology.kubernetes.io/zone"]
                         for n in (await store.list("nodes")).items}
            for p in bound:
                app = p["metadata"].get("labels", {}).get("app")
                z = node_zone[p["spec"]["nodeName"]]
                if app in zones:
                    zones[app][z] = zones[app].get(z, 0) + 1
            # template A counts the plain pod too: 10 matching pods over
            # 3 zones with maxSkew 1 → per-zone counts within 1 of each
            # other; template B within 2.
            a_counts = [zones["a"].get(f"zone-{z}", 0)
                        for z in ("a", "b", "c")]
            b_counts = [zones["b"].get(f"zone-{z}", 0)
                        for z in ("a", "b", "c")]
            assert sum(a_counts) == 10 and sum(b_counts) == 6
            assert max(a_counts) - min(a_counts) <= 1, a_counts
            assert max(b_counts) - min(b_counts) <= 2, b_counts
            # zero requeue churn: nothing was ever unschedulable
            unsched = sched.metrics.schedule_attempts.value(
                result="unschedulable", profile="default-scheduler")
            assert unsched == 0, unsched
            await sched.stop()
            loop.cancel()
            factory.stop()
            store.stop()
        run(body())
