"""gRPC + protobuf wire (SURVEY §5.8): the runtime.Unknown-envelope
service, with the informer stack and scheduler running over it."""

import asyncio

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.apiserver.grpc_server import (
    GRPCAPIServer,
    GRPCRemoteStore,
)
from kubernetes_tpu.client import InformerFactory, ResourceEventHandler
from kubernetes_tpu.store import install_core_validation, new_cluster_store
from kubernetes_tpu.store.mvcc import (
    AlreadyExists,
    Conflict,
    Expired,
    MVCCStore,
    NotFound,
)


def run(coro):
    return asyncio.run(coro)


async def _serve(store=None):
    store = store or new_cluster_store()
    install_core_validation(store)
    srv = GRPCAPIServer(store)
    await srv.start()
    return store, srv


class TestCRUD:
    def test_roundtrip_and_error_mapping(self):
        async def body():
            store, srv = await _serve()
            rs = GRPCRemoteStore(srv.target)
            created = await rs.create("pods", make_pod("a"))
            assert created["metadata"]["resourceVersion"]
            got = await rs.get("pods", "default/a")
            assert got["metadata"]["name"] == "a"
            with pytest.raises(AlreadyExists):
                await rs.create("pods", make_pod("a"))
            with pytest.raises(NotFound):
                await rs.get("pods", "default/nope")
            # Conflict on stale RV update
            stale = dict(got)
            await rs.update("pods", got)
            with pytest.raises(Conflict):
                await rs.update("pods", stale)
            # binding subresource over gRPC
            await rs.create("nodes", make_node("n1"))
            st = await rs.subresource(
                "pods", "default/a", "binding", {"target": {"name": "n1"}})
            assert st["status"] == "Success"
            bound = await rs.get("pods", "default/a")
            assert bound["spec"]["nodeName"] == "n1"
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())

    def test_guaranteed_update_cas(self):
        async def body():
            store, srv = await _serve()
            rs = GRPCRemoteStore(srv.target)
            await rs.create("pods", make_pod("a"))

            def label(obj):
                obj["metadata"].setdefault("labels", {})["x"] = "1"
                return obj
            out = await rs.guaranteed_update("pods", "default/a", label)
            assert out["metadata"]["labels"]["x"] == "1"
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())


class TestWatch:
    def test_watch_streams_and_expires(self):
        async def body():
            small = MVCCStore(event_window=5)
            install_core_validation(small)
            srv = GRPCAPIServer(small)
            await srv.start()
            rs = GRPCRemoteStore(srv.target)

            events = []

            async def consume():
                async for ev in await rs.watch("pods"):
                    if ev.type != "BOOKMARK":
                        events.append((ev.type,
                                       ev.object["metadata"]["name"]))
                    if len(events) >= 2:
                        return
            task = asyncio.ensure_future(consume())
            await asyncio.sleep(0.1)
            await rs.create("pods", make_pod("w1"))
            await rs.delete("pods", "default/w1")
            await asyncio.wait_for(task, timeout=5.0)
            assert events == [("ADDED", "w1"), ("DELETED", "w1")]

            # Expired resourceVersion → Expired (410 analog) for relist.
            for i in range(30):
                await rs.create("pods", make_pod(f"p{i}"))
            with pytest.raises(Expired):
                gen = await rs.watch("pods", resource_version=2)
                async for _ in gen:
                    break
            await rs.close()
            await srv.stop()
            small.stop()
        run(body())


class TestInformersAndSchedulerOverGRPC:
    def test_scheduler_binds_through_grpc(self):
        """The full informer + scheduler stack runs unchanged over the
        gRPC wire — the §3.1 bind POST as a protobuf RPC."""
        async def body():
            from kubernetes_tpu.scheduler import Scheduler
            store, srv = await _serve()
            rs = GRPCRemoteStore(srv.target)
            for i in range(3):
                await rs.create("nodes", make_node(f"n{i}"))
            sched = Scheduler(rs, seed=4)
            factory = InformerFactory(rs)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            task = asyncio.ensure_future(sched.run())
            for i in range(10):
                await rs.create("pods", make_pod(
                    f"p{i}", requests={"cpu": "100m"}))
            for _ in range(200):
                lst = await rs.list("pods")
                if sum(1 for p in lst.items
                       if p["spec"].get("nodeName")) == 10:
                    break
                await asyncio.sleep(0.05)
            lst = await rs.list("pods")
            assert sum(1 for p in lst.items
                       if p["spec"].get("nodeName")) == 10
            await sched.stop()
            task.cancel()
            factory.stop()
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())

    def test_informer_syncs_over_grpc(self):
        async def body():
            store, srv = await _serve()
            rs = GRPCRemoteStore(srv.target)
            for i in range(5):
                await store.create("pods", make_pod(f"p{i}"))
            factory = InformerFactory(rs)
            inf = factory.informer("pods")
            adds = []
            inf.add_event_handler(ResourceEventHandler(
                on_add=lambda o: adds.append(o["metadata"]["name"])))
            factory.start()
            await factory.wait_for_sync()
            assert len(adds) == 5
            await store.create("pods", make_pod("live"))
            await asyncio.sleep(0.3)
            assert "live" in adds
            factory.stop()
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())
