"""ChurnDay battery units: seeded timeline determinism, the open-loop
invariant under saturation, knee detection, and the agent kill seam."""

import asyncio
import math

from kubernetes_tpu.api.types import make_pod
from kubernetes_tpu.perf import PerfRunner
from kubernetes_tpu.perf.churn import (
    BurstArrivals,
    PoissonArrivals,
    RampArrivals,
    build_fault_timeline,
    find_knee,
    make_arrival_process,
)
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def run(coro):
    return asyncio.run(coro)


class TestArrivalDeterminism:
    def test_same_seed_bit_identical_across_instances(self):
        """Two independently constructed processes with the same seed
        produce byte-for-byte equal timelines (cross-run contract: the
        seed derivation avoids randomized str hashing)."""
        for cls, kw in ((PoissonArrivals, {}),
                        (BurstArrivals, {"burst_size": 7}),
                        (RampArrivals, {"end_rate": 120.0})):
            a = cls(40.0, seed=9, **kw).timeline(3.0)
            b = cls(40.0, seed=9, **kw).timeline(3.0)
            assert a == b, cls.kind
            assert a, cls.kind  # non-empty at 40/s over 3s

    def test_timeline_repeatable_per_instance(self):
        p = PoissonArrivals(100.0, seed=3)
        assert p.timeline(1.0) == p.timeline(1.0)

    def test_different_seed_differs(self):
        assert PoissonArrivals(100.0, seed=1).timeline(2.0) != \
            PoissonArrivals(100.0, seed=2).timeline(2.0)

    def test_rate_matches_expectation(self):
        """Mean-rate sanity per model: counts within 5σ of rate×duration
        (deterministic given the seed, so this can't flake)."""
        for spec in ({"model": "poisson", "rate": 200},
                     {"model": "burst", "rate": 200, "burstSize": 16},
                     {"model": "ramp", "rate": 100, "endRate": 300}):
            proc = make_arrival_process(spec, seed=5)
            n = len(proc.timeline(4.0))
            expect = 200 * 4.0  # ramp's mean (100+300)/2 = 200 too
            assert abs(n - expect) < 5 * math.sqrt(expect) + 16, spec

    def test_timeline_sorted_and_bounded(self):
        for spec in ({"model": "poisson", "rate": 150},
                     {"model": "burst", "rate": 150},
                     {"model": "ramp", "rate": 50, "endRate": 400}):
            tl = make_arrival_process(spec, seed=2).timeline(2.0)
            assert tl == sorted(tl)
            assert all(0.0 <= t < 2.0 for t in tl)

    def test_ramp_down_does_not_crash(self):
        """endRate < rate is a legal spec (ramp-DOWN): the concave
        cumulative intensity must terminate the timeline, not raise a
        math domain error, and the mean still tracks (r0+r1)/2."""
        for seed in range(5):
            tl = make_arrival_process(
                {"model": "ramp", "rate": 100, "endRate": 1},
                seed=seed).timeline(10.0)
            assert tl == sorted(tl)
            assert all(0.0 <= t < 10.0 for t in tl)
            expect = (100 + 1) / 2 * 10.0
            assert abs(len(tl) - expect) < 5 * math.sqrt(expect) + 16


class TestFaultTimeline:
    def test_deterministic_victim_selection(self):
        nodes = [f"node-{i}" for i in range(20)]
        specs = [{"at": 1.0, "kind": "nodeDeath"},
                 {"at": 2.5, "kind": "rolloutWave", "count": 5},
                 {"at": 3.0, "kind": "gangArrival", "count": 4}]
        a = build_fault_timeline(specs, seed=7, node_names=nodes)
        b = build_fault_timeline(specs, seed=7, node_names=nodes)
        assert [e.signature() for e in a] == [e.signature() for e in b]
        assert a[0].params["node"] in nodes
        assert [e.at for e in a] == sorted(e.at for e in a)

    def test_no_nodes_for_node_fault_raises(self):
        import pytest
        with pytest.raises(ValueError):
            build_fault_timeline([{"at": 0.5, "kind": "nodeDeath"}],
                                 seed=1, node_names=[])

    def test_explicit_node_wins(self):
        tl = build_fault_timeline(
            [{"at": 0.1, "kind": "drain", "node": "n7"}], seed=3,
            node_names=["a", "b"])
        assert tl[0].params["node"] == "n7"


class TestKnee:
    def _row(self, rate, arrivals, backlog, p999):
        return {"churn_offered_rate": rate,
                "churn_arrivals_total": arrivals,
                "churn_backlog_final": backlog,
                "attempt_p999_ms": p999, "attempt_p99_ms": p999 / 2,
                "attempt_p50_ms": p999 / 10}

    def test_knee_is_highest_unsaturated(self):
        rows = [self._row(100, 1000, 0, 2.0),
                self._row(400, 4000, 10, 3.0),
                self._row(1600, 16000, 9000, 40.0)]
        knee = find_knee(rows)
        assert knee["knee_rate"] == 400
        assert knee["first_saturated_rate"] == 1600
        assert knee["knee_p999_ms"] == 3.0

    def test_all_saturated_has_no_knee(self):
        knee = find_knee([self._row(100, 1000, 900, 5.0)])
        assert knee["knee_rate"] is None
        assert knee["first_saturated_rate"] == 100

    def test_non_monotonic_saturation_keeps_highest_absorbed(self):
        """A saturated trickle row (the un-amortized-dispatch pathology)
        must not erase a higher absorbed rate: knee = highest
        non-saturated row wherever it sits, upper bound = the lowest
        saturated rate ABOVE it."""
        rows = [self._row(50, 500, 400, 8.0),      # trickle, saturated
                self._row(400, 4000, 10, 3.0),     # absorbed
                self._row(1600, 16000, 9000, 40.0)]
        knee = find_knee(rows)
        assert knee["knee_rate"] == 400
        assert knee["first_saturated_rate"] == 1600


class TestOpenLoopInvariant:
    def test_arrivals_keep_coming_under_saturation(self):
        """The open-loop contract: a saturated scheduler (1 tiny node,
        arrivals far beyond capacity) does NOT slow the arrival clock —
        the count matches the seeded timeline exactly and the backlog
        is the saturation witness."""
        template = [
            {"opcode": "createNodes", "count": 1,
             "nodeTemplate": {"allocatable":
                              {"cpu": "1", "memory": "2Gi", "pods": "8"}}},
            {"opcode": "churnOpenLoop", "collectMetrics": True,
             "arrival": {"model": "poisson", "rate": 300},
             "duration": 1.0, "seed": 13},
        ]
        res = run(PerfRunner().run(template, {}, timeout=60.0))
        expected = len(PoissonArrivals(300.0, seed=13).timeline(1.0))
        assert res.churn_arrivals_total == expected
        # rate×duration within tolerance even though the scheduler is
        # saturated (5σ, deterministic for this seed).
        assert abs(res.churn_arrivals_total - 300) < 5 * math.sqrt(300) + 16
        assert res.churn_saturated is True
        assert res.churn_backlog_final > 16
        assert res.churn_create_errors == 0

    def test_unsaturated_run_not_flagged(self):
        template = [
            {"opcode": "createNodes", "count": 20},
            {"opcode": "churnOpenLoop", "collectMetrics": True,
             "arrival": {"model": "burst", "rate": 60, "burstSize": 10},
             "duration": 1.0, "seed": 4},
        ]
        res = run(PerfRunner().run(template, {}, timeout=60.0))
        assert res.churn_saturated is False
        assert res.churn_arrival_model == "burst"
        assert res.churn_offered_rate == 60.0


class TestAgentKillSeam:
    def test_kill_drops_lease_without_touching_siblings(self):
        """stop(graceful=False): the victim's tasks are all gone (no
        leaks), its lease renewTime freezes while a sibling's keeps
        advancing, and the Node object survives to go stale."""
        from kubernetes_tpu.agent import NodeAgent

        async def body(tmp):
            store = new_cluster_store()
            install_core_validation(store)
            agents = [NodeAgent(store, f"kn-{i}", checkpoint_dir=tmp,
                                lease_period=0.05) for i in range(2)]
            await NodeAgent.start_many(agents)
            victim, sibling = agents
            await asyncio.sleep(0.3)
            v0 = (await store.get(
                "leases", "kube-node-lease/kn-0"))["spec"]["renewTime"]
            await victim.stop(graceful=False)
            assert not victim._tasks and not victim._workers
            assert not victim._latest and not victim._armed
            s0 = (await store.get(
                "leases", "kube-node-lease/kn-1"))["spec"]["renewTime"]
            await asyncio.sleep(0.3)
            v1 = (await store.get(
                "leases", "kube-node-lease/kn-0"))["spec"]["renewTime"]
            s1 = (await store.get(
                "leases", "kube-node-lease/kn-1"))["spec"]["renewTime"]
            assert v1 == v0          # dead: renewals stopped
            assert s1 > s0           # sibling untouched
            await store.get("nodes", "kn-0")  # Node left to go stale
            await sibling.stop()
            store.stop()

        import tempfile
        with tempfile.TemporaryDirectory() as tmp:
            run(body(tmp))

    def test_kill_is_idempotent_with_graceful_stop(self):
        from kubernetes_tpu.agent import NodeAgent

        async def body(tmp):
            store = new_cluster_store()
            install_core_validation(store)
            agent = NodeAgent(store, "kn-x", checkpoint_dir=tmp,
                              lease_period=0.05)
            await agent.start()
            await agent.stop(graceful=False)
            await agent.stop()  # the runner's teardown path re-stops
            assert not agent._tasks and not agent._workers
            store.stop()

        import tempfile
        with tempfile.TemporaryDirectory() as tmp:
            run(body(tmp))


class TestQueueBacklogSeam:
    def test_backlog_depth_counts_every_tier(self):
        from kubernetes_tpu.scheduler.framework import Framework
        from kubernetes_tpu.scheduler.queue import SchedulingQueue
        from kubernetes_tpu.scheduler.types import PodInfo

        async def body():
            q = SchedulingQueue(Framework([]))
            assert q.backlog_depth() == 0
            await q.add(PodInfo(make_pod("bl-1")))
            await q.add(PodInfo(make_pod("bl-2")))
            assert q.backlog_depth() == 2
            assert q.stats()["in_flight"] == 0
            popped = await q.pop_batch(1)
            assert q.backlog_depth() == 2  # 1 active + 1 in flight
            assert q.stats()["in_flight"] == 1
            await q.add_unschedulable(popped[0])
            assert q.backlog_depth() == 2  # 1 active + 1 unschedulable
            await q.close()

        run(body())
