"""Topology slice allocator (kubernetes_tpu/topology): device kernel
vs host oracle.

Pins: (a) the device scan's feasibility, fragmentation and coverage
planes are BIT-IDENTICAL to the host oracle over randomized free masks
across 2D/3D, torus/walled meshes; (b) the packed winner key decodes
to exactly the oracle's argmin (min fragmentation, lowest placement id
on ties); (c) the sharded winner reduction agrees at shard counts
{1, 4, 8} — the key encodes the tie-break, so a distributed max IS the
argmin; (d) torus wraparound placements exist exactly when wrap is on;
(e) the mesh model: coordinate labels win over the name-index
fallback, malformed labels go off-mesh, cell collisions resolve to the
lowest node index.
"""

import numpy as np
import pytest

from kubernetes_tpu.topology.device import (
    best_key,
    decode_key,
    device_scan,
    fragmentation_pct,
    frag_cap,
)
from kubernetes_tpu.topology.mesh import (
    MESH_COORD_LABEL,
    MeshSpec,
    node_cell,
    orientations,
    parse_coord_label,
    parse_mesh_shape,
)
from kubernetes_tpu.topology.slices import (
    best_placement,
    coverage,
    is_contiguous_slice,
    oracle_scan,
    placement_members,
)

#: (dims, wrap) mesh configs spanning 2D/3D, torus/walled.
CONFIGS = [
    ((4, 4, 1), True),
    ((4, 4, 1), False),
    ((3, 4, 2), True),
    ((2, 3, 4), False),
]
#: slice shapes per trial (normalize pads to 3-tuples).
SHAPES = [(1, 1, 1), (2, 2, 1), (1, 3, 1), (2, 2, 2)]


def _random_free(rng, spec, p=0.6):
    return rng.random(spec.cells) < p


class TestDifferential:
    def test_device_matches_oracle_randomized(self):
        rng = np.random.default_rng(1234)
        trials = 0
        for dims, wrap in CONFIGS:
            spec = MeshSpec(dims, wrap)
            for shape in SHAPES:
                if any(s > d for s, d in
                       zip(sorted(shape), sorted(dims))):
                    continue  # no orientation fits — separate test
                for _ in range(5):
                    free = _random_free(rng, spec)
                    out = device_scan(free, spec, shape)
                    assert out is not None
                    key, feas_d, frag_d, cov_d = out
                    feas_h, frag_h = oracle_scan(free, spec, shape)
                    np.testing.assert_array_equal(feas_d, feas_h)
                    np.testing.assert_array_equal(frag_d, frag_h)
                    np.testing.assert_array_equal(
                        cov_d, coverage(feas_h, spec, shape))
                    pid_d, fr_d = decode_key(
                        best_key(key, 1), spec, shape)
                    pid_h = best_placement(feas_h, frag_h)
                    assert pid_d == pid_h
                    if pid_h >= 0:
                        assert fr_d == int(frag_h[pid_h])
                    trials += 1
        assert trials >= 60

    def test_sharded_winner_parity(self):
        """The distributed max agrees with the host argmin at shard
        counts {1, 4, 8} — ties included, since the key packs the
        lowest-pid tie-break into its low digits."""
        rng = np.random.default_rng(77)
        spec = MeshSpec((4, 4, 1), True)
        for _ in range(6):
            free = _random_free(rng, spec)
            out = device_scan(free, spec, (2, 2))
            assert out is not None
            key = out[0]
            want = best_placement(*oracle_scan(free, spec, (2, 2)))
            for shards in (1, 4, 8):
                pid, _ = decode_key(best_key(key, shards), spec, (2, 2))
                assert pid == want, f"shards={shards}"

    def test_fully_free_and_fully_occupied(self):
        spec = MeshSpec((4, 4, 1), True)
        free = np.ones(spec.cells, dtype=bool)
        key, feas, frag, cov = device_scan(free, spec, (2, 2))
        assert feas.all() and cov.all()
        assert fragmentation_pct(free, cov) == 0.0
        occupied = np.zeros(spec.cells, dtype=bool)
        key2, feas2, _, cov2 = device_scan(occupied, spec, (2, 2))
        assert not feas2.any()
        pid, _ = decode_key(best_key(key2, 1), spec, (2, 2))
        assert pid == -1
        # no free cells at all → vacuous 0, not NaN
        assert fragmentation_pct(occupied, cov2) == 0.0


class TestWraparound:
    def test_slice_exists_only_via_torus_wrap(self):
        # Ring of 8, free run {6, 7, 0}: a 3-slice must wrap.
        free = np.zeros(8, dtype=bool)
        free[[6, 7, 0]] = True
        torus = MeshSpec((8, 1, 1), True)
        walled = MeshSpec((8, 1, 1), False)
        pid_t = best_placement(*oracle_scan(free, torus, (3,)))
        pid_w = best_placement(*oracle_scan(free, walled, (3,)))
        assert pid_t >= 0 and pid_w == -1
        assert sorted(c % 8 for c in placement_members(
            pid_t, torus, (3,))) == [0, 6, 7]
        # Device side agrees on both.
        for spec, want in ((torus, pid_t), (walled, -1)):
            out = device_scan(free, spec, (3,))
            assert out is not None
            pid, _ = decode_key(best_key(out[0], 1), spec, (3,))
            assert pid == want

    def test_wrap_axis_full_span_has_no_exposed_faces(self):
        # A slice spanning the whole wrap axis has no boundary there:
        # its fragmentation must be strictly below the walled twin's
        # cap-relative cost for the same geometry.
        torus = MeshSpec((4, 2, 1), True)
        free = np.ones(torus.cells, dtype=bool)
        _, frag = oracle_scan(free, torus, (4, 1))
        key, _, frag_d, _ = device_scan(free, torus, (4, 1))
        np.testing.assert_array_equal(frag_d, frag)
        assert frag.max() < frag_cap((4, 1, 1))


class TestContiguity:
    def test_members_of_placement_are_contiguous(self):
        spec = MeshSpec((4, 4, 1), True)
        free = np.ones(spec.cells, dtype=bool)
        feas, frag = oracle_scan(free, spec, (2, 2))
        pid = best_placement(feas, frag)
        cells = placement_members(pid, spec, (2, 2))
        assert len(cells) == 4
        assert is_contiguous_slice(cells, spec, (2, 2))

    def test_scattered_cells_are_not_a_slice(self):
        spec = MeshSpec((4, 4, 1), True)
        # Diagonal: right count, wrong geometry.
        assert not is_contiguous_slice(
            [0, 5, 10, 15], spec, (2, 2))
        # Wrong count.
        assert not is_contiguous_slice([0, 1, 4], spec, (2, 2))

    def test_rotated_slice_is_contiguous(self):
        spec = MeshSpec((4, 4, 1), False)
        # A 1x3 run laid out along axis 0 (cells 0, 4, 8): the (3, 1)
        # orientation of the same shape.
        assert is_contiguous_slice([0, 4, 8], spec, (1, 3))


class TestMeshModel:
    def test_parse_mesh_shape(self):
        spec = parse_mesh_shape("4x8", 32)
        assert spec.dims == (4, 8, 1) and spec.wrap
        spec = parse_mesh_shape("2x3x4:mesh", 24)
        assert spec.dims == (2, 3, 4) and not spec.wrap
        # auto: near-square 2D torus sized to the fleet.
        spec = parse_mesh_shape("auto", 64)
        assert spec.cells >= 64 and spec.wrap
        # malformed degrades to auto, never raises.
        assert parse_mesh_shape("bogus", 16).cells >= 16

    def test_coord_label_wins_over_name(self):
        spec = MeshSpec((4, 4, 1), True)
        cell = node_cell("node-0", {MESH_COORD_LABEL: "2,3"}, spec)
        assert cell == spec.index_of((2, 3, 0))

    def test_name_index_fallback(self):
        spec = MeshSpec((4, 4, 1), True)
        assert node_cell("node-7", {}, spec) == 7
        assert node_cell("rack2-node-11", {}, spec) == 11
        # No trailing integer, out-of-range index → off-mesh.
        assert node_cell("gateway", {}, spec) is None
        assert node_cell("node-99", {}, spec) is None

    def test_malformed_label_goes_off_mesh(self):
        spec = MeshSpec((4, 4, 1), True)
        # Explicit-but-invalid label: off-mesh, NOT the name fallback
        # (a mislabeled node must not silently claim a cell).
        assert node_cell("node-3", {MESH_COORD_LABEL: "9,9"}, spec) is None
        assert node_cell("node-3", {MESH_COORD_LABEL: "x,y"}, spec) is None

    def test_parse_coord_label(self):
        assert parse_coord_label("1,2") == (1, 2, 0)
        assert parse_coord_label("1,2,3") == (1, 2, 3)
        assert parse_coord_label("nope") is None

    def test_cell_collision_lowest_node_index_wins(self):
        from kubernetes_tpu.topology.planes import TopologyPlanes

        class _N:
            def __init__(self, name, labels):
                self.name, self.labels = name, labels

        spec = MeshSpec((2, 2, 1), True)
        nodes = [_N("a", {MESH_COORD_LABEL: "0,0"}),
                 _N("b", {MESH_COORD_LABEL: "0,0"}),
                 _N("c", {MESH_COORD_LABEL: "0,1"})]
        planes = TopologyPlanes(spec, nodes, n_pad=4,
                                fingerprint=("t",))
        assert planes.cell_of_node[0] == 0
        assert planes.cell_of_node[1] == -1   # later claimant off-mesh
        assert planes.node_of_cell[0] == 0
        assert planes.on_mesh == 2

    def test_orientations_dedup_and_fit(self):
        spec = MeshSpec((4, 4, 1), True)
        # A square shape has one distinct orientation; a 1x3 has two
        # in-plane; nothing taller than the mesh fits.
        assert len(orientations((2, 2), spec)) == 1
        assert len(orientations((1, 3), spec)) == 2
        assert orientations((5, 1), spec) == ()


class TestOverflowGuard:
    def test_wide_mesh_key_overflow_returns_none(self):
        # cap * (A + 1) >= 2**31 → the packed int32 key cannot encode
        # the tie-break; device_scan must hand back None so the caller
        # falls back to the host oracle (never a silent wrong winner).
        spec = MeshSpec((256, 256, 128), True)
        free = np.ones(spec.cells, dtype=bool)
        assert device_scan(free, spec, (8, 8, 8)) is None
