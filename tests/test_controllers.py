"""Controller tier: RS/Deployment reconcile, node lifecycle, podgc, kwok."""

import asyncio

from kubernetes_tpu.api.meta import new_object
from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.controllers import (
    ControllerManager,
    DeploymentController,
    KwokController,
    NodeLifecycleController,
    PodGCController,
    ReplicaSetController,
    make_deployment,
    make_replicaset,
)
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def run(coro):
    return asyncio.run(coro)


async def wait_for(predicate, timeout=8.0, interval=0.03):
    for _ in range(int(timeout / interval)):
        v = await predicate()
        if v:
            return v
        await asyncio.sleep(interval)
    return await predicate()


POD_TEMPLATE = {
    "metadata": {"labels": {"app": "web"}},
    "spec": {"containers": [{"name": "main", "image": "web:v1",
                             "resources": {"requests": {"cpu": "100m"}}}]},
}


class TestReplicaSet:
    def test_scales_up_and_down(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            rsc = ReplicaSetController(store)
            mgr = ControllerManager(store, [rsc])
            await mgr.start()
            rs = make_replicaset("web", 5, {"matchLabels": {"app": "web"}},
                                 POD_TEMPLATE)
            await store.create("replicasets", rs)

            async def count():
                pods = (await store.list("pods")).items
                return len(pods) == 5 and pods
            assert await wait_for(count)

            # Scale down to 2.
            await store.guaranteed_update(
                "replicasets", "default/web",
                lambda o: (o["spec"].__setitem__("replicas", 2), o)[1])

            async def count2():
                return len((await store.list("pods")).items) == 2
            assert await wait_for(count2)
            await mgr.stop()
            store.stop()
        run(body())

    def test_replaces_deleted_pod(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            mgr = ControllerManager(store, [ReplicaSetController(store)])
            await mgr.start()
            await store.create("replicasets", make_replicaset(
                "web", 3, {"matchLabels": {"app": "web"}}, POD_TEMPLATE))

            async def three():
                items = (await store.list("pods")).items
                return items if len(items) == 3 else None
            pods = await wait_for(three)
            assert pods
            victim = pods[0]["metadata"]["name"]
            await store.delete("pods", f"default/{victim}")

            async def replaced():
                items = (await store.list("pods")).items
                return len(items) == 3 and all(
                    p["metadata"]["name"] != victim for p in items)
            assert await wait_for(replaced)
            await mgr.stop()
            store.stop()
        run(body())


class TestDeployment:
    def test_creates_rs_and_pods(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            mgr = ControllerManager(store, [
                DeploymentController(store), ReplicaSetController(store)])
            await mgr.start()
            await store.create("deployments", make_deployment(
                "web", 4, {"matchLabels": {"app": "web"}}, POD_TEMPLATE))

            async def ready():
                rses = (await store.list("replicasets")).items
                pods = (await store.list("pods")).items
                return len(rses) == 1 and len(pods) == 4
            assert await wait_for(ready)
            await mgr.stop()
            store.stop()
        run(body())

    def test_rolling_update_replaces_revision(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            mgr = ControllerManager(store, [
                DeploymentController(store), ReplicaSetController(store)])
            await mgr.start()
            await store.create("deployments", make_deployment(
                "web", 3, {"matchLabels": {"app": "web"}}, POD_TEMPLATE))

            async def v1_up():
                pods = (await store.list("pods")).items
                return len(pods) == 3
            assert await wait_for(v1_up)
            # Fake kubelet: mark pods bound/ready so the rollout can judge
            # availability (readyReplicas counts nodeName).
            for p in (await store.list("pods")).items:
                key = f"default/{p['metadata']['name']}"
                await store.guaranteed_update(
                    "pods", key,
                    lambda o: (o["spec"].__setitem__("nodeName", "n1"), o)[1])

            # New template revision.
            def bump(dep):
                dep["spec"]["template"]["spec"]["containers"][0]["image"] = "web:v2"
                return dep
            await store.guaranteed_update("deployments", "default/web", bump)

            async def rolled():
                pods = (await store.list("pods")).items
                images = {p["spec"]["containers"][0]["image"] for p in pods}
                # keep nodeName on new pods so availability advances
                for p in pods:
                    if not p["spec"].get("nodeName"):
                        key = f"default/{p['metadata']['name']}"
                        try:
                            await_ = store.guaranteed_update(
                                "pods", key,
                                lambda o: (o["spec"].__setitem__(
                                    "nodeName", "n1"), o)[1])
                            await await_
                        except Exception:
                            pass
                return images == {"web:v2"} and len(pods) == 3
            assert await wait_for(rolled, timeout=12.0)
            await mgr.stop()
            store.stop()
        run(body())


class TestNodeLifecycle:
    def test_stale_node_tainted_and_pods_evicted(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            nlc = NodeLifecycleController(
                store, node_monitor_period=0.05,
                node_monitor_grace_period=0.2,
                default_toleration_seconds=0.1)
            mgr = ControllerManager(store, [nlc])
            await store.create("nodes", make_node("n1"))
            # Admission injects the default 300s unreachable toleration
            # (defaulttolerationseconds); pin a short one for the test.
            await store.create("pods", make_pod(
                "p1", node_name="n1", tolerations=[
                    {"key": "node.kubernetes.io/unreachable",
                     "operator": "Exists", "effect": "NoExecute",
                     "tolerationSeconds": 0.1},
                    {"key": "node.kubernetes.io/not-ready",
                     "operator": "Exists", "effect": "NoExecute",
                     "tolerationSeconds": 0.1}]))
            await mgr.start()

            async def tainted():
                n = await store.get("nodes", "n1")
                return any(t["key"] == "node.kubernetes.io/unreachable"
                           for t in n["spec"].get("taints") or [])
            assert await wait_for(tainted)

            async def evicted():
                pods = (await store.list("pods")).items
                return not pods
            assert await wait_for(evicted)
            await mgr.stop()
            store.stop()
        run(body())

    def test_heartbeat_prevents_taint_and_recovery_untaints(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            nlc = NodeLifecycleController(
                store, node_monitor_period=0.05,
                node_monitor_grace_period=0.3)
            kwok = KwokController(store, node_count=1, lease_period=0.05)
            mgr = ControllerManager(store, [nlc, kwok])
            await kwok.register_nodes()
            await mgr.start()
            await asyncio.sleep(0.6)
            n = await store.get("nodes", "kwok-node-0")
            assert not (n["spec"].get("taints") or []), "live node got tainted"

            # Kill heartbeats → taint appears; resume → taint removed.
            kwok.fail_node("kwok-node-0")

            async def tainted():
                nn = await store.get("nodes", "kwok-node-0")
                return any(t["key"] == "node.kubernetes.io/unreachable"
                           for t in nn["spec"].get("taints") or [])
            assert await wait_for(tainted)
            kwok._managed.add("kwok-node-0")

            async def untainted():
                nn = await store.get("nodes", "kwok-node-0")
                return not any(
                    t["key"] == "node.kubernetes.io/unreachable"
                    for t in nn["spec"].get("taints") or [])
            assert await wait_for(untainted)
            await mgr.stop()
            store.stop()
        run(body())


class TestPodGC:
    def test_orphans_collected(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            gc = PodGCController(store, gc_period=0.05)
            mgr = ControllerManager(store, [gc])
            await store.create("nodes", make_node("n1"))
            await store.create("pods", make_pod("good", node_name="n1"))
            await store.create("pods", make_pod("orphan", node_name="ghost"))
            await mgr.start()

            async def collected():
                names = {p["metadata"]["name"]
                         for p in (await store.list("pods")).items}
                return names == {"good"}
            assert await wait_for(collected)
            await mgr.stop()
            store.stop()
        run(body())


class TestKwokE2E:
    def test_full_chain_deployment_to_running_pods(self):
        """Deployment → RS → pods → scheduler → kwok marks Running: the
        whole control plane with zero kubelets."""
        async def body():
            from kubernetes_tpu.client import InformerFactory
            from kubernetes_tpu.scheduler import Scheduler

            store = new_cluster_store()
            install_core_validation(store)
            kwok = KwokController(store, node_count=5, lease_period=0.2)
            await kwok.register_nodes()
            mgr = ControllerManager(store, [
                DeploymentController(store), ReplicaSetController(store),
                kwok])
            await mgr.start()
            sched = Scheduler(store, seed=3)
            factory = InformerFactory(store)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            sched_task = asyncio.ensure_future(sched.run())

            await store.create("deployments", make_deployment(
                "web", 6, {"matchLabels": {"app": "web"}}, POD_TEMPLATE))

            async def running():
                pods = (await store.list("pods")).items
                return len(pods) == 6 and all(
                    p["status"].get("phase") == "Running"
                    and p["spec"].get("nodeName", "").startswith("kwok-node-")
                    for p in pods)
            assert await wait_for(running, timeout=10.0)
            await sched.stop()
            sched_task.cancel()
            await mgr.stop()
            factory.stop()
            store.stop()
        run(body())
