"""Tier-1 guard for the fused Pallas wavefront kernel (small-N, fast).

Pins: (a) the AdaptiveTuner's KTPU_PALLAS policy row — auto keeps the
scan on CPU (no compiled lowering), off is the kill switch, and every
structural gate (optimal mode, spread, shortlist, W<=1, working-set
ceiling) routes back to the scan with a labeled fallback reason;
(b) CPU default = the EXACT r20 scan call graph with both pallas
counters at zero (off-by-policy records neither solves nor fallbacks);
(c) KTPU_PALLAS=interpret activating the kernel end-to-end through
TPUBackend with identical assignments and solves counted; (d) the
shape gate counting reason="shape" when a chunk exceeds the kernel's
working-set ceiling. The heavyweight randomized differential parity
lives in tests/test_pallas_solver.py.
"""

import numpy as np

from kubernetes_tpu.metrics.registry import SchedulerMetrics
from kubernetes_tpu.ops import pallas_kernel
from kubernetes_tpu.ops.backend import AdaptiveTuner, TPUBackend, \
    solve_provenance
from kubernetes_tpu.utils import flags


class TestPallasPolicy:
    def test_auto_keeps_scan_on_cpu(self):
        """auto (the default) compiles on accelerator backends only —
        on CPU the chunk keeps the scan with NO fallback count (the
        routing never wanted the kernel), so CPU presets are untouched."""
        t = AdaptiveTuner()
        mode, fall = t.pallas_mode(8, 0, False, "greedy")
        assert mode == "off" and fall is None

    def test_kill_switch_and_force(self):
        t = AdaptiveTuner()
        with flags.scoped_set("KTPU_PALLAS", "off"):
            assert t.pallas_mode(8, 0, False, "greedy") == ("off", None)
        with flags.scoped_set("KTPU_PALLAS", "0"):  # boolean spelling
            assert t.pallas_mode(8, 0, False, "greedy") == ("off", None)
        with flags.scoped_set("KTPU_PALLAS", "interpret"):
            assert t.pallas_mode(8, 0, False, "greedy") == \
                ("interpret", None)
        with flags.scoped_set("KTPU_PALLAS", "on"):
            # CPU has no compiled lowering: "on" degrades to interpret.
            mode, fall = t.pallas_mode(8, 0, False, "greedy")
            assert mode == "interpret" and fall is None

    def test_structural_gates_label_fallbacks(self):
        """The kernel fuses only the plain greedy wave branch; every
        other shape keeps the scan, labeled by why."""
        t = AdaptiveTuner()
        with flags.scoped_set("KTPU_PALLAS", "interpret"):
            assert t.pallas_mode(8, 0, False, "optimal") == \
                ("off", "optimal")
            assert t.pallas_mode(8, 0, True, "greedy") == \
                ("off", "spread")
            assert t.pallas_mode(8, 6, False, "greedy") == \
                ("off", "shortlist")
            assert t.pallas_mode(1, 0, False, "greedy") == \
                ("off", "wave_off")

    def test_shape_gate(self):
        """The working-set ceiling: per grid step the kernel holds the
        (C,N) planes + (W,N) evaluation + (N,R) carries resident."""
        assert pallas_kernel.unsupported_reason(128, 4, 2, 8) is None
        assert pallas_kernel.unsupported_reason(128, 4, 2, 1) == \
            "wave_off"
        big_n = pallas_kernel.MAX_STATE_BYTES  # bytes/row > 1 at any W
        assert pallas_kernel.unsupported_reason(big_n, 4, 2, 8) == "shape"


class TestBackendSmoke:
    def _cluster(self, n):
        from kubernetes_tpu.api.types import make_node
        from kubernetes_tpu.scheduler.cache import SchedulerCache
        cache = SchedulerCache()
        for i in range(n):
            cache.add_node(make_node(
                f"pn{i}", allocatable={"cpu": "8", "memory": "32Gi",
                                       "pods": "110"}))
        return cache.update_snapshot()

    def _pods(self, n):
        from kubernetes_tpu.api.types import make_pod
        from kubernetes_tpu.scheduler.types import PodInfo
        return [PodInfo(make_pod(
            f"pk-{i}", requests={"cpu": "500m", "memory": "512Mi"},
            uid=f"pk-uid-{i}")) for i in range(n)]

    def test_cpu_default_is_scan_with_zero_counters(self):
        """Flagless on CPU: the scan solves every chunk and BOTH pallas
        counters stay zero — no kernel in disguise, no phantom
        fallbacks. KTPU_PALLAS=off produces the same call graph and the
        same assignments (the structural-degrade contract)."""
        from test_tpu_backend import default_fwk
        snap = self._cluster(100)
        pods = self._pods(24)
        fwk = default_fwk()
        b = TPUBackend(max_batch=16, mesh=None)
        b.metrics = SchedulerMetrics()
        auto, _ = b.assign(pods, snap, fwk)
        assert b.metrics.solver_pallas_solves.value() == 0
        assert sum(
            b.metrics.solver_pallas_fallbacks._values.values()) == 0
        prov = solve_provenance()
        assert prov["solve_kernel"] == "scan"
        assert prov["pallas_mode"] == "off"
        b2 = TPUBackend(max_batch=16, mesh=None)
        b2.metrics = SchedulerMetrics()
        with flags.scoped_set("KTPU_PALLAS", "off"):
            off, _ = b2.assign(pods, snap, fwk)
        assert off == auto
        assert b2.metrics.solver_pallas_solves.value() == 0

    def test_interpret_activates_with_identical_assignments(self):
        """KTPU_PALLAS=interpret routes wave chunks through the fused
        kernel end-to-end: assignments match the scan exactly and the
        solves counter records each kernel chunk."""
        from test_tpu_backend import default_fwk
        snap = self._cluster(100)
        pods = self._pods(24)
        fwk = default_fwk()
        base, _ = TPUBackend(max_batch=16, mesh=None).assign(
            pods, snap, fwk)
        b = TPUBackend(max_batch=16, mesh=None)
        b.metrics = SchedulerMetrics()
        with flags.scoped_set("KTPU_PALLAS", "interpret"):
            got, _ = b.assign(pods, snap, fwk)
            prov = solve_provenance()
        assert got == base
        assert b.metrics.solver_pallas_solves.value() > 0
        assert prov["solve_kernel"] == "pallas"
        assert prov["pallas_mode"] == "interpret"

    def test_shape_fallback_counted(self, monkeypatch):
        """A chunk above the working-set ceiling keeps the scan,
        counted under reason="shape", with identical assignments."""
        from test_tpu_backend import default_fwk
        snap = self._cluster(80)
        pods = self._pods(16)
        fwk = default_fwk()
        base, _ = TPUBackend(max_batch=16, mesh=None).assign(
            pods, snap, fwk)
        monkeypatch.setattr(pallas_kernel, "MAX_STATE_BYTES", 1)
        b = TPUBackend(max_batch=16, mesh=None)
        b.metrics = SchedulerMetrics()
        with flags.scoped_set("KTPU_PALLAS", "interpret"):
            got, _ = b.assign(pods, snap, fwk)
        assert got == base
        assert b.metrics.solver_pallas_solves.value() == 0
        assert b.metrics.solver_pallas_fallbacks.value(
            reason="shape") > 0
