"""Multi-start permuted solve + gang all-or-nothing (VERDICT r2 item #6:
beat the oracle's packing, don't just match it)."""

import asyncio

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.ops import TPUBackend
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.framework import Framework
from kubernetes_tpu.scheduler.plugins.coscheduling import make_pod_group
from kubernetes_tpu.scheduler.plugins.registry import (
    DEFAULT_SCORE_WEIGHTS,
    build_plugins,
)
from kubernetes_tpu.scheduler.types import PodInfo
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def run(coro):
    return asyncio.run(coro)


def cpu_pod(name, cpu, **kw):
    return make_pod(name, requests={"cpu": cpu}, uid=f"u-{name}", **kw)


def two_nodes(cap="4"):
    cache = SchedulerCache()
    for i in range(2):
        cache.add_node(make_node(f"n{i}", allocatable={
            "cpu": cap, "memory": "16Gi", "pods": "110"}))
    return cache


class TestMultistartPacking:
    def test_beats_oracle_fragmentation_at_equal_count(self):
        """Queue [2,2,3,3] on two 4-CPU nodes: sequential greedy (the host
        oracle) places the 2s first and strands both 3s (4/8 CPU used);
        the size-descending order places the 3s (6/8 used) — equal pod
        count, strictly better packing. The multi-start solve must pick
        the better order."""
        cache = two_nodes()
        snapshot = cache.update_snapshot()
        pods = [PodInfo(cpu_pod(n, c))
                for n, c in [("a", "2"), ("b", "2"), ("c", "3"), ("d", "3")]]
        fwk = Framework(build_plugins(), DEFAULT_SCORE_WEIGHTS)

        oracle = TPUBackend(max_batch=8, multistart=1)
        o_assign, _ = oracle.assign(pods, snapshot, fwk)
        o_placed = [p for p in pods if o_assign[p.key]]
        o_used = sum(int(p.requests["cpu"]) for p in o_placed)

        multi = TPUBackend(max_batch=8, multistart=4)
        m_assign, _ = multi.assign(pods, snapshot, fwk)
        m_placed = [p for p in pods if m_assign[p.key]]
        m_used = sum(int(p.requests["cpu"]) for p in m_placed)

        assert len(o_placed) == 2 and o_used == 4000  # the 2s
        assert len(m_placed) == 2 and m_used == 6000  # the 3s
        # Equal throughput, strictly less stranded capacity.
        assert m_used > o_used

    def test_places_more_pods_under_contention(self):
        """Queue [3,3,2,2,2]: oracle places the two 3s (2 pods); the
        size-ascending order places three 2s (3 pods)."""
        cache = two_nodes()
        snapshot = cache.update_snapshot()
        pods = [PodInfo(cpu_pod(n, c)) for n, c in
                [("a", "3"), ("b", "3"), ("c", "2"), ("d", "2"), ("e", "2")]]
        fwk = Framework(build_plugins(), DEFAULT_SCORE_WEIGHTS)

        oracle = TPUBackend(max_batch=8, multistart=1)
        o_assign, _ = oracle.assign(pods, snapshot, fwk)
        assert sum(1 for p in pods if o_assign[p.key]) == 2

        multi = TPUBackend(max_batch=8, multistart=4)
        m_assign, _ = multi.assign(pods, snapshot, fwk)
        assert sum(1 for p in pods if m_assign[p.key]) == 3

    def test_identity_wins_when_uncontended(self):
        """No contention → every order places everything → the identity
        (oracle) order is selected: bit-identical to multistart=1."""
        cache = two_nodes(cap="32")
        snapshot = cache.update_snapshot()
        pods = [PodInfo(cpu_pod(f"p{i}", "1")) for i in range(8)]
        fwk = Framework(build_plugins(), DEFAULT_SCORE_WEIGHTS)
        a1, _ = TPUBackend(max_batch=8, multistart=1).assign(
            pods, snapshot, fwk)
        a4, _ = TPUBackend(max_batch=8, multistart=4).assign(
            pods, snapshot, fwk)
        assert a1 == a4


def gang_scheduler(store, backend):
    """Scheduler whose profile actually ENABLES Coscheduling (it is
    registered but deliberately not default-enabled, like the reference's
    out-of-tree plugin). The original tests built the DEFAULT profile —
    no gang plugin at all — and only ever passed because the solver's jit
    compile outlasted their settle window before anything could bind; a
    warm jit cache (any long suite run) exposed 2-of-3 members binding."""
    from kubernetes_tpu.scheduler.plugins.registry import DEFAULT_PLUGINS
    plugins = build_plugins(DEFAULT_PLUGINS + ["Coscheduling"], store=store)
    fwk = Framework(plugins, DEFAULT_SCORE_WEIGHTS)
    return Scheduler(store, profiles={"default-scheduler": fwk},
                     seed=5, backend=backend)


class TestGangInSolver:
    def test_partial_gang_dropped_atomically(self):
        """A 3-member gang (minMember=3) that only fits 2 members is
        rejected whole INSIDE the solve — no partial placement reaches
        assume/Permit."""
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            for i in range(2):
                await store.create("nodes", make_node(f"n{i}", allocatable={
                    "cpu": "2", "memory": "8Gi", "pods": "110"}))
            await store.create("podgroups", make_pod_group("gang", 3))
            backend = TPUBackend(max_batch=8, multistart=2)
            sched = gang_scheduler(store, backend)
            factory = InformerFactory(store)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            task = asyncio.ensure_future(sched.run(batch_size=8))
            # 3 gang pods of 2 CPU on 2x2-CPU nodes: only 2 could fit.
            for i in range(3):
                await store.create("pods", make_pod(
                    f"g{i}", requests={"cpu": "2"},
                    labels={"scheduling.x-k8s.io/pod-group": "gang"}))
            await asyncio.sleep(0.8)
            pods = (await store.list("pods")).items
            bound = [p for p in pods if p["spec"].get("nodeName")]
            assert bound == []  # all-or-nothing: nobody placed
            await sched.stop()
            task.cancel()
            factory.stop()
            store.stop()
        run(body())

    def test_full_gang_places(self):
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            for i in range(3):
                await store.create("nodes", make_node(f"n{i}", allocatable={
                    "cpu": "2", "memory": "8Gi", "pods": "110"}))
            await store.create("podgroups", make_pod_group("gang", 3))
            backend = TPUBackend(max_batch=8, multistart=2)
            sched = gang_scheduler(store, backend)
            factory = InformerFactory(store)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            task = asyncio.ensure_future(sched.run(batch_size=8))
            for i in range(3):
                await store.create("pods", make_pod(
                    f"g{i}", requests={"cpu": "2"},
                    labels={"scheduling.x-k8s.io/pod-group": "gang"}))

            async def all_bound():
                pods = (await store.list("pods")).items
                return sum(1 for p in pods
                           if p["spec"].get("nodeName")) == 3
            for _ in range(200):
                if await all_bound():
                    break
                await asyncio.sleep(0.03)
            assert await all_bound()
            await sched.stop()
            task.cancel()
            factory.stop()
            store.stop()
        run(body())


class TestPriorityFairness:
    def test_high_priority_pod_never_displaced_by_packing(self):
        """Permutations are priority-block-stable: a high-priority pod at
        the queue head cannot lose its slot to a bulkier low-priority
        order (the reference's strict priority contract)."""
        cache = two_nodes()
        snapshot = cache.update_snapshot()
        pods = [PodInfo(cpu_pod("hi", "2", priority=1000)),
                PodInfo(cpu_pod("lo-a", "3")),
                PodInfo(cpu_pod("lo-b", "3"))]
        fwk = Framework(build_plugins(), DEFAULT_SCORE_WEIGHTS)
        assign, _ = TPUBackend(max_batch=8, multistart=4).assign(
            pods, snapshot, fwk)
        # Without block stability, [3,3] (volume 6) would beat [2,3]
        # (volume 5) and starve the high-priority pod.
        assert assign["default/hi"] is not None


class TestNominatedFastPath:
    def test_preemptor_lands_on_nominated_node_via_batch_path(self):
        """A preemptor retrying with status.nominatedNodeName must take the
        host fast path ahead of the batch solve (no nominee bias there) and
        land exactly once, on its nominee."""
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            for i in range(2):
                await store.create("nodes", make_node(f"n{i}", allocatable={
                    "cpu": "2", "memory": "8Gi", "pods": "16"}))
            backend = TPUBackend(max_batch=8, multistart=2)
            sched = Scheduler(store, seed=9, backend=backend)
            factory = InformerFactory(store)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            task = asyncio.ensure_future(sched.run(batch_size=8))
            # Saturate with low-priority fillers.
            for i in range(4):
                await store.create("pods", make_pod(
                    f"filler-{i}", requests={"cpu": "1"}, priority=0))

            async def full():
                pods = (await store.list("pods")).items
                return sum(1 for p in pods
                           if p["spec"].get("nodeName")) == 4
            for _ in range(200):
                if await full():
                    break
                await asyncio.sleep(0.03)
            assert await full()
            # High-priority pod arrives WITH low-priority company, so the
            # post-eviction retry pops a MULTI-pod batch and the nominee
            # fast path on the batch branch is what actually runs (a
            # 1-pod retry would take the single-pod host path and this
            # test would guard nothing).
            await store.create("pods", make_pod(
                "vip", requests={"cpu": "1"}, priority=1000))
            for i in range(3):
                await store.create("pods", make_pod(
                    f"extra-{i}", requests={"cpu": "1"}, priority=0))

            async def vip_bound():
                p = await store.get("pods", "default/vip")
                return p["spec"].get("nodeName")
            for _ in range(400):
                if await vip_bound():
                    break
                await asyncio.sleep(0.05)
            node = await vip_bound()
            assert node  # scheduled after eviction
            # Exactly the victims needed were evicted (no churn): 4
            # fillers - 1 victim = 3 remain; the low-priority extras stay
            # pending (no capacity, and they must not have stolen the
            # vip's freed node).
            pods = (await store.list("pods")).items
            fillers = [p for p in pods
                       if p["metadata"]["name"].startswith("filler")]
            assert len(fillers) == 3
            extras_bound = [p for p in pods
                            if p["metadata"]["name"].startswith("extra")
                            and p["spec"].get("nodeName")]
            assert extras_bound == []
            await sched.stop()
            task.cancel()
            factory.stop()
            store.stop()
        run(body())
