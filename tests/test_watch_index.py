"""Interned watch-dispatch index: O(matching) fan-out, parity with the
linear predicate scan.

Three guards:
- scaling smoke: with 500 registered field-selector watchers,
  `watch_predicate_checks_total` grows O(events), not O(events×watchers)
  — the regression guard for the index;
- selector-signature interning: N watchers sharing one selector pay one
  predicate evaluation per event and share one synthesized twin (and its
  wire encoding);
- differential: randomized label/field mutation sequences dispatched
  through the index must yield, per watcher shape, exactly the stream
  the old linear scan (namespace check + `_select_for` per watcher per
  event) produces — synthesized enter/leave ADDED/DELETED included —
  and the replay path (which still IS the linear scan) must agree with
  the live-index stream, 410 behavior unchanged.
"""

import asyncio
import random

import pytest

from kubernetes_tpu.api.labels import parse_selector
from kubernetes_tpu.api.meta import namespace_of
from kubernetes_tpu.apiserver.wire import (
    encode_event_object,
    encode_event_object_mp,
)
from kubernetes_tpu.store.mvcc import (
    Expired,
    MVCCStore,
    _WatchChannel,
)


def run(coro):
    return asyncio.run(coro)


async def collect(gen, out):
    async for ev in gen:
        if ev.type != "BOOKMARK":
            out.append(ev)


def fingerprint(evs):
    return [(e.type, e.object["metadata"]["name"], e.rv) for e in evs]


class TestScalingSmoke:
    def test_predicate_checks_sublinear_in_watcher_count(self):
        """500 field watchers; checks stay O(events) — the tier-1 guard."""
        async def body():
            s = MVCCStore()
            for i in range(500):
                await s.watch("pods", fields={"spec.nodeName": f"n{i}"})
            base_checks = s.watch_metrics.predicate_checks.value()
            base_hits = s.watch_metrics.index_hits.value()
            n_pods = 100
            for i in range(n_pods):
                await s.create("pods", {
                    "metadata": {"name": f"p{i}", "namespace": "default"},
                    "spec": {}})
                cur = await s.get("pods", f"default/p{i}")
                cur["spec"]["nodeName"] = f"n{i % 500}"
                await s.update("pods", cur)
            events = 2 * n_pods  # ADDED + bind MODIFIED per pod
            checks = s.watch_metrics.predicate_checks.value() - base_checks
            hits = s.watch_metrics.index_hits.value() - base_hits
            # Linear scan would be events × 500 = 100,000 checks; the
            # index pays ~1 per bind (the one matching bucket).
            assert checks <= 2 * events, checks
            assert checks < events * 500 / 50
            assert hits >= n_pods  # every bind routed through the index
            s.stop()
        run(body())


class TestSelectorGroupInterning:
    def test_shared_signature_one_check_shared_twin(self):
        async def body():
            s = MVCCStore()
            sel = "app=web"
            out1, out2, out3 = [], [], []
            t1 = asyncio.ensure_future(collect(
                await s.watch("pods", selector=parse_selector(sel)), out1))
            t2 = asyncio.ensure_future(collect(
                await s.watch("pods", selector=parse_selector(sel)), out2))
            t3 = asyncio.ensure_future(collect(
                await s.watch("pods", selector=parse_selector("app=db")),
                out3))
            base = s.watch_metrics.predicate_checks.value()
            await s.create("pods", {
                "metadata": {"name": "a", "namespace": "default",
                             "labels": {"app": "web"}}, "spec": {}})
            # 2 signatures registered → exactly 2 evaluations for this
            # event, regardless of 3 watchers.
            assert s.watch_metrics.predicate_checks.value() - base == 2
            # Label leave: the group's synthesized DELETED twin is ONE
            # shared Event (and one shared wire encoding).
            cur = await s.get("pods", "default/a")
            cur["metadata"]["labels"] = {"app": "db"}
            await s.update("pods", cur)
            await asyncio.sleep(0.05)
            assert [e.type for e in out1] == ["ADDED", "DELETED"]
            assert [e.type for e in out2] == ["ADDED", "DELETED"]
            assert out1[1] is out2[1]  # shared twin, not per-watcher copies
            assert [e.type for e in out3] == ["ADDED"]  # label enter
            # encode-once across the twin and its source: same bytes obj.
            assert encode_event_object(out1[1]) is \
                encode_event_object(out3[0])
            assert encode_event_object_mp(out1[1]) is \
                encode_event_object_mp(out3[0])
            for t in (t1, t2, t3):
                t.cancel()
            s.stop()
        run(body())


class TestFieldIndexTransitions:
    def test_bind_move_delete_enter_leave(self):
        async def body():
            s = MVCCStore()
            out1, out2 = [], []
            t1 = asyncio.ensure_future(collect(
                await s.watch("pods", fields={"spec.nodeName": "n1"}), out1))
            t2 = asyncio.ensure_future(collect(
                await s.watch("pods", fields={"spec.nodeName": "n2"}), out2))
            await s.create("pods", {
                "metadata": {"name": "p", "namespace": "default"},
                "spec": {}})
            cur = await s.get("pods", "default/p")
            cur["spec"]["nodeName"] = "n1"     # bind → enter n1
            cur = await s.update("pods", cur)
            cur["spec"]["nodeName"] = "n2"     # move → leave n1, enter n2
            await s.update("pods", cur)
            await s.delete("pods", "default/p")
            await asyncio.sleep(0.05)
            assert [e.type for e in out1] == ["ADDED", "DELETED"]
            assert [e.type for e in out2] == ["ADDED", "DELETED"]
            t1.cancel()
            t2.cancel()
            s.stop()
        run(body())


# Watcher shapes the differential covers: plain, namespaced, interned
# selector groups (shared + distinct signatures), tracked-field exact
# values, joint field+selector, an untracked field (residue path), and a
# namespaced field watcher.
def _shapes():
    return [
        {},
        {"namespace": "ns1"},
        {"selector": parse_selector("app=web")},
        {"selector": parse_selector("app=web")},
        {"selector": parse_selector("tier in (a,b),app")},
        {"fields": {"spec.nodeName": "n1"}},
        {"fields": {"spec.nodeName": "n2"}},
        {"fields": {"spec.nodeName": "n1"},
         "selector": parse_selector("app=web")},
        {"fields": {"status.phase": "Running"}},
        {"fields": {"spec.untracked": "x"}},
        {"namespace": "ns2", "fields": {"spec.nodeName": "n1"}},
    ]


def _linear_stream(store: MVCCStore, shape: dict, after_rv: int):
    """The pre-index dispatch algorithm, verbatim: namespace check +
    `_select_for` per watcher per recorded event."""
    chan = _WatchChannel(
        queue=None, resource="pods", namespace=shape.get("namespace"),
        selector=shape.get("selector"), fields=shape.get("fields"))
    out = []
    for res, ev in store._events:
        if res != "pods" or ev.rv <= after_rv:
            continue
        if chan.namespace and namespace_of(ev.object) != chan.namespace:
            continue
        selected = MVCCStore._select_for(ev, chan)
        if selected is not None:
            out.append(selected)
    return out


class TestDifferentialDispatchParity:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_randomized_mutations_match_linear_scan(self, seed):
        async def body():
            rng = random.Random(seed)
            s = MVCCStore()
            # Seed write so rv0 > 0 (rv=0 means "from now": a replay
            # watch from it would skip history instead of replaying).
            await s.create("pods", {
                "metadata": {"name": "seed", "namespace": "default"},
                "spec": {}})
            await s.delete("pods", "default/seed")
            shapes = _shapes()
            streams = [[] for _ in shapes]
            tasks = []
            for shape, out in zip(shapes, streams):
                tasks.append(asyncio.ensure_future(collect(
                    await s.watch("pods", **shape), out)))
            rv0 = s.resource_version
            names = [(f"o{i}", ("default", "ns1", "ns2")[i % 3])
                     for i in range(24)]
            alive = set()

            def rand_labels():
                labels = {}
                if rng.random() < 0.7:
                    labels["app"] = rng.choice(["web", "db"])
                if rng.random() < 0.5:
                    labels["tier"] = rng.choice(["a", "b", "c"])
                return labels

            for _ in range(250):
                name, ns = rng.choice(names)
                key = f"{ns}/{name}"
                if key not in alive:
                    await s.create("pods", {
                        "metadata": {"name": name, "namespace": ns,
                                     "labels": rand_labels()},
                        "spec": {
                            "nodeName": rng.choice(["", "n1", "n2", "n3"]),
                            "untracked": rng.choice(["x", "y"])},
                        "status": {"phase": rng.choice(
                            ["Pending", "Running"])}})
                    alive.add(key)
                elif rng.random() < 0.25:
                    await s.delete("pods", key)
                    alive.discard(key)
                else:
                    cur = await s.get("pods", key)
                    mutation = rng.random()
                    if mutation < 0.4:
                        cur["metadata"]["labels"] = rand_labels()
                    elif mutation < 0.7:
                        cur["spec"]["nodeName"] = rng.choice(
                            ["", "n1", "n2", "n3"])
                    else:
                        cur["status"]["phase"] = rng.choice(
                            ["Pending", "Running", "Succeeded"])
                    if rng.random() < 0.3:  # compound mutation
                        cur["spec"]["untracked"] = rng.choice(["x", "y"])
                        cur["metadata"]["labels"] = rand_labels()
                    await s.update("pods", cur)
            await asyncio.sleep(0.05)
            for shape, got in zip(shapes, streams):
                want = _linear_stream(s, shape, rv0)
                assert fingerprint(got) == fingerprint(want), shape
            # Replay resume (the other linear path): a late watcher from
            # rv0 must reconstruct the live stream exactly.
            for shape, got in zip(shapes[:6], streams[:6]):
                replay = await s.watch("pods", resource_version=rv0,
                                       **shape)
                replayed = []
                for _ in range(len(got)):
                    replayed.append(await asyncio.wait_for(
                        replay.__anext__(), 2.0))
                assert fingerprint(replayed) == fingerprint(got), shape
                await replay.aclose()
            for t in tasks:
                t.cancel()
            s.stop()
        run(body())

    def test_compacted_rv_still_410s_for_indexed_watchers(self):
        async def body():
            s = MVCCStore(event_window=5)
            for i in range(20):
                await s.create("pods", {
                    "metadata": {"name": f"p{i}", "namespace": "default"},
                    "spec": {"nodeName": "n1"}})
            with pytest.raises(Expired):
                await s.watch("pods", resource_version=1,
                              fields={"spec.nodeName": "n1"})
            s.stop()
        run(body())

    def test_watch_counters_scrapable_from_metrics_endpoint(self):
        async def body():
            import aiohttp

            from kubernetes_tpu.apiserver.server import APIServer
            from kubernetes_tpu.metrics.registry import Registry
            s = MVCCStore()
            api = APIServer(s, metrics_registry=Registry())
            await api.start()
            try:
                t = asyncio.ensure_future(collect(
                    await s.watch("pods",
                                  fields={"spec.nodeName": "n1"}), []))
                await s.create("pods", {
                    "metadata": {"name": "p", "namespace": "default"},
                    "spec": {"nodeName": "n1"}})
                async with aiohttp.ClientSession() as sess:
                    async with sess.get(api.url + "/metrics") as r:
                        text = await r.text()
                assert "watch_predicate_checks_total 1" in text, text
                assert "watch_index_hits_total 1" in text
                assert "watch_events_dispatched_total 1" in text
                t.cancel()
            finally:
                await api.stop()
                s.stop()
        run(body())

    def test_http_wire_field_selector_watch(self):
        """fieldSelector rides the HTTP wire end to end (list + watch):
        the kubelet shape now works over BOTH apiserver wires and lands
        in the store's tracked-field index."""
        async def body():
            from kubernetes_tpu.apiserver.client import RemoteStore
            from kubernetes_tpu.apiserver.server import APIServer
            s = MVCCStore()
            api = APIServer(s)
            await api.start()
            client = RemoteStore(api.url)
            try:
                await s.create("pods", {
                    "metadata": {"name": "bound", "namespace": "default"},
                    "spec": {"nodeName": "n1"}})
                await s.create("pods", {
                    "metadata": {"name": "free", "namespace": "default"},
                    "spec": {}})
                lst = await client.list(
                    "pods", fields={"spec.nodeName": "n1"})
                assert [p["metadata"]["name"] for p in lst.items] == \
                    ["bound"]
                out = []
                t = asyncio.ensure_future(collect(await client.watch(
                    "pods", resource_version=lst.resource_version,
                    fields={"spec.nodeName": "n1"}), out))
                await asyncio.sleep(0.05)
                # Server-side the channel sits in the field index.
                assert s._index["pods"].fields["spec.nodeName"]["n1"]
                cur = await s.get("pods", "default/free")
                cur["spec"]["nodeName"] = "n1"
                await s.update("pods", cur)  # enter → synthesized ADDED
                for _ in range(100):
                    if out:
                        break
                    await asyncio.sleep(0.02)
                assert [(e.type, e.object["metadata"]["name"])
                        for e in out] == [("ADDED", "free")]
                t.cancel()
            finally:
                await client.close()
                await api.stop()
                s.stop()
        run(body())

    def test_unregister_cleans_index_slots(self):
        async def body():
            s = MVCCStore()
            shapes = [
                {"fields": {"spec.nodeName": "n1"}},
                {"selector": parse_selector("app=web")},
                {},
                {"fields": {"spec.oddball": "y"}},
            ]
            outs = [[] for _ in shapes]
            tasks = [asyncio.ensure_future(collect(
                await s.watch("pods", **shape), out))
                for shape, out in zip(shapes, outs)]
            await asyncio.sleep(0)  # start the generators
            assert len(s._watchers) == 4
            idx = s._index["pods"]
            assert idx.fields and idx.groups and idx.plain and idx.residue
            for t in tasks:
                t.cancel()
            await asyncio.sleep(0.02)  # cancellation runs gen finally
            assert s._watchers == []
            assert "pods" not in s._index  # empty index slots pruned
            s.stop()
        run(body())
