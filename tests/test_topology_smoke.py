"""Tier-1 smoke for topology-aware slice placement (kubernetes_tpu/
topology — ISSUE 19).

Pins: (a) the subsystem is ACTIVE BY DEFAULT — KTPU_TOPOLOGY defaults
on and ClusterTensors carries coordinate planes, rebuilt only when the
mesh flags or node set move; (b) the KTPU_TOPOLOGY=0 kill switch
degrades STRUCTURALLY — no topology planes, TopologySlice skips — and
topology-free workloads assign BIT-IDENTICALLY with the flag on or
off (the flat-capacity call graph is untouched); (c) slice-shaped
gangs bind ALL-OR-NOTHING onto one contiguous sub-mesh, at device
shard counts {1, 4, 8}, counted by scheduler_slice_gangs_bound_total;
(d) a shape with no feasible placement leaves the whole gang pending;
(e) the ChurnDay SlicePacking family (KTPU_MESH_SHAPE=auto staging,
gangArrival/sliceDeath timeline) stays schema-valid and deterministic.
"""

import asyncio
import random

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.metrics.registry import SchedulerMetrics
from kubernetes_tpu.ops import TPUBackend
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.scheduler.cache import SchedulerCache
from kubernetes_tpu.scheduler.framework import Framework
from kubernetes_tpu.scheduler.plugins.coscheduling import (
    POD_GROUP_LABEL,
    make_pod_group,
)
from kubernetes_tpu.scheduler.plugins.registry import (
    DEFAULT_PLUGINS,
    DEFAULT_SCORE_WEIGHTS,
    build_plugins,
)
from kubernetes_tpu.store import install_core_validation, new_cluster_store
from kubernetes_tpu.topology import MeshSpec, is_contiguous_slice, node_cell
from kubernetes_tpu.utils import flags
from test_tpu_backend import default_fwk, random_cluster, random_pending


def run(coro):
    return asyncio.run(coro)


class TestActiveByDefault:
    def test_flags_default_on(self):
        assert flags.get("KTPU_TOPOLOGY") is True
        assert flags.get("KTPU_MESH_SHAPE") == "auto"

    def test_cluster_tensors_carry_planes(self):
        from kubernetes_tpu.ops.tensorize import ClusterTensors
        cache = SchedulerCache()
        for i in range(8):
            cache.add_node(make_node(f"node-{i}"))
        ct = ClusterTensors(cache.update_snapshot())
        assert ct.topology is not None
        assert ct.topology.on_mesh == 8
        assert ct.topology.rebuilt

    def test_planes_reused_for_stable_node_set(self):
        from kubernetes_tpu.topology.planes import build_topology_planes
        cache = SchedulerCache()
        for i in range(4):
            cache.add_node(make_node(f"node-{i}"))
        nodes = cache.update_snapshot().nodes
        first = build_topology_planes(nodes, 8, None)
        again = build_topology_planes(nodes, 8, first)
        assert again is first and not again.rebuilt


class TestKillSwitch:
    def test_structural_degrade(self, monkeypatch):
        monkeypatch.setenv("KTPU_TOPOLOGY", "0")
        from kubernetes_tpu.ops.tensorize import ClusterTensors
        cache = SchedulerCache()
        for i in range(4):
            cache.add_node(make_node(f"node-{i}"))
        assert ClusterTensors(cache.update_snapshot()).topology is None

    def test_topology_free_assignments_bit_identical(self, monkeypatch):
        """The flat-capacity call graph with the flag OFF must place a
        topology-free workload exactly like the flag-ON default."""
        rng = random.Random(19)
        snapshot = random_cluster(rng, 24)
        pods = random_pending(rng, 12)
        on, _ = TPUBackend(max_batch=8).assign(
            pods, snapshot, default_fwk())
        monkeypatch.setenv("KTPU_TOPOLOGY", "0")
        off, _ = TPUBackend(max_batch=8).assign(
            pods, snapshot, default_fwk())
        assert on == off

    def test_gang_plugin_skips_when_off(self, monkeypatch):
        """With the switch off a slice-shaped gang still gang-schedules
        (count-only Permit), but TopologySlice never activates."""
        from kubernetes_tpu.scheduler.plugins.topologyslice import (
            TopologySlice,
        )
        monkeypatch.setenv("KTPU_TOPOLOGY", "0")
        plugin = TopologySlice()
        assert not plugin.active_for(object())


async def _gang_sched(store, shards):
    plugins = build_plugins(
        DEFAULT_PLUGINS + ["Coscheduling", "TopologySlice"],
        {"TopologySlice": {"shards": shards}}, store=store)
    fwk = Framework(plugins, DEFAULT_SCORE_WEIGHTS,
                    metrics=SchedulerMetrics())
    sched = Scheduler(store, profiles={"default-scheduler": fwk},
                      seed=7, backend=TPUBackend(max_batch=8))
    factory = InformerFactory(store)
    await sched.setup_informers(factory)
    factory.start()
    await factory.wait_for_sync()
    return sched, factory


async def _bound_map(store):
    return {p["metadata"]["name"]: p["spec"]["nodeName"]
            for p in (await store.list("pods")).items
            if p["spec"].get("nodeName")}


def _slice_pod(name, group):
    return make_pod(name, labels={POD_GROUP_LABEL: group},
                    requests={"cpu": "500m"}, uid=name)


class TestShapedGangs:
    @pytest.mark.parametrize("shards", [1, 4, 8])
    def test_all_or_nothing_contiguous_bind(self, shards):
        """A 2x2 slice gang on a 4x4 auto torus: nothing binds until
        the LAST member arrives, then all four land on nodes forming
        one contiguous sub-mesh."""
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            # node-i name fallback maps the fleet onto the auto mesh.
            for i in range(16):
                await store.create("nodes", make_node(f"node-{i}"))
            await store.create("podgroups", make_pod_group(
                "tile", min_member=4, schedule_timeout_seconds=5.0,
                slice_shape=(2, 2)))
            sched, factory = await _gang_sched(store, shards)
            task = asyncio.ensure_future(sched.run(batch_size=8))
            try:
                for i in range(3):
                    await store.create("pods", _slice_pod(f"t-{i}", "tile"))
                await asyncio.sleep(0.4)
                assert await _bound_map(store) == {}

                await store.create("pods", _slice_pod("t-3", "tile"))
                for _ in range(200):
                    if len(await _bound_map(store)) == 4:
                        break
                    await asyncio.sleep(0.05)
                bound = await _bound_map(store)
                assert set(bound) == {"t-0", "t-1", "t-2", "t-3"}

                # The four nodes form one contiguous 2x2 sub-mesh.
                spec = MeshSpec((4, 4, 1), True)
                cells = [node_cell(n, {}, spec) for n in bound.values()]
                assert None not in cells
                assert is_contiguous_slice(cells, spec, (2, 2))
                assert sched.metrics.slice_gangs_bound.value() == 1
            finally:
                await sched.stop()
                task.cancel()
                factory.stop()
                store.stop()
        run(body())

    def test_impossible_shape_leaves_gang_pending(self):
        """No orientation of the shape fits the mesh: the whole gang
        stays pending — no partial binds, ever."""
        async def body():
            store = new_cluster_store()
            install_core_validation(store)
            for i in range(4):   # auto mesh: 2x2 — a 1x3 can't fit
                await store.create("nodes", make_node(f"node-{i}"))
            await store.create("podgroups", make_pod_group(
                "bar", min_member=3, schedule_timeout_seconds=0.5,
                slice_shape=(1, 3)))
            sched, factory = await _gang_sched(store, shards=1)
            task = asyncio.ensure_future(sched.run(batch_size=8))
            try:
                for i in range(3):
                    await store.create("pods", _slice_pod(f"b-{i}", "bar"))
                await asyncio.sleep(0.8)
                assert await _bound_map(store) == {}
                assert sched.metrics.slice_gangs_bound.value() == 0
            finally:
                await sched.stop()
                task.cancel()
                factory.stop()
                store.stop()
        run(body())


class TestChurnFamilySchema:
    def test_slice_packing_family_wellformed(self):
        import os

        import yaml

        from kubernetes_tpu.config.scheduler import ProfileConfig
        from kubernetes_tpu.perf.churn.faults import build_fault_timeline
        path = os.path.join(
            os.path.dirname(__file__), "..", "kubernetes_tpu", "perf",
            "config", "performance-config.yaml")
        with open(path) as f:
            families = yaml.safe_load(f)
        fam = next(c for c in families
                   if c["name"] == "ChurnSlicePacking")
        # The profile enables the gang pair at every extension point.
        prof = ProfileConfig(fam["schedulerConfig"]["profiles"][0])
        assert "Coscheduling" in prof.active["Permit"]
        assert "TopologySlice" in prof.active["PreFilter"]
        assert "TopologySlice" in prof.active["Filter"]
        churn = next(op for op in fam["workloadTemplate"]
                     if op["opcode"] == "churnOpenLoop")
        kinds = [f["kind"] for f in churn["faults"]]
        assert kinds == ["gangArrival", "sliceDeath"]
        for wl in fam["workloads"]:
            params = wl["params"]
            specs = [{k: (params[v[1:]] if isinstance(v, str)
                          and v.startswith("$") else v)
                      for k, v in f.items()} for f in churn["faults"]]
            t1 = build_fault_timeline(specs, seed=17,
                                      node_names=["node-0"])
            t2 = build_fault_timeline(specs, seed=17,
                                      node_names=["node-0"])
            assert [e.signature() for e in t1] \
                == [e.signature() for e in t2]
            # the re-coalesce fault targets the arrival's group
            death = next(e for e in t1 if e.kind == "sliceDeath")
            arrive = next(e for e in t1 if e.kind == "gangArrival")
            assert death.params["group"] == \
                f"slice-{round(arrive.at * 1e3)}"
