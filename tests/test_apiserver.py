"""API server + RemoteStore: the §3.2 PROCESS BOUNDARY made real.

The scheduler/informers/controllers consume the same duck-typed store
interface; these tests run them against an APIServer over localhost sockets
instead of the in-proc MVCCStore.
"""

import asyncio

import pytest

from kubernetes_tpu.api.types import make_node, make_pod
from kubernetes_tpu.apiserver import APIServer, PriorityLevel, RemoteStore
from kubernetes_tpu.client import InformerFactory, ResourceEventHandler
from kubernetes_tpu.store import install_core_validation, new_cluster_store
from kubernetes_tpu.store.mvcc import (
    AlreadyExists,
    Conflict,
    Expired,
    MVCCStore,
    NotFound,
)


def run(coro):
    return asyncio.run(coro)


async def _serve(store=None, **kw):
    store = store or new_cluster_store()
    install_core_validation(store)
    srv = APIServer(store, **kw)
    await srv.start()
    return store, srv


class TestCRUD:
    def test_create_get_update_delete_roundtrip(self):
        async def body():
            store, srv = await _serve()
            rs = RemoteStore(srv.url)
            created = await rs.create("pods", make_pod("a", "default"))
            assert created["metadata"]["resourceVersion"]
            got = await rs.get("pods", "default/a")
            assert got["metadata"]["name"] == "a"
            got["metadata"]["labels"] = {"app": "x"}
            updated = await rs.update("pods", got)
            assert updated["metadata"]["labels"] == {"app": "x"}
            tomb = await rs.delete("pods", "default/a")
            assert tomb["metadata"]["name"] == "a"
            with pytest.raises(NotFound):
                await rs.get("pods", "default/a")
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())

    def test_error_mapping(self):
        async def body():
            store, srv = await _serve()
            rs = RemoteStore(srv.url)
            await rs.create("pods", make_pod("a", "default"))
            with pytest.raises(AlreadyExists):
                await rs.create("pods", make_pod("a", "default"))
            got = await rs.get("pods", "default/a")
            got["metadata"]["resourceVersion"] = "999999"
            with pytest.raises(Conflict):
                await rs.update("pods", got)
            with pytest.raises(NotFound):
                await rs.get("pods", "default/nope")
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())

    def test_binding_subresource_over_http(self):
        async def body():
            store, srv = await _serve()
            rs = RemoteStore(srv.url)
            await rs.create("nodes", make_node("n1"))
            await rs.create("pods", make_pod("a", "default"))
            st = await rs.subresource(
                "pods", "default/a", "binding", {"target": {"name": "n1"}})
            assert st["status"] == "Success"
            bound = await rs.get("pods", "default/a")
            assert bound["spec"]["nodeName"] == "n1"
            with pytest.raises(Conflict):
                await rs.subresource(
                    "pods", "default/a", "binding",
                    {"target": {"name": "n2"}})
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())

    def test_guaranteed_update_cas_loop(self):
        async def body():
            store, srv = await _serve()
            rs = RemoteStore(srv.url)
            await rs.create("nodes", make_node("n1"))

            async def bump(i):
                def mut(n):
                    n["metadata"].setdefault(
                        "annotations", {})[f"w{i}"] = "1"
                    return n
                await rs.guaranteed_update("nodes", "n1", mut)
            await asyncio.gather(*(bump(i) for i in range(6)))
            got = await rs.get("nodes", "n1")
            assert len(got["metadata"]["annotations"]) == 6
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())


class TestListSemantics:
    def test_limit_continue_pages_through(self):
        async def body():
            store, srv = await _serve()
            rs = RemoteStore(srv.url)
            for i in range(7):
                await rs.create("pods", make_pod(f"p{i}", "default"))
            seen, cont = [], None
            while True:
                import aiohttp
                params = {"limit": "3"}
                if cont:
                    params["continue"] = cont
                async with aiohttp.ClientSession() as s:
                    async with s.get(
                            srv.url + "/api/v1/pods",
                            params=params) as resp:
                        body_ = await resp.json()
                seen += [o["metadata"]["name"] for o in body_["items"]]
                cont = body_["metadata"].get("continue")
                if not cont:
                    break
            assert sorted(seen) == sorted(f"p{i}" for i in range(7))
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())

    def test_malformed_selector_is_400(self):
        async def body():
            store, srv = await _serve()
            import aiohttp
            async with aiohttp.ClientSession() as s:
                async with s.get(
                        srv.url + "/api/v1/pods",
                        params={"labelSelector": "bad(("}) as resp:
                    assert resp.status == 400
                    body_ = await resp.json()
                    assert body_["reason"] == "BadRequest"
            await srv.stop()
            store.stop()
        run(body())


class TestWatch:
    def test_list_watch_stream_and_selector(self):
        async def body():
            store, srv = await _serve()
            rs = RemoteStore(srv.url)
            await rs.create("pods", make_pod(
                "keep", "default", labels={"app": "web"}))
            await rs.create("pods", make_pod(
                "skip", "default", labels={"app": "db"}))
            from kubernetes_tpu.api.labels import parse_selector
            sel = parse_selector("app=web")
            lst = await rs.list("pods", selector=sel)
            assert [o["metadata"]["name"] for o in lst.items] == ["keep"]

            watch = await rs.watch(
                "pods", resource_version=lst.resource_version, selector=sel)
            seen = []

            async def consume():
                async for ev in watch:
                    seen.append((ev.type, ev.object["metadata"]["name"]))
                    if len(seen) == 2:
                        break
            task = asyncio.ensure_future(consume())
            await asyncio.sleep(0.05)
            await rs.create("pods", make_pod(
                "keep2", "default", labels={"app": "web"}))
            await rs.create("pods", make_pod(
                "skip2", "default", labels={"app": "db"}))
            await rs.delete("pods", "default/keep")
            await asyncio.wait_for(task, 5)
            assert seen == [("ADDED", "keep2"), ("DELETED", "keep")]
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())

    def test_expired_rv_raises_410(self):
        async def body():
            small = MVCCStore(event_window=5)
            install_core_validation(small)
            srv = APIServer(small)
            await srv.start()
            rs = RemoteStore(srv.url)
            for i in range(30):
                await rs.create("pods", make_pod(f"p{i}", "default"))
            with pytest.raises(Expired):
                await rs.watch("pods", resource_version=2)
            await rs.close()
            await srv.stop()
            small.stop()
        run(body())

    def test_informer_over_socket_syncs_and_recovers(self):
        """The informer stack runs UNCHANGED against the remote store."""
        async def body():
            store, srv = await _serve()
            rs = RemoteStore(srv.url)
            for i in range(10):
                await store.create("pods", make_pod(f"p{i}", "default"))
            factory = InformerFactory(rs)
            inf = factory.informer("pods")
            adds = []
            inf.add_event_handler(ResourceEventHandler(
                on_add=lambda o: adds.append(o["metadata"]["name"])))
            factory.start()
            await factory.wait_for_sync()
            assert len(adds) == 10
            await store.create("pods", make_pod("live", "default"))
            await asyncio.sleep(0.2)
            assert "live" in adds
            factory.stop()
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())


class TestAPF:
    def test_inflight_limit_queues_and_rejects(self):
        async def body():
            store = new_cluster_store()
            gate = asyncio.Event()

            # Stall every list so seats stay occupied.
            orig_list = store.list

            async def slow_list(resource, **kw):
                await gate.wait()
                return await orig_list(resource, **kw)
            store.list = slow_list

            srv = APIServer(store, priority_levels={
                "system": PriorityLevel("system", seats=64),
                # Single queue: queue_limit acts as the level's total
                # backlog bound, the reject-when-full shape this test pins.
                "workload": PriorityLevel(
                    "workload", seats=2, queue_limit=2, num_queues=1),
            })
            await srv.start()
            rs = RemoteStore(srv.url)

            tasks = [asyncio.ensure_future(rs.list("pods"))
                     for _ in range(4)]
            await asyncio.sleep(0.1)
            level = srv.priority_levels["workload"]
            assert level.in_use == 2 and level.queued == 2
            # Queue full → 429 mapped to StoreError by the client.
            from kubernetes_tpu.store.mvcc import StoreError
            with pytest.raises(StoreError):
                await rs.list("pods")
            gate.set()
            await asyncio.gather(*tasks)
            assert level.in_use == 0 and level.queued == 0
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())

    def test_system_traffic_unaffected_by_workload_flood(self):
        async def body():
            store = new_cluster_store()
            gate = asyncio.Event()
            orig_list = store.list

            async def slow_list(resource, **kw):
                if resource == "pods":
                    await gate.wait()
                return await orig_list(resource, **kw)
            store.list = slow_list
            srv = APIServer(store, priority_levels={
                "system": PriorityLevel("system", seats=4),
                "workload": PriorityLevel("workload", seats=1,
                                          queue_limit=8),
            })
            await srv.start()
            rs = RemoteStore(srv.url)
            flood = [asyncio.ensure_future(rs.list("pods"))
                     for _ in range(5)]
            await asyncio.sleep(0.05)
            # Leases ride the system level: unaffected by the pod flood.
            got = await asyncio.wait_for(rs.list("leases"), 2)
            assert got.items == []
            gate.set()
            await asyncio.gather(*flood)
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())


class TestSchedulerOverSocket:
    def test_scheduler_binds_pods_through_apiserver(self):
        """End-to-end across the process boundary: informers LIST+WATCH over
        HTTP, scheduler assigns, DefaultBinder POSTs the binding
        subresource — the §3.1 bind POST for real."""
        async def body():
            from kubernetes_tpu.scheduler import Scheduler
            store, srv = await _serve()
            rs = RemoteStore(srv.url)
            for i in range(5):
                await rs.create("nodes", make_node(
                    f"n{i}", allocatable={"cpu": "8", "memory": "16Gi",
                                          "pods": "110"}))
            sched = Scheduler(rs)
            factory = InformerFactory(rs)
            await sched.setup_informers(factory)
            factory.start()
            await factory.wait_for_sync()
            runner = asyncio.ensure_future(sched.run())
            for i in range(20):
                await rs.create("pods", make_pod(
                    f"p{i}", "default",
                    requests={"cpu": "100m", "memory": "128Mi"}))
            for _ in range(100):
                await asyncio.sleep(0.1)
                lst = await rs.list("pods")
                bound = [o for o in lst.items
                         if o.get("spec", {}).get("nodeName")]
                if len(bound) == 20:
                    break
            assert len(bound) == 20, f"only {len(bound)} bound"
            await sched.stop()
            runner.cancel()
            factory.stop()
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())


class TestDiscoveryAndAggregation:
    def test_discovery_and_openapi(self):
        async def body():
            store, srv = await _serve()
            rs = RemoteStore(srv.url)
            import aiohttp
            async with aiohttp.ClientSession() as s:
                async with s.get(srv.url + "/api") as r:
                    assert (await r.json())["versions"] == ["v1"]
                async with s.get(srv.url + "/apis") as r:
                    groups = {g["name"]
                              for g in (await r.json())["groups"]}
                    assert "apps" in groups and "batch" in groups
                async with s.get(srv.url + "/openapi/v2") as r:
                    doc = await r.json()
                    assert doc["swagger"] == "2.0"
                    assert "/api/v1/namespaces/{namespace}/pods" in \
                        doc["paths"]
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())

    def test_apiservice_routes_group_to_extension_server(self):
        """kube-aggregator: an APIService proxies /apis/<group>/... to the
        extension apiserver (handler_proxy.go)."""
        async def body():
            from aiohttp import web as aioweb
            hits = []

            async def extension(request):
                hits.append(request.path)
                return aioweb.json_response(
                    {"kind": "WidgetList", "items": [{"name": "w1"}]})

            ext_app = aioweb.Application()
            ext_app.router.add_route(
                "*", "/apis/metrics.ktpu.dev/{tail:.*}", extension)
            runner = aioweb.AppRunner(ext_app)
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            ext_port = site._server.sockets[0].getsockname()[1]

            store, srv = await _serve()
            await store.create("apiservices", {
                "kind": "APIService",
                "metadata": {"name": "v1.metrics.ktpu.dev"},
                "spec": {"group": "metrics.ktpu.dev", "version": "v1",
                         "service": {
                             "url": f"http://127.0.0.1:{ext_port}"}}})
            import aiohttp
            async with aiohttp.ClientSession() as s:
                url = srv.url + "/apis/metrics.ktpu.dev/v1/namespaces/" \
                    "default/widgets"
                async with s.get(url) as r:
                    assert r.status == 200
                    body_json = await r.json()
                    assert body_json["kind"] == "WidgetList"
            assert hits  # the extension server actually served it
            # Non-aggregated groups still serve locally.
            async with aiohttp.ClientSession() as s:
                async with s.get(
                        srv.url + "/apis/apps/v1/namespaces/default/"
                        "deployments") as r:
                    assert r.status == 200
                    assert (await r.json())["kind"] == "List"
            await srv.stop()
            await runner.cleanup()
            store.stop()
        run(body())

    def test_remote_store_learns_crd_scope_from_discovery(self):
        """CRD scope is store-local server-side; a RemoteStore must learn
        it via /api/v1 discovery or cluster-scoped custom resources would
        silently list empty through namespaced URLs."""
        async def body():
            from kubernetes_tpu.apiserver.admission import (
                install_crd_support, make_crd)
            store = new_cluster_store()
            install_core_validation(store)
            install_crd_support(store)
            await store.create("customresourcedefinitions",
                               make_crd("tpuslices", "TPUSlice",
                                        scope="Cluster"))
            srv = APIServer(store)
            await srv.start()
            from kubernetes_tpu.apiserver import RemoteStore
            rs = RemoteStore(srv.url)
            await rs.refresh_discovery()
            assert rs.is_cluster_scoped("tpuslices")
            assert rs.resource_for_kind("TPUSlice") == "tpuslices"
            await rs.create("tpuslices", {
                "kind": "TPUSlice", "metadata": {"name": "s0"}})
            # namespace arg must not produce a namespaced URL for a
            # cluster-scoped resource (would filter to empty).
            lst = await rs.list("tpuslices", namespace="default")
            assert [o["metadata"]["name"] for o in lst.items] == ["s0"]
            await rs.close()
            await srv.stop()
            store.stop()
        run(body())

    def test_aggregator_strips_credentials_forwards_identity(self):
        """The proxy must NOT forward client bearer tokens/cookies to
        extension servers (an APIService creator could harvest every
        caller's credential); identity rides X-Remote-User instead —
        kube-aggregator's requestheader pattern (ADVICE r3)."""
        async def body():
            from aiohttp import web as aioweb
            seen = {}

            async def extension(request):
                seen.update(request.headers)
                return aioweb.json_response({"kind": "Status"})

            ext_app = aioweb.Application()
            ext_app.router.add_route("*", "/apis/ext.ktpu.dev/{tail:.*}",
                                     extension)
            runner = aioweb.AppRunner(ext_app)
            await runner.setup()
            site = aioweb.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            ext_port = site._server.sockets[0].getsockname()[1]

            store, srv = await _serve(
                bearer_tokens={"sekret": "alice"},
                user_groups={"alice": ["sre"]})
            await store.create("apiservices", {
                "kind": "APIService",
                "metadata": {"name": "v1.ext.ktpu.dev"},
                "spec": {"group": "ext.ktpu.dev", "version": "v1",
                         "service": {
                             "url": f"http://127.0.0.1:{ext_port}"}}})
            import aiohttp
            async with aiohttp.ClientSession() as s:
                async with s.get(
                        srv.url + "/apis/ext.ktpu.dev/v1/widgets",
                        headers={"Authorization": "Bearer sekret",
                                 "Cookie": "session=abc",
                                 "X-Remote-User": "spoofed",
                                 "X-Remote-Extra-Scopes": "admin"}) as r:
                    assert r.status == 200
            assert "Authorization" not in seen
            assert "Cookie" not in seen
            assert "X-Remote-Extra-Scopes" not in seen
            assert seen.get("X-Remote-User") == "alice"  # not "spoofed"
            assert seen.get("X-Remote-Group") == "sre,system:authenticated"
            await srv.stop()
            await runner.cleanup()
            store.stop()
        run(body())

    def test_resource_list_discovery(self):
        async def body():
            store, srv = await _serve()
            import aiohttp
            async with aiohttp.ClientSession() as s:
                async with s.get(srv.url + "/apis/apps/v1") as r:
                    assert r.status == 200
                    doc = await r.json()
                    assert doc["kind"] == "APIResourceList"
                    by_name = {x["name"]: x for x in doc["resources"]}
                    assert by_name["deployments"]["kind"] == "Deployment"
                    assert by_name["deployments"]["namespaced"] is True
                    assert by_name["nodes"]["namespaced"] is False
                async with s.get(srv.url + "/api/v1") as r:
                    assert r.status == 200
            await srv.stop()
            store.stop()
        run(body())


class TestProtobufContentNegotiation:
    def test_protobuf_clients_and_json_clients_interop(self):
        """§5.8: components can speak the protobuf (runtime.Unknown
        envelope) wire over HTTP while JSON clients share the server."""
        async def body():
            store, srv = await _serve()
            pb = RemoteStore(srv.url, protobuf=True)
            js = RemoteStore(srv.url)
            created = await pb.create("pods", make_pod("a"))
            assert created["metadata"]["name"] == "a"
            assert created["metadata"]["resourceVersion"]
            # JSON client reads what the protobuf client wrote.
            got = await js.get("pods", "default/a")
            assert got["metadata"]["uid"] == created["metadata"]["uid"]
            # protobuf client reads + updates.
            got_pb = await pb.get("pods", "default/a")
            got_pb["metadata"]["labels"] = {"wire": "proto"}
            updated = await pb.update("pods", got_pb)
            assert updated["metadata"]["labels"] == {"wire": "proto"}
            # Errors still map on the protobuf path.
            with pytest.raises(NotFound):
                await pb.get("pods", "default/nope")
            await pb.close()
            await js.close()
            await srv.stop()
            store.stop()
        run(body())


class TestAPFFairQueuing:
    """Shuffle-shard fair queuing (pkg/util/flowcontrol parity): an
    elephant flow's backlog cannot starve a well-behaved mouse flow."""

    def test_mouse_latency_bounded_under_elephant_flood(self):
        async def body():
            level = PriorityLevel("workload", seats=4, queue_limit=64,
                                  num_queues=64, hand_size=8)

            async def hold(flow, secs):
                await level.acquire(flow)
                try:
                    await asyncio.sleep(secs)
                finally:
                    level.release()

            # Elephant: 200 long requests from ONE flow — enough to fill
            # its whole hand many times over.
            flood = [asyncio.ensure_future(hold("elephant", 0.05))
                     for _ in range(200)]
            await asyncio.sleep(0.01)
            assert level.queued > 100
            # Mouse: sequential requests from another flow while seats
            # stay contended. Its hand almost surely includes queues the
            # elephant's hand doesn't cover, so its wait is ~one seat
            # rotation, not the elephant's whole backlog drain.
            import time as _t
            lat = []
            for _ in range(10):
                t0 = _t.monotonic()
                await hold("mouse", 0.001)
                lat.append(_t.monotonic() - t0)
            lat.sort()
            p99 = lat[-1]
            # Elephant backlog is ~200*0.05/4 ≈ 2.5s total; the mouse's
            # SLO is a small multiple of one request's service time.
            assert p99 < 0.5, f"mouse starved: p99={p99:.3f}s"
            assert level.queued > 0, "flood should still be queued"
            for f in flood:
                f.cancel()
            await asyncio.gather(*flood, return_exceptions=True)
        run(body())

    def test_shuffle_shard_deterministic_and_distinct(self):
        level = PriorityLevel("w", num_queues=64, hand_size=8)
        h1 = level._hand("flow-a")
        assert h1 == level._hand("flow-a")
        assert len(set(h1)) == 8
        assert all(0 <= i < 64 for i in h1)
        # different flows overwhelmingly get different hands
        assert h1 != level._hand("flow-b")

    def test_elephant_rejected_mouse_admitted_when_hand_full(self):
        async def body():
            # Tiny level: the elephant saturates its hand's queues and
            # gets 429s; a mouse with a disjoint-ish hand still enqueues.
            level = PriorityLevel("w", seats=1, queue_limit=1,
                                  num_queues=16, hand_size=2)

            async def hold(flow):
                await level.acquire(flow)

            blocker = asyncio.ensure_future(hold("elephant"))
            await asyncio.sleep(0)
            # fill the elephant's two hand queues
            parked = [asyncio.ensure_future(hold("elephant"))
                      for _ in range(2)]
            await asyncio.sleep(0)
            from aiohttp import web
            with pytest.raises(web.HTTPTooManyRequests):
                await level.acquire("elephant")
            # the mouse's hand has room unless it collides on BOTH queues
            # (this flow is chosen to not collide for the fixed hash)
            for name in ("mouse-a", "mouse-b", "mouse-c"):
                if set(level._hand(name)) != set(level._hand("elephant")):
                    mouse = asyncio.ensure_future(hold(name))
                    await asyncio.sleep(0)
                    assert not mouse.done() or mouse.exception() is None
                    mouse.cancel()
                    break
            else:
                raise AssertionError("all mice collided (hash broken?)")
            for t in (blocker, *parked):
                t.cancel()
            await asyncio.gather(blocker, *parked,
                                 return_exceptions=True)
        run(body())
