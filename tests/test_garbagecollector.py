"""GC cascade + namespace deletion fan-out (SURVEY §2.4
garbagecollector/, namespace/; VERDICT r2 item #9: deleting a Deployment
must remove RS+Pods via the ownerReference graph, not via RS-controller
cleanup)."""

import asyncio

from kubernetes_tpu.api.meta import namespaced_name
from kubernetes_tpu.api.types import make_namespace, make_node, make_pod
from kubernetes_tpu.client import InformerFactory
from kubernetes_tpu.controllers import (
    ControllerManager,
    DeploymentController,
    GarbageCollectorController,
    NamespaceController,
    ReplicaSetController,
    make_deployment,
)
from kubernetes_tpu.store import install_core_validation, new_cluster_store


def run(coro):
    return asyncio.run(coro)


async def wait_for(predicate, timeout=10.0, interval=0.03):
    for _ in range(int(timeout / interval)):
        v = await predicate()
        if v:
            return v
        await asyncio.sleep(interval)
    return await predicate()


async def gc_stack(controllers):
    store = new_cluster_store()
    install_core_validation(store)
    for i in range(2):
        await store.create("nodes", make_node(f"n{i}"))
    mgr = ControllerManager(store, [c(store) for c in controllers])
    await mgr.start()

    async def teardown():
        await mgr.stop()
        store.stop()
    return store, mgr, teardown


DEPLOY_TEMPLATE = {
    "metadata": {"labels": {"app": "web"}},
    "spec": {"containers": [{"name": "c", "image": "web:1"}]},
}


class TestGCCascade:
    def test_deleting_deployment_cascades_to_rs_and_pods(self):
        """The RS controller does NOT clean up after its owner vanishes —
        the GC's ownerReference graph must do it: Deployment → RS → Pods
        all disappear after a single Deployment delete."""
        async def body():
            store, mgr, teardown = await gc_stack(
                [DeploymentController, ReplicaSetController,
                 GarbageCollectorController])
            await store.create("deployments", make_deployment(
                "web", 3, {"matchLabels": {"app": "web"}}, DEPLOY_TEMPLATE))

            async def pods_up():
                return len((await store.list("pods")).items) == 3
            assert await wait_for(pods_up)
            rss = (await store.list("replicasets")).items
            assert len(rss) == 1

            await store.delete("deployments", "default/web")

            async def all_gone():
                pods = (await store.list("pods")).items
                rss = (await store.list("replicasets")).items
                return not pods and not rss
            assert await wait_for(all_gone, timeout=15.0)
            await teardown()
        run(body())

    def test_orphan_annotation_keeps_dependent(self):
        """kubernetes.io/orphan: the dependent survives, ownerReferences
        stripped (the reference's orphan deletion policy)."""
        async def body():
            store, mgr, teardown = await gc_stack(
                [GarbageCollectorController])
            owner = await store.create("replicasets", {
                "apiVersion": "apps/v1", "kind": "ReplicaSet",
                "metadata": {"name": "rs", "namespace": "default",
                             "uid": "rs-uid-1"},
                "spec": {"replicas": 0}})
            pod = make_pod("kept")
            pod["metadata"]["ownerReferences"] = [{
                "kind": "ReplicaSet", "name": "rs",
                "uid": owner["metadata"]["uid"], "controller": True}]
            pod["metadata"]["annotations"] = {"kubernetes.io/orphan": "true"}
            await store.create("pods", pod)
            await asyncio.sleep(0.3)
            await store.delete("replicasets", "default/rs")

            async def orphaned():
                p = await store.get("pods", "default/kept")
                return "ownerReferences" not in p["metadata"]
            assert await wait_for(orphaned)
            await teardown()
        run(body())

    def test_dependent_created_after_owner_died_is_collected(self):
        """A dependent whose owner uid never existed (or died before the
        dependent appeared) is collected by the orphan sweep."""
        async def body():
            store, mgr, teardown = await gc_stack(
                [GarbageCollectorController])
            pod = make_pod("stray")
            pod["metadata"]["ownerReferences"] = [{
                "kind": "ReplicaSet", "name": "ghost",
                "uid": "no-such-uid", "controller": True}]
            await store.create("pods", pod)

            async def gone():
                items = (await store.list("pods")).items
                return not items
            assert await wait_for(gone, timeout=15.0)
            await teardown()
        run(body())


class TestGraphHygiene:
    def test_mixed_watched_unwatched_owners_leave_no_graph_entries(self):
        """A dependent with one watched + one UNWATCHED owner kind is never
        collectable; it must leave NO _dependents entries behind (the
        ADVICE r3 map leak: per-ref writes before the collectable check
        stranded entries that enqueued spurious sync work forever)."""
        async def body():
            store, mgr, teardown = await gc_stack(
                [GarbageCollectorController])
            gc = mgr.controllers[0]
            created = await store.create("deployments", make_deployment(
                "web", 1, {"matchLabels": {"app": "web"}}, DEPLOY_TEMPLATE))
            pod = make_pod("mixed", "default")
            pod["metadata"]["ownerReferences"] = [
                {"kind": "Deployment", "name": "web",
                 "uid": created["metadata"]["uid"]},
                # Node is not a GC-watched resource → never collectable.
                {"kind": "Node", "name": "n0", "uid": "node-uid"},
            ]
            await store.create("pods", pod)
            await wait_for(lambda: asyncio.sleep(0.1, True))
            key = ("pods", "default/mixed")
            assert key not in gc._owners_of
            assert all(key not in deps
                       for deps in gc._dependents.values()), \
                "unwatched-owner dependent leaked into _dependents"
            # And the pod survives owner deletion (kept forever).
            await store.delete("deployments", "default/web")
            await asyncio.sleep(0.3)
            got = await store.get("pods", "default/mixed")
            assert got["metadata"]["name"] == "mixed"
            await teardown()
        run(body())


class TestNamespaceFanout:
    def test_namespace_delete_purges_contents(self):
        async def body():
            store, mgr, teardown = await gc_stack([NamespaceController])
            await store.create("namespaces", make_namespace("team-a"))
            for i in range(3):
                await store.create("pods", make_pod(f"p{i}", "team-a"))
            await store.create("pods", make_pod("keep", "default"))
            await asyncio.sleep(0.2)
            await store.delete("namespaces", "team-a")

            async def purged():
                pods = (await store.list("pods")).items
                names = {namespaced_name(p) for p in pods}
                return names == {"default/keep"}
            assert await wait_for(purged)
            await teardown()
        run(body())
