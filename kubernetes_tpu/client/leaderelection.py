"""Lease-based leader election for active/passive HA.

Parity target: staging/src/k8s.io/client-go/tools/leaderelection
(`LeaderElector.Run`: acquire → renew loop → on lost call OnStoppedLeading;
resourcelock on coordination.k8s.io/Lease). Fencing is by lease holder identity
+ RV CAS, exactly as the reference.
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable

from kubernetes_tpu.api.meta import new_object
from kubernetes_tpu.store.mvcc import AlreadyExists, Conflict, MVCCStore, NotFound

LEASES = "leases"


class LeaderElector:
    def __init__(
        self,
        store: MVCCStore,
        lock_name: str,
        identity: str,
        lease_duration: float = 15.0,
        renew_deadline: float = 10.0,
        retry_period: float = 2.0,
        namespace: str = "kube-system",
    ):
        self.store = store
        self.lock_name = lock_name
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.namespace = namespace
        self.is_leader = False

    def _key(self) -> str:
        return f"{self.namespace}/{self.lock_name}"

    async def _try_acquire_or_renew(self) -> bool:
        now = time.time()
        try:
            lease = await self.store.get(LEASES, self._key())
        except NotFound:
            lease = new_object(
                "Lease", self.lock_name, self.namespace,
                spec={"holderIdentity": self.identity,
                      "acquireTime": now, "renewTime": now,
                      "leaseDurationSeconds": self.lease_duration},
            )
            try:
                await self.store.create(LEASES, lease)
                return True
            except AlreadyExists:
                return False
        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity")
        expired = now > spec.get("renewTime", 0) + spec.get(
            "leaseDurationSeconds", self.lease_duration)
        if holder != self.identity and not expired:
            return False

        def mutate(obj):
            s = obj.setdefault("spec", {})
            if s.get("holderIdentity") != self.identity:
                if time.time() <= s.get("renewTime", 0) + s.get(
                        "leaseDurationSeconds", self.lease_duration):
                    return None  # someone else renewed first
                s["acquireTime"] = time.time()
            s["holderIdentity"] = self.identity
            s["renewTime"] = time.time()
            s["leaseDurationSeconds"] = self.lease_duration
            return obj

        try:
            updated = await self.store.guaranteed_update(LEASES, self._key(), mutate)
        except Conflict:
            return False
        return updated.get("spec", {}).get("holderIdentity") == self.identity

    async def run(
        self,
        on_started_leading: Callable[[], Awaitable[None]],
        on_stopped_leading: Callable[[], None] | None = None,
    ) -> None:
        """Block acquiring; then run the payload while renewing. If renewal
        fails past the deadline, cancel the payload (fencing)."""
        while not await self._try_acquire_or_renew():
            await asyncio.sleep(self.retry_period)
        self.is_leader = True
        payload = asyncio.ensure_future(on_started_leading())
        try:
            last_renew = time.time()
            while not payload.done():
                await asyncio.sleep(self.retry_period)
                if await self._try_acquire_or_renew():
                    last_renew = time.time()
                elif time.time() - last_renew > self.renew_deadline:
                    payload.cancel()
                    break
            res = (await asyncio.gather(payload, return_exceptions=True))[0]
            # A crashed payload must surface, not read as a clean lease
            # handover — silently absorbing it leaves a replica "running"
            # that schedules nothing.
            if isinstance(res, BaseException) and \
                    not isinstance(res, asyncio.CancelledError):
                raise res
        finally:
            # run() itself cancelled (or renewal raised): the payload must
            # not keep doing leader work without the lease.
            if not payload.done():
                payload.cancel()
                try:
                    await asyncio.gather(payload, return_exceptions=True)
                except asyncio.CancelledError:
                    pass
            self.is_leader = False
            if on_stopped_leading:
                on_stopped_leading()
