"""Lease-based leader election for active/passive HA.

Parity target: staging/src/k8s.io/client-go/tools/leaderelection
(`LeaderElector.Run`: acquire → renew loop → on lost call OnStoppedLeading;
resourcelock on coordination.k8s.io/Lease). Fencing is by lease holder identity
+ RV CAS, exactly as the reference.
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable

from kubernetes_tpu.api.meta import new_object
from kubernetes_tpu.store.mvcc import MVCCStore, NotFound, StoreError

LEASES = "leases"


class LeaderElector:
    def __init__(
        self,
        store: MVCCStore,
        lock_name: str,
        identity: str,
        lease_duration: float | None = None,
        renew_deadline: float | None = None,
        retry_period: float | None = None,
        namespace: str = "kube-system",
        metrics=None,
    ):
        from kubernetes_tpu.utils import flags
        self.store = store
        self.lock_name = lock_name
        self.identity = identity
        # KTPU_LEASE_DURATION scales the whole election clock: the
        # renew deadline and retry period keep the reference's 15/10/2
        # proportions unless pinned explicitly, so a short-lease test
        # (or the failover bench row) tightens detection end to end.
        if lease_duration is None:
            lease_duration = float(flags.get("KTPU_LEASE_DURATION"))
        self.lease_duration = lease_duration
        self.renew_deadline = (renew_deadline if renew_deadline
                               is not None else lease_duration * (2 / 3))
        self.retry_period = (retry_period if retry_period is not None
                             else lease_duration * (2 / 15))
        self.namespace = namespace
        #: HAMetrics (metrics/registry.py): elections won + the
        #: is-leader gauge — failover is data, not log noise.
        if metrics is None:
            from kubernetes_tpu.metrics.registry import HAMetrics
            metrics = HAMetrics()
        self.metrics = metrics
        self.is_leader = False

    def _key(self) -> str:
        return f"{self.namespace}/{self.lock_name}"

    async def _try_acquire_or_renew(self) -> bool:
        now = time.time()
        try:
            lease = await self.store.get(LEASES, self._key())
        except NotFound:
            lease = new_object(
                "Lease", self.lock_name, self.namespace,
                spec={"holderIdentity": self.identity,
                      "acquireTime": now, "renewTime": now,
                      "leaseDurationSeconds": self.lease_duration},
            )
            try:
                await self.store.create(LEASES, lease)
                return True
            except StoreError:  # AlreadyExists (lost race) or transient
                return False
        except StoreError:
            # Transient store failure (the lease shard restarting, a
            # wire blip): a FAILED ATTEMPT, retried on retry_period —
            # client-go's tryAcquireOrRenew contract. Fencing still
            # holds: the leader cancels its payload once renewals fail
            # past renew_deadline; a replica must never crash out of
            # the election because the apiserver bounced.
            return False
        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity")
        expired = now > spec.get("renewTime", 0) + spec.get(
            "leaseDurationSeconds", self.lease_duration)
        if holder != self.identity and not expired:
            return False

        def mutate(obj):
            s = obj.setdefault("spec", {})
            if s.get("holderIdentity") != self.identity:
                if time.time() <= s.get("renewTime", 0) + s.get(
                        "leaseDurationSeconds", self.lease_duration):
                    return None  # someone else renewed first
                s["acquireTime"] = time.time()
            s["holderIdentity"] = self.identity
            s["renewTime"] = time.time()
            s["leaseDurationSeconds"] = self.lease_duration
            return obj

        try:
            updated = await self.store.guaranteed_update(LEASES, self._key(), mutate)
        except StoreError:  # Conflict (lost CAS race) or transient
            return False
        return updated.get("spec", {}).get("holderIdentity") == self.identity

    async def run(
        self,
        on_started_leading: Callable[[], Awaitable[None]],
        on_stopped_leading: Callable[[], None] | None = None,
    ) -> None:
        """Block acquiring; then run the payload while renewing. If renewal
        fails past the deadline, cancel the payload (fencing)."""
        while not await self._try_acquire_or_renew():
            await asyncio.sleep(self.retry_period)
        self.is_leader = True
        self.metrics.elections.inc()
        self.metrics.is_leader.set(1)
        payload = asyncio.ensure_future(on_started_leading())
        try:
            last_renew = time.time()
            while not payload.done():
                await asyncio.sleep(self.retry_period)
                if await self._try_acquire_or_renew():
                    last_renew = time.time()
                elif time.time() - last_renew > self.renew_deadline:
                    payload.cancel()
                    break
            res = (await asyncio.gather(payload, return_exceptions=True))[0]
            # A crashed payload must surface, not read as a clean lease
            # handover — silently absorbing it leaves a replica "running"
            # that schedules nothing.
            if isinstance(res, BaseException) and \
                    not isinstance(res, asyncio.CancelledError):
                raise res
        finally:
            # run() itself cancelled (or renewal raised): the payload must
            # not keep doing leader work without the lease.
            if not payload.done():
                payload.cancel()
                try:
                    await asyncio.gather(payload, return_exceptions=True)
                except asyncio.CancelledError:
                    pass
            self.is_leader = False
            self.metrics.is_leader.set(0)
            if on_stopped_leading:
                on_stopped_leading()
