"""Client-side retry helpers (client-go util/retry).

`retry_on_conflict` is the remote-store CAS loop: GET → mutate → PUT with
the observed resourceVersion, retrying on Conflict — RetryOnConflict in
the reference. Shared by every remote store implementation (HTTP
RemoteStore, KTPU WireStore) so the semantics can't drift between wires.
"""

from __future__ import annotations

import copy
from typing import Callable

from kubernetes_tpu.store.mvcc import Conflict


async def retry_on_conflict(
    store, resource: str, key: str,
    mutate: Callable[[dict], dict | None],
    max_retries: int = 16, return_copy: bool = True,
) -> dict | None:
    """guaranteed_update over a remote store's get/update surface."""
    for _ in range(max_retries):
        current = await store.get(resource, key)
        want_rv = current["metadata"]["resourceVersion"]
        pristine = copy.deepcopy(current) if return_copy else None
        updated = mutate(current)
        if updated is None:
            # mutate may have scribbled on `current`; the pristine copy
            # honors the "unchanged" contract without a second GET.
            return pristine
        updated["metadata"]["resourceVersion"] = want_rv
        try:
            out = await store.update(resource, updated)
            return out if return_copy else None
        except Conflict:
            continue
    raise Conflict(
        f"{resource} {key!r}: too many conflicts in guaranteed_update")
