"""Rate-limited work queues.

Parity target: staging/src/k8s.io/client-go/util/workqueue
(`Type` (dedup + in-flight tracking), `delaying_queue.go`,
`rate_limiting_queue.go`, `default_rate_limiters.go`:
ItemExponentialFailureRateLimiter + BucketRateLimiter `MaxOfRateLimiter`).

Semantics preserved exactly, because controllers depend on them:
- An item added while queued is deduped (one entry).
- An item added while *being processed* is re-queued only after the worker
  calls done() — so a given key is never processed concurrently.
- forget() resets an item's failure count; num_requeues() exposes it.

asyncio-native (workers are tasks, not goroutines).
"""

from __future__ import annotations

import asyncio
import heapq
import time
from collections import deque
from typing import Any, Hashable


class ExponentialFailureRateLimiter:
    """per-item exponential backoff: base * 2^failures, capped."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: dict[Hashable, int] = {}

    def when(self, item: Hashable) -> float:
        n = self._failures.get(item, 0)
        self._failures[item] = n + 1
        return min(self.base_delay * (2 ** n), self.max_delay)

    def forget(self, item: Hashable) -> None:
        self._failures.pop(item, None)

    def num_requeues(self, item: Hashable) -> int:
        return self._failures.get(item, 0)


class WorkQueue:
    """Deduping queue with in-flight ("dirty"/"processing") tracking."""

    def __init__(self):
        self._queue: deque[Hashable] = deque()
        self._dirty: set[Hashable] = set()
        self._processing: set[Hashable] = set()
        self._cond = asyncio.Condition()
        self._shutting_down = False

    def __len__(self) -> int:
        return len(self._queue)

    async def add(self, item: Hashable) -> None:
        async with self._cond:
            if self._shutting_down or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return  # re-queued by done()
            self._queue.append(item)
            self._cond.notify()

    async def get(self) -> tuple[Any, bool]:
        """Returns (item, shutdown). Blocks until an item or shutdown."""
        async with self._cond:
            while not self._queue and not self._shutting_down:
                await self._cond.wait()
            if not self._queue:
                return None, True
            item = self._queue.popleft()
            self._processing.add(item)
            self._dirty.discard(item)
            return item, False

    async def done(self, item: Hashable) -> None:
        async with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    async def shut_down(self) -> None:
        async with self._cond:
            self._shutting_down = True
            self._cond.notify_all()

    @property
    def shutting_down(self) -> bool:
        return self._shutting_down


class DelayingQueue(WorkQueue):
    """add_after support via a heap + single timer task.

    The timer is woken whenever a new item lands with an earlier deadline than
    the one it is sleeping toward (the reference's delaying_queue wakes its
    loop via waitingForAddCh on every AddAfter) — otherwise a 5 ms requeue
    would be stuck behind a minutes-long backoff.
    """

    def __init__(self):
        super().__init__()
        self._heap: list[tuple[float, int, Hashable]] = []
        self._seq = 0
        self._timer: asyncio.Task | None = None
        self._wake = asyncio.Event()

    async def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            await self.add(item)
            return
        at = time.monotonic() + delay
        earlier = not self._heap or at < self._heap[0][0]
        heapq.heappush(self._heap, (at, self._seq, item))
        self._seq += 1
        if self._timer is None or self._timer.done():
            self._timer = asyncio.ensure_future(self._drain())
        elif earlier:
            self._wake.set()

    async def _drain(self) -> None:
        while self._heap and not self._shutting_down:
            at, _, _ = self._heap[0]
            now = time.monotonic()
            if at > now:
                self._wake.clear()
                try:
                    await asyncio.wait_for(self._wake.wait(), at - now)
                except asyncio.TimeoutError:
                    pass
                continue
            _, _, item = heapq.heappop(self._heap)
            await self.add(item)

    async def shut_down(self) -> None:
        if self._timer:
            self._timer.cancel()
        await super().shut_down()


class RateLimitingQueue(DelayingQueue):
    """DelayingQueue + per-item failure rate limiter."""

    def __init__(self, rate_limiter: ExponentialFailureRateLimiter | None = None):
        super().__init__()
        self.rate_limiter = rate_limiter or ExponentialFailureRateLimiter()

    async def add_rate_limited(self, item: Hashable) -> None:
        await self.add_after(item, self.rate_limiter.when(item))

    def forget(self, item: Hashable) -> None:
        self.rate_limiter.forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return self.rate_limiter.num_requeues(item)
