"""Client runtime: the reflector → informer → workqueue triangle (client-go
tools/cache + util/workqueue equivalents), event recording, leader election."""

from kubernetes_tpu.client.informer import (
    Indexer,
    InformerFactory,
    ResourceEventHandler,
    SharedInformer,
    ShardedInformer,
    namespace_index,
)
from kubernetes_tpu.client.workqueue import (
    DelayingQueue,
    ExponentialFailureRateLimiter,
    RateLimitingQueue,
    WorkQueue,
)
from kubernetes_tpu.client.events import EventRecorder
from kubernetes_tpu.client.leaderelection import LeaderElector

__all__ = [
    "Indexer",
    "InformerFactory",
    "ResourceEventHandler",
    "SharedInformer",
    "ShardedInformer",
    "namespace_index",
    "DelayingQueue",
    "ExponentialFailureRateLimiter",
    "RateLimitingQueue",
    "WorkQueue",
    "EventRecorder",
    "LeaderElector",
]
