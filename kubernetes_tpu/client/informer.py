"""Reflector + shared informer: the LIST+WATCH cache every component runs on.

Parity target: staging/src/k8s.io/client-go/tools/cache —
`reflector.go` (`Reflector.ListAndWatch`: LIST at RV, then WATCH from that RV,
relist on Expired/410), `thread_safe_store.go` (indexed object cache),
`shared_informer.go` (`sharedIndexInformer`: one reflector fanned out to many
event handlers, handlers get add/update/delete with old+new objects).

Deviation from the reference: no DeltaFIFO stage. The reference needs it to
decouple the watch goroutine from handler processing and to compress deltas
during slow consumption; under a single asyncio loop, events are applied to the
cache and dispatched to handlers in the same tick, which preserves the ordering
guarantees DeltaFIFO exists to protect (cache is updated *before* handlers see
the event — same as HandleDeltas).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Callable, Iterable, Mapping

from kubernetes_tpu.api.labels import Selector
from kubernetes_tpu.api.meta import namespaced_name, resource_version_of
from kubernetes_tpu.store.mvcc import Expired, MVCCStore

logger = logging.getLogger(__name__)


class Indexer:
    """thread_safe_store.go ThreadSafeStore: key→object plus named indices
    (index fn → set of keys). Single-loop ownership; no lock needed."""

    def __init__(self, indexers: Mapping[str, Callable[[Mapping], list[str]]] | None = None):
        self._objects: dict[str, dict] = {}
        self._indexers = dict(indexers or {})
        # index name -> index value -> set of object keys
        self._indices: dict[str, dict[str, set[str]]] = {n: {} for n in self._indexers}

    def __len__(self) -> int:
        return len(self._objects)

    def __contains__(self, key: str) -> bool:
        return key in self._objects

    def get(self, key: str) -> dict | None:
        return self._objects.get(key)

    def list(self) -> list[dict]:
        return list(self._objects.values())

    def keys(self) -> list[str]:
        return list(self._objects.keys())

    def by_index(self, index_name: str, value: str) -> list[dict]:
        keys = self._indices.get(index_name, {}).get(value, ())
        return [self._objects[k] for k in keys]

    def add_indexer(self, name: str,
                    fn: Callable[[Mapping], list[str]]) -> None:
        """Register a named index after construction (AddIndexers); existing
        objects are back-filled. Idempotent for the same name."""
        if name in self._indexers:
            return
        self._indexers[name] = fn
        idx: dict[str, set[str]] = {}
        self._indices[name] = idx
        for key, obj in self._objects.items():
            for v in fn(obj):
                idx.setdefault(v, set()).add(key)

    def _update_indices(self, key: str, old: Mapping | None, new: Mapping | None) -> None:
        for name, fn in self._indexers.items():
            idx = self._indices[name]
            old_vals = set(fn(old)) if old is not None else set()
            new_vals = set(fn(new)) if new is not None else set()
            for v in old_vals - new_vals:
                bucket = idx.get(v)
                if bucket:
                    bucket.discard(key)
                    if not bucket:
                        del idx[v]
            for v in new_vals - old_vals:
                idx.setdefault(v, set()).add(key)

    def upsert(self, obj: dict) -> dict | None:
        key = namespaced_name(obj)
        old = self._objects.get(key)
        self._objects[key] = obj
        self._update_indices(key, old, obj)
        return old

    def delete(self, obj: Mapping) -> dict | None:
        key = namespaced_name(obj)
        old = self._objects.pop(key, None)
        if old is not None:
            self._update_indices(key, old, None)
        return old

    def replace(self, objs: Iterable[dict]) -> None:
        self._objects = {}
        self._indices = {n: {} for n in self._indexers}
        for obj in objs:
            self.upsert(obj)


def namespace_index(obj: Mapping) -> list[str]:
    """The default "namespace" indexer (cache.MetaNamespaceIndexFunc)."""
    ns = obj.get("metadata", {}).get("namespace", "")
    return [ns] if ns else []


class ResourceEventHandler:
    """Handler triple; any of the three may be None."""

    def __init__(self, on_add=None, on_update=None, on_delete=None):
        self.on_add = on_add
        self.on_update = on_update
        self.on_delete = on_delete


class SharedInformer:
    """One reflector + indexer + N handlers for a single resource."""

    def __init__(
        self,
        store: MVCCStore,
        resource: str,
        selector: Selector | None = None,
        indexers: Mapping[str, Callable] | None = None,
    ):
        self.store = store
        self.resource = resource
        self.selector = selector
        idx = {"namespace": namespace_index}
        idx.update(indexers or {})
        self.indexer = Indexer(idx)
        self.handlers: list[ResourceEventHandler] = []
        self._task: asyncio.Task | None = None
        self._synced = asyncio.Event()
        self.last_rv = 0

    def add_event_handler(self, handler: ResourceEventHandler) -> None:
        self.handlers.append(handler)
        # Late joiners get synthetic adds for existing state, as the
        # reference's AddEventHandler does.
        if self._synced.is_set():
            for obj in self.indexer.list():
                self._call(handler.on_add, obj)

    @staticmethod
    def _call(fn, *args) -> None:
        if fn is None:
            return
        try:
            res = fn(*args)
            if asyncio.iscoroutine(res):
                asyncio.ensure_future(res)
        except Exception:  # handler errors must not kill the informer
            logger.exception("informer handler error")

    def has_synced(self) -> bool:
        return self._synced.is_set()

    async def wait_for_sync(self, timeout: float = 10.0) -> None:
        await asyncio.wait_for(self._synced.wait(), timeout)

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._run())

    def stop(self) -> None:
        if self._task:
            self._task.cancel()
            self._task = None

    async def _run(self) -> None:
        """Reflector.ListAndWatch with relist-on-410 and bookmark-driven
        resume: a watch error that is NOT a 410 re-watches from the last
        bookmark/event RV instead of unconditionally relisting — the
        watch cache's ring replays the gap, so a transport hiccup across
        N informers costs N backfills of a shared ring, not N store
        LISTs (the client half of the relist-storm fix). Only Expired —
        the server saying the gap is unservable — forces the full LIST."""
        relist = True
        while True:
            try:
                if relist or not self.last_rv:
                    lst = await self.store.list(
                        self.resource, selector=self.selector)
                    self._replace(lst.items)
                    self.last_rv = lst.resource_version
                    self._synced.set()
                    relist = False
                watch = await self.store.watch(
                    self.resource, resource_version=self.last_rv,
                    selector=self.selector,
                )
                async for ev in watch:
                    if ev.type == "BOOKMARK":
                        self.last_rv = max(self.last_rv, ev.rv)
                        continue
                    self._apply(ev.type, ev.object)
                    self.last_rv = ev.rv
            except Expired:
                logger.info("informer %s: watch expired, relisting", self.resource)
                relist = True
                continue
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception(
                    "informer %s: reflector error, resuming from rv %d",
                    self.resource, self.last_rv)
                await asyncio.sleep(0.2)

    def _replace(self, objs: list[dict], key_filter=None) -> None:
        """Relist reconciliation. `key_filter` scopes the deletion sweep
        to a subset of the key space (a sharded informer relisting ONE
        shard must not delete the other shards' objects)."""
        old_keys = set(self.indexer.keys())
        if key_filter is not None:
            old_keys = {k for k in old_keys if key_filter(k)}
        new_keys = {namespaced_name(o) for o in objs}
        for obj in objs:
            self._apply("MODIFIED" if namespaced_name(obj) in old_keys else "ADDED", obj)
        for key in old_keys - new_keys:
            gone = self.indexer.get(key)
            if gone is not None:
                self._apply("DELETED", gone)

    def _apply(self, ev_type: str, obj: dict) -> None:
        if ev_type == "DELETED":
            old = self.indexer.delete(obj)
            for h in self.handlers:
                self._call(h.on_delete, old if old is not None else obj)
            return
        old = self.indexer.upsert(obj)
        if old is None:
            for h in self.handlers:
                self._call(h.on_add, obj)
        else:
            if resource_version_of(old) == resource_version_of(obj):
                return  # relist echo of known state
            for h in self.handlers:
                self._call(h.on_update, old, obj)


class ShardedInformer(SharedInformer):
    """Per-shard reflectors behind one indexer + handler set.

    Against a sharded control plane (store/sharded.ShardedNodeStore, or
    a wire client whose server advertises shards via `control_topology`)
    a partitioned resource is consumed as S independent LIST+WATCH
    loops — one per shard — so watch establishment, backfill, and
    Expired relists stay SHARD-LOCAL: a relist storm re-reads one
    shard's snapshot, not the cluster's. The initial sync is ONE merged
    LIST (the facade merge-sorts by key — the same order a single
    store's sorted scan yields, which is what keeps sharded-vs-unsharded
    scheduling assignments bit-identical under the index tie rule).
    Stores without shards (plain MVCCStore, HTTP/gRPC clients) degrade
    to the classic single-reflector path untouched."""

    async def _topology(self) -> tuple[int, tuple[str, ...]]:
        fn = getattr(self.store, "control_topology", None)
        if fn is not None:
            t = await fn()
            return (int(t.get("nodeShards", 1) or 1),
                    tuple(t.get("partitioned") or ()))
        return (int(getattr(self.store, "node_shards", 1) or 1),
                tuple(getattr(self.store, "partitioned_resources", ())))

    async def _run(self) -> None:
        try:
            shards, partitioned = await self._topology()
        except asyncio.CancelledError:
            return
        except Exception:
            logger.exception("informer %s: topology probe failed; "
                             "using the single-stream path", self.resource)
            shards, partitioned = 1, ()
        if shards <= 1 or self.resource not in partitioned:
            return await super()._run()
        self._shard_count = shards
        # ONE merged LIST seeds the cache in global key order; each
        # shard's watch then resumes from the list's (global) RV.
        while True:
            try:
                lst = await self.store.list(
                    self.resource, selector=self.selector)
                break
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("informer %s: initial sharded LIST "
                                 "failed; retrying", self.resource)
                await asyncio.sleep(0.2)
        self._replace(lst.items)
        self.last_rv = lst.resource_version
        self._synced.set()
        loops = [asyncio.ensure_future(
            self._shard_loop(i, shards, lst.resource_version))
            for i in range(shards)]
        try:
            await asyncio.gather(*loops)
        finally:
            for t in loops:
                t.cancel()

    async def _shard_loop(self, i: int, shards: int, from_rv: int) -> None:
        """One shard's reflector: watch with bookmark-driven resume;
        only Expired forces a relist — and the relist is SHARD-SCOPED
        (list(shard=i) replaces only this shard's keys)."""
        rv = from_rv
        while True:
            try:
                watch = await self.store.watch(
                    self.resource, resource_version=rv,
                    selector=self.selector, shard=i)
                async for ev in watch:
                    if ev.type == "BOOKMARK":
                        rv = max(rv, ev.rv)
                        continue
                    self._apply(ev.type, ev.object)
                    rv = max(rv, ev.rv)
                    self.last_rv = max(self.last_rv, ev.rv)
            except Expired:
                logger.info("informer %s[shard %d]: watch expired, "
                            "shard-scoped relist", self.resource, i)
                try:
                    lst = await self.store.list(
                        self.resource, selector=self.selector, shard=i)
                except asyncio.CancelledError:
                    return
                except Exception:
                    await asyncio.sleep(0.2)
                    continue
                self._replace_shard(lst.items, i, shards)
                rv = lst.resource_version
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception(
                    "informer %s[shard %d]: reflector error, resuming "
                    "from rv %d", self.resource, i, rv)
                await asyncio.sleep(0.2)

    def _replace_shard(self, objs: list[dict], i: int, shards: int) -> None:
        """_replace scoped to shard i's key space: other shards' objects
        must survive this shard's relist."""
        from kubernetes_tpu.store.sharded import _name_of_key, shard_of
        self._replace(objs, key_filter=lambda k: shard_of(
            _name_of_key(k), shards) == i)


class InformerFactory:
    """SharedInformerFactory: one informer per resource, shared across
    consumers (controllers + scheduler share pod/node informers).
    Partitionable resources get a ShardedInformer, which degrades to
    the classic reflector when the store advertises no shards."""

    def __init__(self, store: MVCCStore):
        self.store = store
        self._informers: dict[str, SharedInformer] = {}

    def informer(self, resource: str, **kwargs: Any) -> SharedInformer:
        if resource not in self._informers:
            from kubernetes_tpu.store.sharded import PARTITIONED_RESOURCES
            cls = ShardedInformer if resource in PARTITIONED_RESOURCES \
                else SharedInformer
            self._informers[resource] = cls(self.store, resource, **kwargs)
        return self._informers[resource]

    def start(self) -> None:
        for inf in self._informers.values():
            inf.start()

    async def wait_for_sync(self, timeout: float = 10.0) -> None:
        for inf in self._informers.values():
            await inf.wait_for_sync(timeout)

    def stop(self) -> None:
        for inf in self._informers.values():
            inf.stop()
