"""Event recording — user-facing explainability ("FailedScheduling" etc.).

Parity target: staging/src/k8s.io/client-go/tools/record/event.go
(`EventRecorder.Eventf` → Event API objects with involvedObject/reason/message,
count-aggregated). The scheduler must keep emitting per-pod failure reasons even
when plugins fuse into one XLA program (SURVEY §5.5) — the per-plugin unsat
masks feed `reason`/`message` here.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Mapping

from kubernetes_tpu.api.meta import name_of, namespace_of, new_object, now_iso
from kubernetes_tpu.store.mvcc import MVCCStore, StoreError

logger = logging.getLogger(__name__)
_seq = itertools.count(1)


class EventRecorder:
    """Buffered broadcaster: events are queued synchronously and drained by
    ONE background task (the reference's record.EventBroadcaster watch loop)
    instead of one asyncio task per event — at scheduler_perf scale the
    per-event task + write copies were a top host cost."""

    #: Bounded queue, reference semantics: record.NewBroadcaster(1000)
    #: with DropIfChannelFull — under a scheduling burst the sink cannot
    #: keep up, and events beyond the buffer are dropped (counted), never
    #: allowed to backpressure the scheduling path.
    MAX_PENDING = 1000

    #: create() concurrency per drain window: the wire transport coalesces
    #: a whole window into one multiplexed frame, so draining 128-wide
    #: instead of one-awaited-create-per-tick is what keeps the buffer
    #: ahead of a scheduling burst (the drop-rate fix).
    DRAIN_WINDOW = 128

    def __init__(self, store: MVCCStore, component: str):
        self.store = store
        self.component = component
        self._pending: list[dict] = []
        #: EventCorrelator-lite (record/events_cache.go EventAggregator):
        #: (kind, namespace, name, type, reason) → the pending Event dict,
        #: so a repeat while the first is still buffered bumps `count`
        #: instead of occupying another slot. Aggregation is buffer-local
        #: — once drained, a recurrence creates a fresh Event (the
        #: reference would PATCH the stored one; not worth a read-modify-
        #: write per recurrence here).
        self._pending_by_key: dict[tuple, dict] = {}
        self._draining = False
        self.dropped = 0
        #: every event() call, dropped or not — dropped/emitted is the
        #: drop RATE consumers (the perf harness detail JSON) report.
        self.emitted = 0
        #: event() calls folded into an already-pending Event's count.
        self.aggregated = 0

    def event(self, obj: Mapping, event_type: str, reason: str, message: str) -> None:
        """Fire-and-forget, like the reference's buffered broadcaster."""
        self.emitted += 1
        agg_key = (obj.get("kind", ""), namespace_of(obj), name_of(obj),
                   event_type, reason)
        pending = self._pending_by_key.get(agg_key)
        if pending is not None:
            pending["count"] = pending.get("count", 1) + 1
            pending["lastTimestamp"] = now_iso()
            self.aggregated += 1
            # Still kick the drainer: the buffer may predate the loop
            # (events recorded before asyncio.run), and an aggregated
            # recurrence must flush it just like a fresh event would.
            self._kick_drain()
            return
        if len(self._pending) >= self.MAX_PENDING:
            self.dropped += 1
            if self.dropped % 1000 == 1:
                logger.warning(
                    "event buffer full (%d pending); dropped %d events so "
                    "far (DropIfChannelFull)", len(self._pending),
                    self.dropped)
            return
        ev = new_object(
            "Event",
            f"{name_of(obj)}.{next(_seq):x}",
            namespace_of(obj) or "default",
            involvedObject={
                "kind": obj.get("kind", ""),
                "name": name_of(obj),
                "namespace": namespace_of(obj),
                "uid": obj.get("metadata", {}).get("uid", ""),
            },
            type=event_type,  # Normal | Warning
            reason=reason,
            message=message,
            source={"component": self.component},
            firstTimestamp=now_iso(),
            count=1,
        )
        self._pending.append(ev)
        self._pending_by_key[agg_key] = ev
        self._kick_drain()

    def _kick_drain(self) -> None:
        if self._draining or not self._pending:
            return
        # Only create the drain coroutine when a loop is actually
        # running — otherwise it would be dropped un-awaited and warn.
        # With no loop (sync unit tests) the buffer flushes with the
        # next event recorded under a loop.
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return
        asyncio.ensure_future(self._drain())
        self._draining = True

    async def _drain(self) -> None:
        try:
            while self._pending:
                batch, self._pending = self._pending, []
                # Batch taken: its entries can no longer aggregate (the
                # writes are in flight); recurrences start fresh Events.
                self._pending_by_key.clear()
                for lo in range(0, len(batch), self.DRAIN_WINDOW):
                    # The recorder built these and never touches them
                    # again (_owned); store rejections are per-event debug
                    # noise (the pre-batch behavior), but a programming
                    # error must stay loud — not vanish into a dropped
                    # gather result.
                    results = await asyncio.gather(
                        *(self.store.create("events", ev, _owned=True,
                                            return_copy=False)
                          for ev in batch[lo:lo + self.DRAIN_WINDOW]),
                        return_exceptions=True)
                    for r in results:
                        if isinstance(r, StoreError):
                            logger.debug("event write failed: %s", r)
                        elif isinstance(r, Exception):
                            logger.exception("event drain error",
                                             exc_info=r)
                        elif isinstance(r, BaseException):
                            raise r  # CancelledError: stop draining
        finally:
            self._draining = False
